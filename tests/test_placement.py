"""Topology-aware slice placement: torus allocator, planning engine,
controller end-to-end, slice-manager consumption, nodepool determinism.

The acceptance drill lives in tests/drill.py (priority preemption over
the wire, run under the shipped RBAC gate in test_rbac_gate.py); the
chaos rider lives in tests/test_chaos.py.
"""

import math
import random

from tpu_operator import consts
from tpu_operator.api.tpuslice import (
    TPU_SLICE_API_VERSION,
    TPU_SLICE_KIND,
    new_tpu_slice,
)
from tpu_operator.controllers.placement_controller import (
    QUEUE_REQUEST,
    PlacementReconciler,
)
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.sim import make_torus_nodes, make_tpu_node
from tpu_operator.nodepool import get_node_pools
from tpu_operator.placement.engine import (
    PlacementEngine,
    PlacementPhase,
    PreemptionPolicy,
)
from tpu_operator.placement.torus import (
    Torus,
    chip_topology_for,
    host_grid_dims,
    parse_shape,
    worker_coords,
)

NS = "tpu-operator"


def placement_slice(name, shape, priority=0, policy="Never", pool="", created=""):
    obj = new_tpu_slice(
        name,
        {"placement": {
            "shape": shape, "priority": priority,
            "preemptionPolicy": policy, **({"pool": pool} if pool else {}),
        }},
    )
    obj["metadata"]["creationTimestamp"] = created or "2026-01-01T00:00:00Z"
    return obj


def scheduled_nodes(status):
    return ((status or {}).get("placement") or {}).get("nodes") or []


def assert_no_double_booking(statuses, nodes):
    """The acceptance invariant: no host serves two gangs — neither in
    any status.placement nor in the node assignment labels."""
    claimed = {}
    for name, st in statuses.items():
        if st.get("phase") != PlacementPhase.SCHEDULED:
            continue
        for node in st.get("nodes") or []:
            assert claimed.setdefault(node, name) == name, (
                f"host {node} booked by both {claimed[node]} and {name}"
            )
    by_label = {}
    for node in nodes:
        owner = (node["metadata"].get("labels") or {}).get(consts.PLACEMENT_LABEL)
        if owner:
            assert by_label.setdefault(node["metadata"]["name"], owner) == owner


# ---------------------------------------------------------------------------
# Torus geometry
# ---------------------------------------------------------------------------


class TestShapes:
    def test_parse_shape(self):
        assert parse_shape("4x4x4") == (4, 4, 4)
        assert parse_shape("2x4") == (2, 4, 1)
        assert parse_shape("8") == (8, 1, 1)
        assert parse_shape("") is None
        assert parse_shape("2x0x2") is None
        assert parse_shape("axb") is None
        assert parse_shape("1x2x3x4") is None

    def test_host_grid_dims(self):
        # v4/v5p: 4 chips per host as a 2x2x1 block
        assert host_grid_dims("16x16x8", 4) == (8, 8, 8)
        assert host_grid_dims("4x4x4", 4) == (2, 2, 4)
        # v5e 2-D mesh, 4-chip hosts
        assert host_grid_dims("4x4", 4) == (2, 2, 1)
        # non-dividing axis: unknown wiring
        assert host_grid_dims("3x4x4", 4) is None
        assert host_grid_dims("garbage", 4) is None

    def test_chip_topology_roundtrip(self):
        assert chip_topology_for((8, 8, 8), 4) == "16x16x8"
        # v4/v5p topology strings are 3-D by platform convention — a
        # flat block keeps its trailing unit axis
        assert chip_topology_for((2, 2, 1), 4) == "4x4x1"
        # 2-D mesh generations (v5e/v6e) drop it
        assert chip_topology_for((2, 2, 1), 4, topology_dims=2) == "4x4"
        assert chip_topology_for((2, 2, 2), 4) == "4x4x2"

    def test_worker_coords_row_major(self):
        dims = (4, 2, 2)
        seen = {worker_coords(i, dims) for i in range(16)}
        assert len(seen) == 16
        assert worker_coords(0, dims) == (0, 0, 0)
        assert worker_coords(1, dims) == (1, 0, 0)
        assert worker_coords(4, dims) == (0, 1, 0)
        assert worker_coords(8, dims) == (0, 0, 1)


class TestTorus:
    def test_from_labelled_nodes(self):
        nodes = make_torus_nodes((4, 2, 1))
        torus = Torus.from_nodes(nodes)
        assert torus.dims == (4, 2, 1)
        assert torus.free_count() == 8
        assert torus.node_at[(3, 1, 0)] == "tpu-7"

    def test_unlabelled_pool_falls_back_deterministically(self):
        nodes = [make_tpu_node(f"n{i}", "tpu-v4-podslice", "4x4x4") for i in range(8)]
        a = Torus.from_nodes(list(nodes))
        b = Torus.from_nodes(list(reversed(nodes)))
        assert a.dims == b.dims == (2, 2, 2)
        assert a.node_at == b.node_at

    def test_fallback_layout_is_stable_under_membership_shrink(self):
        """The fallback grid is anchored to the DECLARED host grid, not
        the current member count: a pool losing its last-ranked member
        must keep every other host's synthetic coordinate (a count-based
        near-cubic grid would re-dimension (2,2,2)->(7,1,1) and tear down
        every scheduled gang in the pool), and a scheduled gang on the
        surviving hosts must stay intact through the engine."""
        nodes = make_torus_nodes((2, 2, 2))
        for node in nodes:
            del node["metadata"]["labels"][consts.TORUS_COORDS_LABEL]
        ts = placement_slice("gang", "2x2x1")
        plan = PlacementEngine([ts], nodes).plan()
        assert plan.statuses["gang"]["phase"] == PlacementPhase.SCHEDULED
        self._apply_engine_plan(plan, nodes, [ts])
        assert "tpu-7" not in plan.statuses["gang"]["nodes"]
        survivors = [n for n in nodes if n["metadata"]["name"] != "tpu-7"]
        plan2 = PlacementEngine([ts], survivors).plan()
        assert "gang" not in plan2.teardowns, plan2.teardowns
        assert plan2.statuses["gang"]["phase"] == PlacementPhase.SCHEDULED

    @staticmethod
    def _apply_engine_plan(plan, nodes, slices):
        by_name = {n["metadata"]["name"]: n for n in nodes}
        for node_name, delta in plan.label_deltas.items():
            labels = by_name[node_name]["metadata"].setdefault("labels", {})
            for key, value in delta.items():
                if value is None:
                    labels.pop(key, None)
                else:
                    labels[key] = value
        for s in slices:
            if s["metadata"]["name"] in plan.statuses:
                s.setdefault("status", {})["placement"] = plan.statuses[s["metadata"]["name"]]

    def test_half_labelled_pool_is_not_trusted(self):
        nodes = make_torus_nodes((2, 2, 1))
        del nodes[0]["metadata"]["labels"][consts.TORUS_COORDS_LABEL]
        torus = Torus.from_nodes(nodes)
        # fallback layout, not a torus with a hole at (0,0,0)
        assert len(torus.node_at) == 4 and torus.free_count() == 4

    def test_exact_fit_packs_completely(self):
        torus = Torus.from_nodes(make_torus_nodes((4, 2, 1)))
        first, victims = torus.find_block(parse_shape("2x2x1"))
        assert victims == frozenset()
        torus.occupy("a", first.cells)
        second, _ = torus.find_block(parse_shape("2x2x1"))
        assert set(second.cells).isdisjoint(first.cells)
        torus.occupy("b", second.cells)
        assert torus.free_count() == 0
        assert torus.find_block(parse_shape("1x1x1")) is None

    def test_wraparound_block_is_found(self):
        torus = Torus.from_nodes(make_torus_nodes((4, 1, 1)))
        torus.occupy("mid", [(1, 0, 0), (2, 0, 0)])
        found = torus.find_block(parse_shape("2x1x1"))
        assert found is not None
        block, _ = found
        # only the wrapped pair (3,0,0)+(0,0,0) is free
        assert set(block.cells) == {(3, 0, 0), (0, 0, 0)}

    def test_degraded_wrap_edge_cuts_the_wrapped_block(self):
        """A severed WRAP link (fabric link blame) must block exactly
        the candidates that would route it: the wrapped pair is refused,
        an interior pair still places, and both endpoints remain
        individually placeable capacity."""
        torus = Torus.from_nodes(make_torus_nodes((4, 1, 1)))
        torus.occupy("mid", [(1, 0, 0), (2, 0, 0)])
        # only the wrapped pair tpu-3+tpu-0 is free — cut their link
        torus.set_degraded_edges([("tpu-3", "tpu-0")])
        assert torus.find_block(parse_shape("2x1x1")) is None
        found = torus.find_block(parse_shape("1x1x1"))
        assert found is not None  # the endpoints themselves still place
        fresh = Torus.from_nodes(make_torus_nodes((4, 1, 1)))
        fresh.set_degraded_edges([("tpu-3", "tpu-0")])
        found = fresh.find_block(parse_shape("2x1x1"))
        assert found is not None
        assert not ({(3, 0, 0), (0, 0, 0)} <= set(found[0].cells))

    def test_degraded_edge_constrains_preemption_candidates(self):
        """Preemption search must respect cuts too: a victim block that
        would seat the preemptor across a severed link is no rescue."""
        torus = Torus.from_nodes(make_torus_nodes((2, 1, 1)))
        torus.occupy("low", [(0, 0, 0), (1, 0, 0)])
        torus.set_degraded_edges([("tpu-0", "tpu-1")])
        assert torus.find_block(parse_shape("2x1x1"), victim_ok=lambda o: True) is None

    def test_mesh_pool_never_wraps(self):
        """v5e/v6e are meshes without edge ICI links: a block folding
        around the boundary would advertise a hop that doesn't exist."""
        nodes = make_torus_nodes((4, 1, 1))
        torus = Torus.from_nodes(nodes, wrap=False)
        torus.occupy("mid", [(1, 0, 0), (2, 0, 0)])
        # only the wrapped pair (3,0,0)+(0,0,0) would fit — rejected
        assert torus.find_block(parse_shape("2x1x1")) is None
        fresh = Torus.from_nodes(nodes, wrap=False)
        found = fresh.find_block(parse_shape("4x1x1"))
        assert found is not None  # non-wrapping blocks still place

    def test_partial_pool_keeps_true_dims_no_fictional_wrap(self):
        """A partially-registered pool must not shrink the torus to the
        max labelled coordinate: that would invent wrap adjacency
        between hosts that are really several hops apart. The declared
        grid makes unregistered positions holes instead."""
        nodes = [
            n for n in make_torus_nodes((4, 1, 1))
            if n["metadata"]["name"] != "tpu-3"
        ]
        torus = Torus.from_nodes(nodes, grid=(4, 1, 1))
        assert torus.dims == (4, 1, 1)
        torus.occupy("mid", [(1, 0, 0)])
        # free cells are (0,0,0) and (2,0,0) — 2 hops apart on the true
        # 4-wide ring; a max(coord)+1 torus would wrap them adjacent
        assert torus.find_block(parse_shape("2x1x1")) is None

    def test_rotation_fits_where_raw_shape_cannot(self):
        torus = Torus.from_nodes(make_torus_nodes((4, 2, 1)))
        found = torus.find_block(parse_shape("1x4x1"))  # must rotate onto x
        assert found is not None
        assert sorted(found[0].shape, reverse=True) == [4, 1, 1]

    def test_impossible_shape(self):
        torus = Torus.from_nodes(make_torus_nodes((4, 2, 1)))
        assert torus.find_block(parse_shape("3x3x1")) is None
        assert torus.find_block(parse_shape("8x1x1")) is None

    def test_best_fit_prefers_snug_placement(self):
        torus = Torus.from_nodes(make_torus_nodes((4, 4, 4)))
        first, _ = torus.find_block(parse_shape("2x2x2"))
        torus.occupy("a", first.cells)
        second, _ = torus.find_block(parse_shape("2x2x2"))
        # the next block must sit flush against the first (shares a face),
        # not float in open space leaving slivers on both sides
        adjacent = False
        occupied = set(first.cells)
        for (x, y, z) in second.cells:
            for dx, dy, dz in ((1, 0, 0), (-1, 0, 0), (0, 1, 0), (0, -1, 0), (0, 0, 1), (0, 0, -1)):
                if ((x + dx) % 4, (y + dy) % 4, (z + dz) % 4) in occupied:
                    adjacent = True
        assert adjacent, (first.cells, second.cells)

    def test_unavailable_cells_are_neither_free_nor_victims(self):
        torus = Torus.from_nodes(make_torus_nodes((2, 2, 1)))
        torus.set_unavailable(["tpu-0"])
        assert torus.free_count() == 3
        assert torus.find_block(parse_shape("2x2x1")) is None
        assert torus.find_block(parse_shape("2x2x1"), victim_ok=lambda o: True) is None

    def test_fragmentation_metric(self):
        torus = Torus.from_nodes(make_torus_nodes((4, 4, 1)))
        assert torus.fragmentation() == 0.0  # empty = one free block
        # checkerboard-ish scatter: plenty free, nothing contiguous
        torus.occupy("x", [(x, y, 0) for x in range(4) for y in range(4) if (x + y) % 2])
        assert torus.fragmentation() > 0.5
        torus.release("x")
        assert torus.fragmentation() == 0.0


# ---------------------------------------------------------------------------
# Node pool determinism (satellite regression)
# ---------------------------------------------------------------------------


class TestNodePoolDeterminism:
    def test_pools_independent_of_informer_list_order(self):
        """Placement decisions and gang worker ids both key off
        get_node_pools output; a re-list returning the same nodes in a
        different order must produce byte-identical pools — including
        the representative info (it used to be first-seen input order)."""
        nodes = make_torus_nodes((2, 2, 1), prefix="pool-a") + [
            make_tpu_node(f"pool-b-{i}", "tpu-v5-lite-podslice", "4x4", nodepool="b")
            for i in range(4)
        ]
        rng = random.Random(7)
        baseline = get_node_pools(list(nodes))
        for _ in range(5):
            shuffled = list(nodes)
            rng.shuffle(shuffled)
            pools = get_node_pools(shuffled)
            assert [p.name for p in pools] == [p.name for p in baseline]
            for got, want in zip(pools, baseline):
                assert got.node_names == want.node_names
                assert got.info == want.info
                assert got.info.node_name == want.node_names[0]


# ---------------------------------------------------------------------------
# Planning engine
# ---------------------------------------------------------------------------


class TestEngine:
    def test_mixed_shapes_never_double_book(self):
        nodes = make_torus_nodes((4, 4, 2))
        slices = [
            placement_slice("a", "2x2x2", created="2026-01-01T00:00:01Z"),
            placement_slice("b", "4x2x1", created="2026-01-01T00:00:02Z"),
            placement_slice("c", "2x2x1", created="2026-01-01T00:00:03Z"),
            placement_slice("d", "2x2x2", created="2026-01-01T00:00:04Z"),
        ]
        plan = PlacementEngine(slices, nodes).plan()
        assert all(
            st["phase"] == PlacementPhase.SCHEDULED for st in plan.statuses.values()
        ), plan.statuses
        assert_no_double_booking(plan.statuses, nodes)
        assert plan.queue_depth == 0
        for name, st in plan.statuses.items():
            shape = parse_shape(st["shape"])
            assert len(st["nodes"]) == math.prod(shape)

    def test_priority_beats_fifo(self):
        nodes = make_torus_nodes((2, 2, 1))
        slices = [
            placement_slice("early-low", "2x2x1", priority=0, created="2026-01-01T00:00:01Z"),
            placement_slice("late-high", "2x2x1", priority=10, created="2026-01-02T00:00:00Z"),
        ]
        plan = PlacementEngine(slices, nodes).plan()
        assert plan.statuses["late-high"]["phase"] == PlacementPhase.SCHEDULED
        assert plan.statuses["early-low"]["phase"] == PlacementPhase.UNSCHEDULABLE
        assert plan.queue_depth == 1

    def test_fifo_within_priority_band(self):
        nodes = make_torus_nodes((2, 2, 1))
        slices = [
            placement_slice("second", "2x2x1", created="2026-01-02T00:00:00Z"),
            placement_slice("first", "2x2x1", created="2026-01-01T00:00:00Z"),
        ]
        plan = PlacementEngine(slices, nodes).plan()
        assert plan.statuses["first"]["phase"] == PlacementPhase.SCHEDULED
        assert plan.statuses["second"]["phase"] == PlacementPhase.UNSCHEDULABLE

    def test_invalid_and_impossible_shapes_unschedulable(self):
        nodes = make_torus_nodes((2, 2, 1))
        plan = PlacementEngine(
            [placement_slice("bad", "axb"), placement_slice("big", "4x4x4")], nodes
        ).plan()
        assert plan.statuses["bad"]["phase"] == PlacementPhase.UNSCHEDULABLE
        assert "invalid" in plan.statuses["bad"]["message"]
        assert plan.statuses["big"]["phase"] == PlacementPhase.UNSCHEDULABLE

    def test_preemption_evicts_minimal_victim_set(self):
        """Two low-priority gangs fill the torus; a high-priority request
        must displace EXACTLY one of them (the allocator ranks candidate
        blocks by victim count), never both."""
        nodes = make_torus_nodes((4, 2, 1))
        low = [
            placement_slice("low-a", "2x2x1", created="2026-01-01T00:00:01Z"),
            placement_slice("low-b", "2x2x1", created="2026-01-01T00:00:02Z"),
        ]
        engine = PlacementEngine(low, nodes)
        plan = engine.plan()
        self._apply(plan, nodes, low)
        high = placement_slice("high", "2x2x1", priority=5,
                               policy=PreemptionPolicy.PREEMPT_LOWER,
                               created="2026-01-03T00:00:00Z")
        plan = PlacementEngine(low + [high], nodes).plan()
        assert plan.statuses["high"]["phase"] == PlacementPhase.SCHEDULED
        victims = [
            n for n in ("low-a", "low-b")
            if plan.statuses[n]["phase"] == PlacementPhase.QUEUED
        ]
        survivors = [
            n for n in ("low-a", "low-b")
            if plan.statuses[n]["phase"] == PlacementPhase.SCHEDULED
        ]
        assert len(victims) == 1 and len(survivors) == 1, plan.statuses
        assert "preempted" in plan.statuses[victims[0]]["message"]
        assert plan.teardowns == victims
        assert_no_double_booking(plan.statuses, nodes)

    def test_preemption_never_touches_equal_or_higher_priority(self):
        nodes = make_torus_nodes((2, 2, 1))
        occupant = placement_slice("same-prio", "2x2x1", priority=5)
        engine = PlacementEngine([occupant], nodes)
        self._apply(engine.plan(), nodes, [occupant])
        contender = placement_slice(
            "contender", "2x2x1", priority=5,
            policy=PreemptionPolicy.PREEMPT_LOWER, created="2026-01-02T00:00:00Z",
        )
        plan = PlacementEngine([occupant, contender], nodes).plan()
        assert plan.statuses["contender"]["phase"] == PlacementPhase.UNSCHEDULABLE
        assert plan.statuses["same-prio"]["phase"] == PlacementPhase.SCHEDULED

    def test_never_policy_does_not_preempt(self):
        nodes = make_torus_nodes((2, 2, 1))
        low = placement_slice("low", "2x2x1", priority=0)
        engine = PlacementEngine([low], nodes)
        self._apply(engine.plan(), nodes, [low])
        high = placement_slice("high", "2x2x1", priority=10, created="2026-01-02T00:00:00Z")
        plan = PlacementEngine([low, high], nodes).plan()
        assert plan.statuses["high"]["phase"] == PlacementPhase.UNSCHEDULABLE
        assert plan.statuses["low"]["phase"] == PlacementPhase.SCHEDULED

    def test_quarantined_member_triggers_replacement(self):
        """Health-integration satellite: a gang member entering repair
        tears the gang down and the re-placement avoids the sick host."""
        nodes = make_torus_nodes((4, 2, 1))
        ts = placement_slice("gang", "2x2x1")
        engine = PlacementEngine([ts], nodes)
        plan = engine.plan()
        self._apply(plan, nodes, [ts])
        placed = set(plan.statuses["gang"]["nodes"])
        sick = sorted(placed)[0]
        for node in nodes:
            if node["metadata"]["name"] == sick:
                node["metadata"]["labels"][consts.REPAIR_STATE_LABEL] = "quarantined"
        plan2 = PlacementEngine([ts], nodes).plan()
        assert "gang" in plan2.teardowns
        st = plan2.statuses["gang"]
        assert st["phase"] == PlacementPhase.SCHEDULED  # re-placed same pass
        assert sick not in st["nodes"]
        # the sick host's assignment labels clear
        assert plan2.label_deltas[sick][consts.PLACEMENT_LABEL] is None

    def test_mesh_generation_never_wraps_through_engine(self):
        """The engine derives wrap from the pool's accelerator family: a
        v5e (mesh) pool must refuse the edge-spanning block a v4 torus
        accepts."""
        def chain_with_occupied_middle(accelerator):
            nodes = make_torus_nodes((4, 1, 1), accelerator=accelerator)
            mid = placement_slice("mid", "2x1x1", created="2026-01-01T00:00:01Z")
            for name, index in (("tpu-1", "0"), ("tpu-2", "1")):
                node = next(n for n in nodes if n["metadata"]["name"] == name)
                node["metadata"]["labels"][consts.PLACEMENT_LABEL] = "mid"
                node["metadata"]["labels"][consts.PLACEMENT_INDEX_LABEL] = index
            new = placement_slice("new", "2x1x1", created="2026-01-01T00:00:02Z")
            return PlacementEngine([mid, new], nodes).plan()

        torus_plan = chain_with_occupied_middle("tpu-v4-podslice")
        assert torus_plan.statuses["new"]["phase"] == PlacementPhase.SCHEDULED
        assert set(torus_plan.statuses["new"]["nodes"]) == {"tpu-3", "tpu-0"}
        mesh_plan = chain_with_occupied_middle("tpu-v5-lite-podslice")
        assert mesh_plan.statuses["new"]["phase"] == PlacementPhase.UNSCHEDULABLE

    def test_partially_registered_pool_through_engine(self):
        """The engine sizes each pool's torus from its topology label,
        so a scaling-up pool places only on really-contiguous hosts."""
        nodes = [
            n for n in make_torus_nodes((4, 1, 1))
            if n["metadata"]["name"] != "tpu-3"
        ]
        mid = placement_slice("mid", "1x1x1", created="2026-01-01T00:00:01Z")
        node1 = next(n for n in nodes if n["metadata"]["name"] == "tpu-1")
        node1["metadata"]["labels"][consts.PLACEMENT_LABEL] = "mid"
        node1["metadata"]["labels"][consts.PLACEMENT_INDEX_LABEL] = "0"
        new = placement_slice("new", "2x1x1", created="2026-01-01T00:00:02Z")
        plan = PlacementEngine([mid, new], nodes).plan()
        # tpu-0 and tpu-2 are free but 2 hops apart on the true 4-ring
        assert plan.statuses["new"]["phase"] == PlacementPhase.UNSCHEDULABLE

    def test_equal_volume_shape_edit_triggers_replacement(self):
        """An edited spec shape with the same host count must re-place
        (the old block no longer matches the spec), while a pure
        rotation of the placed shape must NOT (same block)."""
        nodes = make_torus_nodes((4, 2, 1))
        ts = placement_slice("gang", "4x1x1")
        plan = PlacementEngine([ts], nodes).plan()
        assert plan.statuses["gang"]["phase"] == PlacementPhase.SCHEDULED
        self._apply(plan, nodes, [ts])
        ts["spec"]["placement"]["shape"] = "1x4x1"  # rotation: same block
        plan2 = PlacementEngine([ts], nodes).plan()
        assert "gang" not in plan2.teardowns
        ts["spec"]["placement"]["shape"] = "2x2x1"  # same volume, new geometry
        plan3 = PlacementEngine([ts], nodes).plan()
        assert "gang" in plan3.teardowns
        st = plan3.statuses["gang"]
        assert st["phase"] == PlacementPhase.SCHEDULED and st["shape"] == "2x2x1"

    def test_stale_status_shape_does_not_tear_down_valid_gang(self):
        """Gang validity is judged from node labels alone: after a
        shape-edit re-place whose STATUS write failed (5xx), the next
        pass sees labels forming a valid block of the spec shape but a
        status still naming the old shape — it must converge the status,
        not tear the healthy new block down again on every pass."""
        nodes = make_torus_nodes((4, 2, 1))
        ts = placement_slice("gang", "2x2x1")
        plan = PlacementEngine([ts], nodes).plan()
        assert plan.statuses["gang"]["phase"] == PlacementPhase.SCHEDULED
        self._apply(plan, nodes, [ts])
        # labels applied, but the status write never landed: status still
        # records the pre-edit shape
        ts["status"]["placement"]["shape"] = "4x1x1"
        plan2 = PlacementEngine([ts], nodes).plan()
        assert "gang" not in plan2.teardowns
        st = plan2.statuses["gang"]
        assert st["phase"] == PlacementPhase.SCHEDULED and st["shape"] == "2x2x1"
        assert sorted(st["nodes"]) == sorted(scheduled_nodes(ts.get("status")))

    def test_pool_repin_triggers_replacement(self):
        nodes = (
            make_torus_nodes((2, 2, 1), prefix="a", nodepool="pool-a")
            + make_torus_nodes((2, 2, 1), prefix="b", nodepool="pool-b")
        )
        pool_names = [p.name for p in get_node_pools(nodes)]
        ts = placement_slice("gang", "2x2x1")
        plan = PlacementEngine([ts], nodes).plan()
        self._apply(plan, nodes, [ts])
        placed = plan.statuses["gang"]["pool"]
        other = next(p for p in pool_names if p != placed)
        ts["spec"]["placement"]["pool"] = other
        plan2 = PlacementEngine([ts], nodes).plan()
        assert "gang" in plan2.teardowns
        st = plan2.statuses["gang"]
        assert st["phase"] == PlacementPhase.SCHEDULED and st["pool"] == other

    def test_split_gang_from_crash_mid_apply_is_replaced(self):
        """Count/index/pool checks all pass on a SPLIT gang — a crash
        between the label writes of a teardown + re-place leaves old and
        new members sharing the owner label with unique indexes. The
        geometry check must catch it and re-place."""
        nodes = make_torus_nodes((4, 2, 1))
        ts = placement_slice("gang", "2x2x1")
        # members straddle two opposite edges with worker order that
        # matches no row-major block anchored at index 0
        members = {"tpu-0": "0", "tpu-3": "1", "tpu-4": "2", "tpu-7": "3"}
        for node in nodes:
            index = members.get(node["metadata"]["name"])
            if index is not None:
                node["metadata"]["labels"][consts.PLACEMENT_LABEL] = "gang"
                node["metadata"]["labels"][consts.PLACEMENT_INDEX_LABEL] = index
        plan = PlacementEngine([ts], nodes).plan()
        assert "gang" in plan.teardowns, "split gang accepted as intact"
        st = plan.statuses["gang"]
        assert st["phase"] == PlacementPhase.SCHEDULED  # re-placed same pass
        assert_no_double_booking(plan.statuses, nodes)

    def test_intact_wrapped_gang_is_not_torn_down(self):
        """The geometry check must accept a legitimately wrapped block
        exactly as the engine writes it (cells anchored at the origin)."""
        nodes = make_torus_nodes((4, 1, 1))
        ts = placement_slice("gang", "2x1x1")
        # the engine's own wrapped placement: origin (3,0,0), then (0,0,0)
        for name, index in (("tpu-3", "0"), ("tpu-0", "1")):
            node = next(n for n in nodes if n["metadata"]["name"] == name)
            node["metadata"]["labels"][consts.PLACEMENT_LABEL] = "gang"
            node["metadata"]["labels"][consts.PLACEMENT_INDEX_LABEL] = index
        plan = PlacementEngine([ts], nodes).plan()
        assert "gang" not in plan.teardowns
        assert plan.statuses["gang"]["phase"] == PlacementPhase.SCHEDULED

    def test_orphaned_assignments_cleared(self):
        nodes = make_torus_nodes((2, 2, 1))
        for node in nodes:
            node["metadata"]["labels"][consts.PLACEMENT_LABEL] = "ghost"
            node["metadata"]["labels"][consts.PLACEMENT_INDEX_LABEL] = "0"
        plan = PlacementEngine([], nodes).plan()
        for node in nodes:
            delta = plan.label_deltas[node["metadata"]["name"]]
            assert delta[consts.PLACEMENT_LABEL] is None

    def test_risk_scores_steer_placement_off_hazardous_hosts(self):
        nodes = make_torus_nodes((4, 2, 1))
        slices = [placement_slice("g", "2x2x1", created="2026-01-01T00:00:01Z")]
        baseline = PlacementEngine(slices, nodes).plan()
        risky = baseline.statuses["g"]["nodes"][0]
        plan = PlacementEngine(
            slices, nodes, node_risk={risky: 0.9}
        ).plan()
        assert plan.statuses["g"]["phase"] == PlacementPhase.SCHEDULED
        assert risky not in plan.statuses["g"]["nodes"]
        assert_no_double_booking(plan.statuses, nodes)

    def test_risk_is_a_bias_not_a_gate(self):
        # every host risky: the shape still lands (advisory, never blocks)
        nodes = make_torus_nodes((2, 2, 1))
        risk = {n["metadata"]["name"]: 1.0 for n in nodes}
        slices = [placement_slice("g", "2x2x1", created="2026-01-01T00:00:01Z")]
        plan = PlacementEngine(slices, nodes, node_risk=risk).plan()
        assert plan.statuses["g"]["phase"] == PlacementPhase.SCHEDULED

    def test_empty_risk_map_is_byte_identical_to_stock(self):
        nodes = make_torus_nodes((4, 4, 2))
        slices = [
            placement_slice("a", "2x2x2", created="2026-01-01T00:00:01Z"),
            placement_slice("b", "4x2x1", created="2026-01-01T00:00:02Z"),
        ]
        stock = PlacementEngine(slices, nodes).plan()
        hooked = PlacementEngine(slices, nodes, node_risk={}).plan()
        assert stock.statuses == hooked.statuses
        assert stock.label_deltas == hooked.label_deltas

    @staticmethod
    def _apply(plan, nodes, slices):
        """Apply a plan back onto the in-memory objects, the way the
        controller would against the apiserver."""
        by_name = {n["metadata"]["name"]: n for n in nodes}
        for node_name, delta in plan.label_deltas.items():
            labels = by_name[node_name]["metadata"].setdefault("labels", {})
            for key, value in delta.items():
                if value is None:
                    labels.pop(key, None)
                else:
                    labels[key] = value
        by_slice = {s["metadata"]["name"]: s for s in slices}
        for name, status in plan.statuses.items():
            if name in by_slice:
                by_slice[name].setdefault("status", {})["placement"] = status


# ---------------------------------------------------------------------------
# Controller end-to-end on the fake apiserver
# ---------------------------------------------------------------------------


class TestPlacementController:
    def _seed(self, client, dims=(4, 2, 1)):
        for node in make_torus_nodes(dims):
            client.create(node)

    def test_reconcile_places_and_publishes(self):
        client = FakeClient()
        self._seed(client)
        client.create(placement_slice("train", "2x2x1"))
        rec = PlacementReconciler(client, NS)
        rec.reconcile(QUEUE_REQUEST)
        ts = client.get(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, "train")
        st = ts["status"]["placement"]
        assert st["phase"] == PlacementPhase.SCHEDULED
        assert len(st["nodes"]) == 4 and st["pool"]
        for index, node_name in enumerate(st["nodes"]):
            labels = client.get("v1", "Node", node_name)["metadata"]["labels"]
            assert labels[consts.PLACEMENT_LABEL] == "train"
            assert labels[consts.PLACEMENT_INDEX_LABEL] == str(index)
            assert labels[consts.PLACEMENT_TOPOLOGY_LABEL] == "4x4x1"  # v4: 3-D string

    def test_reconcile_is_idempotent(self):
        client = FakeClient()
        self._seed(client)
        client.create(placement_slice("train", "2x2x1"))
        rec = PlacementReconciler(client, NS)
        rec.reconcile(QUEUE_REQUEST)
        before = client.get(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, "train")
        node_rvs = {
            n["metadata"]["name"]: n["metadata"].get("resourceVersion")
            for n in client.list("v1", "Node")
        }
        rec.reconcile(QUEUE_REQUEST)
        after = client.get(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, "train")
        assert after["status"]["placement"] == before["status"]["placement"]
        for node in client.list("v1", "Node"):
            assert node["metadata"].get("resourceVersion") == node_rvs[node["metadata"]["name"]], (
                "idempotent pass re-wrote node labels"
            )

    def test_deleted_slice_releases_hosts(self):
        client = FakeClient()
        self._seed(client)
        client.create(placement_slice("gone", "2x2x1"))
        rec = PlacementReconciler(client, NS)
        rec.reconcile(QUEUE_REQUEST)
        client.delete(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, "gone")
        rec.reconcile(QUEUE_REQUEST)
        for node in client.list("v1", "Node"):
            assert consts.PLACEMENT_LABEL not in (node["metadata"].get("labels") or {})

    def test_queue_metrics_published(self):
        import prometheus_client

        client = FakeClient()
        self._seed(client, dims=(2, 2, 1))
        client.create(placement_slice("fits", "2x2x1", created="2026-01-01T00:00:00Z"))
        client.create(placement_slice("waits", "2x2x1", created="2026-01-02T00:00:00Z"))
        rec = PlacementReconciler(client, NS)
        result = rec.reconcile(QUEUE_REQUEST)
        depth = prometheus_client.REGISTRY.get_sample_value(
            "tpu_operator_placement_queue_depth"
        )
        assert depth == 1.0
        assert result.requeue_after == consts.PLACEMENT_REPLAN_SECONDS
        (pool,) = get_node_pools(client.list("v1", "Node"))
        frag = prometheus_client.REGISTRY.get_sample_value(
            "tpu_operator_torus_fragmentation", {"pool": pool.name}
        )
        assert frag is not None

    def test_failed_status_patch_requeues(self):
        """Once labels converge nothing re-enqueues the queue, so a
        swallowed status-write failure must force a requeue or the
        status stays stale forever."""
        from tpu_operator.kube import errors

        client = FakeClient()
        self._seed(client)
        client.create(placement_slice("train", "2x2x1"))
        rec = PlacementReconciler(client, NS)
        real_patch_status = client.patch_status

        def failing_patch_status(*args, **kwargs):
            raise errors.ApiError("injected status-write failure")

        client.patch_status = failing_patch_status
        result = rec.reconcile(QUEUE_REQUEST)
        assert result.requeue, "failed status write did not requeue"
        client.patch_status = real_patch_status
        result = rec.reconcile(QUEUE_REQUEST)
        assert not result.requeue
        ts = client.get(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, "train")
        assert ts["status"]["placement"]["phase"] == PlacementPhase.SCHEDULED

    def test_fragmentation_series_removed_with_pool(self):
        import prometheus_client

        client = FakeClient()
        self._seed(client, dims=(2, 2, 1))
        rec = PlacementReconciler(client, NS)
        rec.reconcile(QUEUE_REQUEST)
        (pool,) = get_node_pools(client.list("v1", "Node"))
        sample = lambda: prometheus_client.REGISTRY.get_sample_value(
            "tpu_operator_torus_fragmentation", {"pool": pool.name}
        )
        assert sample() is not None
        for node in client.list("v1", "Node"):
            client.delete("v1", "Node", node["metadata"]["name"])
        rec.reconcile(QUEUE_REQUEST)
        assert sample() is None, "drained pool kept exporting fragmentation"

    def test_index_label_damage_heals_over_watch(self):
        """Mangling an assignment index label must trigger a replan via
        the watch predicate — nothing else re-enqueues a settled queue."""
        import time

        from tpu_operator.controllers.placement_controller import setup_with_manager
        from tpu_operator.kube.manager import Manager

        client = FakeClient()
        self._seed(client)
        client.create(placement_slice("train", "2x2x1"))
        mgr = Manager(client)
        setup_with_manager(mgr, PlacementReconciler(client, NS))
        mgr.start()
        try:
            def gang_indexes():
                return sorted(
                    labels[consts.PLACEMENT_INDEX_LABEL]
                    for n in client.list("v1", "Node")
                    if (labels := n["metadata"].get("labels") or {}).get(
                        consts.PLACEMENT_LABEL
                    ) == "train" and consts.PLACEMENT_INDEX_LABEL in labels
                )

            deadline = time.time() + 20
            while time.time() < deadline and gang_indexes() != ["0", "1", "2", "3"]:
                time.sleep(0.1)
            assert gang_indexes() == ["0", "1", "2", "3"]
            victim = next(
                n["metadata"]["name"] for n in client.list("v1", "Node")
                if (n["metadata"].get("labels") or {}).get(
                    consts.PLACEMENT_INDEX_LABEL
                ) == "3"
            )
            client.patch("v1", "Node", victim, {"metadata": {"labels": {
                consts.PLACEMENT_INDEX_LABEL: "0",  # duplicate worker id
            }}})
            deadline = time.time() + 20
            while time.time() < deadline and gang_indexes() != ["0", "1", "2", "3"]:
                time.sleep(0.1)
            assert gang_indexes() == ["0", "1", "2", "3"], (
                "damaged index labels never healed"
            )
        finally:
            mgr.stop()

    def test_wiped_status_republished_over_watch(self):
        """An externally wiped status.placement (CRD structural pruning,
        manual status edit) must be re-published by the watch — a
        settled queue has nothing else to re-enqueue it."""
        import time

        from tpu_operator.controllers.placement_controller import setup_with_manager
        from tpu_operator.kube.manager import Manager

        client = FakeClient()
        self._seed(client)
        client.create(placement_slice("train", "2x2x1"))
        mgr = Manager(client)
        setup_with_manager(mgr, PlacementReconciler(client, NS))
        mgr.start()
        try:
            def phase():
                ts = client.get(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, "train")
                return ((ts.get("status") or {}).get("placement") or {}).get("phase")

            deadline = time.time() + 20
            while time.time() < deadline and phase() != PlacementPhase.SCHEDULED:
                time.sleep(0.1)
            assert phase() == PlacementPhase.SCHEDULED
            client.patch_status(
                TPU_SLICE_API_VERSION, TPU_SLICE_KIND, "train",
                {"status": {"placement": None}},
            )
            deadline = time.time() + 20
            while time.time() < deadline and phase() != PlacementPhase.SCHEDULED:
                time.sleep(0.1)
            assert phase() == PlacementPhase.SCHEDULED, (
                "wiped status.placement never re-published"
            )
        finally:
            mgr.stop()

    def test_preemption_over_fake_apiserver(self):
        client = FakeClient()
        self._seed(client, dims=(2, 2, 1))
        client.create(placement_slice("low", "2x2x1", priority=0))
        rec = PlacementReconciler(client, NS)
        rec.reconcile(QUEUE_REQUEST)
        client.create(placement_slice(
            "high", "2x2x1", priority=9,
            policy=PreemptionPolicy.PREEMPT_LOWER, created="2026-01-02T00:00:00Z",
        ))
        rec.reconcile(QUEUE_REQUEST)
        high = client.get(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, "high")
        low = client.get(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, "low")
        assert high["status"]["placement"]["phase"] == PlacementPhase.SCHEDULED
        assert low["status"]["placement"]["phase"] in (
            PlacementPhase.QUEUED, PlacementPhase.UNSCHEDULABLE
        )
        for node_name in high["status"]["placement"]["nodes"]:
            labels = client.get("v1", "Node", node_name)["metadata"]["labels"]
            assert labels[consts.PLACEMENT_LABEL] == "high"
        # a preemption event landed on the victim (cluster-scoped CR
        # events land in "default" per apiserver rules)
        events = client.list("v1", "Event")
        assert any(e.get("reason") == "PlacementPreempted" for e in events), [
            e.get("reason") for e in events
        ]


# ---------------------------------------------------------------------------
# Slice-manager consumption of assignments + health exclusion
# ---------------------------------------------------------------------------


class TestSliceManagerPlacement:
    def _seed_assigned(self, client):
        """A 4-host pool where the placement controller assigned 2 hosts
        to gang 'train-a' — with index order deliberately OPPOSITE the
        alphabetical node order, to prove worker ids follow the torus."""
        for i, node in enumerate(make_torus_nodes((4, 1, 1), prefix="host")):
            node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
            client.create(node)
        for name, index in (("host-1", "1"), ("host-0", "0")):
            client.patch("v1", "Node", name, {"metadata": {"labels": {
                consts.PLACEMENT_LABEL: "train-a",
                consts.PLACEMENT_INDEX_LABEL: index,
                consts.PLACEMENT_TOPOLOGY_LABEL: "4x2",
            }}})
        # make index order differ from name order on purpose
        client.patch("v1", "Node", "host-0", {"metadata": {"labels": {
            consts.PLACEMENT_INDEX_LABEL: "1",
        }}})
        client.patch("v1", "Node", "host-1", {"metadata": {"labels": {
            consts.PLACEMENT_INDEX_LABEL: "0",
        }}})

    def test_assigned_gang_replaces_implicit_pool(self):
        from tpu_operator.agents.slice_manager_agent import (
            WORKER_ID_LABEL,
            SliceManagerAgent,
        )

        client = FakeClient()
        self._seed_assigned(client)
        agent = SliceManagerAgent(client, NS)
        names = agent.reconcile_once()
        # ONE gang — the placement's — not the implicit whole-pool gang
        assert names == ["tpu-slice-train-a"], names
        cm = client.get("v1", "ConfigMap", "tpu-slice-train-a-gang", NS)
        assert cm["data"]["TPU_SLICE_HOSTS"] == "2"
        assert cm["data"]["TPU_TOPOLOGY"] == "4x2"  # the placed block, not the pool
        # worker ids follow the placement index (torus order), not names
        assert client.get("v1", "Node", "host-1")["metadata"]["labels"][WORKER_ID_LABEL] == "0"
        assert client.get("v1", "Node", "host-0")["metadata"]["labels"][WORKER_ID_LABEL] == "1"
        # unassigned pool members get no worker identity
        for name in ("host-2", "host-3"):
            assert WORKER_ID_LABEL not in client.get("v1", "Node", name)["metadata"]["labels"]

    def test_quarantined_placement_member_defers_gang(self):
        """A placed gang whose member the health subsystem excluded must
        DEFER, not materialize short: the assignment labels are all still
        present (cluster-wide completeness passes), but publishing the
        survivors would pair the block's full TPU_TOPOLOGY with a
        truncated hostlist (libtpu hang) and renumber worker ids off the
        block's ICI order. The placement engine re-places the gang; until
        then its plumbing stays down."""
        from tpu_operator.agents.slice_manager_agent import (
            WORKER_ID_LABEL,
            SliceManagerAgent,
        )

        client = FakeClient()
        self._seed_assigned(client)
        agent = SliceManagerAgent(client, NS)
        assert agent.reconcile_once() == ["tpu-slice-train-a"]
        client.patch("v1", "Node", "host-0", {"metadata": {"labels": {
            consts.REPAIR_STATE_LABEL: "quarantined",
        }}})
        assert agent.reconcile_once() == []
        assert client.get_or_none("v1", "ConfigMap", "tpu-slice-train-a-gang", NS) is None
        for name in ("host-0", "host-1"):
            labels = client.get("v1", "Node", name)["metadata"]["labels"]
            assert WORKER_ID_LABEL not in labels, name

    def test_quarantined_member_leaves_gang_and_loses_worker_id(self):
        """A quarantined member makes the implicit gang defer entirely:
        a shrunk hostlist under the pool's full TPU_TOPOLOGY would hang
        libtpu init on every surviving worker, and no placement engine
        stands behind an implicit gang to re-place it. Teardown, then
        re-materialize whole when the node heals."""
        from tpu_operator.agents.slice_manager_agent import (
            WORKER_ID_LABEL,
            SliceManagerAgent,
        )

        client = FakeClient()
        for node in make_torus_nodes((4, 1, 1), prefix="host"):
            node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
            client.create(node)
        agent = SliceManagerAgent(client, NS)
        (gang,) = agent.reconcile_once()
        assert client.get("v1", "Node", "host-2")["metadata"]["labels"][WORKER_ID_LABEL] == "2"
        # the health subsystem quarantines a member
        client.patch("v1", "Node", "host-2", {"metadata": {"labels": {
            consts.REPAIR_STATE_LABEL: "quarantined",
        }}})
        assert agent.reconcile_once() == []
        for name in ("host-0", "host-1", "host-2", "host-3"):
            labels = client.get("v1", "Node", name)["metadata"]["labels"]
            assert WORKER_ID_LABEL not in labels, (
                f"{name} kept a worker identity in a torn-down gang"
            )
        assert client.get_or_none("v1", "ConfigMap", f"{gang}-gang", NS) is None
        assert client.get_or_none("v1", "Service", gang, NS) is None
        # repair completes: the gang comes back whole
        client.patch("v1", "Node", "host-2", {"metadata": {"labels": {
            consts.REPAIR_STATE_LABEL: None,
        }}})
        assert agent.reconcile_once() == [gang]
        cm = client.get("v1", "ConfigMap", f"{gang}-gang", NS)
        assert cm["data"]["TPU_SLICE_HOSTS"] == "4"
        assert client.get("v1", "Node", "host-2")["metadata"]["labels"][WORKER_ID_LABEL] == "2"

    def test_half_written_assignment_defers_gang(self):
        """The controller patches assignment labels one node at a time;
        a reconcile landing mid-write must not materialize a short gang
        (full-block topology + truncated hostlist hangs libtpu on every
        worker) NOR fall back to the implicit whole-pool gang."""
        from tpu_operator.agents.slice_manager_agent import (
            WORKER_ID_LABEL,
            SliceManagerAgent,
        )

        client = FakeClient()
        for node in make_torus_nodes((4, 1, 1), prefix="host"):
            node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
            client.create(node)
        # crashed after labelling 1 of the 2 hosts of a 4x2x1 block
        client.patch("v1", "Node", "host-0", {"metadata": {"labels": {
            consts.PLACEMENT_LABEL: "train-a",
            consts.PLACEMENT_INDEX_LABEL: "0",
            consts.PLACEMENT_TOPOLOGY_LABEL: "4x2x1",
        }}})
        agent = SliceManagerAgent(client, NS)
        assert agent.reconcile_once() == []
        assert WORKER_ID_LABEL not in client.get("v1", "Node", "host-0")["metadata"]["labels"]
        # the remaining label lands: the complete gang materializes
        client.patch("v1", "Node", "host-1", {"metadata": {"labels": {
            consts.PLACEMENT_LABEL: "train-a",
            consts.PLACEMENT_INDEX_LABEL: "1",
            consts.PLACEMENT_TOPOLOGY_LABEL: "4x2x1",
        }}})
        assert agent.reconcile_once() == ["tpu-slice-train-a"]
        cm = client.get("v1", "ConfigMap", "tpu-slice-train-a-gang", NS)
        assert cm["data"]["TPU_SLICE_HOSTS"] == "2"
