"""Control-plane side of the pod data plane: render, converge and sweep
the worker Pods the job and serving controllers own.

Reuses the operand rendering machinery (``render.Renderer`` over
``manifests/workload-worker/``) and the slice manager's convergence
idiom: the rendered pod's spec hash is stamped into an annotation, an
existing pod with the same hash is left alone, a different hash is
delete+recreated (pods are immutable where it matters — env, node
pinning — so convergence IS replacement, exactly the DaemonSet
controller's own model).

Ownership discipline (the PR 13/15 pin, extended to pods): every pod
rendered here carries a controller ownerReference to its TPUJob /
TPUServing, and the sweep deletes ONLY pods that carry it. A user's
standalone pod whose name merely collides with ``<job>-worker-<i>`` or
``<serving>-prefill-<i>`` is never touched.
"""

from __future__ import annotations

import logging
import os
from typing import Dict, Iterable, List, Optional

from tpu_operator import consts
from tpu_operator.kube import errors
from tpu_operator.kube.client import Client
from tpu_operator.render import Renderer
from tpu_operator.utils import object_hash

log = logging.getLogger(__name__)

MANAGED_BY = {"app.kubernetes.io/managed-by": "tpu-workload-dataplane"}

WORKER_MANIFEST_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "manifests", "workload-worker",
)


def job_worker_name(job_name: str, index: int) -> str:
    return f"{job_name}{consts.JOB_WORKER_INFIX}{index}"


def serving_worker_name(serving_name: str, pool: str, index: int) -> str:
    infix = (
        consts.SERVING_PREFILL_INFIX
        if pool == consts.SERVING_POOL_PREFILL
        else consts.SERVING_DECODE_INFIX
    )
    return f"{serving_name}{infix}{index}"


def _owned_by(pod: dict, owner_kind: str, owner_name: str) -> bool:
    """True when the pod carries a controller ownerReference to the
    named CR — the ONLY license to delete it."""
    for ref in (pod.get("metadata", {}).get("ownerReferences") or []):
        if ref.get("kind") == owner_kind and ref.get("name") == owner_name:
            return True
    return False


class WorkerPodSet:
    """Converges the worker Pods one owning CR wants against what
    exists, and sweeps what it no longer wants. One instance per
    reconciler (the renderer caches its templates)."""

    def __init__(self, client: Client, namespace: str,
                 image: str = "tpu-operator-worker",
                 image_pull_policy: str = "IfNotPresent"):
        self.client = client
        self.namespace = namespace
        self.image = image
        self.image_pull_policy = image_pull_policy
        self._renderer = Renderer([WORKER_MANIFEST_DIR])

    # -- render + converge --------------------------------------------------

    def converge(self, owner: dict, pod_main: str,
                 workers: List[dict]) -> Dict[str, List[str]]:
        """Make the owner's worker pods match ``workers``.

        ``owner`` is the owning CR (apiVersion/kind/metadata read for
        the ownerReference); ``workers`` is a list of dicts with keys
        ``name``, ``env`` (str->str), and optional ``node``, ``chips``,
        ``labels``. Returns {created, replaced, kept} pod-name lists;
        pods whose name exists but is NOT owned by this CR are left
        untouched (reported under ``foreign``)."""
        app = owner["metadata"]["name"]
        rendered = self._renderer.render_objects({
            "workers": [
                {
                    "name": w["name"],
                    "env": w.get("env") or {},
                    "node": w.get("node", ""),
                    "chips": w.get("chips", 0),
                    "labels": w.get("labels") or {},
                }
                for w in workers
            ],
            "namespace": self.namespace,
            "app": app,
            "managed_by": MANAGED_BY["app.kubernetes.io/managed-by"],
            "pod_main_label": consts.POD_MAIN_LABEL,
            "pod_main": pod_main,
            "tpu_resource": consts.TPU_RESOURCE_NAME,
            "image": self.image,
            "image_pull_policy": self.image_pull_policy,
        })
        report: Dict[str, List[str]] = {
            "created": [], "replaced": [], "kept": [], "foreign": [],
        }
        for pod in rendered:
            name = pod["metadata"]["name"]
            # hash BEFORE the ownerReference lands: the owner uid is
            # metadata, and folding it into the hash would delete+
            # recreate every worker on operator reinstall
            spec_hash = object_hash(pod)
            pod["metadata"]["ownerReferences"] = [{
                "apiVersion": owner["apiVersion"],
                "kind": owner["kind"],
                "name": owner["metadata"]["name"],
                "uid": owner["metadata"].get("uid", ""),
                "controller": True,
            }]
            pod["metadata"].setdefault("annotations", {})[
                consts.WORKER_HASH_ANNOTATION] = spec_hash
            existing = self.client.get_or_none("v1", "Pod", name, self.namespace)
            if existing is not None:
                if not _owned_by(existing, owner["kind"], owner["metadata"]["name"]):
                    log.warning(
                        "worker pod name %s/%s is taken by a pod this %s does "
                        "not own; leaving it alone", self.namespace, name,
                        owner["kind"])
                    report["foreign"].append(name)
                    continue
                if (existing.get("metadata", {}).get("annotations") or {}).get(
                        consts.WORKER_HASH_ANNOTATION) == spec_hash:
                    report["kept"].append(name)
                    continue
                try:
                    self.client.delete(
                        "v1", "Pod", name, self.namespace,
                        grace_period_seconds=0)
                except errors.NotFound:
                    pass
                report["replaced"].append(name)
            else:
                report["created"].append(name)
            try:
                self.client.create(pod)  # tpuop-lint: kinds=v1/Pod
            except (errors.AlreadyExists, errors.Conflict):
                pass  # raced another pass; next reconcile converges
        return report

    # -- sweep --------------------------------------------------------------

    def sweep(self, owner_kind: str, owner_name: str,
              live: Iterable[str] = ()) -> List[str]:
        """Delete the owner's worker pods that are not in ``live``
        (empty ``live`` = tear down everything it owns). Only pods
        carrying the owner's controller ownerReference are candidates —
        a same-named standalone pod survives."""
        keep = set(live)
        deleted: List[str] = []
        for pod in self.client.list(
                "v1", "Pod", self.namespace, label_selector=dict(MANAGED_BY)):
            name = pod["metadata"]["name"]
            if name in keep:
                continue
            if not _owned_by(pod, owner_kind, owner_name):
                continue
            try:
                self.client.delete(
                    "v1", "Pod", name, self.namespace, grace_period_seconds=0)
                deleted.append(name)
            except errors.NotFound:
                pass
        return deleted

    # -- observation + routing ----------------------------------------------

    def owned_pods(self, owner_kind: str, owner_name: str) -> List[dict]:
        return [
            pod
            for pod in self.client.list(
                "v1", "Pod", self.namespace, label_selector=dict(MANAGED_BY))
            if _owned_by(pod, owner_kind, owner_name)
        ]

    def worker_phases(self, owner_kind: str, owner_name: str) -> Dict[str, str]:
        """{pod name: status.phase} for the owner's workers ("" until
        the kubelet reports)."""
        return {
            pod["metadata"]["name"]: (pod.get("status") or {}).get("phase", "")
            for pod in self.owned_pods(owner_kind, owner_name)
        }

    def patch_route_weight(self, name: str, weight: float) -> bool:
        """Stamp the router-weight annotation on one worker pod (the
        data-plane router reads its weight from the pod itself; the
        load-CM routing key stays authoritative). Returns False when
        the pod is gone — the caller's next converge recreates it."""
        try:
            self.client.patch(
                "v1", "Pod", name,
                {"metadata": {"annotations": {
                    consts.WORKER_ROUTE_WEIGHT_ANNOTATION: f"{weight:g}"}}},
                self.namespace,
            )
            return True
        except errors.NotFound:
            return False


def rendezvous_state(progress_data: Optional[dict], expected: int,
                     gang_hash: str) -> dict:
    """Evaluate the rendezvous handshake from the progress-CM data:
    which of the ``expected`` member indexes have published
    ``rendezvous.<i>`` = the CURRENT gang hash (a stale hash is a
    worker from a previous generation still draining)."""
    data = progress_data or {}
    checked_in = []
    stale = []
    for index in range(expected):
        value = data.get(f"{consts.JOB_RENDEZVOUS_PREFIX}{index}")
        if value == gang_hash:
            checked_in.append(index)
        elif value:
            stale.append(index)
    return {
        "expected": expected,
        "checked_in": checked_in,
        "stale": stale,
        "complete": len(checked_in) == expected and expected > 0,
    }
