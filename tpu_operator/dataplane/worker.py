"""Data-plane side of the pod runtime: the worker-pod mains.

A worker pod's container command is opaque to the sim; what the sim
kubelet (``kube/sim.py`` :class:`PodKubelet`) actually runs is the
*pod main* resolved from the pod's ``POD_MAIN_LABEL`` value through
the registry here. A main is a tiny object with one contract:

- ``step() -> bool`` — one data-plane beat on the kubelet's thread;
  True means the pod's work is finished (phase ``Succeeded``). An
  exception fails the pod (phase ``Failed``).

Two mains exist:

- :class:`JobWorkerMain` — one TPUJob gang member. Every member
  publishes ``rendezvous.<index> = <gang hash>`` into the job's
  progress ConfigMap; the chief (index 0) wraps the proven
  :class:`~tpu_operator.workloads.training.InProcessJobRunner` and
  gates training until every expected index has checked in with the
  CURRENT gang hash (a stale hash is a worker from a previous
  generation still draining). Checkpoint/restart barriers ride the
  same progress CM unchanged.
- :class:`ServingWorkerMain` — one TPUServing replica. Owns a
  :class:`~tpu_operator.workloads.serving.DecodeEngine`; the KV-aware
  router feeds it and reads its KV-affinity state. The ``TPU_POOL``
  env selects aggregated serving, a prefill-pool replica
  (``prefill_only`` engine) or a decode-pool replica (handoff
  importer with session retention).

This module is never imported by the controllers (it is workload-side
code running under the workload's credentials); the control-plane
helpers live in ``dataplane/pods.py``.
"""

from __future__ import annotations

import os
import tempfile
from typing import Callable, Dict, Optional

from tpu_operator import consts
from tpu_operator.dataplane.pods import rendezvous_state

# pod-main registry: POD_MAIN_LABEL value -> factory(client, namespace, env)
_POD_MAINS: Dict[str, Callable] = {}


def register_pod_main(kind: str, factory: Callable) -> None:
    _POD_MAINS[kind] = factory


def resolve_pod_main(kind: str) -> Optional[Callable]:
    return _POD_MAINS.get(kind)


def default_checkpoint_dir(namespace: str, job_name: str) -> str:
    """Deterministic fallback store location when the TPUJob spec does
    not pin one — every gang generation of one job must resume from the
    SAME store or checkpoint-resume silently becomes restart-from-zero."""
    return os.path.join(
        tempfile.gettempdir(), f"tpuop-ckpt-{namespace}-{job_name}"
    )


class JobWorkerMain:
    """One gang member's training loop (chief) or rendezvous heartbeat
    (non-chief)."""

    def __init__(self, client, namespace: str, env: Dict[str, str]):
        self.client = client
        self.namespace = env.get(consts.WORKER_ENV_NAMESPACE) or namespace
        self.job_name = env[consts.WORKER_ENV_JOB_NAME]
        self.index = int(env.get(consts.WORKER_ENV_WORKER_INDEX, "0"))
        self.count = int(env.get(consts.WORKER_ENV_WORKER_COUNT, "1"))
        self.gang_hash = env.get(consts.WORKER_ENV_GANG_HASH, "")
        self.checkpoint_dir = (
            env.get(consts.WORKER_ENV_CHECKPOINT_DIR)
            or default_checkpoint_dir(self.namespace, self.job_name)
        )
        self.steps_per_sync = int(env.get(consts.WORKER_ENV_STEPS_PER_SYNC, "3"))
        self.runner = None  # chief-only, built on first step
        self.rendezvous: dict = {}

    @property
    def is_chief(self) -> bool:
        return self.index == 0

    @property
    def trainer(self):
        """The chief's trainer (history/checkpoints harvested by bench
        and drills across pod generations); None on non-chiefs."""
        return self.runner.trainer if self.runner is not None else None

    def _progress_name(self) -> str:
        return self.job_name + consts.JOB_PROGRESS_SUFFIX

    def _progress(self) -> dict:
        cm = self.client.get_or_none(
            "v1", "ConfigMap", self._progress_name(), self.namespace
        )
        return (cm or {}).get("data") or {}

    def _publish(self, data: Dict[str, str]) -> None:
        from tpu_operator.kube import errors
        from tpu_operator.kube.objects import new_object

        try:
            self.client.patch(
                "v1", "ConfigMap", self._progress_name(), {"data": data},
                self.namespace,
            )
        except errors.NotFound:
            try:
                self.client.create(  # tpuop-lint: kinds=v1/ConfigMap
                    new_object("v1", "ConfigMap", self._progress_name(),
                               self.namespace, data=data)
                )
            except errors.AlreadyExists:
                self.client.patch(
                    "v1", "ConfigMap", self._progress_name(), {"data": data},
                    self.namespace,
                )

    def step(self) -> bool:
        progress = self._progress()
        # check in (idempotent): rendezvous.<index> = this generation's
        # gang hash — the CM may have been recreated, so re-verify
        key = f"{consts.JOB_RENDEZVOUS_PREFIX}{self.index}"
        if progress.get(key) != self.gang_hash:
            self._publish({key: self.gang_hash})
            progress = dict(progress, **{key: self.gang_hash})
        status = progress.get(consts.JOB_PROGRESS_STATUS, "")
        if status == consts.JOB_PROGRESS_COMPLETE:
            return True  # training finished (possibly by a prior chief)
        if not self.is_chief:
            return False  # heartbeat only; the pod runs until swept
        self.rendezvous = rendezvous_state(progress, self.count, self.gang_hash)
        if not self.rendezvous["complete"]:
            return False  # gate training until the whole gang checked in
        if self.runner is None:
            from tpu_operator.workloads.checkpoint import CheckpointStore
            from tpu_operator.workloads.training import InProcessJobRunner

            self.runner = InProcessJobRunner(
                self.client, self.namespace, self.job_name,
                CheckpointStore(self.checkpoint_dir),
                steps_per_sync=self.steps_per_sync,
            )
        self.runner.sync()
        trainer = self.runner.trainer
        return trainer is not None and trainer.done


class ServingWorkerMain:
    """One serving replica: a decode engine beating under the kubelet.
    The router holds a reference (via the kubelet's worker registry)
    and submits/harvests requests between beats."""

    def __init__(self, client, namespace: str, env: Dict[str, str],
                 cfg=None, seed: int = 0):
        from tpu_operator.workloads.serving import DecodeEngine, ServingModelConfig

        self.client = client
        self.namespace = env.get(consts.WORKER_ENV_NAMESPACE) or namespace
        self.serving_name = env.get(consts.WORKER_ENV_SERVING_NAME, "")
        self.replica = env.get(consts.WORKER_ENV_REPLICA_NAME, "")
        self.pool = env.get(consts.WORKER_ENV_POOL, "")
        # compile-cache addressing: the controller renders the replica's
        # generation + topology into the pod env; absent (older specs,
        # unit fixtures) the warmup runs unkeyed and the cache is inert
        self.generation = env.get(consts.WORKER_ENV_GENERATION, "")
        self.topology = env.get(consts.WORKER_ENV_TOPOLOGY, "")
        cfg = cfg or ServingModelConfig()
        prefill = self.pool == consts.SERVING_POOL_PREFILL
        self.engine = DecodeEngine(
            cfg, seed=seed,
            prefill_only=prefill,
            # decode + aggregated replicas keep session KV warm; a
            # prefill replica's lanes retire at the first token, so
            # retention would only pin dead pages
            retain_sessions=not prefill,
        )
        from tpu_operator.workloads.compilecache import CompileCacheStore

        store = CompileCacheStore(client, self.namespace)
        # warmup resolves through the fleet compile cache: a hit means a
        # prior replica (or an AOT prewarm) already paid this compile;
        # a miss measures and publishes it so the next replica is warm
        self.compile_outcome, self.warmup_seconds = store.warm_start(
            self.engine, self.generation, self.topology,
            serving=self.serving_name,
        )

    def submit(self, request) -> None:
        self.engine.submit(request)

    def submit_prefilled(self, request, kv: dict) -> None:
        self.engine.submit_prefilled(request, kv)

    def step(self) -> bool:
        if not self.engine.idle:
            self.engine.step()
        return False  # a serving worker runs until its pod is swept


register_pod_main(
    consts.POD_MAIN_JOB_WORKER,
    lambda client, namespace, env: JobWorkerMain(client, namespace, env),
)
register_pod_main(
    consts.POD_MAIN_SERVING_WORKER,
    lambda client, namespace, env: ServingWorkerMain(client, namespace, env),
)
