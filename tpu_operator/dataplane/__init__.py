"""The pod data plane: worker Pods for TPUJob gangs and TPUServing
replicas, the pod mains the sim kubelet runs in threads, and the
KV-aware serving router.

Layering (mirrors the control-plane/data-plane split on a real
cluster, and keeps the RBAC closure honest):

- ``pods.py`` — control-plane side. Imported by the job and serving
  controllers; renders/converges/sweeps worker Pods through the same
  manifest + hash machinery the slice manager uses. Every apiserver
  verb it sends is attributed to the operator ClusterRole by
  ``lint/rbac_static.py``.
- ``worker.py`` — data-plane side. The pod mains (job gang member,
  serving replica) plus the registry the sim kubelet resolves
  POD_MAIN_LABEL values against. Runs under the workload's own
  credentials, never the operator's.
- ``router.py`` — data-plane side. The KV-aware router: session
  affinity, prefix-cache scoring, chunked-prefill admission, and the
  prefill->decode paged-KV handoff.
"""
