"""The KV-aware serving router: the data-plane component between
traffic and the replica worker pods.

Routing is a scored choice over the routable replica set (the
controller's routing weights stay authoritative — weight 0 excludes a
replica here exactly as in the dumb round-robin sim):

1. **session affinity** — a multi-turn conversation re-lands on the
   replica already holding its KV pages (the engine delta-prefills only
   the new turn instead of re-ingesting the whole conversation);
2. **prefix-cache awareness** — replicas holding a cached page-aligned
   prefix of the prompt (shared system prompts) score higher,
   proportional to how much of the prompt the cache covers;
3. **chunked-prefill admission** — replicas saturated with prefill
   lanes are skipped so one burst of long prompts cannot starve every
   replica's decode lanes at once; requests wait in the router queue
   until some replica has prefill headroom (admission coordinated
   ACROSS replicas, which no per-engine policy can do);
4. **load** — ties break toward the emptier engine.

With disaggregation, prompts route to the prefill pool
(least-saturated replica), and each finished prefill's paged KV hands
off to a scored decode replica (``DecodeEngine.submit_prefilled``).

The router publishes its KV telemetry (``kvHitRatio``,
``handoffBytes``, ``prefillTtftP99``, ``decodeTokensPerS``) into the
serving's load ConfigMap — the signals the controller's per-pool
autoscalers read.
"""

from __future__ import annotations

import json
import time
from typing import Dict, List, Optional

from tpu_operator import consts
from tpu_operator.kube import errors

# skip a replica whose engine already ingests this many prompts at once
PREFILL_ADMISSION_CAP = 2
SESSION_AFFINITY_BONUS = 3.0
PREFIX_BONUS = 1.0
LOAD_PENALTY = 0.5


class KVAwareRouter:
    """One serving's router. Workers attach/detach as the kubelet
    starts/stops their pods; ``tick()`` is one routing beat (admit
    queued requests, collect prefill handoffs, publish telemetry)."""

    def __init__(self, client, namespace: str, serving_name: str,
                 prefill_admission_cap: int = PREFILL_ADMISSION_CAP):
        self.client = client
        self.namespace = namespace
        self.serving_name = serving_name
        self.prefill_admission_cap = prefill_admission_cap
        self.workers: Dict[str, object] = {}          # decode/aggregated mains
        self.prefill_workers: Dict[str, object] = {}  # prefill-pool mains
        self.queue: List[object] = []                 # awaiting admission
        self.sessions: Dict[str, str] = {}            # session -> last replica
        self.routed: Dict[str, int] = {}
        self.session_total = 0
        self.session_hits = 0
        self.prefix_routed = 0
        self.handoffs = 0
        self.handoff_bytes = 0
        self._t0 = time.perf_counter()
        self._decode_counts: Dict[str, int] = {}      # tokens at last publish

    # -- worker attachment ---------------------------------------------------

    def sync_workers(self, workers: Dict[str, object]) -> None:
        """Adopt the kubelet's live serving workers for this serving
        (replica name -> ServingWorkerMain). Called every tick — pod
        churn (scale-down, hash replacement) drops out naturally."""
        self.workers = {}
        self.prefill_workers = {}
        for name, main in workers.items():
            if getattr(main, "serving_name", "") != self.serving_name:
                continue
            if getattr(main, "pool", "") == consts.SERVING_POOL_PREFILL:
                self.prefill_workers[name] = main
            else:
                self.workers[name] = main

    # -- controller state ----------------------------------------------------

    def _load_cm(self) -> Optional[dict]:
        return self.client.get_or_none(
            "v1", "ConfigMap",
            self.serving_name + consts.SERVING_LOAD_SUFFIX, self.namespace,
        )

    def weights(self) -> Dict[str, float]:
        """The controller's routing weights over decode/aggregated
        replica SLICES; a worker pod maps to its slice by the replica
        name its env carries. Unlisted replicas default routable (the
        controller has not spoken yet)."""
        data = (self._load_cm() or {}).get("data") or {}
        try:
            return {
                k: float(v)
                for k, v in json.loads(
                    data.get(consts.SERVING_ROUTING_KEY, "{}")).items()
            }
        except (ValueError, TypeError):
            return {}

    # -- routing -------------------------------------------------------------

    def submit(self, request) -> None:
        self.queue.append(request)

    def _routable(self, pool: Dict[str, object]) -> Dict[str, object]:
        weights = self.weights()
        out = {}
        for name, main in pool.items():
            replica = getattr(main, "replica", name)
            if weights and weights.get(replica, 1.0) <= 0.0:
                continue
            out[name] = main
        return out

    def _score(self, main, request) -> float:
        engine = main.engine
        score = 0.0
        if request.session:
            holder = self.sessions.get(request.session)
            if holder == getattr(main, "replica", "") or engine.has_session(
                    request.session):
                score += SESSION_AFFINITY_BONUS
        plen = max(1, int(request.prompt.shape[0]))
        score += PREFIX_BONUS * (engine.cached_prefix_tokens(request.prompt) / plen)
        load = (len(engine.slots) + len(engine.queue)) / max(1, engine.cfg.max_batch)
        score -= LOAD_PENALTY * load
        return score

    def _admit(self) -> int:
        """Route queued requests. Chunked-prefill admission: a request
        only lands on a replica with prefill headroom; when every
        routable replica is saturated the queue holds (coordinated
        backpressure, re-tried next tick)."""
        admitted = 0
        while self.queue:
            request = self.queue[0]
            if self.prefill_workers:
                target = self._pick_prefill()
            else:
                target = self._pick_decode(request)
            if target is None:
                break  # no headroom anywhere: hold the line
            name, main = target
            self.queue.pop(0)
            main.submit(request)
            self.routed[name] = self.routed.get(name, 0) + 1
            if request.session:
                self.session_total += 1
                if self.sessions.get(request.session) == getattr(
                        main, "replica", name):
                    self.session_hits += 1
                self.sessions[request.session] = getattr(main, "replica", name)
            if main.engine.cached_prefix_tokens(request.prompt) > 0:
                self.prefix_routed += 1
            admitted += 1
        return admitted

    def _pick_decode(self, request):
        candidates = [
            (name, main)
            for name, main in self._routable(self.workers).items()
            if main.engine.prefilling_lanes < self.prefill_admission_cap
        ]
        if not candidates:
            return None
        return max(
            candidates,
            key=lambda item: (self._score(item[1], request), item[0]),
        )

    def _pick_prefill(self):
        candidates = [
            (name, main)
            for name, main in self.prefill_workers.items()
            if main.engine.prefilling_lanes < self.prefill_admission_cap
        ]
        if not candidates:
            return None
        return min(
            candidates,
            key=lambda item: (item[1].engine.prefilling_lanes, item[0]),
        )

    def _collect_handoffs(self) -> int:
        """Drain finished prefills into scored decode replicas (the
        paged-KV handoff)."""
        moved = 0
        for main in self.prefill_workers.values():
            while main.engine.prefilled_done:
                entry = main.engine.prefilled_done[0]
                target = self._pick_decode(entry["request"])
                if target is None:
                    break  # decode pool saturated: handoff waits
                main.engine.prefilled_done.pop(0)
                name, decode_main = target
                request, kv = entry["request"], entry["kv"]
                decode_main.submit_prefilled(request, kv)
                self.handoffs += 1
                self.handoff_bytes += kv["k"].nbytes + kv["v"].nbytes
                if request.session:
                    self.sessions[request.session] = getattr(
                        decode_main, "replica", name)
                moved += 1
        return moved

    # -- telemetry -----------------------------------------------------------

    @property
    def kv_hit_ratio(self) -> float:
        if not self.session_total:
            return 0.0
        return self.session_hits / self.session_total

    def _prefill_ttft_p99(self) -> float:
        ttfts = sorted(
            r.ttft_s
            for main in self.prefill_workers.values()
            for r in main.engine.completed
            if r.ttft_s is not None
        )
        if not ttfts:
            return 0.0
        from tpu_operator.workloads.telemetry import _percentile

        return _percentile(ttfts, 0.99)

    def _decode_tokens_per_s(self) -> float:
        total = sum(
            main.engine.decoded_tokens for main in self.workers.values()
        )
        elapsed = time.perf_counter() - self._t0
        return total / elapsed if elapsed > 0 else 0.0

    def publish(self) -> None:
        """Best-effort KV telemetry into the load CM (traffic-side keys;
        the controller's pool autoscalers read them)."""
        data = {
            consts.SERVING_LOAD_KV_HIT_RATIO: f"{self.kv_hit_ratio:.4f}",
            consts.SERVING_LOAD_HANDOFF_BYTES: str(self.handoff_bytes),
        }
        if self.prefill_workers:
            data[consts.SERVING_LOAD_PREFILL_TTFT_P99] = (
                f"{self._prefill_ttft_p99():.4f}")
            data[consts.SERVING_LOAD_DECODE_TOKENS_PER_S] = (
                f"{self._decode_tokens_per_s():.2f}")
        name = self.serving_name + consts.SERVING_LOAD_SUFFIX
        try:
            self.client.patch(
                "v1", "ConfigMap", name, {"data": data}, self.namespace)
        except errors.NotFound:
            from tpu_operator.kube.objects import new_object

            try:
                self.client.create(  # tpuop-lint: ignore
                    new_object("v1", "ConfigMap", name, self.namespace,
                               data=data))
            except errors.ApiError:
                pass
        except errors.ApiError:
            pass

    def tick(self) -> dict:
        """One routing beat: collect finished prefills, admit queued
        requests, publish telemetry."""
        moved = self._collect_handoffs()
        admitted = self._admit()
        self.publish()
        return {
            "admitted": admitted,
            "handoffs": moved,
            "queued": len(self.queue),
            "kv_hit_ratio": round(self.kv_hit_ratio, 4),
        }

    def completed_requests(self) -> List[object]:
        """Every finished request across the decode/aggregated workers
        (prefill completions are transport, not answers)."""
        return [
            r for main in self.workers.values() for r in main.engine.completed
        ]
