"""Rolling libtpu upgrade: the node-label state machine.

Reference: the vendored upgrade library
(vendor/github.com/NVIDIA/k8s-operator-libs/pkg/upgrade) — per-node FSM
driven by the ``upgrade-state`` node label:

    upgrade-required → cordon-required → wait-for-jobs-required →
    pod-deletion-required → drain-required → pod-restart-required →
    validation-required → uncordon-required → upgrade-done
    (consts.go:44-67)

The design is re-implemented, not ported: states are pure functions over
the cluster, the whole machine is stateless and idempotent
(upgrade_state.go:68-74 — every decision is recomputed from pods + labels
each pass), and concurrency limits (maxParallelUpgrades / maxUnavailable)
bound how many nodes may be in flight.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Dict, List, Optional

from tpu_operator import consts
from tpu_operator.api.clusterpolicy import UpgradePolicySpec
from tpu_operator.kube import errors
from tpu_operator.kube.client import Client
from tpu_operator.kube.objects import ObjectDict, matches_selector

log = logging.getLogger(__name__)

DRIVER_POD_COMPONENT_LABEL = "app.kubernetes.io/component"
DRIVER_POD_COMPONENT = "libtpu-installer"
VALIDATOR_POD_APP = "tpu-operator-validator"
POD_TEMPLATE_GENERATION_LABEL = "pod-template-generation"


class UpgradeState:
    UNKNOWN = ""
    UPGRADE_REQUIRED = "upgrade-required"
    CORDON_REQUIRED = "cordon-required"
    WAIT_FOR_JOBS_REQUIRED = "wait-for-jobs-required"
    POD_DELETION_REQUIRED = "pod-deletion-required"
    DRAIN_REQUIRED = "drain-required"
    POD_RESTART_REQUIRED = "pod-restart-required"
    VALIDATION_REQUIRED = "validation-required"
    UNCORDON_REQUIRED = "uncordon-required"
    DONE = "upgrade-done"
    FAILED = "upgrade-failed"


# states counting as "in progress" for the maxParallel budget
IN_PROGRESS = {
    UpgradeState.CORDON_REQUIRED,
    UpgradeState.WAIT_FOR_JOBS_REQUIRED,
    UpgradeState.POD_DELETION_REQUIRED,
    UpgradeState.DRAIN_REQUIRED,
    UpgradeState.POD_RESTART_REQUIRED,
    UpgradeState.VALIDATION_REQUIRED,
    UpgradeState.UNCORDON_REQUIRED,
}


@dataclasses.dataclass
class NodeUpgradeState:
    node: ObjectDict
    driver_pods: List[ObjectDict]
    daemonset: Optional[ObjectDict]
    state: str

    @property
    def name(self) -> str:
        return self.node["metadata"]["name"]


@dataclasses.dataclass
class ClusterUpgradeState:
    nodes: Dict[str, NodeUpgradeState]

    def in_state(self, *states: str) -> List[NodeUpgradeState]:
        return sorted(
            (n for n in self.nodes.values() if n.state in states), key=lambda n: n.name
        )

    def count(self, *states: str) -> int:
        return len(self.in_state(*states))


class ClusterUpgradeStateManager:
    """reference: ClusterUpgradeStateManager upgrade_state.go:67-101
    (BuildState + ApplyState)."""

    def __init__(self, client: Client, namespace: str, recorder=None):
        self.client = client
        self.namespace = namespace
        if recorder is None:
            from tpu_operator.kube.events import EventRecorder

            recorder = EventRecorder(client, namespace)
        self.recorder = recorder

    # -- BuildState ----------------------------------------------------------

    def build_state(self) -> ClusterUpgradeState:
        """Recompute every node's upgrade state from driver pods + labels."""
        daemonsets = {
            ds["metadata"]["name"]: ds
            for ds in self.client.list("apps/v1", "DaemonSet", self.namespace)
        }
        pods_by_node: Dict[str, List[ObjectDict]] = {}
        for pod in self.client.list(
            "v1", "Pod", self.namespace,
            label_selector={DRIVER_POD_COMPONENT_LABEL: DRIVER_POD_COMPONENT},
        ):
            node_name = pod.get("spec", {}).get("nodeName")
            if node_name:
                pods_by_node.setdefault(node_name, []).append(pod)

        nodes: Dict[str, NodeUpgradeState] = {}
        for node in self.client.list("v1", "Node"):
            name = node["metadata"]["name"]
            pods = pods_by_node.get(name, [])
            if not pods and consts.UPGRADE_STATE_LABEL not in (node["metadata"].get("labels") or {}):
                continue  # not a driver node
            ds = self._owning_daemonset(pods, daemonsets)
            label_state = (node["metadata"].get("labels") or {}).get(consts.UPGRADE_STATE_LABEL, "")
            state = label_state
            if not label_state and self._pod_outdated(pods, ds):
                state = UpgradeState.UPGRADE_REQUIRED
            if label_state == UpgradeState.DONE and self._pod_outdated(pods, ds):
                # a new upgrade round begins
                state = UpgradeState.UPGRADE_REQUIRED
            nodes[name] = NodeUpgradeState(node=node, driver_pods=pods, daemonset=ds, state=state)
        return ClusterUpgradeState(nodes=nodes)

    @staticmethod
    def _owning_daemonset(pods: List[ObjectDict], daemonsets: Dict[str, ObjectDict]):
        for pod in pods:
            for ref in pod["metadata"].get("ownerReferences", []):
                if ref.get("kind") == "DaemonSet" and ref.get("name") in daemonsets:
                    return daemonsets[ref["name"]]
        return None

    @staticmethod
    def _pod_outdated(pods: List[ObjectDict], ds: Optional[ObjectDict]) -> bool:
        """A driver pod is outdated when its template generation no longer
        matches its DaemonSet's (the reference compares pod template
        hashes; kube stamps pod-template-generation on DS pods)."""
        if ds is None or not pods:
            return False
        want = str(ds["metadata"].get("generation", 1))
        for pod in pods:
            have = (pod["metadata"].get("labels") or {}).get(POD_TEMPLATE_GENERATION_LABEL)
            if have is not None and have != want:
                return True
        return False

    # -- ApplyState ----------------------------------------------------------

    def apply_state(self, state: ClusterUpgradeState, policy: UpgradePolicySpec) -> None:
        """One idempotent pass: advance each node by at most one step.
        Buckets are snapshotted up front so a node moved this pass isn't
        reprocessed by the next bucket (the reference processes the buckets
        BuildState computed, never intra-pass transitions)."""
        # one cluster-wide pod list per pass; every bucket filters this
        # snapshot in memory instead of re-listing per node
        pods_by_node: Dict[str, List[ObjectDict]] = {}
        for pod in self.client.list("v1", "Pod"):
            node_name = pod.get("spec", {}).get("nodeName")
            if node_name and pod.get("status", {}).get("phase") not in ("Succeeded", "Failed"):
                pods_by_node.setdefault(node_name, []).append(pod)
        buckets = {
            s: state.in_state(s)
            for s in (
                UpgradeState.UPGRADE_REQUIRED,
                UpgradeState.CORDON_REQUIRED,
                UpgradeState.WAIT_FOR_JOBS_REQUIRED,
                UpgradeState.POD_DELETION_REQUIRED,
                UpgradeState.DRAIN_REQUIRED,
                UpgradeState.POD_RESTART_REQUIRED,
                UpgradeState.VALIDATION_REQUIRED,
                UpgradeState.UNCORDON_REQUIRED,
            )
        }
        max_parallel = policy.max_parallel_upgrades or len(state.nodes) or 1
        in_progress = state.count(*IN_PROGRESS)
        budget = max(0, max_parallel - in_progress)
        budget = min(budget, self._unavailable_budget(state, policy))

        for node_state in buckets[UpgradeState.UPGRADE_REQUIRED]:
            if budget > 0:
                self._set_state(node_state, UpgradeState.CORDON_REQUIRED)
                budget -= 1
            else:
                # persist the computed upgrade-required label so progress is
                # visible and survives operator restarts
                self._set_state(node_state, UpgradeState.UPGRADE_REQUIRED)

        for node_state in buckets[UpgradeState.CORDON_REQUIRED]:
            self._cordon(node_state.node, True)
            if policy.wait_for_completion.pod_selector:
                self._set_state(node_state, UpgradeState.WAIT_FOR_JOBS_REQUIRED)
            else:
                self._set_state(node_state, UpgradeState.POD_DELETION_REQUIRED)

        for node_state in buckets[UpgradeState.WAIT_FOR_JOBS_REQUIRED]:
            pods = self._filter_pods(
                pods_by_node.get(node_state.name, ()), policy.wait_for_completion.pod_selector
            )
            if not pods:
                self._set_state(node_state, UpgradeState.POD_DELETION_REQUIRED)
            elif self._state_expired(node_state, policy.wait_for_completion.timeout_seconds):
                # a hung job must not stall the whole rolling upgrade:
                # after the policy timeout the node is parked in
                # upgrade-failed (operator intervention required, like the
                # reference lib) and stops consuming the parallel budget
                log.error("upgrade: node %s wait-for-jobs timed out", node_state.name)
                self._set_state(node_state, UpgradeState.FAILED)

        for node_state in buckets[UpgradeState.POD_DELETION_REQUIRED]:
            targets = [
                p
                for p in pods_by_node.get(node_state.name, ())
                if not self._is_daemonset_pod(p) and self._consumes_tpu(p)
            ]
            self._evict_phase(
                node_state,
                targets,
                force=policy.pod_deletion.force,
                timeout_seconds=policy.pod_deletion.timeout_seconds,
                next_state=(
                    UpgradeState.DRAIN_REQUIRED
                    if policy.drain.enable
                    else UpgradeState.POD_RESTART_REQUIRED
                ),
            )

        for node_state in buckets[UpgradeState.DRAIN_REQUIRED]:
            targets = [
                p
                for p in self._filter_pods(
                    pods_by_node.get(node_state.name, ()), policy.drain.pod_selector
                )
                if not self._is_daemonset_pod(p)
            ]
            self._evict_phase(
                node_state,
                targets,
                force=policy.drain.force,
                timeout_seconds=policy.drain.timeout_seconds,
                next_state=UpgradeState.POD_RESTART_REQUIRED,
            )

        for node_state in buckets[UpgradeState.POD_RESTART_REQUIRED]:
            want = (
                str(node_state.daemonset["metadata"].get("generation", 1))
                if node_state.daemonset
                else None
            )
            outdated = [
                p
                for p in node_state.driver_pods
                if want is not None
                and (p["metadata"].get("labels") or {}).get(POD_TEMPLATE_GENERATION_LABEL)
                not in (None, want)
            ]
            for pod in outdated:
                md = pod["metadata"]
                try:
                    self.client.delete("v1", "Pod", md["name"], md.get("namespace"))
                except errors.NotFound:
                    pass
            if not outdated:
                # only advance once the stale pods are gone — moving to
                # VALIDATION in the deletion pass just burns a replan on a
                # node with no driver pod yet
                self._set_state(node_state, UpgradeState.VALIDATION_REQUIRED)

        for node_state in buckets[UpgradeState.VALIDATION_REQUIRED]:
            if self._node_validated(node_state, pods_by_node.get(node_state.name, ())):
                self._set_state(node_state, UpgradeState.UNCORDON_REQUIRED)

        for node_state in buckets[UpgradeState.UNCORDON_REQUIRED]:
            self._cordon(node_state.node, False)
            self._set_state(node_state, UpgradeState.DONE)

    @staticmethod
    def _state_expired(node_state: NodeUpgradeState, timeout_seconds: int) -> bool:
        if not timeout_seconds:
            return False
        since = (node_state.node["metadata"].get("annotations") or {}).get(
            consts.UPGRADE_STATE_SINCE_ANNOTATION
        )
        if not since:
            return False
        try:
            return time.time() - float(since) > timeout_seconds
        except ValueError:
            return False

    def _unavailable_budget(self, state: ClusterUpgradeState, policy: UpgradePolicySpec) -> int:
        """maxUnavailable bounds total unavailable nodes (absolute or
        percentage of driver nodes), like the vendored lib."""
        total = len(state.nodes) or 1
        raw = str(policy.max_unavailable or "25%").strip()
        try:
            if raw.endswith("%"):
                limit = max(1, int(total * int(raw[:-1].strip()) / 100))
            else:
                limit = max(1, int(raw))
        except ValueError:
            # malformed user value must degrade, not crash the upgrade loop
            log.warning("invalid maxUnavailable %r, falling back to 25%%", raw)
            limit = max(1, total // 4)
        unavailable = sum(
            1 for n in state.nodes.values() if n.node.get("spec", {}).get("unschedulable")
        )
        return max(0, limit - unavailable)

    # -- node/pod operations -------------------------------------------------

    def _set_state(self, node_state: NodeUpgradeState, new_state: str) -> None:
        node = self.client.get_or_none("v1", "Node", node_state.name)
        if node is None:
            return
        labels = node["metadata"].setdefault("labels", {})
        if labels.get(consts.UPGRADE_STATE_LABEL) == new_state:
            node_state.state = new_state
            return
        labels[consts.UPGRADE_STATE_LABEL] = new_state
        # timestamp the transition so per-state timeouts survive operator
        # restarts (all FSM state lives in the cluster)
        node["metadata"].setdefault("annotations", {})[
            consts.UPGRADE_STATE_SINCE_ANNOTATION
        ] = str(int(time.time()))
        try:
            self.client.update(node)
            node_state.state = new_state
            node_state.node = node
            log.info("upgrade: node %s -> %s", node_state.name, new_state)
            event_type = "Warning" if new_state == UpgradeState.FAILED else "Normal"
            self.recorder.event(node, event_type, f"LibtpuUpgrade",
                                f"node {node_state.name}: {new_state}")
        except errors.Conflict:
            pass  # re-planned next pass

    def _cordon(self, node: ObjectDict, cordon: bool) -> None:
        live = self.client.get_or_none("v1", "Node", node["metadata"]["name"])
        if live is None:
            return
        if bool(live.get("spec", {}).get("unschedulable")) == cordon:
            return
        # one-field merge patch: no rv, so concurrent label writers (the
        # health agent, kubelet heartbeats) can never Conflict a cordon
        try:
            self.client.patch(
                "v1", "Node", node["metadata"]["name"],
                {"spec": {"unschedulable": True if cordon else None}},
            )
        except errors.NotFound:
            pass  # node deleted mid-walk; next pass re-plans

    def _evict_phase(
        self,
        node_state: NodeUpgradeState,
        targets: List[ObjectDict],
        force: bool,
        timeout_seconds: int,
        next_state: str,
    ) -> None:
        """Shared pod-deletion/drain step: evict the targets, advance when
        none remain blocked, or park until the phase's own timeout sends
        the node to upgrade-failed (reference: drain manager + DrainSpec —
        a PDB-blocked eviction feeds the same timeout->failed path as hung
        jobs, visible via the state label meanwhile)."""
        blocked = self._evict_pods(targets, force=force)
        if not blocked:
            self._set_state(node_state, next_state)
        elif self._state_expired(node_state, timeout_seconds):
            log.error(
                "upgrade: node %s %s blocked past timeout", node_state.name, node_state.state
            )
            self._set_state(node_state, UpgradeState.FAILED)

    @staticmethod
    def _filter_pods(pods, selector) -> List[ObjectDict]:
        if not selector:
            return list(pods)
        return [p for p in pods if matches_selector(p["metadata"].get("labels"), selector)]

    def _evict_pods(self, pods: List[ObjectDict], force: bool = False) -> List[ObjectDict]:
        """Evict via the pods/eviction subresource so PodDisruptionBudgets
        are honored (reference: the vendored drain manager); returns the
        pods a PDB blocked. ``force`` falls back to plain DELETE for
        blocked pods (DrainSpec.force, kubectl drain --disable-eviction
        semantics)."""
        blocked: List[ObjectDict] = []
        for pod in pods:
            md = pod["metadata"]
            try:
                self.client.evict(md["name"], md.get("namespace"))
            except errors.NotFound:
                pass
            except errors.TooManyRequests:
                if force:
                    try:
                        self.client.delete("v1", "Pod", md["name"], md.get("namespace"))
                    except errors.NotFound:
                        pass
                else:
                    log.info(
                        "upgrade: eviction of %s/%s blocked by disruption budget",
                        md.get("namespace"), md["name"],
                    )
                    blocked.append(pod)
        return blocked

    @staticmethod
    def _is_daemonset_pod(pod: ObjectDict) -> bool:
        return any(
            ref.get("kind") == "DaemonSet" for ref in pod["metadata"].get("ownerReferences", [])
        )

    @staticmethod
    def _consumes_tpu(pod: ObjectDict) -> bool:
        for ctr in pod.get("spec", {}).get("containers", []):
            limits = ctr.get("resources", {}).get("limits", {}) or {}
            requests = ctr.get("resources", {}).get("requests", {}) or {}
            if consts.TPU_RESOURCE_NAME in limits or consts.TPU_RESOURCE_NAME in requests:
                return True
        return False

    def _node_validated(self, node_state: NodeUpgradeState, node_pods) -> bool:
        """Fresh driver pod running with the current template generation,
        and — when the validator operand is deployed — its pod Running on
        the node (reference waits on app=nvidia-operator-validator pods,
        cmd/gpu-operator/main.go:151). ``node_pods`` is this node's slice
        of the pass-wide pod snapshot."""
        in_ns = [p for p in node_pods if p["metadata"].get("namespace") == self.namespace]
        pods = [
            p
            for p in in_ns
            if (p["metadata"].get("labels") or {}).get(DRIVER_POD_COMPONENT_LABEL)
            == DRIVER_POD_COMPONENT
        ]
        if not pods:
            return False
        ds = node_state.daemonset
        want = str(ds["metadata"].get("generation", 1)) if ds else None
        for pod in pods:
            if pod.get("status", {}).get("phase") != "Running":
                return False
            have = (pod["metadata"].get("labels") or {}).get(POD_TEMPLATE_GENERATION_LABEL)
            if want is not None and have is not None and have != want:
                return False
        validators = [
            p for p in in_ns if (p["metadata"].get("labels") or {}).get("app") == VALIDATOR_POD_APP
        ]
        if validators and any(p.get("status", {}).get("phase") != "Running" for p in validators):
            return False
        return True

    # -- label cleanup -------------------------------------------------------

    def remove_upgrade_labels(self) -> None:
        """reference: removeNodeUpgradeStateLabels upgrade_controller.go:201-227."""
        for node in self.client.list("v1", "Node"):
            labels = node["metadata"].get("labels") or {}
            if consts.UPGRADE_STATE_LABEL in labels:
                del labels[consts.UPGRADE_STATE_LABEL]
                try:
                    self.client.update(node)
                except errors.Conflict:
                    pass
