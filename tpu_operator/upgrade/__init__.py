from tpu_operator.upgrade.fsm import (  # noqa: F401
    ClusterUpgradeStateManager,
    UpgradeState,
)
