"""tpuop-lint: static analysis CLI.

    tpuop-lint                         # text report, exit 1 on errors
    tpuop-lint --format json           # machine-readable (CI, must-gather;
                                       # includes per-analyzer wall time)
    tpuop-lint --only rbac,drift       # subset of analyzers
    tpuop-lint --only TPUOP-C002       # single rule (runs only its family)
    tpuop-lint --skip concurrency      # everything except one family
    tpuop-lint --skip TPUOP-M007      # drop one rule's findings
    tpuop-lint --rules                 # print the rule catalog
    tpuop-lint --update-baseline       # rewrite the baseline from current
                                       # error findings (review the diff!)

``--only``/``--skip`` both accept analyzer names and rule ids, mixed;
rule ids select/deselect their findings and (for --only) imply their
analyzer family so nothing else runs.

Exit status: 0 clean (warnings/info allowed), 1 when any unsuppressed
error-severity finding remains, 2 on usage errors.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List

from tpu_operator.lint import runner
from tpu_operator.lint.findings import (
    RULES,
    Finding,
    failing,
    render_json,
    render_text,
    sort_findings,
)


def _write_baseline(path: str, findings: List[Finding]) -> int:
    lines = [
        "# tpuop-lint baseline: intentional exceptions, one per line:",
        "#   RULE-ID  location-prefix  # one-line justification",
        "# Regenerate with `tpuop-lint --update-baseline`, then EDIT the",
        "# justifications — an unexplained suppression fails review.",
    ]
    for f in sort_findings(findings):
        if f.severity != "error":
            continue
        lines.append(f"{f.rule} {f.location}  # TODO: justify")
    with open(path, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    print(f"wrote {path} ({sum(1 for l in lines if not l.startswith('#'))} entries)")
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "tpuop-lint", description="static analysis over shipped operator artifacts"
    )
    p.add_argument("--format", choices=["text", "json"], default="text")
    p.add_argument(
        "--baseline",
        default=None,
        help=f"suppression file (default: {runner.DEFAULT_BASELINE})",
    )
    p.add_argument(
        "--only",
        default=None,
        help="comma-separated analyzers and/or rule ids to run "
             f"(analyzers: {','.join(runner.ANALYZERS)})",
    )
    p.add_argument(
        "--skip",
        default=None,
        help="comma-separated analyzers and/or rule ids to exclude",
    )
    p.add_argument(
        "--show-suppressed",
        action="store_true",
        help="include baseline-suppressed findings in text output",
    )
    p.add_argument("--rules", action="store_true", help="print the rule catalog and exit")
    p.add_argument(
        "--update-baseline",
        action="store_true",
        help="rewrite the baseline from current error findings",
    )
    return p


def _parse_selector(raw: str, flag: str):
    """Split a --only/--skip value into (analyzer set, rule-id set);
    None on an unknown token (after printing why)."""
    analyzers, rules = set(), set()
    for token in (t.strip() for t in raw.split(",")):
        if not token:
            continue
        if token in runner.ANALYZERS:
            analyzers.add(token)
        elif token in RULES:
            rules.add(token)
        else:
            print(
                f"{flag}: unknown analyzer or rule id '{token}' "
                f"(analyzers: {', '.join(runner.ANALYZERS)}; rules: see --rules)",
                file=sys.stderr,
            )
            return None
    return analyzers, rules


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.rules:
        for rule, (severity, desc) in sorted(RULES.items()):
            print(f"{rule}  {severity:8s} {desc}")
        return 0
    only = None
    only_rules: set = set()
    skip_rules: set = set()
    if args.only:
        parsed = _parse_selector(args.only, "--only")
        if parsed is None:
            return 2
        analyzers, only_rules = parsed
        # a rule id implies its analyzer family: --only TPUOP-C002 runs
        # just the concurrency analyzer, then keeps only that rule's rows
        analyzers |= {runner.family_of_rule(r) for r in only_rules} - {None}
        if not analyzers:
            # e.g. --only TPUOP-B001: a valid rule id that no analyzer
            # produces — running nothing and printing "clean" would be a
            # lie a CI job happily believes
            print(
                "--only: selection matches no analyzer "
                f"(rule(s) {', '.join(sorted(only_rules))} have no analyzer family)",
                file=sys.stderr,
            )
            return 2
        only = sorted(analyzers)
    if args.skip:
        parsed = _parse_selector(args.skip, "--skip")
        if parsed is None:
            return 2
        skipped_analyzers, skip_rules = parsed
        only = [a for a in (only or list(runner.ANALYZERS)) if a not in skipped_analyzers]
    nothing_selected = only is not None and not only

    def apply_rule_filters(found):
        if only_rules:
            found = [f for f in found if f.rule in only_rules or f.rule == "TPUOP-B001"]
        if skip_rules:
            found = [f for f in found if f.rule not in skip_rules]
        return found

    if args.update_baseline:
        if nothing_selected:
            print(
                "--update-baseline with every analyzer excluded would "
                "erase the baseline; refusing",
                file=sys.stderr,
            )
            return 2
        # run WITHOUT the existing baseline so every current error lands;
        # rule filters apply so `--only TPUOP-C003 --update-baseline`
        # writes only that rule's entries
        findings = apply_rule_filters(
            runner.run_lint(baseline_path=os.devnull, only=only)
        )
        return _write_baseline(args.baseline or runner.DEFAULT_BASELINE, findings)
    timings: dict = {}
    if nothing_selected:
        # --skip excluded every analyzer: run nothing (run_lint would
        # read an empty list as "default to all" — the exact opposite)
        findings = []
    else:
        findings = runner.run_lint(baseline_path=args.baseline, only=only, timings=timings)
    findings = apply_rule_filters(findings)
    if args.format == "json":
        sys.stdout.write(render_json(findings, timings=timings))
    else:
        sys.stdout.write(render_text(findings, show_suppressed=args.show_suppressed))
    return 1 if failing(findings) else 0


if __name__ == "__main__":
    sys.exit(main())
