"""tpuop-lint: static analysis CLI.

    tpuop-lint                         # text report, exit 1 on errors
    tpuop-lint --format json           # machine-readable (CI, must-gather)
    tpuop-lint --only rbac,drift       # subset of analyzers
    tpuop-lint --rules                 # print the rule catalog
    tpuop-lint --update-baseline       # rewrite the baseline from current
                                       # error findings (review the diff!)

Exit status: 0 clean (warnings/info allowed), 1 when any unsuppressed
error-severity finding remains, 2 on usage errors.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List

from tpu_operator.lint import runner
from tpu_operator.lint.findings import (
    RULES,
    Finding,
    failing,
    render_json,
    render_text,
    sort_findings,
)


def _write_baseline(path: str, findings: List[Finding]) -> int:
    lines = [
        "# tpuop-lint baseline: intentional exceptions, one per line:",
        "#   RULE-ID  location-prefix  # one-line justification",
        "# Regenerate with `tpuop-lint --update-baseline`, then EDIT the",
        "# justifications — an unexplained suppression fails review.",
    ]
    for f in sort_findings(findings):
        if f.severity != "error":
            continue
        lines.append(f"{f.rule} {f.location}  # TODO: justify")
    with open(path, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    print(f"wrote {path} ({sum(1 for l in lines if not l.startswith('#'))} entries)")
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "tpuop-lint", description="static analysis over shipped operator artifacts"
    )
    p.add_argument("--format", choices=["text", "json"], default="text")
    p.add_argument(
        "--baseline",
        default=None,
        help=f"suppression file (default: {runner.DEFAULT_BASELINE})",
    )
    p.add_argument(
        "--only",
        default=None,
        help=f"comma-separated analyzers to run (default: all of {','.join(runner.ANALYZERS)})",
    )
    p.add_argument(
        "--show-suppressed",
        action="store_true",
        help="include baseline-suppressed findings in text output",
    )
    p.add_argument("--rules", action="store_true", help="print the rule catalog and exit")
    p.add_argument(
        "--update-baseline",
        action="store_true",
        help="rewrite the baseline from current error findings",
    )
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.rules:
        for rule, (severity, desc) in sorted(RULES.items()):
            print(f"{rule}  {severity:8s} {desc}")
        return 0
    only = None
    if args.only:
        only = [a.strip() for a in args.only.split(",") if a.strip()]
        unknown = [a for a in only if a not in runner.ANALYZERS]
        if unknown:
            print(f"unknown analyzer(s): {', '.join(unknown)}", file=sys.stderr)
            return 2
    if args.update_baseline:
        # run WITHOUT the existing baseline so every current error lands
        findings = runner.run_lint(baseline_path=os.devnull, only=only)
        return _write_baseline(args.baseline or runner.DEFAULT_BASELINE, findings)
    findings = runner.run_lint(baseline_path=args.baseline, only=only)
    if args.format == "json":
        sys.stdout.write(render_json(findings))
    else:
        sys.stdout.write(render_text(findings, show_suppressed=args.show_suppressed))
    return 1 if failing(findings) else 0


if __name__ == "__main__":
    sys.exit(main())
