"""tpu-operator controller-manager entrypoint.

Reference: ``cmd/gpu-operator/main.go:72-196`` — flags, zap-style logging,
leader election, health probe on :8081, metrics on :8080, the four
controllers, run until signalled. A ``--fake-cluster`` mode runs against
the in-memory apiserver + sim (the CPU-only kind-cluster configuration)
for local development and e2e scripts.
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys
import threading

from tpu_operator import consts
from tpu_operator.controllers.autotune_controller import (
    AutotuneReconciler,
    setup_with_manager as setup_autotune,
)
from tpu_operator.controllers.clusterpolicy_controller import (
    ClusterPolicyReconciler,
    setup_with_manager as setup_clusterpolicy,
)
from tpu_operator.controllers.compilecache_controller import (
    CompileCacheReconciler,
    setup_with_manager as setup_compilecache,
)
from tpu_operator.controllers.defrag_controller import (
    DefragReconciler,
    setup_with_manager as setup_defrag,
)
from tpu_operator.controllers.health_controller import (
    HealthReconciler,
    setup_with_manager as setup_health,
)
from tpu_operator.controllers.job_controller import (
    JobReconciler,
    setup_with_manager as setup_job,
)
from tpu_operator.controllers.placement_controller import (
    PlacementReconciler,
    setup_with_manager as setup_placement,
)
from tpu_operator.controllers.serving_controller import (
    ServingReconciler,
    setup_with_manager as setup_serving,
)
from tpu_operator.controllers.tenancy_controller import (
    TenancyReconciler,
    setup_with_manager as setup_tenancy,
)
from tpu_operator.controllers.tpuslice_controller import (
    TPUSliceReconciler,
    setup_with_manager as setup_tpuslice,
)
from tpu_operator.controllers.upgrade_controller import (
    UpgradeReconciler,
    setup_with_manager as setup_upgrade,
)
from tpu_operator.kube.manager import Manager

log = logging.getLogger("tpu-operator")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("tpu-operator", description="TPU operator controller-manager")
    p.add_argument("--metrics-bind-address", default=":8080")
    p.add_argument("--health-probe-bind-address", default=":8081")
    p.add_argument("--leader-elect", action="store_true", default=False)
    p.add_argument("--zap-log-level", default="info", help="debug|info|warning|error")
    p.add_argument(
        "--webhook-cert-dir",
        default="",
        help="serve the validating admission webhook on :9443 using tls.crt/tls.key from this dir",
    )
    p.add_argument("--webhook-bind-address", default=":9443")
    p.add_argument(
        "--webhook-manage-certs",
        action="store_true",
        help="generate + rotate the webhook serving cert in-process "
        "(publishes the TLS Secret and patches the VWC caBundle)",
    )
    p.add_argument(
        "--fake-cluster",
        type=int,
        metavar="N",
        default=None,
        help="run against an in-memory apiserver seeded with N simulated TPU nodes",
    )
    return p


def _addr(spec: str, default_host: str = "0.0.0.0"):
    host, _, port = spec.rpartition(":")
    return (host or default_host, int(port))


def make_client(args):
    if args.fake_cluster is not None:
        from tpu_operator.kube.fake import FakeClient
        from tpu_operator.kube.sim import ClusterSim, make_tpu_node

        client = FakeClient()
        for i in range(args.fake_cluster):
            client.create(make_tpu_node(f"tpu-{i}", "tpu-v5-lite-podslice", "4x4"))  # tpuop-lint: ignore
        ClusterSim(client, ready_delay=0.5).start()
        return client
    from tpu_operator.kube.http_client import HttpClient

    return HttpClient.in_cluster()


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=getattr(logging, args.zap_log_level.upper(), logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )
    namespace = os.environ.get(consts.OPERATOR_NAMESPACE_ENV)
    if not namespace:
        # reference: OPERATOR_NAMESPACE is mandatory (state_manager.go:762-770)
        log.warning("%s not set; defaulting to %s", consts.OPERATOR_NAMESPACE_ENV, consts.DEFAULT_OPERATOR_NAMESPACE)
        namespace = consts.DEFAULT_OPERATOR_NAMESPACE

    client = make_client(args)
    mgr = Manager(
        client,
        namespace=namespace,
        leader_election=args.leader_elect,
        health_addr=_addr(args.health_probe_bind_address),
        metrics_addr=_addr(args.metrics_bind_address),
    )
    setup_clusterpolicy(mgr, ClusterPolicyReconciler(client, namespace))
    setup_tpuslice(mgr, TPUSliceReconciler(client, namespace))
    setup_upgrade(mgr, UpgradeReconciler(client, namespace))
    setup_health(mgr, HealthReconciler(client, namespace))
    setup_placement(mgr, PlacementReconciler(client, namespace))
    setup_autotune(mgr, AutotuneReconciler(client, namespace))
    setup_job(mgr, JobReconciler(client, namespace))
    setup_serving(mgr, ServingReconciler(client, namespace))
    setup_defrag(mgr, DefragReconciler(client, namespace))
    setup_compilecache(mgr, CompileCacheReconciler(client, namespace))
    setup_tenancy(mgr, TenancyReconciler(client, namespace))

    stop = threading.Event()
    webhook_holder: dict = {}
    cert_manager = None
    if args.webhook_cert_dir:
        from tpu_operator.webhook import WebhookServer

        cert = os.path.join(args.webhook_cert_dir, "tls.crt")
        key = os.path.join(args.webhook_cert_dir, "tls.key")

        def start_webhook() -> None:
            webhook_holder["server"] = WebhookServer(
                client, addr=_addr(args.webhook_bind_address), cert_file=cert, key_file=key
            ).start()
            if cert_manager is not None:
                cert_manager.attach(webhook_holder["server"])
            log.info("admission webhook serving on %s", args.webhook_bind_address)

        if args.webhook_manage_certs:
            from tpu_operator.certs import WebhookCertManager

            cert_manager = WebhookCertManager(client, namespace, args.webhook_cert_dir)
            try:
                cert_manager.ensure()  # bootstrap before the first TLS bind
            except Exception as e:  # noqa: BLE001 — the loop retries; don't crash startup
                log.warning("webhook cert bootstrap failed (will retry): %s", e)
            cert_manager.start()
            if os.path.exists(cert) and os.path.exists(key):
                start_webhook()
            else:
                # bootstrap could not publish yet (e.g. apiserver down):
                # serve as soon as the rotation loop lands the cert files
                # instead of crashing on a missing chain
                def start_when_ready() -> None:
                    while not stop.is_set():
                        if os.path.exists(cert) and os.path.exists(key):
                            start_webhook()
                            return
                        stop.wait(2.0)

                threading.Thread(target=start_when_ready, daemon=True).start()
        else:
            start_webhook()

    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    mgr.start()
    log.info("tpu-operator running (namespace=%s)", namespace)
    try:
        while not stop.is_set() and not mgr.stopped():
            stop.wait(1.0)
    finally:
        if cert_manager is not None:
            cert_manager.stop()
        if webhook_holder.get("server") is not None:
            webhook_holder["server"].stop()
        mgr.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
