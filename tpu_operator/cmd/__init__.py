"""Entrypoints (reference: cmd/ — controller-manager, gpuop-cfg CLI)."""
