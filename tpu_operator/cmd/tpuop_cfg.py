"""tpuop-cfg: config lint + generation CLI.

Reference: ``cmd/gpuop-cfg`` (main.go:35-74) — validate ClusterPolicy YAML
(image fields, env consistency) and the CSV analog; extended here with CRD
and chart generation so everything the operator serves can be produced and
checked offline.

    tpuop-cfg validate clusterpolicy --input cr.yaml
    tpuop-cfg validate tpuslice --input ts.yaml
    tpuop-cfg generate crds
    tpuop-cfg render --values deploy/values.yaml
"""

from __future__ import annotations

import argparse
import sys
from typing import List

import yaml

from tpu_operator.api.clusterpolicy import ClusterPolicy
from tpu_operator.api.crds import all_crds
from tpu_operator.api.tpuslice import TPUSlice

IMAGE_COMPONENTS = (
    "libtpu",
    "device_plugin",
    "tpu_feature_discovery",
    "slice_manager",
    "metrics_exporter",
    "node_status_exporter",
    "health_monitor",
    "validator",
)


def validate_clusterpolicy(doc: dict) -> List[str]:
    """Image/env lint (reference: validate/clusterpolicy/images.go) — every
    enabled component must resolve to a pullable image path, env entries
    must be {name, value} shaped, enablement flags must be booleans."""
    problems: List[str] = []
    if doc.get("kind") != "ClusterPolicy":
        problems.append(f"kind must be ClusterPolicy, got {doc.get('kind')!r}")
        return problems
    cp = ClusterPolicy.from_unstructured(doc)
    from tpu_operator import images as images_mod

    for name in IMAGE_COMPONENTS:
        spec = getattr(cp.spec, name)
        if hasattr(spec, "is_enabled") and not spec.is_enabled():
            continue
        key = {"tpu_feature_discovery": "tfd"}.get(name, name)
        path = images_mod.resolve(key, spec)
        if not path:
            problems.append(f"{name}: no image resolvable (CR fields, env, defaults all empty)")
        if spec.version and spec.version.startswith("sha256:") and not spec.image:
            problems.append(f"{name}: digest version without image")
        for e in spec.env:
            if not isinstance(e, dict) or "name" not in e:
                problems.append(f"{name}: malformed env entry {e!r}")
    raw_spec = doc.get("spec", {}) or {}
    for comp, sub in raw_spec.items():
        if isinstance(sub, dict) and "enabled" in sub and not isinstance(sub["enabled"], bool):
            problems.append(f"{comp}.enabled must be a boolean, got {sub['enabled']!r}")
    return problems


def validate_tpuslice(doc: dict) -> List[str]:
    problems: List[str] = []
    if doc.get("kind") != "TPUSlice":
        problems.append(f"kind must be TPUSlice, got {doc.get('kind')!r}")
        return problems
    ts = TPUSlice.from_unstructured(doc)
    for key, value in ts.spec.get_node_selector().items():
        if not isinstance(value, str):
            problems.append(f"nodeSelector[{key!r}] must be a string")
    return problems


def cmd_validate(args) -> int:
    with open(args.input) as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    problems: List[str] = []
    for doc in docs:
        if args.what == "clusterpolicy":
            problems += validate_clusterpolicy(doc)
        else:
            problems += validate_tpuslice(doc)
    for p in problems:
        print(f"INVALID: {p}", file=sys.stderr)
    if not problems:
        print(f"{args.input}: OK ({len(docs)} document(s))")
    return 1 if problems else 0


def cmd_generate_crds(args) -> int:
    yaml.safe_dump_all(all_crds(), sys.stdout, default_flow_style=False, sort_keys=False)
    return 0


def cmd_render(args) -> int:
    from tpu_operator.chart import render_chart

    with open(args.values) as f:
        values = yaml.safe_load(f) or {}
    objs = render_chart(values)
    yaml.safe_dump_all(objs, sys.stdout, default_flow_style=False, sort_keys=False)
    return 0


def cmd_must_gather(args) -> int:
    """kubectl-free support bundle (reference: hack/must-gather.sh shells
    out to kubectl; this rides the in-repo client — kubeconfig or
    in-cluster — and is tested against the served fake apiserver)."""
    import os

    from tpu_operator import consts, mustgather
    from tpu_operator.kube.http_client import HttpClient

    if os.environ.get("KUBERNETES_SERVICE_HOST") and not args.kubeconfig:
        client = HttpClient.in_cluster()
    else:
        client = HttpClient.from_kubeconfig(args.kubeconfig or None)
    ns = args.namespace or os.environ.get(
        consts.OPERATOR_NAMESPACE_ENV, consts.DEFAULT_OPERATOR_NAMESPACE
    )
    written = mustgather.collect(client, ns, args.output)
    print(f"collected {len(written)} artifacts into {args.output}")
    return 0


def cmd_plan(args) -> int:
    """Capacity planning report: pool posture (utilization /
    fragmentation), the analytical model's per-generation predictions,
    admission answers for queued shapes, and an optional what-if
    (`--shape 8x8x8 --within 600`: "can this land, and what would
    defrag have to move?"). Same client resolution as must-gather."""
    import os

    from tpu_operator import consts
    from tpu_operator.api.tpuslice import TPU_SLICE_API_VERSION
    from tpu_operator.controllers.fabric_telemetry import degraded_link_pairs
    from tpu_operator.kube import errors as kube_errors
    from tpu_operator.kube.http_client import HttpClient
    from tpu_operator.planning.whatif import plan_report

    if os.environ.get("KUBERNETES_SERVICE_HOST") and not args.kubeconfig:
        client = HttpClient.in_cluster()
    else:
        client = HttpClient.from_kubeconfig(args.kubeconfig or None)
    ns = args.namespace or os.environ.get(
        consts.OPERATOR_NAMESPACE_ENV, consts.DEFAULT_OPERATOR_NAMESPACE
    )
    slices = client.list(TPU_SLICE_API_VERSION, "TPUSlice")
    nodes = client.list("v1", "Node")
    quotas = None
    if args.tenant:
        from tpu_operator.api.tpuquota import TPU_QUOTA_API_VERSION

        try:
            quotas = client.list(TPU_QUOTA_API_VERSION, "TPUQuota")
        except kube_errors.ApiError:
            quotas = None  # headroom annotation degrades, verdict stands
    try:
        links = degraded_link_pairs(client, ns)
    except kube_errors.ApiError:
        links = []
    entries = None
    try:
        cm = client.get_or_none(
            "v1", "ConfigMap", consts.AUTOTUNE_RESULTS_CONFIGMAP, ns
        )
        if cm is not None:
            from tpu_operator.workloads.autotune import cached_entries

            entries = cached_entries(cm.get("data"))
    except kube_errors.ApiError:
        entries = None
    compile_entries = None
    try:
        cm = client.get_or_none(
            "v1", "ConfigMap", consts.COMPILE_CACHE_CONFIGMAP, ns
        )
        if cm is not None:
            from tpu_operator.workloads import compilecache

            compile_entries = compilecache.cached_entries(cm.get("data"))
    except kube_errors.ApiError:
        compile_entries = None
    from tpu_operator.workloads.autotune import runtime_fingerprint

    # price the what-if against the model serving workers actually run
    # (the same default-config hash their warm_start publishes under)
    try:
        from tpu_operator.workloads.compilecache import model_descriptor_hash

        model_hash = model_descriptor_hash()
    except Exception:  # noqa: BLE001 — pricing is optional; no jax, no hash
        model_hash = ""
    sys.stdout.write(
        plan_report(
            slices, nodes, shape=args.shape, pool=args.pool,
            horizon_seconds=args.within, degraded_links=links,
            autotune_entries=entries,
            compile_entries=compile_entries,
            libtpu_version=runtime_fingerprint(),
            model_hash=model_hash,
            tenant=args.tenant, quotas=quotas,
        )
    )
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser("tpuop-cfg")
    sub = p.add_subparsers(dest="cmd", required=True)
    v = sub.add_parser("validate", help="lint a CR YAML file")
    v.add_argument("what", choices=["clusterpolicy", "tpuslice"])
    v.add_argument("--input", required=True)
    v.set_defaults(fn=cmd_validate)
    g = sub.add_parser("generate", help="generate artifacts")
    gsub = g.add_subparsers(dest="what", required=True)
    gc = gsub.add_parser("crds")
    gc.set_defaults(fn=cmd_generate_crds)
    r = sub.add_parser("render", help="render the deployment chart from values")
    r.add_argument("--values", required=True)
    r.set_defaults(fn=cmd_render)
    mg = sub.add_parser("must-gather", help="collect a kubectl-free support bundle")
    mg.add_argument("--output", default="/tmp/tpu-operator-must-gather")
    mg.add_argument("--namespace", default="")
    mg.add_argument("--kubeconfig", default="")
    mg.set_defaults(fn=cmd_must_gather)
    pl = sub.add_parser(
        "plan", help="capacity report + admission what-ifs (the planning engine)"
    )
    pl.add_argument("--shape", default="", help="what-if gang shape, e.g. 8x8x8")
    pl.add_argument("--pool", default="", help="pin the what-if to one pool")
    pl.add_argument(
        "--tenant", default="",
        help="ask the what-if on behalf of this tenant: folds TPUQuota "
        "guaranteed headroom into the verdict (inside quota vs borrow)",
    )
    pl.add_argument(
        "--within", type=float, default=600.0,
        help="admission horizon in seconds (defrag migrations are priced "
        "at the cooldown)",
    )
    pl.add_argument("--namespace", default="")
    pl.add_argument("--kubeconfig", default="")
    pl.set_defaults(fn=cmd_plan)
    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
