"""Operand agents: the payloads of the operand container images.

The reference operator only *templates* its operands (device plugin, GFD,
DCGM exporter live in sibling repos — SURVEY.md §2.3). This framework
ships the TPU equivalents in-repo so the whole stack is one codebase:

    tfd_agent              tpu-feature-discovery container payload
    slice_manager_agent    tpu-slice-manager container payload
    metrics_exporter_agent tpu-metrics-exporter container payload
    device_plugin_agent    tpu-device-plugin container payload (kubelet
                           gRPC device plugin, v1beta1)
    (validator/            the tpu-operator-validator payload)
"""
