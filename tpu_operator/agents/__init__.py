"""Operand agents: the payloads of the operand container images.

The reference operator only *templates* its operands (device plugin, GFD,
DCGM exporter live in sibling repos — SURVEY.md §2.3). This framework
ships the TPU equivalents in-repo so the whole stack is one codebase:

    tfd_agent              tpu-feature-discovery container payload
    slice_manager_agent    tpu-slice-manager container payload
    metrics_exporter_agent tpu-metrics-exporter container payload
    (validator/            the tpu-operator-validator payload)

The Cloud TPU device plugin (kubelet gRPC registration) is the remaining
external operand; its DaemonSet templates the upstream image.
"""
