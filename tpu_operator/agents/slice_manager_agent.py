"""tpu-slice-manager agent (the mig-manager analog, re-imagined for TPUs).

MIG partitions one GPU into sub-devices; a TPU slice composes many hosts
into one accelerator. So where mig-manager applies mig-parted profiles per
node, the slice manager materializes the *gang plumbing* each multi-host
slice needs (reference concept: state-mig-manager + the per-node
``nvidia.com/mig.config`` label loop):

  - a headless Service per slice (stable DNS for worker discovery)
  - a ConfigMap carrying the gang env contract: TPU_WORKER_HOSTNAMES,
    chips/topology, and — when multiSlice is on — the DCN coordinator
    address (MEGASCALE_COORDINATOR_ADDRESS, BASELINE config 5)
  - per-node worker identity labels (tpu.google.com/worker-id) mirroring
    the reference's per-node config label reconciliation
  - the gang itself: one COMPONENT=slice validator worker pod per host
    (manifests/slice-gang/0100_worker_pod.yaml), hostname ``<slice>-<i>``
    + subdomain ``<slice>`` so every TPU_WORKER_HOSTNAMES entry resolves
    through the headless Service (reference analog: Plugin.runWorkload
    validator/main.go:941-1028, gang-sized)
  - for multi-slice, the DCN coordinator Service the gang env advertises,
    selecting worker 0 of the first active slice

Workload pods join a slice gang by mounting the ConfigMap and using the
headless Service DNS — which is exactly what the validator's slice
component consumes (workloads/distributed.py).
"""

from __future__ import annotations

import logging
import os
import time
from typing import List, Optional

from tpu_operator import consts
from tpu_operator.kube import errors
from tpu_operator.kube.client import Client
from tpu_operator.kube.objects import new_object
from tpu_operator.nodepool import NodePool, get_node_pools
from tpu_operator.render import Renderer
from tpu_operator.utils import object_hash

log = logging.getLogger(__name__)

WORKER_ID_LABEL = "tpu.google.com/worker-id"
SLICE_LABEL = "tpu.google.com/slice"
SLICE_SERVICE_PREFIX = "tpu-slice"
GANG_HASH_ANNOTATION = "tpu.google.com/gang-hash"
MANAGED_BY = {"app.kubernetes.io/managed-by": "tpu-slice-manager"}

GANG_MANIFEST_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "manifests", "slice-gang"
)


class SliceManagerAgent:
    def __init__(
        self,
        client: Client,
        namespace: str,
        multi_slice: bool = False,
        coordinator_port: int = 8476,
        interval: float = 30.0,
        config_map: str = "",
        validator_image: str = "tpu-operator-validator",
        image_pull_policy: str = "IfNotPresent",
        validation_dir: str = consts.VALIDATION_DIR,
        min_psum_gbps_per_chip: str = "",
    ):
        self.client = client
        self.namespace = namespace
        self.multi_slice = multi_slice
        self.coordinator_port = coordinator_port
        self.interval = interval
        # named slice profiles (the mig-parted-config analog rendered by
        # state-slice-manager/0400_configmap.yaml)
        self.config_map = config_map
        self.validator_image = validator_image
        self.image_pull_policy = image_pull_policy
        self.validation_dir = validation_dir
        # forwarded into every gang worker so COMPONENT=slice enforces the
        # ICI bandwidth floor (spec.validator.minPsumGbpsPerChip)
        self.min_psum_gbps_per_chip = min_psum_gbps_per_chip
        self._renderer = Renderer([GANG_MANIFEST_DIR])

    def _load_profile(self) -> dict:
        """The selected slice profile: {accelerator-type -> gang mode}.
        Empty dict -> everything defaults to per-slice gangs."""
        if not self.config_map:
            return {}
        cm = self.client.get_or_none("v1", "ConfigMap", self.config_map, self.namespace)
        if cm is None:
            return {}
        import yaml

        try:
            config = yaml.safe_load((cm.get("data", {}) or {}).get("config.yaml", "")) or {}
        except yaml.YAMLError:
            log.warning("slice config %s has invalid YAML", self.config_map)
            return {}
        # a user-malformed (but parseable) config must degrade to defaults,
        # never crash-loop the DaemonSet
        if not isinstance(config, dict):
            log.warning("slice config %s: config.yaml is not a mapping", self.config_map)
            return {}
        profiles = config.get("slice-configs", {})
        if not isinstance(profiles, dict):
            log.warning("slice config %s: slice-configs is not a mapping", self.config_map)
            return {}
        selected = (cm.get("data", {}) or {}).get("default", "") or "default"
        entries = profiles.get(selected, [])
        if not isinstance(entries, list):
            log.warning("slice config %s: profile %r is not a list", self.config_map, selected)
            return {}
        return {
            e.get("accelerator-type", "all"): e.get("gang", "per-slice")
            for e in entries
            if isinstance(e, dict)
        }

    # -- reconcile ------------------------------------------------------------

    def reconcile_once(self) -> List[str]:
        """Converge gang plumbing for every multi-host pool; returns the
        slice names reconciled. Idempotent — every host of the slice runs
        this and the create-or-update converges."""
        # server-side selector: only TPU nodes come over the wire (and a
        # cached read serves it from the informer's label index)
        nodes = self.client.list(
            "v1", "Node", label_selector={consts.TPU_PRESENT_LABEL: "true"}
        )
        node_labels = {
            n["metadata"]["name"]: n["metadata"].get("labels") or {} for n in nodes
        }
        # hosts the health subsystem took out of service (quarantined or
        # mid-repair, or flagged degraded) leave their gang NOW: keeping
        # a sick member in the hostlist hangs every peer's collectives,
        # and its stale worker-id label would survive quarantine forever
        healthy = [
            n for n in nodes
            if not self._out_of_service(node_labels[n["metadata"]["name"]])
        ]
        pools = get_node_pools(healthy)
        placement_pools = self._placement_pools(healthy, node_labels)
        # ownership hands over on the FIRST assignment label, not on
        # materialization: a half-written (or quarantine-degraded) gang
        # defers above, and its hosts must not fall back into an
        # implicit whole-pool gang while the labels converge
        placed_nodes = {
            name for name, labels in node_labels.items()
            if labels.get(consts.PLACEMENT_LABEL)
        }
        profile = self._load_profile()

        def participates(pool) -> bool:
            gang = profile.get(pool.accelerator_type, profile.get("all", "per-slice"))
            return pool.info.multi_host and gang != "disabled"

        # a pool with any placement-assigned member hands gang ownership
        # to the placement engine wholesale: an implicit whole-pool gang
        # would double-book the placed hosts. A pool the health exclusion
        # (or mid-registration) shrank below its declared topology defers
        # the same way a half-written placement does — TPU_TOPOLOGY still
        # names the full block, and a short hostlist under it hangs
        # libtpu init on every surviving worker, with no placement engine
        # behind an implicit gang to ever re-place it
        implicit = [
            p for p in pools
            if not any(name in placed_nodes for name in p.node_names)
            and self._pool_complete(p)
        ]
        # slice ids/count must enumerate only PARTICIPATING slices: a DCN
        # mesh sized over disabled pools would wait forever for slices
        # that never join
        active = [p for p in implicit + placement_pools if participates(p)]
        coordinator = self._coordinator_name(active) if self.multi_slice else ""
        self._owner_ref = self._managing_daemonset_ref()
        reconciled = []
        gang_pods: List[str] = []
        for index, pool in enumerate(active):
            name = self._slice_name(pool)
            self._apply_service(name)
            self._apply_gang_configmap(
                name, pool, slice_index=index, total_slices=len(active), coordinator=coordinator
            )
            self._apply_worker_ids(pool, node_labels)
            gang_pods.extend(self._apply_gang_pods(name, pool))
            reconciled.append(name)
        if coordinator and active:
            self._apply_coordinator_service(coordinator, self._slice_name(active[0]))
        self._clear_stale_worker_ids(node_labels, active)
        self._cleanup_stale(reconciled, gang_pods, coordinator)
        return reconciled

    def _clear_stale_worker_ids(self, node_labels: dict, active: List[NodePool]) -> None:
        """A node that is no longer a member of any live gang — taken out
        of service by the health subsystem, handed to the placement
        engine without an assignment, or left by a shrunk pool — must not
        keep a worker identity label: gang Services select on it, and a
        quarantined node answering slice DNS is exactly the degraded-gang
        hang the exclusion exists to prevent."""
        from tpu_operator import consts as _consts

        members = {name for pool in active for name in pool.node_names}
        record_key = (
            _consts.APPLY_SET_ANNOTATION_PREFIX + _consts.APPLY_SET_MANAGER_SLICE
        )
        for node_name, labels in node_labels.items():
            if node_name in members or WORKER_ID_LABEL not in labels:
                continue
            try:
                # one patch nulls the label AND the apply-set ownership
                # record together (the slice manager only ever declares
                # the worker id, so the record goes with it): a stale
                # record claiming a removed label would contradict the
                # removals-derive-from-the-record contract
                self.client.patch(
                    "v1", "Node", node_name,
                    {"metadata": {
                        "labels": {WORKER_ID_LABEL: None},
                        "annotations": {record_key: None},
                    }},
                )
            except errors.NotFound:
                pass

    @staticmethod
    def _out_of_service(labels: dict) -> bool:
        """Health-subsystem exclusion, shared with the placement engine
        so gang membership can never disagree between the two."""
        from tpu_operator.placement.engine import labels_unavailable

        return labels_unavailable(labels)

    @staticmethod
    def _pool_complete(pool: NodePool) -> bool:
        """Whether an implicit pool's (healthy) membership fills its
        declared topology's host grid. A shrunk torus cannot run — it
        defers until the missing hosts heal or register. Unknown wiring
        (unparseable topology) keeps the pre-placement behavior."""
        from tpu_operator.placement.torus import host_grid_dims

        grid = host_grid_dims(pool.topology, max(1, pool.info.chips_per_node))
        if grid is None:
            return True
        return len(pool.node_names) == grid[0] * grid[1] * grid[2]

    def _placement_pools(self, nodes: List[dict], node_labels: dict) -> List[NodePool]:
        """Gangs the placement controller assigned: one pseudo-pool per
        placement, members ordered by their placement index (worker ids
        then follow the placed block's ICI wiring, not alphabetical node
        names). The gang env gets the placed block's own size/topology,
        not the whole pool's."""
        import dataclasses

        from tpu_operator.nodeinfo import tpu_info
        from tpu_operator.placement.torus import host_grid_dims

        groups: dict = {}
        for node in nodes:
            labels = node_labels[node["metadata"]["name"]]
            owner = labels.get(consts.PLACEMENT_LABEL)
            if not owner:
                continue
            try:
                index = int(labels.get(consts.PLACEMENT_INDEX_LABEL, "0"))
            except ValueError:
                index = 0
            groups.setdefault(owner, []).append((index, node))
        # completeness is judged against the CLUSTER-WIDE label state (the
        # controller patches one node at a time, so a reconcile can land
        # mid-write): a gang only materializes once every index of its
        # placed block is labelled SOMEWHERE. Ownership still hands over
        # on the first label (see ``placed_nodes``), so a deferred gang's
        # hosts never fall back into an implicit whole-pool gang.
        cluster_indexes: dict = {}
        for labels in node_labels.values():
            owner = labels.get(consts.PLACEMENT_LABEL)
            if not owner:
                continue
            try:
                cluster_indexes.setdefault(owner, set()).add(
                    int(labels.get(consts.PLACEMENT_INDEX_LABEL, "0"))
                )
            except ValueError:
                pass
        pools: List[NodePool] = []
        for owner in sorted(groups):
            members = sorted(
                groups[owner], key=lambda t: (t[0], t[1]["metadata"]["name"])
            )
            info = tpu_info(members[0][1])
            if info is None:
                continue
            names = [n["metadata"]["name"] for _, n in members]
            topology = (
                node_labels[names[0]].get(consts.PLACEMENT_TOPOLOGY_LABEL)
                or info.topology
            )
            grid = host_grid_dims(topology, max(1, info.chips_per_node))
            if grid is not None:
                expected = grid[0] * grid[1] * grid[2]
                if cluster_indexes.get(owner, set()) != set(range(expected)):
                    # half-written assignment: advertising the block's
                    # topology with a short hostlist hangs libtpu init
                    # on every worker — wait for the labels to converge
                    continue
                if {i for i, _ in members} != set(range(expected)):
                    # fully labelled but a member is health-excluded:
                    # materializing the survivors would publish that same
                    # libtpu-hanging short hostlist AND renumber worker
                    # ids off the block's ICI order — defer (gang plumbing
                    # tears down, every member's worker id clears) while
                    # the engine re-places the gang away from the sick
                    # host
                    continue
            pools.append(
                NodePool(
                    name=owner,
                    accelerator_type=info.accelerator_type,
                    topology=topology,
                    gke_nodepool=info.nodepool,
                    node_names=names,
                    info=dataclasses.replace(
                        info,
                        topology=topology,
                        slice_hosts=len(names),
                        chips_in_slice=len(names) * info.chips_per_node,
                    ),
                )
            )
        return pools

    def _managing_daemonset_ref(self) -> Optional[dict]:
        """ownerReference to the slice-manager DaemonSet: gang objects are
        runtime state, so uninstalling the operator (CR delete -> operand
        DS GC) must cascade to them instead of leaking Services/Pods.
        Falls back to the last known ref — a lookup failure (restrictive
        RBAC, DS mid-delete) must never strip ownership or kill the
        reconcile."""
        try:
            ds = self.client.get_or_none(
                "apps/v1", "DaemonSet", "tpu-slice-manager", self.namespace
            )
        except errors.ApiError as e:
            log.debug("owner DaemonSet lookup failed (%s); keeping previous ref", e)
            return getattr(self, "_owner_ref", None)
        if ds is None or not ds["metadata"].get("uid"):
            return getattr(self, "_owner_ref", None)
        return {
            "apiVersion": "apps/v1",
            "kind": "DaemonSet",
            "name": ds["metadata"]["name"],
            "uid": ds["metadata"]["uid"],
        }

    def _own(self, obj: dict) -> dict:
        if getattr(self, "_owner_ref", None):
            obj["metadata"]["ownerReferences"] = [dict(self._owner_ref)]
        return obj

    @staticmethod
    def _slice_name(pool: NodePool) -> str:
        # leave room for "-<worker id>" pod/hostname suffixes within the
        # 63-char DNS label limit; long names get a content-hash suffix so
        # two pools differing only past the cut never collide (same scheme
        # as states/tpuslice_state._dns_safe)
        name = f"{SLICE_SERVICE_PREFIX}-{pool.name}"
        if len(name) <= 58:
            return name.rstrip("-")
        return f"{name[:49].rstrip('-')}-{object_hash(pool.name)[:8]}"

    @staticmethod
    def _coordinator_name(active: List[NodePool]) -> str:
        """DCN coordinator Service name, derived from the first ACTIVE
        slice (slice 0 of the megascale mesh) so the advertised address
        always matches a Service this agent creates."""
        if not active:
            return ""
        first = SliceManagerAgent._slice_name(active[0])
        return f"{first}-coord"[:63].rstrip("-")

    def _apply_service(self, name: str) -> None:
        svc = new_object(
            "v1",
            "Service",
            name,
            self.namespace,
            labels=dict(MANAGED_BY),
            spec={
                "clusterIP": "None",  # headless: per-worker DNS
                "selector": {SLICE_LABEL: name},
                "ports": [{"name": "coordinator", "port": self.coordinator_port}],
            },
        )
        self.client.apply(self._own(svc))

    def _apply_coordinator_service(self, name: str, slice0: str) -> None:
        """The multi-slice DCN coordinator: a stable ClusterIP in front of
        slice 0's worker 0 (the megascale coordinator process)."""
        svc = new_object(
            "v1",
            "Service",
            name,
            self.namespace,
            labels=dict(MANAGED_BY),
            spec={
                "selector": {SLICE_LABEL: slice0, WORKER_ID_LABEL: "0"},
                "ports": [{"name": "coordinator", "port": self.coordinator_port}],
            },
        )
        self.client.apply(self._own(svc))

    def _apply_gang_pods(self, name: str, pool: NodePool) -> List[str]:
        """One COMPONENT=slice worker pod per host of the slice, scheduled
        through the scheduler (hostname nodeSelector + TPU resource limit)
        and resolvable as ``<name>-<i>.<name>.<ns>.svc`` via the headless
        Service. Pods are effectively immutable, so spec changes are
        rolled by delete+create, gated on a rendered-spec hash."""
        objs = self._renderer.render_objects(
            {
                "slice_name": name,
                "workers": [
                    {"worker_id": i, "node_name": n} for i, n in enumerate(pool.node_names)
                ],
                "namespace": self.namespace,
                "validator_image": self.validator_image,
                "image_pull_policy": self.image_pull_policy,
                "tpu_resource": consts.TPU_RESOURCE_NAME,
                "chips_per_host": pool.info.chips_per_node,
                "coordinator_port": self.coordinator_port,
                "validation_dir": self.validation_dir,
                "min_psum_gbps_per_chip": self.min_psum_gbps_per_chip,
                "autotune_results_configmap": consts.AUTOTUNE_RESULTS_CONFIGMAP,
            }
        )
        created = []
        for pod in objs:
            # hash BEFORE attaching the ownerReference: the DS uid is
            # metadata, and folding it into the hash would delete+recreate
            # every running gang worker on any operator reinstall
            spec_hash = object_hash(pod)
            self._own(pod)
            pod["metadata"].setdefault("annotations", {})[GANG_HASH_ANNOTATION] = spec_hash
            pod_name = pod["metadata"]["name"]
            existing = self.client.get_or_none("v1", "Pod", pod_name, self.namespace)
            if existing is not None:
                old = (existing["metadata"].get("annotations") or {}).get(GANG_HASH_ANNOTATION)
                if old == spec_hash:
                    created.append(pod_name)
                    continue
                try:
                    self.client.delete("v1", "Pod", pod_name, self.namespace)
                except errors.NotFound:
                    pass  # another host's agent deleted it first
            try:
                self.client.create(pod)  # tpuop-lint: kinds=v1/Pod
            except (errors.Conflict, errors.AlreadyExists):
                pass  # another host's agent won the race; converged either way
            created.append(pod_name)
        return created

    def _apply_gang_configmap(
        self, name: str, pool: NodePool, slice_index: int, total_slices: int, coordinator: str = ""
    ) -> None:
        hostnames = ",".join(
            f"{name}-{i}.{name}.{self.namespace}.svc" for i in range(len(pool.node_names))
        )
        data = {
            "TPU_WORKER_HOSTNAMES": hostnames,
            "TPU_ACCELERATOR_TYPE": pool.accelerator_type,
            "TPU_TOPOLOGY": pool.topology,
            # the ACTUAL gang size, not the topology-derived pool size:
            # the two disagree whenever a sick host was excluded or a
            # placement block is smaller than the pool, and every worker
            # sizes its world from this env
            "TPU_SLICE_HOSTS": str(len(pool.node_names)),
            "TPU_CHIPS_PER_HOST": str(pool.info.chips_per_node),
        }
        if self.multi_slice and coordinator:
            # slice 0's worker 0 coordinates the DCN mesh, fronted by the
            # coordinator Service this same reconcile creates
            data["MEGASCALE_COORDINATOR_ADDRESS"] = (
                f"{coordinator}.{self.namespace}.svc:{self.coordinator_port}"
            )
            data["MEGASCALE_NUM_SLICES"] = str(total_slices)
            data["MEGASCALE_SLICE_ID"] = str(slice_index)
        cm = new_object(
            "v1",
            "ConfigMap",
            f"{name}-gang",
            self.namespace,
            labels=dict(MANAGED_BY),
            data=data,
        )
        self.client.apply(self._own(cm))

    def publish_gang_telemetry(self, slice_name: str, artifact: dict) -> bool:
        """Publish a gang's merged step-time artifact
        (``workloads.telemetry.merge_gang_reports``) onto its gang
        ConfigMap as the ``consts.GANG_TELEMETRY_ANNOTATION`` — the
        hand-off point between the data plane (workload harnesses
        measuring their own steps) and the control plane (the operator's
        fleet aggregation reads the annotation back into the
        ``tpu_operator_gang_*`` series). An annotation-only merge patch:
        concurrent hosts publishing the same gang converge, and the gang
        env data is never touched. Returns False when the gang ConfigMap
        is gone (torn down between measure and publish)."""
        return self._publish_gang_annotation(
            slice_name, consts.GANG_TELEMETRY_ANNOTATION, artifact
        )

    def publish_gang_fabric(self, slice_name: str, artifact: dict) -> bool:
        """Publish a gang's fabric matrix
        (``workloads.fabric.gang_fabric_artifact``: per-edge ICI
        bandwidth + per-axis allreduce latency) beside the step-time
        artifact. The operator's fabric analyzer
        (``controllers/fabric_telemetry.py``) reads it back into the
        ``tpu_operator_ici_link_*`` series and runs blame assignment —
        the layer that tells a slow link from a slow chip."""
        return self._publish_gang_annotation(
            slice_name, consts.GANG_FABRIC_ANNOTATION, artifact
        )

    def _publish_gang_annotation(self, slice_name: str, annotation: str, artifact: dict) -> bool:
        import json

        try:
            self.client.patch(
                "v1", "ConfigMap", f"{slice_name}-gang", {
                    "metadata": {"annotations": {
                        annotation: json.dumps(artifact, sort_keys=True)
                    }}
                },
                self.namespace,
            )
        except errors.NotFound:
            return False
        return True

    def _apply_worker_ids(self, pool: NodePool, node_labels: dict) -> None:
        """Stable worker ids: sorted node order within the pool (reference
        concept: per-node mig.config label loop). One forced apply-set per
        changed node — the slice manager is the sole authority for worker
        identity, so the declaration always wins (kube SSA force), the
        ownership record makes removals restart-safe, and no rv travels,
        so every host's concurrent agent converges instead of
        Conflict-bouncing. The current labels still come from the
        reconcile's own node list: a settled pool writes nothing."""
        from tpu_operator import consts as _consts

        for worker_id, node_name in enumerate(pool.node_names):
            labels = node_labels.get(node_name, {})
            if labels.get(WORKER_ID_LABEL) != str(worker_id):
                try:
                    self.client.apply_set(
                        "v1", "Node", node_name,
                        _consts.APPLY_SET_MANAGER_SLICE,
                        labels={WORKER_ID_LABEL: str(worker_id)},
                        force=True,
                    )
                except errors.NotFound:
                    pass

    def _cleanup_stale(
        self, live_names: List[str], live_pods: Optional[List[str]] = None, coordinator: str = ""
    ) -> None:
        # every node's agent runs this concurrently: a racing agent deleting
        # the same stale object first must not abort the rest of the pass
        def delete_quietly(api_version: str, kind: str, name: str) -> None:
            try:
                self.client.delete(api_version, kind, name, self.namespace)  # tpuop-lint: kinds=v1/Service,v1/ConfigMap,v1/Pod
            except errors.NotFound:
                pass

        live_services = set(live_names) | ({coordinator} if coordinator else set())
        for svc in self.client.list("v1", "Service", self.namespace, label_selector=MANAGED_BY):
            if svc["metadata"]["name"] not in live_services:
                delete_quietly("v1", "Service", svc["metadata"]["name"])
        live_cms = {f"{n}-gang" for n in live_names}
        for cm in self.client.list("v1", "ConfigMap", self.namespace, label_selector=MANAGED_BY):
            if cm["metadata"]["name"] not in live_cms:
                delete_quietly("v1", "ConfigMap", cm["metadata"]["name"])
        live_pod_set = set(live_pods or [])
        for pod in self.client.list("v1", "Pod", self.namespace, label_selector=MANAGED_BY):
            if pod["metadata"]["name"] not in live_pod_set:
                delete_quietly("v1", "Pod", pod["metadata"]["name"])

    def run_forever(self) -> None:
        while True:
            try:
                self.reconcile_once()
            except errors.ApiError as e:
                log.warning("slice-manager: %s", e)
            time.sleep(self.interval)


def _int_env(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)).strip())
    except ValueError:
        log.warning("invalid %s %r; using %d", name, os.environ.get(name), default)
        return default


def agent_from_env(client: Client) -> "SliceManagerAgent":
    """Construct the agent from the DaemonSet's env contract (split from
    main() so tests pin the env→constructor hop of e.g. the psum floor)."""
    return SliceManagerAgent(
        client,
        namespace=os.environ.get(consts.OPERATOR_NAMESPACE_ENV, consts.DEFAULT_OPERATOR_NAMESPACE),
        multi_slice=os.environ.get("MULTI_SLICE_ENABLED", "").lower() == "true",
        coordinator_port=_int_env("COORDINATOR_PORT", 8476),
        config_map=os.environ.get("SLICE_CONFIG_MAP", ""),
        validator_image=os.environ.get("VALIDATOR_IMAGE", "tpu-operator-validator"),
        image_pull_policy=os.environ.get("VALIDATOR_IMAGE_PULL_POLICY", "IfNotPresent"),
        validation_dir=os.environ.get("VALIDATION_DIR", consts.VALIDATION_DIR),
        min_psum_gbps_per_chip=os.environ.get("MIN_PSUM_GBPS_PER_CHIP", ""),
    )


def main() -> int:
    logging.basicConfig(level=logging.INFO)
    from tpu_operator.kube.http_client import HttpClient

    agent_from_env(HttpClient.in_cluster()).run_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
