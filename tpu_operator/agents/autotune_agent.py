"""tpu-autotuner agent: the elected-node half of the autotune loop.

The autotune controller elects ONE in-service node per un-swept TPU
generation by stamping ``consts.AUTOTUNE_ELECTED_LABEL`` — and the
autotuner DaemonSet's nodeSelector includes that label, so this agent
only ever runs on an elected node, holding the node's chips through the
``google.com/tpu`` extended resource for exactly the sweep window (no
privileged container, no hostPath: the device plugin injects the
devices, and resource ownership guarantees no co-tenant skews the
measurements).

The loop per tick:

  1. read the own Node (election label + generation labels);
  2. read the ``tpu-autotune-results`` ConfigMap: a valid cached entry
     for (generation, libtpu version) — every kernel family swept with
     a winner — is a CACHE HIT: zero writes, nothing re-runs (the
     sweep-once fleet-wide contract; a rebooted elected node lands
     here);
  3. otherwise run the generation sweep
     (``workloads.autotune.run_generation_sweep``: flash fwd / fwd+bwd
     block grid, matmul + int8 chain tilings, dominated configs pruned)
     and publish the entry as the ``<generation>.json`` data key (a
     key-scoped merge patch; the ConfigMap is created on first use).

The controller notices the published entry, clears the election label
(which descheduled this pod), folds the winners into the perf-floors
pipeline, and publishes the winning configs for workloads.

Off-TPU the sweep still runs (interpret-mode pallas) and publishes
CONFIG winners, but the entry records its platform — the controller
never folds non-TPU rates into the floors.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Callable, Optional

from tpu_operator import consts
from tpu_operator.kube import errors
from tpu_operator.kube.client import Client
from tpu_operator.kube.objects import new_object
from tpu_operator.nodeinfo import tpu_info
from tpu_operator.workloads.autotune import (
    entry_key,
    entry_valid,
    parse_entry,
    run_generation_sweep,
    runtime_fingerprint,
)

log = logging.getLogger(__name__)


class AutotuneAgent:
    def __init__(
        self,
        client: Client,
        node_name: str,
        namespace: str = consts.DEFAULT_OPERATOR_NAMESPACE,
        interval: float = 60.0,
        sweep_fn: Optional[Callable[[str, str], dict]] = None,
        profile: Optional[str] = None,
    ):
        self.client = client
        self.node_name = node_name
        self.namespace = namespace
        self.interval = interval
        # injectable for tests/smokes; the default is the real sweep
        self.sweep_fn = sweep_fn or (
            lambda gen, version: run_generation_sweep(gen, version, profile=profile)
        )
        self._stop = False

    # -- one pass -------------------------------------------------------------

    def reconcile_once(self) -> str:
        """Returns the pass outcome (tests and logs read it):
        ``not-elected`` | ``no-generation`` | ``cache-hit`` | ``swept``."""
        node = self.client.get_or_none("v1", "Node", self.node_name)
        if node is None:
            return "not-elected"
        labels = node["metadata"].get("labels") or {}
        if labels.get(consts.AUTOTUNE_ELECTED_LABEL) != consts.AUTOTUNE_ELECTED:
            # the DaemonSet nodeSelector should make this unreachable,
            # but a just-cleared label can race the pod teardown
            return "not-elected"
        info = tpu_info(node)
        generation = info.generation if info else ""
        if not generation or generation == "unknown":
            log.warning("autotune: node %s has no recognizable TPU generation", self.node_name)
            return "no-generation"
        version = runtime_fingerprint()
        cm = self.client.get_or_none(
            "v1", "ConfigMap", consts.AUTOTUNE_RESULTS_CONFIGMAP, self.namespace
        )
        entry = parse_entry(((cm or {}).get("data") or {}).get(entry_key(generation)))
        if entry_valid(entry, version):
            # sweep-once: the generation is already measured for this
            # toolchain — a rebooted elected node issues ZERO writes
            return "cache-hit"
        log.info(
            "autotune: sweeping generation %s on %s (libtpu %s)",
            generation, self.node_name, version,
        )
        started = time.monotonic()
        entry = self.sweep_fn(generation, version)
        entry["swept_by"] = self.node_name
        entry["sweep_seconds"] = round(time.monotonic() - started, 2)
        self._publish(generation, entry, cm_exists=cm is not None)
        return "swept"

    def _publish(self, generation: str, entry: dict, cm_exists: bool) -> None:
        """Key-scoped merge patch of this generation's entry; the
        ConfigMap is created on first use (concurrent creators converge
        through AlreadyExists -> patch)."""
        body = {"data": {entry_key(generation): json.dumps(entry, sort_keys=True)}}
        if not cm_exists:
            cm = new_object(
                "v1", "ConfigMap", consts.AUTOTUNE_RESULTS_CONFIGMAP,
                self.namespace, labels={"app": "tpu-autotuner"},
                data=body["data"],
            )
            try:
                self.client.create(cm)
                return
            except errors.AlreadyExists:
                pass  # another generation's agent won the race
        self.client.patch(
            "v1", "ConfigMap", consts.AUTOTUNE_RESULTS_CONFIGMAP, body,
            self.namespace,
        )

    # -- loop -----------------------------------------------------------------

    def run_forever(self) -> None:
        while not self._stop:
            try:
                outcome = self.reconcile_once()
                log.info("autotune: pass outcome %s", outcome)
            except errors.ApiError as e:
                log.warning("autotune: pass failed: %s", e)
            except Exception:  # noqa: BLE001 — a sweep crash must not kill the pod
                log.exception("autotune: sweep failed")
            time.sleep(self.interval)

    def stop(self) -> None:
        self._stop = True


def _float_env(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)).strip())
    except ValueError:
        log.warning("invalid %s %r; using %s", name, os.environ.get(name), default)
        return default


def main() -> int:
    logging.basicConfig(level=logging.INFO)
    import argparse

    p = argparse.ArgumentParser("tpu-autotuner")
    p.add_argument(
        "--oneshot", action="store_true",
        help="run one reconcile pass and exit (image smoke / debugging)",
    )
    args = p.parse_args()
    from tpu_operator.kube.http_client import HttpClient

    client = HttpClient.in_cluster()
    agent = AutotuneAgent(
        client,
        node_name=os.environ.get("NODE_NAME", ""),
        namespace=os.environ.get(
            consts.OPERATOR_NAMESPACE_ENV, consts.DEFAULT_OPERATOR_NAMESPACE
        ),
        interval=_float_env("AUTOTUNE_INTERVAL", 60.0),
        profile=os.environ.get("AUTOTUNE_PROFILE") or None,
    )
    if args.oneshot:
        print(json.dumps({"outcome": agent.reconcile_once()}))
        return 0
    agent.run_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
