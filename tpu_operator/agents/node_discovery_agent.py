"""tpu-node-discovery agent: the NFD-analog bootstrap.

The reference recognizes GPU nodes anywhere because NFD's PCI scan
(pci-10de → ``nvidia.com/gpu.present``) runs on every node of any
cluster (state_manager.go:113-117). This operator's GKE path instead
consumes the ``cloud.google.com/gke-tpu-*`` labels — which nothing
stamps on a self-managed TPU-VM cluster, and the tfd DaemonSet that
could probe hardware only schedules on nodes already recognized as TPU
nodes (a circular dependency).

This agent breaks the circle. Its DaemonSet (state-node-discovery)
schedules on EVERY Linux node with no TPU gate and no validation
barriers, probes the kernel's accelerator inventory with the native
``tpuinfo`` probe (/dev/accel*, /sys/class/accel), and — when chips are
present — publishes the vendor-neutral ``tpu.google.com/*`` labels that
``nodeinfo.tpu_info`` accepts as an alternative to GKE's. From there the
normal flow takes over: the ClusterPolicy reconciler stamps
``tpu.present`` + per-operand deploy gates and the operand DaemonSets
schedule, exactly as on GKE.

Accelerator identity: the Cloud TPU VM runtime contract publishes
``TPU_ACCELERATOR_TYPE`` (e.g. "v5litepod-16") and optionally
``TPU_TOPOLOGY`` in the VM environment; when present they are mapped to
the catalog types. Without them the node is still recognized (type
``tpu-unknown-device``) and the probed local chip count stands in for
catalog attributes — discovery degrades, it never blocks.
"""

from __future__ import annotations

import logging
import os
import re
import time
from typing import Dict, Optional, Tuple

from tpu_operator import consts
from tpu_operator.kube import errors
from tpu_operator.kube.client import Client

log = logging.getLogger(__name__)

# Accelerator type published when hardware is present but the VM
# environment does not identify the generation. nodeinfo treats catalog
# misses gracefully (probed chip count stands in for chips_per_host).
UNKNOWN_ACCELERATOR = "tpu-unknown-device"

# Cloud TPU VM accelerator-type strings → (catalog type, chips per
# TensorCore-count divisor). v4/v5p type strings count TensorCores
# (2 per chip); v5e/v6e strings count chips directly.
_VM_TYPE_PATTERNS: Tuple[Tuple[str, str, int], ...] = (
    (r"^v4-(\d+)$", "tpu-v4-podslice", 2),
    (r"^v5litepod-(\d+)$", "tpu-v5-lite-podslice", 1),
    (r"^v5p-(\d+)$", "tpu-v5p-slice", 2),
    (r"^v6e-(\d+)$", "tpu-v6e-slice", 1),
)

# 2D slice topologies by chip count (v5e/v6e podslice shapes). 3D
# generations (v4/v5p) are ambiguous by count alone and require
# TPU_TOPOLOGY.
# every label discover() can emit — the strip-when-underivable set; the
# other TFD_LABELS (slice-hosts, generation) are the tfd operand's richer
# publication and are never this agent's to remove while hardware remains
_SELF_PUBLISHED_LABELS = (
    consts.TFD_ACCELERATOR_TYPE_LABEL,
    consts.TFD_TOPOLOGY_LABEL,
    consts.TFD_CHIPS_PER_NODE_LABEL,
    consts.TORUS_COORDS_LABEL,
)

_2D_TOPOLOGY_BY_CHIPS = {
    1: "1x1",
    4: "2x2",
    8: "2x4",
    16: "4x4",
    32: "4x8",
    64: "8x8",
    128: "8x16",
    256: "16x16",
}


def parse_vm_accelerator_type(vm_type: str) -> Optional[Tuple[str, int]]:
    """"v5litepod-16" → ("tpu-v5-lite-podslice", 16 chips); None when the
    string matches no known generation."""
    for pattern, catalog_type, divisor in _VM_TYPE_PATTERNS:
        m = re.match(pattern, vm_type.strip())
        if m:
            return catalog_type, max(1, int(m.group(1)) // divisor)
    return None


class NodeDiscoveryAgent:
    """Probe local TPU hardware and publish discovery labels on the Node."""

    def __init__(self, client: Client, node_name: str, interval: float = 60.0):
        self.client = client
        self.node_name = node_name
        self.interval = interval

    # -- discovery -----------------------------------------------------------

    @staticmethod
    def probe_chips() -> Optional[int]:
        """Locally visible chip count; None when the probe itself failed.
        The distinction matters: a successful probe of an empty inventory
        justifies stripping labels, a transient failure must not (it would
        tear down every gated operand on the node for one bad tick)."""
        try:
            from tpu_operator.native import tpuinfo

            return int(tpuinfo.probe().get("chip_count") or 0)
        except Exception:  # noqa: BLE001 — probe machinery failed
            return None

    def discover(self) -> Optional[Dict[str, str]]:
        """Labels to publish: empty when a successful probe saw no TPU
        hardware, None when the probe failed (indeterminate — change
        nothing this tick)."""
        chips = self.probe_chips()
        if chips is None:
            return None
        if chips <= 0:
            return {}
        labels = {consts.TFD_CHIPS_PER_NODE_LABEL: str(chips)}
        acc_type = UNKNOWN_ACCELERATOR
        topology = os.environ.get("TPU_TOPOLOGY", "").strip()
        slice_chips = 0
        vm_type = os.environ.get("TPU_ACCELERATOR_TYPE", "").strip()
        parsed = parse_vm_accelerator_type(vm_type) if vm_type else None
        if parsed:
            acc_type, slice_chips = parsed
            if not topology and acc_type in ("tpu-v5-lite-podslice", "tpu-v6e-slice"):
                topology = _2D_TOPOLOGY_BY_CHIPS.get(slice_chips, "")
        labels[consts.TFD_ACCELERATOR_TYPE_LABEL] = acc_type
        if topology:
            labels[consts.TFD_TOPOLOGY_LABEL] = topology
            coords = self._torus_coords(topology, chips)
            if coords:
                labels[consts.TORUS_COORDS_LABEL] = coords
        return labels

    @staticmethod
    def _torus_coords(topology: str, chips_per_host: int) -> str:
        """This host's coordinate on the slice's host grid, from the TPU
        VM runtime contract's TPU_WORKER_ID (workers enumerate row-major
        over the host grid). Empty when the id is absent/garbage or the
        grid can't be derived — placement then degrades to the
        deterministic row-major fallback layout, it never blocks."""
        worker_env = os.environ.get("TPU_WORKER_ID", "").strip()
        if not worker_env:
            return ""
        try:
            worker_id = int(worker_env)
        except ValueError:
            return ""
        from tpu_operator.placement.torus import host_grid_dims, worker_coords

        dims = host_grid_dims(topology, chips_per_host)
        if dims is None or worker_id < 0 or worker_id >= dims[0] * dims[1] * dims[2]:
            return ""
        return "-".join(str(c) for c in worker_coords(worker_id, dims))

    # -- publication ---------------------------------------------------------

    def apply_once(self) -> bool:
        """Stamp discovery labels when they differ; strip them when a
        successful probe found no hardware AND the node has no GKE
        accelerator label (on GKE the tfd operand owns the tpu.google.com
        labels — never fight it). A failed probe changes nothing."""
        want = self.discover()
        if want is None:
            return False  # indeterminate probe: keep current state
        try:
            node = self.client.get("v1", "Node", self.node_name)
        except errors.NotFound:
            return False
        labels = node["metadata"].setdefault("labels", {})
        changed = False
        if want:
            # On a GKE-labelled node the platform (and the tfd operand's
            # richer publication) own TPU identity: publish only directly
            # probed facts (chip count), never the env/count-derived
            # identity guesses — a guessed accelerator-type could persist
            # wrongly whenever tfd is disabled or hasn't run yet.
            gke_owned = bool(labels.get(consts.GKE_TPU_ACCELERATOR_LABEL))
            if gke_owned:
                want = {
                    k: v
                    for k, v in want.items()
                    if k == consts.TFD_CHIPS_PER_NODE_LABEL
                }
            for key, value in want.items():
                if labels.get(key) != value:
                    labels[key] = value
                    changed = True
            if not gke_owned:
                # hardware still present but a fact this agent itself
                # publishes is no longer derivable (worker id lost, the
                # runtime's TPU_TOPOLOGY env gone after re-provisioning):
                # a stale identity is worse than none — a stale topology
                # would keep sizing the placement torus for a grid the
                # host no longer belongs to, and a stale coordinate would
                # claim a position the host may no longer hold. Strip
                # only discovery's own keys: slice-hosts/generation
                # belong to the richer tfd operand publication.
                for key in _SELF_PUBLISHED_LABELS:
                    if key not in want and key in labels:
                        del labels[key]
                        changed = True
        elif not labels.get(consts.GKE_TPU_ACCELERATOR_LABEL):
            for key in consts.TFD_LABELS + (consts.TORUS_COORDS_LABEL,):
                if key in labels:
                    del labels[key]
                    changed = True
        if changed:
            try:
                self.client.update(node)
            except errors.Conflict:
                return False  # node moved under us; next tick retries
        return changed

    def run_forever(self) -> None:
        while True:
            try:
                self.apply_once()
            except errors.ApiError as e:
                log.warning("node-discovery: %s", e)
            time.sleep(self.interval)


def main() -> int:
    logging.basicConfig(level=logging.INFO)
    node_name = os.environ.get("NODE_NAME", "")
    if not node_name:
        log.error("NODE_NAME required")
        return 1
    from tpu_operator.kube.http_client import HttpClient

    try:
        interval = float(os.environ.get("DISCOVERY_SLEEP_INTERVAL", "60").strip())
    except ValueError:
        log.warning(
            "invalid DISCOVERY_SLEEP_INTERVAL %r; using 60s",
            os.environ.get("DISCOVERY_SLEEP_INTERVAL"),
        )
        interval = 60.0
    NodeDiscoveryAgent(HttpClient.in_cluster(), node_name, interval=interval).run_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
