"""TPU device plugin: kubelet gRPC device plugin (v1beta1).

The in-repo analog of the Cloud TPU / NVIDIA k8s-device-plugin operand:
advertises ``google.com/tpu`` extended resources to the kubelet and wires
``/dev/accel*`` + libtpu into allocated containers.

Flow (the standard device plugin contract):
  1. serve the DevicePlugin service on a unix socket under
     /var/lib/kubelet/device-plugins/
  2. dial the kubelet's Registration service on kubelet.sock and Register
     (resource name, our endpoint)
  3. kubelet calls ListAndWatch (stream of device inventories) and
     Allocate (per-container device specs/mounts/env)

gRPC service bindings are hand-rolled over ``grpc.method_handlers_generic_handler``
(message classes come from protoc — native/deviceplugin.proto); no
grpc_tools codegen needed.
"""

from __future__ import annotations

import json
import logging
import math
import os
import queue
import re
import threading
import time
from typing import Dict, List, Optional

import grpc

from tpu_operator.kube import racecheck
from tpu_operator import consts
from tpu_operator.agents.dpapi import deviceplugin_pb2 as pb

log = logging.getLogger(__name__)

API_VERSION = "v1beta1"
# per-node plugin config selection label (reference: the device-plugin
# config label driving the config-manager sidecar)
PLUGIN_CONFIG_LABEL = "tpu.google.com/device-plugin.config"
KUBELET_SOCKET_DIR = "/var/lib/kubelet/device-plugins"
PLUGIN_SOCKET_NAME = "tpu-device-plugin.sock"


def _unary(fn, request_cls):
    return grpc.unary_unary_rpc_method_handler(
        fn,
        request_deserializer=request_cls.FromString,
        response_serializer=lambda msg: msg.SerializeToString(),
    )


def _stream(fn, request_cls):
    return grpc.unary_stream_rpc_method_handler(
        fn,
        request_deserializer=request_cls.FromString,
        response_serializer=lambda msg: msg.SerializeToString(),
    )


class TPUDevicePlugin:
    """Serves DevicePlugin; device inventory from the native probe."""

    def __init__(
        self,
        socket_dir: str = KUBELET_SOCKET_DIR,
        resource_name: str = consts.TPU_RESOURCE_NAME,
        install_dir: str = consts.LIBTPU_INSTALL_DIR,
        devices: Optional[List[str]] = None,  # override for tests
        health_check_interval: float = 30.0,
        config: Optional[dict] = None,  # selected named config
        health_dir: Optional[str] = None,  # health agent's verdicts dir
    ):
        # supported config keys (the time-slicing analog): ``replicas``
        # advertises each physical chip N times so N pods can share it
        self.config = config or {}
        self.socket_dir = socket_dir
        self.socket_path = os.path.join(socket_dir, PLUGIN_SOCKET_NAME)
        self.resource_name = resource_name
        self.install_dir = install_dir
        self.health_dir = health_dir if health_dir is not None else os.environ.get(
            "HEALTH_DIR", consts.HEALTH_DIR
        )
        self._devices_override = devices
        self.health_check_interval = health_check_interval
        self._server: Optional[grpc.Server] = None
        # per-stream subscriber queues: a re-dialled ListAndWatch must not
        # have its updates stolen by a zombie predecessor stream
        self._subscribers: List["queue.Queue"] = []
        self._sub_lock = racecheck.lock("DevicePlugin._sub_lock")
        self._stop = threading.Event()
        # every device ever advertised: a yanked chip must be re-reported
        # as Unhealthy (kubelet keeps it in capacity, stops allocating),
        # not silently dropped from the inventory
        self._known_devices: set = set()
        self._last_health: Dict[str, str] = {}
        self._coords_cache: Optional[dict] = None

    # -- inventory -----------------------------------------------------------

    def discover(self) -> List[str]:
        if self._devices_override is not None:
            return list(self._devices_override)
        from tpu_operator.native import tpuinfo

        return tpuinfo.probe().get("devices", [])

    # verdicts older than this are ignored: the agent rewrites the file
    # every probe tick, so a stale mtime means it is dead or disabled —
    # its last word must not pin chips Unhealthy forever
    VERDICTS_TTL_SECONDS = 600.0

    def read_external_verdicts(self) -> Dict[str, str]:
        """Per-chip verdicts published by the health monitor agent
        (hostPath JSON, written atomically). Missing/torn/stale file
        degrades to no verdicts — the plugin's own device probe still
        stands."""
        path = os.path.join(self.health_dir, consts.HEALTH_VERDICTS_FILE)
        try:
            ttl = float(os.environ.get("HEALTH_VERDICTS_TTL", "") or self.VERDICTS_TTL_SECONDS)
        except ValueError:
            ttl = self.VERDICTS_TTL_SECONDS
        try:
            if ttl > 0 and time.time() - os.stat(path).st_mtime > ttl:
                return {}
            with open(path) as f:
                data = json.load(f)
            chips = data.get("chips") if isinstance(data, dict) else None
            if not isinstance(chips, dict):
                return {}  # any non-conforming shape degrades, never raises
            return {str(k): str(v) for k, v in chips.items()}
        except (OSError, ValueError):
            return {}

    def current_health(self) -> Dict[str, str]:
        """The authoritative per-chip health map: a probe of /dev/accel*
        (present → Healthy, previously-seen-but-gone → Unhealthy) merged
        with the health agent's verdicts (its Unhealthy overrides ours —
        it sees degradation a bare device-node check cannot, e.g. a
        failing matmul)."""
        present = {os.path.basename(p) for p in self.discover()}
        self._known_devices |= present
        health = {
            dev: "Healthy" if dev in present else "Unhealthy"
            for dev in sorted(self._known_devices)
        }
        for dev, verdict in self.read_external_verdicts().items():
            if dev in health and verdict != "Healthy":
                health[dev] = "Unhealthy"
        return health

    def _device_list(self, inventory) -> pb.ListAndWatchResponse:
        """Build the ListAndWatch response from a health map ({device:
        verdict}); a plain path list is accepted for compatibility and
        reads as all-Healthy."""
        if not isinstance(inventory, dict):
            inventory = {os.path.basename(p): "Healthy" for p in inventory}
        replicas = int(self.config.get("replicas", 1) or 1)
        devices = []
        for base in sorted(inventory, key=self._chip_index):
            health = inventory[base]
            if replicas <= 1:
                devices.append(pb.Device(ID=base, health=health))
            else:
                devices.extend(
                    pb.Device(ID=f"{base}-rep{r}", health=health) for r in range(replicas)
                )
        return pb.ListAndWatchResponse(devices=devices)

    # -- DevicePlugin service -------------------------------------------------

    def GetDevicePluginOptions(self, request, context):
        return pb.DevicePluginOptions(pre_start_required=False, get_preferred_allocation_available=True)

    def ListAndWatch(self, request, context):
        """Stream the inventory; re-send whenever it changes."""
        my_queue: "queue.Queue" = queue.Queue()
        with self._sub_lock:
            self._subscribers.append(my_queue)
        try:
            # note: _last_health is owned by health_loop — writing it here
            # would suppress the publish other subscribers rely on
            yield self._device_list(self.current_health())
            while not self._stop.is_set():
                try:
                    current = my_queue.get(timeout=0.2)
                except queue.Empty:
                    continue
                yield self._device_list(current)
        finally:
            with self._sub_lock:
                if my_queue in self._subscribers:
                    self._subscribers.remove(my_queue)

    # combination cap for torus-aware search; beyond it the index-window
    # heuristic answers (C(16,8)=12870 is the realistic worst case)
    _MAX_COMBINATIONS = 20000

    def GetPreferredAllocation(self, request, context):
        """Prefer ICI-adjacent chips using real chip coordinates: choose
        the candidate set minimizing total pairwise Manhattan distance in
        the host's block (tie-break: bounding-box volume), so a 2x2 face
        beats an equal-index-spread line. Coordinates come from the native
        probe's host-bounds contract (native/tpuinfo.cc:tpuinfo_chip_coords);
        falls back to the contiguous index-window heuristic when the
        search space is too large."""
        responses = []
        for req in request.container_requests:
            available = list(req.available_deviceIDs)
            size = req.allocation_size or len(available)
            must = list(req.must_include_deviceIDs)
            if not available or size <= 0:
                responses.append(pb.ContainerPreferredAllocationResponse(deviceIDs=must))
                continue
            best = self._torus_preferred(available, size, must)
            if best is None:
                best = self._window_preferred(available, size, must)
            responses.append(pb.ContainerPreferredAllocationResponse(deviceIDs=best))
        return pb.PreferredAllocationResponse(container_responses=responses)

    @staticmethod
    def _chip_index(dev_id: str) -> int:
        digits = re.sub(r"\D", "", dev_id.split("-rep")[0])
        return int(digits) if digits else 0

    def _torus_preferred(self, available, size, must):
        """Exhaustive search over candidate sets by block-local Manhattan
        distance; None when infeasible or the combination count exceeds
        the cap. Distances do NOT wrap: TPU_CHIPS_PER_HOST_BOUNDS is one
        host's sub-block of the slice — opposite block edges link onward
        to other hosts, never to each other (torus closure exists only at
        full-pod scale)."""
        import itertools

        if self._coords_cache is None:
            from tpu_operator.native import tpuinfo

            # host bounds are immutable for the plugin's lifetime
            self._coords_cache = tpuinfo.chip_coords()
        coords = self._coords_cache["coords"]
        free = [d for d in available if d not in must]
        needed = size - len(must)
        if needed < 0 or needed > len(free):
            return None
        if math.comb(len(free), needed) > self._MAX_COMBINATIONS:
            return None

        def coord(dev_id):
            idx = self._chip_index(dev_id)
            return coords[idx] if idx < len(coords) else [idx, 0, 0]

        def dist(a, b):
            return sum(abs(a[axis] - b[axis]) for axis in range(3))

        def score(devs):
            pts = [coord(d) for d in devs]
            pairwise = sum(
                dist(pts[i], pts[j]) for i in range(len(pts)) for j in range(i + 1, len(pts))
            )
            volume = 1
            for axis in range(3):
                vals = [p[axis] for p in pts]
                volume *= max(vals) - min(vals) + 1
            return (pairwise, volume)

        best, best_score = None, None
        for combo in itertools.combinations(free, needed):
            devs = must + list(combo)
            s = score(devs)
            if best_score is None or s < best_score:
                best, best_score = devs, s
        return best

    def _window_preferred(self, available, size, must):
        """Contiguous index-window fallback: smallest index spread that
        still satisfies must_include."""
        ordered = sorted(available, key=self._chip_index)
        rest = [d for d in ordered if d not in must]
        best = (must + rest)[:size]
        best_spread = None
        for start in range(0, max(1, len(ordered) - size + 1)):
            window = ordered[start : start + size]
            if len(window) < size or not all(m in window for m in must):
                continue
            spread = self._chip_index(window[-1]) - self._chip_index(window[0])
            if best_spread is None or spread < best_spread:
                best, best_spread = window, spread
        return best

    def Allocate(self, request, context):
        """Per-container device nodes + libtpu mount + TPU env (the
        container-toolkit's job on GPUs collapses into this)."""
        responses = []
        for creq in request.container_requests:
            ids = list(creq.devicesIDs)
            # replicated ids (chip sharing) collapse back onto their
            # physical device node
            physical = []
            for dev_id in ids:
                phys = dev_id.split("-rep")[0]
                if phys not in physical:
                    physical.append(phys)
            devices = [
                pb.DeviceSpec(
                    container_path=f"/dev/{dev_id}",
                    host_path=f"/dev/{dev_id}",
                    permissions="rw",
                )
                for dev_id in physical
            ]
            mounts = [
                pb.Mount(container_path=self.install_dir, host_path=self.install_dir, read_only=True)
            ]
            # chip indices come from the device ids themselves (accel2 ->
            # chip 2): the env must match the /dev nodes actually injected
            chip_ids = [re.sub(r"\D", "", dev_id) or dev_id for dev_id in physical]
            envs = {
                "TPU_VISIBLE_CHIPS": ",".join(chip_ids),
                "TPU_LIBRARY_PATH": os.path.join(self.install_dir, "libtpu.so"),
            }
            responses.append(
                pb.ContainerAllocateResponse(envs=envs, mounts=mounts, devices=devices)
            )
        return pb.AllocateResponse(container_responses=responses)

    def PreStartContainer(self, request, context):
        return pb.PreStartContainerResponse()

    # -- lifecycle ------------------------------------------------------------

    def _handlers(self) -> grpc.GenericRpcHandler:
        return grpc.method_handlers_generic_handler(
            "v1beta1.DevicePlugin",
            {
                "GetDevicePluginOptions": _unary(self.GetDevicePluginOptions, pb.Empty),
                "ListAndWatch": _stream(self.ListAndWatch, pb.Empty),
                "GetPreferredAllocation": _unary(self.GetPreferredAllocation, pb.PreferredAllocationRequest),
                "Allocate": _unary(self.Allocate, pb.AllocateRequest),
                "PreStartContainer": _unary(self.PreStartContainer, pb.PreStartContainerRequest),
            },
        )

    def serve(self) -> str:
        try:
            os.remove(self.socket_path)
        except FileNotFoundError:
            pass
        os.makedirs(self.socket_dir, exist_ok=True)
        server = grpc.server(thread_pool=_pool())
        server.add_generic_rpc_handlers((self._handlers(),))
        server.add_insecure_port(f"unix://{self.socket_path}")
        server.start()
        self._server = server
        return self.socket_path

    def register(self, kubelet_socket: Optional[str] = None) -> None:
        """Dial the kubelet Registration service and announce ourselves."""
        kubelet_socket = kubelet_socket or os.path.join(self.socket_dir, "kubelet.sock")
        channel = grpc.insecure_channel(f"unix://{kubelet_socket}")
        register = channel.unary_unary(
            "/v1beta1.Registration/Register",
            request_serializer=lambda msg: msg.SerializeToString(),
            response_deserializer=pb.Empty.FromString,
        )
        register(
            pb.RegisterRequest(
                version=API_VERSION,
                endpoint=PLUGIN_SOCKET_NAME,
                resource_name=self.resource_name,
            ),
            timeout=10,
        )
        channel.close()
        log.info("registered %s with kubelet (%d device(s))", self.resource_name, len(self.discover()))

    def health_tick(self) -> bool:
        """One health pass: re-probe /dev/accel*, merge the health
        agent's verdicts, and publish a ListAndWatch update ONLY on
        change (a yanked device transitions to Unhealthy, a restored one
        back to Healthy). Returns True when an update was published."""
        current = self.current_health()
        if current == self._last_health:
            return False
        self._last_health = current
        self._publish(current)
        return True

    def health_loop(self, kubelet_socket: Optional[str] = None) -> None:
        """Re-probe and re-publish the per-device health each tick (chip
        hotplug, driver restart, health-agent verdicts), and re-serve +
        re-register when the kubelet restarts — a kubelet restart wipes
        /var/lib/kubelet/device-plugins/ including our socket, and the
        v1beta1 contract requires plugins to register again."""
        while not self._stop.is_set():
            self.health_tick()
            if not os.path.exists(self.socket_path):
                log.warning("plugin socket vanished (kubelet restart?); re-registering")
                try:
                    if self._server is not None:
                        self._server.stop(grace=1)
                    self.serve()
                    self.register(kubelet_socket)
                except Exception as e:  # noqa: BLE001 — retry next tick
                    log.warning("re-registration failed: %s", e)
            self._stop.wait(self.health_check_interval)

    def _publish(self, inventory) -> None:
        with self._sub_lock:
            for sub in self._subscribers:
                sub.put(inventory)

    def run_forever(self, kubelet_socket: Optional[str] = None) -> None:
        self._last_health = self.current_health()
        self.serve()
        self.register(kubelet_socket)
        self.health_loop(kubelet_socket)

    def stop(self) -> None:
        self._stop.set()
        if self._server is not None:
            self._server.stop(grace=1)


def select_plugin_config(client, node_name: str, configmap_name: str, namespace: str, default: str = "") -> dict:
    """Named-config selection (reference: handleDevicePluginConfig
    object_controls.go:2355-2466): the ConfigMap holds one entry per named
    config (YAML); a node opts into one via the PLUGIN_CONFIG_LABEL label,
    else ``default`` applies. Returns {} when nothing is configured."""
    import yaml

    if not configmap_name or client is None:
        return {}
    cm = client.get_or_none("v1", "ConfigMap", configmap_name, namespace)
    if cm is None:
        return {}
    data = cm.get("data", {}) or {}
    wanted = default
    if node_name:
        node = client.get_or_none("v1", "Node", node_name)
        if node is not None:
            wanted = (node["metadata"].get("labels") or {}).get(PLUGIN_CONFIG_LABEL, default)
    raw = data.get(wanted, "")
    if not raw:
        return {}
    try:
        return yaml.safe_load(raw) or {}
    except yaml.YAMLError:
        log.warning("plugin config %r in %s is invalid YAML", wanted, configmap_name)
        return {}


def _pool():
    from concurrent import futures

    return futures.ThreadPoolExecutor(max_workers=8)


def main() -> int:
    logging.basicConfig(level=logging.INFO)
    config = {}
    configmap = os.environ.get("PLUGIN_CONFIG_MAP", "")
    if configmap and os.environ.get("KUBERNETES_SERVICE_HOST"):
        try:
            from tpu_operator.kube.http_client import HttpClient

            config = select_plugin_config(
                HttpClient.in_cluster(),
                os.environ.get("NODE_NAME", ""),
                configmap,
                os.environ.get("OPERATOR_NAMESPACE", consts.DEFAULT_OPERATOR_NAMESPACE),
                default=os.environ.get("PLUGIN_CONFIG_DEFAULT", ""),
            )
        except Exception as e:  # noqa: BLE001 — config is optional: a 403/
            # network error must degrade to defaults, never crash-loop the
            # plugin (that would take down TPU scheduling on the node)
            log.warning("plugin config unavailable (%s); using defaults", e)
        log.info("plugin config: %s", config or "(none)")
    plugin = TPUDevicePlugin(
        # KUBELET_SOCKET_DIR: the kubelet's device-plugin dir is a fixed
        # host path in production; overridable so the image smoke can run
        # the real entrypoint against a stub kubelet socket
        socket_dir=os.environ.get("KUBELET_SOCKET_DIR", KUBELET_SOCKET_DIR),
        install_dir=os.environ.get("LIBTPU_INSTALL_DIR", consts.LIBTPU_INSTALL_DIR),
        config=config,
    )
    plugin.run_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
