"""tpu-metrics-exporter agent (the dcgm + dcgm-exporter analog).

One operand where NVIDIA needs two (DCGM daemon + exporter): libtpu's
runtime stats are reachable in-process, so the exporter collects and
serves in one loop. Exported series (tpu swap of dcgm-exporter's
DCGM_FI_DEV_*):

    tpu_exporter_chips                visible TPU chips
    tpu_exporter_hbm_used_bytes       per-chip HBM in use (libtpu
                                      memory_stats via the jax runtime)
    tpu_exporter_hbm_limit_bytes      per-chip HBM capacity
    tpu_exporter_hbm_bandwidth_gbps   measured pallas-triad HBM bandwidth
    tpu_exporter_ici_bandwidth_gbps   measured psum all-reduce bus
                                      bandwidth per chip (multi-chip
                                      hosts only — the NVLink/DCGM
                                      counter analog; absent on 1 chip)
    tpu_exporter_matmul_tflops        measured bf16 matmul throughput
    tpu_exporter_mxu_utilization_pct  matmul_tflops / generation peak

Utilization is an ACTIVE probe (calibrated matmul burst), not a passive
busy-fraction counter: no passive source exists on every deployment —
PJRT memory_stats carries no duty-cycle key, and relay-attached chips
expose neither /dev/accel nor libtpu's runtime-metrics gRPC (probed
round 3; native/tpuinfo.cc reads the device nodes where they do exist).
The probe measures what the DCGM-utilization analog actually promises:
the fraction of the chip's compute the node can currently deliver.

Unlike DCGM's passive counters, the active probes BORROW the chip: a
burst steals MXU/HBM time from any co-resident tenant. Deployments
control this via ``TPU_EXPORTER_ACTIVE_PROBES``:

    auto (default)  probe, but treat an unacquirable runtime/chip as
                    "allocated to a tenant" and skip quietly (on
                    single-client runtimes, successfully acquiring the
                    chip implies nobody else holds it — that is the
                    allocation gate)
    on              probe and count every failure as a collect error
    off             never run active probes (passive stats only)

and ``TPU_EXPORTER_PROBE_INTERVAL`` (seconds between probe bursts,
default 600).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Optional

import prometheus_client

log = logging.getLogger(__name__)


class MetricsExporterAgent:
    def __init__(
        self,
        node_name: str = "",
        port: int = 8431,
        interval: float = 30.0,
        bandwidth_probe_interval: float = 600.0,
        active_probes: str = "auto",
        registry: Optional[prometheus_client.CollectorRegistry] = None,
    ):
        if active_probes not in ("auto", "on", "off"):
            raise ValueError(f"active_probes must be auto/on/off, got {active_probes!r}")
        self.node_name = node_name or "unknown"
        self.port = port
        self.interval = interval
        self.bandwidth_probe_interval = bandwidth_probe_interval
        self.active_probes = active_probes
        self.registry = registry or prometheus_client.CollectorRegistry()
        self.chips = prometheus_client.Gauge(
            "tpu_exporter_chips", "Visible TPU chips", ["node"], registry=self.registry
        )
        self.hbm_used = prometheus_client.Gauge(
            "tpu_exporter_hbm_used_bytes", "HBM bytes in use", ["node", "chip"], registry=self.registry
        )
        self.hbm_limit = prometheus_client.Gauge(
            "tpu_exporter_hbm_limit_bytes", "HBM bytes capacity", ["node", "chip"], registry=self.registry
        )
        self.hbm_bandwidth = prometheus_client.Gauge(
            "tpu_exporter_hbm_bandwidth_gbps",
            "Measured triad HBM bandwidth",
            ["node"],
            registry=self.registry,
        )
        self.ici_bandwidth = prometheus_client.Gauge(
            "tpu_exporter_ici_bandwidth_gbps",
            "Measured psum all-reduce bus bandwidth per chip (multi-chip hosts)",
            ["node"],
            registry=self.registry,
        )
        self.matmul_tflops = prometheus_client.Gauge(
            "tpu_exporter_matmul_tflops",
            "Measured bf16 matmul throughput",
            ["node"],
            registry=self.registry,
        )
        self.mxu_utilization = prometheus_client.Gauge(
            "tpu_exporter_mxu_utilization_pct",
            "Measured matmul throughput as % of the generation's MXU peak",
            ["node"],
            registry=self.registry,
        )
        self.collect_errors = prometheus_client.Counter(
            "tpu_exporter_collect_errors_total", "Collection failures", ["node"], registry=self.registry
        )
        self._stop = threading.Event()

    # -- collection -----------------------------------------------------------

    def collect_device_stats(self) -> None:
        """Chip inventory + HBM occupancy from the libtpu-backed runtime."""
        try:
            import jax

            devices = jax.local_devices()
        except Exception as e:  # noqa: BLE001 — no runtime -> no chips
            log.warning("metrics: jax runtime unavailable: %s", e)
            self.collect_errors.labels(self.node_name).inc()
            self.chips.labels(self.node_name).set(0)
            return
        self.chips.labels(self.node_name).set(len(devices))
        for dev in devices:
            chip = str(getattr(dev, "id", dev))
            try:
                stats = dev.memory_stats() or {}
            except Exception:  # noqa: BLE001 — some platforms expose none
                stats = {}
            if "bytes_in_use" in stats:
                self.hbm_used.labels(self.node_name, chip).set(stats["bytes_in_use"])
            if "bytes_limit" in stats:
                self.hbm_limit.labels(self.node_name, chip).set(stats["bytes_limit"])

    def probe_bandwidth(self) -> None:
        """Occasional active probe — the pallas triad — for achievable HBM
        bandwidth (the ICI-bandwidth analog lives in the slice validator)."""
        try:
            from tpu_operator.workloads.kernels import hbm_bandwidth_probe

            report = hbm_bandwidth_probe(size_mb=64, iters=25)
            self.hbm_bandwidth.labels(self.node_name).set(report["bandwidth_gbps"])
        except Exception as e:  # noqa: BLE001
            self._probe_failed("bandwidth", e)

    def probe_ici(self) -> None:
        """Active inter-chip probe — chained psum all-reduce over every
        local chip — for achieved ICI bus bandwidth per chip (the
        NVLink-counter analog; DCGM reads passive counters, TPUs expose
        none here). Single-chip nodes have no ICI: the gauge stays
        absent rather than reporting a loopback artifact."""
        try:
            import jax

            devices = jax.local_devices()
            if len(devices) < 2:
                return
            from tpu_operator.workloads.allreduce import run_allreduce

            ar = run_allreduce(sizes_mb=(16,), iters=10, devices=devices)
            self.ici_bandwidth.labels(self.node_name).set(
                ar["peak_busbw_gbps_per_chip"]
            )
        except Exception as e:  # noqa: BLE001
            self._probe_failed("ici", e)

    def probe_utilization(self) -> None:
        """Active compute probe: achieved bf16 matmul TFLOP/s (and % of the
        generation's MXU peak when known) — the DCGM-utilization analog."""
        try:
            import jax

            from tpu_operator.workloads.matmul_bench import (
                PEAK_TFLOPS,
                chip_generation,
                matmul_tflops,
            )

            on_tpu = jax.local_devices()[0].platform == "tpu"
            # the 8192/16 configuration matches the headline probe: shorter
            # chains under-resolve per-iter time and can report >100% peak
            report = matmul_tflops(
                size=8192 if on_tpu else 256, iters=16 if on_tpu else 2
            )
            self.matmul_tflops.labels(self.node_name).set(report["tflops"])
            # generation from the runtime's device_kind: rendered pods set
            # no generation env var, so an env-only lookup would leave the
            # utilization gauge silently absent in-cluster
            gen = chip_generation()
            if on_tpu and gen in PEAK_TFLOPS and not report.get("unstable_timing"):
                self.mxu_utilization.labels(self.node_name).set(
                    100.0 * report["tflops"] / PEAK_TFLOPS[gen]
                )
        except Exception as e:  # noqa: BLE001
            self._probe_failed("utilization", e)

    def _probe_failed(self, what: str, exc: Exception) -> None:
        """In auto mode an unacquirable chip means a tenant owns it (the
        single-client runtime rejects a second client): skip quietly
        rather than spam collect_errors every cycle. ``on`` means the
        operator asked for unconditional probing — count the failure."""
        if self.active_probes == "auto":
            log.info("metrics: %s probe skipped (chip busy or unavailable): %s", what, exc)
            return
        log.warning("metrics: %s probe failed: %s", what, exc)
        self.collect_errors.labels(self.node_name).inc()

    # -- server ---------------------------------------------------------------

    def run_forever(self) -> None:
        prometheus_client.start_http_server(self.port, registry=self.registry)
        last_probe = 0.0
        while not self._stop.is_set():
            self.collect_device_stats()
            now = time.monotonic()
            if (
                self.active_probes != "off"
                and now - last_probe >= self.bandwidth_probe_interval
            ):
                self.probe_bandwidth()
                self.probe_utilization()
                self.probe_ici()
                last_probe = now
            self._stop.wait(self.interval)

    def stop(self) -> None:
        self._stop.set()


def main() -> int:
    logging.basicConfig(level=logging.INFO)
    import argparse

    p = argparse.ArgumentParser("tpu-metrics-exporter")
    p.add_argument("--port", type=int, default=None)
    args = p.parse_args()
    port = args.port
    if port is None:
        # env fallback resolved AFTER flag parsing, tolerantly: a malformed
        # METRICS_PORT must not crash-loop the exporter
        try:
            port = int(os.environ.get("METRICS_PORT", "8431").strip())
        except ValueError:
            log.warning("invalid METRICS_PORT %r; using 8431", os.environ.get("METRICS_PORT"))
            port = 8431
    active = os.environ.get("TPU_EXPORTER_ACTIVE_PROBES", "auto").strip().lower()
    if active not in ("auto", "on", "off"):
        log.warning("invalid TPU_EXPORTER_ACTIVE_PROBES %r; using auto", active)
        active = "auto"
    try:
        probe_interval = float(os.environ.get("TPU_EXPORTER_PROBE_INTERVAL", "600").strip())
    except ValueError:
        log.warning(
            "invalid TPU_EXPORTER_PROBE_INTERVAL %r; using 600",
            os.environ.get("TPU_EXPORTER_PROBE_INTERVAL"),
        )
        probe_interval = 600.0
    MetricsExporterAgent(
        node_name=os.environ.get("NODE_NAME", ""),
        port=port,
        bandwidth_probe_interval=probe_interval,
        active_probes=active,
    ).run_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
