"""tpu-metrics-exporter agent (the dcgm + dcgm-exporter analog).

One operand where NVIDIA needs two (DCGM daemon + exporter): libtpu's
runtime stats are reachable in-process, so the exporter collects and
serves in one loop. Exported series (tpu swap of dcgm-exporter's
DCGM_FI_DEV_*):

    tpu_exporter_chips                visible TPU chips
    tpu_exporter_hbm_used_bytes       per-chip HBM in use (libtpu
                                      memory_stats via the jax runtime)
    tpu_exporter_hbm_limit_bytes      per-chip HBM capacity
    tpu_exporter_hbm_bandwidth_gbps   measured pallas-triad HBM bandwidth
    tpu_exporter_ici_bandwidth_gbps   measured psum all-reduce bus
                                      bandwidth per chip (multi-chip
                                      hosts only — the NVLink/DCGM
                                      counter analog; absent on 1 chip)
    tpu_exporter_matmul_tflops        measured bf16 matmul throughput
    tpu_exporter_mxu_utilization_pct  matmul_tflops / generation peak

Utilization is an ACTIVE probe (calibrated matmul burst), not a passive
busy-fraction counter: no passive source exists on every deployment —
PJRT memory_stats carries no duty-cycle key, and relay-attached chips
expose neither /dev/accel nor libtpu's runtime-metrics gRPC (probed
round 3; native/tpuinfo.cc reads the device nodes where they do exist).
The probe measures what the DCGM-utilization analog actually promises:
the fraction of the chip's compute the node can currently deliver.

Unlike DCGM's passive counters, the active probes BORROW the chip: a
burst steals MXU/HBM time from any co-resident tenant. Deployments
control this via ``TPU_EXPORTER_ACTIVE_PROBES``:

    auto (default)  probe, but treat an unacquirable runtime/chip as
                    "allocated to a tenant" and skip quietly (on
                    single-client runtimes, successfully acquiring the
                    chip implies nobody else holds it — that is the
                    allocation gate)
    on              probe and count every failure as a collect error
    off             never run active probes (passive stats only)

and ``TPU_EXPORTER_PROBE_INTERVAL`` (seconds between probe bursts,
default 600).

Grey-failure detection (the data-plane telemetry pipeline's middle
layer): the exporter compares its measured matmul/triad probes against
the per-generation perf floors the operator publishes
(``consts.PERF_FLOORS_CONFIGMAP``, seeded from the measured BENCH roofs
in ``tpu_operator/perf.py`` and delivered to this pod as the
``PERF_FLOORS_JSON`` env via configMapKeyRef), maintains a rolling
baseline per probe, and after ``PERF_BREACH_SAMPLES`` consecutive
samples below floor publishes ``tpu_exporter_perf_degraded{node,probe}``
plus the ``tpu.google.com/perf=degraded`` node label — the signal the
health controller's grey-failure FSM path and the placement engine's
availability predicate consume, so a slow chip leaves its gang the same
way a dead one does. The label clears the same way it sets: a sample at
or above floor resets the breach counter and un-labels the node.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time
from typing import Dict, Optional

import prometheus_client

from tpu_operator import consts
from tpu_operator.controllers.operator_metrics import _get_or_create
from tpu_operator.kube import errors

log = logging.getLogger(__name__)

# rolling-baseline window per probe: enough history that the baseline
# gauge reflects the node's recent normal, small enough to stay O(1)
_BASELINE_WINDOW = 20


class MetricsExporterAgent:
    def __init__(
        self,
        node_name: str = "",
        port: int = 8431,
        interval: float = 30.0,
        bandwidth_probe_interval: float = 600.0,
        active_probes: str = "auto",
        registry: Optional[prometheus_client.CollectorRegistry] = None,
        client=None,
        floors: Optional[Dict[str, float]] = None,
        breach_samples: int = consts.PERF_BREACH_SAMPLES,
        namespace: str = consts.DEFAULT_OPERATOR_NAMESPACE,
        generation: str = "",
    ):
        if active_probes not in ("auto", "on", "off"):
            raise ValueError(f"active_probes must be auto/on/off, got {active_probes!r}")
        self.node_name = node_name or "unknown"
        self.port = port
        self.interval = interval
        self.bandwidth_probe_interval = bandwidth_probe_interval
        self.active_probes = active_probes
        self.registry = registry or prometheus_client.CollectorRegistry()
        # optional apiserver client: grey-failure detection publishes the
        # perf label through it; without one the Prometheus series still
        # flip but the cluster-side signal stays unpublished
        self.client = client
        # {probe: floor} for THIS node's generation (resolved by the
        # caller / main() from PERF_FLOORS_JSON); empty = detection off.
        # refresh_floors() re-reads the floors ConfigMap each probe
        # cycle, so a floor the operator tightens (e.g. the autotune
        # loop folding measured roofs) applies to the very next
        # comparison instead of waiting for a DaemonSet restart.
        self.floors = dict(floors or {})
        self.namespace = namespace
        # the generation the floors are keyed by (resolved by main()
        # from the runtime); empty disables hot-reload
        self.generation = generation
        self.breach_samples = max(1, breach_samples)
        self._probe_history: Dict[str, collections.deque] = {}
        self._breach_counts: Dict[str, int] = {}
        self._degraded_probes: set = set()
        self._seen_chips: set = set()  # chip ids with live per-chip series
        self._perf_label_state: Optional[bool] = None  # last published
        # collector construction is idempotent against any shared
        # registry (same _get_or_create contract as OperatorMetrics): a
        # second in-process exporter — drills boot one per simulated
        # node into one registry — reuses the series instead of tripping
        # the duplicate-registration ValueError
        reg = self.registry
        self.chips = _get_or_create(
            prometheus_client.Gauge, "tpu_exporter_chips", "Visible TPU chips",
            ["node"], registry=reg,
        )
        self.hbm_used = _get_or_create(
            prometheus_client.Gauge, "tpu_exporter_hbm_used_bytes",
            "HBM bytes in use", ["node", "chip"], registry=reg,
        )
        self.hbm_limit = _get_or_create(
            prometheus_client.Gauge, "tpu_exporter_hbm_limit_bytes",
            "HBM bytes capacity", ["node", "chip"], registry=reg,
        )
        self.hbm_bandwidth = _get_or_create(
            prometheus_client.Gauge, "tpu_exporter_hbm_bandwidth_gbps",
            "Measured triad HBM bandwidth", ["node"], registry=reg,
        )
        self.ici_bandwidth = _get_or_create(
            prometheus_client.Gauge, "tpu_exporter_ici_bandwidth_gbps",
            "Measured psum all-reduce bus bandwidth per chip (multi-chip hosts)",
            ["node"], registry=reg,
        )
        self.matmul_tflops = _get_or_create(
            prometheus_client.Gauge, "tpu_exporter_matmul_tflops",
            "Measured bf16 matmul throughput", ["node"], registry=reg,
        )
        self.mxu_utilization = _get_or_create(
            prometheus_client.Gauge, "tpu_exporter_mxu_utilization_pct",
            "Measured matmul throughput as % of the generation's MXU peak",
            ["node"], registry=reg,
        )
        self.collect_errors = _get_or_create(
            prometheus_client.Counter, "tpu_exporter_collect_errors_total",
            "Collection failures", ["node"], registry=reg,
        )
        self.perf_floor = _get_or_create(
            prometheus_client.Gauge, "tpu_exporter_perf_floor",
            "Per-generation perf floor this probe is held to",
            ["node", "probe"], registry=reg,
        )
        self.probe_baseline = _get_or_create(
            prometheus_client.Gauge, "tpu_exporter_probe_baseline",
            "Rolling median of recent probe samples (the node's normal)",
            ["node", "probe"], registry=reg,
        )
        self.perf_degraded = _get_or_create(
            prometheus_client.Gauge, "tpu_exporter_perf_degraded",
            "1 while the probe has sustained below its floor (N "
            "consecutive samples) — a grey failure, not a dead chip",
            ["node", "probe"], registry=reg,
        )
        self._stop = threading.Event()

    # -- collection -----------------------------------------------------------

    def collect_device_stats(self) -> None:
        """Chip inventory + HBM occupancy from the libtpu-backed runtime."""
        try:
            import jax

            devices = jax.local_devices()
        except Exception as e:  # noqa: BLE001 — no runtime -> no chips
            log.warning("metrics: jax runtime unavailable: %s", e)
            self.collect_errors.labels(self.node_name).inc()
            self.chips.labels(self.node_name).set(0)
            self._retire_stale_series(chips=0)
            self._retire_vanished_chips(set())
            return
        self.chips.labels(self.node_name).set(len(devices))
        self._retire_stale_series(chips=len(devices))
        present = set()
        for dev in devices:
            chip = str(getattr(dev, "id", dev))
            present.add(chip)
            try:
                stats = dev.memory_stats() or {}
            except Exception:  # noqa: BLE001 — some platforms expose none
                stats = {}
            if "bytes_in_use" in stats:
                self.hbm_used.labels(self.node_name, chip).set(stats["bytes_in_use"])
            if "bytes_limit" in stats:
                self.hbm_limit.labels(self.node_name, chip).set(stats["bytes_limit"])
        self._retire_vanished_chips(present)

    # -- stale-series hygiene -------------------------------------------------

    def _retire_vanished_chips(self, present: set) -> None:
        """Per-chip HBM series of chips no longer visible go with the
        chips: a vanished chip frozen at 95% HBM would keep the
        near-capacity alert firing for hardware that no longer exists."""
        for chip in self._seen_chips - present:
            for gauge in (self.hbm_used, self.hbm_limit):
                try:
                    gauge.remove(self.node_name, chip)
                except KeyError:
                    pass
        self._seen_chips = set(present)

    def _remove_probe_series(self, probe: str) -> None:
        """Drop one probe's floor/baseline/degraded series and its
        detection state — without touching the node perf label (hardware
        going away is the health agent's verdict to make, and "the probe
        can no longer run" is not recovery evidence)."""
        for gauge in (self.perf_floor, self.probe_baseline, self.perf_degraded):
            try:
                gauge.remove(self.node_name, probe)
            except KeyError:
                pass
        self._probe_history.pop(probe, None)
        self._breach_counts.pop(probe, None)
        self._degraded_probes.discard(probe)

    def _retire_stale_series(self, chips: int) -> None:
        """Stale-series hygiene, same discipline as fleet telemetry's
        torn-down gang series: a gauge that outlives its hardware keeps
        exporting the last measured value forever (node discovery strips
        the labels, nothing used to strip the series), which reads as "a
        healthy link/chip at exactly yesterday's bandwidth" on every
        dashboard. Chips <= 1 retires the ICI series (no interconnect to
        measure — a frozen value is a phantom link); chips == 0 retires
        every probe-derived series (nothing can probe)."""
        if chips > 1:
            return
        try:
            self.ici_bandwidth.remove(self.node_name)
        except KeyError:
            pass
        self._remove_probe_series("ici_gbps")
        if chips > 0:
            return
        for probe in set(self._probe_history) | set(self.floors):
            self._remove_probe_series(probe)
        for gauge in (
            self.hbm_bandwidth, self.matmul_tflops, self.mxu_utilization
        ):
            try:
                gauge.remove(self.node_name)
            except KeyError:
                pass

    # -- grey-failure detection ----------------------------------------------

    def refresh_floors(self) -> bool:
        """Hot-reload the floor table from the live perf-floors
        ConfigMap (the configMapKeyRef env is frozen at pod start —
        before this, a floor the operator tightened waited for a
        DaemonSet restart to bite). Reads through the agent's apiserver
        client; any failure keeps the current floors (stale-but-sane
        beats detection flapping on apiserver blips). Returns True when
        the table changed."""
        if self.client is None or not self.generation:
            return False
        from tpu_operator.perf import floors_for

        try:
            cm = self.client.get_or_none(
                "v1", "ConfigMap", consts.PERF_FLOORS_CONFIGMAP, self.namespace
            )
        except errors.ApiError as e:
            log.debug("metrics: floors ConfigMap read failed: %s", e)
            return False
        if cm is None:
            return False
        blob = (cm.get("data") or {}).get(consts.PERF_FLOORS_KEY, "")
        fresh = floors_for(self.generation, blob)
        if not fresh or fresh == self.floors:
            return False
        log.info(
            "metrics: perf floors updated for %s: %s -> %s",
            self.generation, self.floors, fresh,
        )
        self.floors = fresh
        return True

    def observe_probe(self, probe: str, value: float) -> bool:
        """Feed one measured probe sample through the floor comparison:
        updates the rolling baseline, counts consecutive below-floor
        samples, flips ``tpu_exporter_perf_degraded{node,probe}`` on
        sustained breach, and (re)publishes the node perf label when the
        node-level verdict changes. Returns True while this probe is in
        sustained breach. A probe with no configured floor only feeds
        the baseline."""
        history = self._probe_history.setdefault(
            probe, collections.deque(maxlen=_BASELINE_WINDOW)
        )
        history.append(value)
        ordered = sorted(history)
        self.probe_baseline.labels(self.node_name, probe).set(
            ordered[len(ordered) // 2]
        )
        floor = self.floors.get(probe)
        if floor is None:
            return False
        self.perf_floor.labels(self.node_name, probe).set(floor)
        if value < floor:
            self._breach_counts[probe] = self._breach_counts.get(probe, 0) + 1
        else:
            self._breach_counts[probe] = 0
        breached = self._breach_counts[probe] >= self.breach_samples
        self.perf_degraded.labels(self.node_name, probe).set(1 if breached else 0)
        if breached and probe not in self._degraded_probes:
            log.warning(
                "metrics: %s sustained below floor on %s (%.2f < %.2f for %d samples)",
                probe, self.node_name, value, floor, self.breach_samples,
            )
            self._degraded_probes.add(probe)
        elif not breached:
            self._degraded_probes.discard(probe)
        self._publish_perf_label()
        return breached

    def _recovery_evidence(self) -> bool:
        """Whether the sampled history AFFIRMS recovery: at least one
        floored probe observed, and every observed floored probe's
        latest sample was at/above floor (breach count 0). A restarted
        exporter starts with empty counters — "no sustained breach YET"
        is not recovery, and clearing a live degraded label on a first
        still-below-floor sample would prematurely uncordon a node the
        FSM is holding at revalidation."""
        sampled = [p for p in self.floors if p in self._probe_history]
        return bool(sampled) and all(self._breach_counts.get(p) == 0 for p in sampled)

    def _publish_perf_label(self) -> None:
        """Set/clear ``tpu.google.com/perf=degraded`` when the node-level
        verdict (any probe in sustained breach) changes. A labels-only
        merge patch, same convention as every other agent writer; a
        failed write retries on the next probe sample (the verdict is
        re-derived every pass, nothing is lost). A clear additionally
        requires positive recovery evidence (see above)."""
        degraded = bool(self._degraded_probes)
        if self.client is None or degraded == self._perf_label_state:
            return
        if not degraded and not self._recovery_evidence():
            return
        try:
            self.client.patch(
                "v1", "Node", self.node_name,
                {"metadata": {"labels": {
                    consts.TPU_PERF_LABEL: consts.PERF_DEGRADED if degraded else None
                }}},
            )
        except errors.ApiError as e:
            log.warning("metrics: perf label publish failed: %s", e)
            return
        self._perf_label_state = degraded
        log.info(
            "metrics: node %s perf label %s", self.node_name,
            "degraded" if degraded else "cleared",
        )

    def probe_bandwidth(self) -> None:
        """Occasional active probe — the pallas triad — for achievable HBM
        bandwidth (the ICI-bandwidth analog lives in the slice validator)."""
        try:
            from tpu_operator.workloads.kernels import hbm_bandwidth_probe

            report = hbm_bandwidth_probe(size_mb=64, iters=25)
            self.hbm_bandwidth.labels(self.node_name).set(report["bandwidth_gbps"])
            if not report.get("unstable_timing"):
                # an unstable slope is a lower bound, not a measurement —
                # feeding it to the floor comparison would brand relay
                # noise a grey failure
                self.observe_probe("triad_gbps", report["bandwidth_gbps"])
        except Exception as e:  # noqa: BLE001
            self._probe_failed("bandwidth", e)

    def probe_ici(self) -> None:
        """Active inter-chip probe — chained psum all-reduce over every
        local chip — for achieved ICI bus bandwidth per chip (the
        NVLink-counter analog; DCGM reads passive counters, TPUs expose
        none here). Single-chip nodes have no ICI: the gauge stays
        absent rather than reporting a loopback artifact."""
        try:
            import jax

            devices = jax.local_devices()
            if len(devices) < 2:
                return
            from tpu_operator.workloads.allreduce import run_allreduce

            ar = run_allreduce(sizes_mb=(16,), iters=10, devices=devices)
            self.ici_bandwidth.labels(self.node_name).set(
                ar["peak_busbw_gbps_per_chip"]
            )
            self.observe_probe("ici_gbps", ar["peak_busbw_gbps_per_chip"])
        except Exception as e:  # noqa: BLE001
            self._probe_failed("ici", e)

    def probe_utilization(self) -> None:
        """Active compute probe: achieved bf16 matmul TFLOP/s (and % of the
        generation's MXU peak when known) — the DCGM-utilization analog."""
        try:
            import jax

            from tpu_operator.workloads.matmul_bench import (
                PEAK_TFLOPS,
                chip_generation,
                matmul_tflops,
            )

            on_tpu = jax.local_devices()[0].platform == "tpu"
            # the 8192/16 configuration matches the headline probe: shorter
            # chains under-resolve per-iter time and can report >100% peak
            report = matmul_tflops(
                size=8192 if on_tpu else 256, iters=16 if on_tpu else 2
            )
            self.matmul_tflops.labels(self.node_name).set(report["tflops"])
            if on_tpu and not report.get("unstable_timing"):
                self.observe_probe("matmul_tflops", report["tflops"])
            # generation from the runtime's device_kind: rendered pods set
            # no generation env var, so an env-only lookup would leave the
            # utilization gauge silently absent in-cluster
            gen = chip_generation()
            if on_tpu and gen in PEAK_TFLOPS and not report.get("unstable_timing"):
                self.mxu_utilization.labels(self.node_name).set(
                    100.0 * report["tflops"] / PEAK_TFLOPS[gen]
                )
        except Exception as e:  # noqa: BLE001
            self._probe_failed("utilization", e)

    def _probe_failed(self, what: str, exc: Exception) -> None:
        """In auto mode an unacquirable chip means a tenant owns it (the
        single-client runtime rejects a second client): skip quietly
        rather than spam collect_errors every cycle. ``on`` means the
        operator asked for unconditional probing — count the failure."""
        if self.active_probes == "auto":
            log.info("metrics: %s probe skipped (chip busy or unavailable): %s", what, exc)
            return
        log.warning("metrics: %s probe failed: %s", what, exc)
        self.collect_errors.labels(self.node_name).inc()

    # -- server ---------------------------------------------------------------

    def run_forever(self) -> None:
        prometheus_client.start_http_server(self.port, registry=self.registry)
        last_probe = 0.0
        while not self._stop.is_set():
            self.collect_device_stats()
            now = time.monotonic()
            if (
                self.active_probes != "off"
                and now - last_probe >= self.bandwidth_probe_interval
            ):
                self.refresh_floors()
                self.probe_bandwidth()
                self.probe_utilization()
                self.probe_ici()
                last_probe = now
            self._stop.wait(self.interval)

    def stop(self) -> None:
        self._stop.set()


def floors_from_env() -> Dict[str, float]:
    """Resolve this node's floor map: the PERF_FLOORS_JSON blob the
    perf-floors ConfigMap delivers (falling back to the built-in
    defaults) keyed by the runtime's chip generation. Off-TPU (or when
    the generation is unrecognized) there is nothing to hold a floor
    to: {} disables detection."""
    from tpu_operator.perf import floors_for
    from tpu_operator.workloads.matmul_bench import chip_generation

    gen = chip_generation()
    if not gen:
        return {}
    return floors_for(gen, os.environ.get("PERF_FLOORS_JSON", ""))


def main() -> int:
    logging.basicConfig(level=logging.INFO)
    import argparse

    p = argparse.ArgumentParser("tpu-metrics-exporter")
    p.add_argument("--port", type=int, default=None)
    args = p.parse_args()
    port = args.port
    if port is None:
        # env fallback resolved AFTER flag parsing, tolerantly: a malformed
        # METRICS_PORT must not crash-loop the exporter
        try:
            port = int(os.environ.get("METRICS_PORT", "8431").strip())
        except ValueError:
            log.warning("invalid METRICS_PORT %r; using 8431", os.environ.get("METRICS_PORT"))
            port = 8431
    active = os.environ.get("TPU_EXPORTER_ACTIVE_PROBES", "auto").strip().lower()
    if active not in ("auto", "on", "off"):
        log.warning("invalid TPU_EXPORTER_ACTIVE_PROBES %r; using auto", active)
        active = "auto"
    try:
        probe_interval = float(os.environ.get("TPU_EXPORTER_PROBE_INTERVAL", "600").strip())
    except ValueError:
        log.warning(
            "invalid TPU_EXPORTER_PROBE_INTERVAL %r; using 600",
            os.environ.get("TPU_EXPORTER_PROBE_INTERVAL"),
        )
        probe_interval = 600.0
    try:
        breach_samples = int(
            os.environ.get("TPU_EXPORTER_BREACH_SAMPLES",
                           str(consts.PERF_BREACH_SAMPLES)).strip()
        )
    except ValueError:
        log.warning(
            "invalid TPU_EXPORTER_BREACH_SAMPLES %r; using %d",
            os.environ.get("TPU_EXPORTER_BREACH_SAMPLES"), consts.PERF_BREACH_SAMPLES,
        )
        breach_samples = consts.PERF_BREACH_SAMPLES
    generation = ""
    try:
        from tpu_operator.workloads.matmul_bench import chip_generation

        generation = chip_generation()
    except Exception as e:  # noqa: BLE001 — no runtime, hot-reload off
        log.warning("chip generation unresolvable: %s", e)
    try:
        floors = floors_from_env()
    except Exception as e:  # noqa: BLE001 — detection off, exporter lives
        log.warning("perf floors unavailable: %s", e)
        floors = {}
    # the apiserver client only carries the perf label; a pod that can't
    # build one (no in-cluster env) still serves every series
    client = None
    try:
        from tpu_operator.kube.http_client import HttpClient

        client = HttpClient.in_cluster()
    except Exception as e:  # noqa: BLE001
        log.warning("apiserver client unavailable (perf label off): %s", e)
    MetricsExporterAgent(
        node_name=os.environ.get("NODE_NAME", ""),
        port=port,
        bandwidth_probe_interval=probe_interval,
        active_probes=active,
        client=client,
        floors=floors,
        breach_samples=breach_samples,
        namespace=os.environ.get(
            consts.OPERATOR_NAMESPACE_ENV, consts.DEFAULT_OPERATOR_NAMESPACE
        ),
        generation=generation,
    ).run_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
