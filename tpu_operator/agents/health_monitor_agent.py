"""tpu-health-monitor agent: continuous per-node TPU health probing.

The NVIDIA reference pairs provisioning with continuous DCGM health
checks; TPUs expose no passive health counters, so this agent probes the
observable surfaces directly every tick:

    chips    /dev/accel* presence vs the chip count the node advertises
             (a yanked chip or dead driver shows up as a missing device
             node) — per-chip verdicts
    libtpu   the installer's ready marker on the host install path
             (consts.LIBTPU_CTR_READY_FILE; a wiped node image or broken
             install loses it)
    plugin   device-plugin socket liveness under the kubelet's
             device-plugins dir (a crashed plugin leaves TPUs
             unschedulable silently)
    matmul   optional cheap matmul sanity burst (reusing the metrics
             exporter's active-probe gating: ``auto`` skips quietly when
             a tenant owns the chip, ``on`` counts failures, ``off``
             never runs it)

Verdicts are published three ways, each feeding a different consumer:

    1. an atomically-written JSON file in ``consts.HEALTH_DIR`` (hostPath
       shared with the device plugin, which flips devices Unhealthy in
       ListAndWatch so the kubelet stops allocating them),
    2. the ``tpu.google.com/tpu.health`` node label + per-chip verdict
       annotation (consumed by the remediation controller),
    3. a ``TPUHealthy`` node status condition + Kubernetes Events on
       transitions (kubectl-describe visibility).

A probe that *fails to run* is indeterminate and changes nothing — only
a successful probe that *observes* degradation flips the verdict (same
contract as the node-discovery agent's probe).
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import time
from typing import Dict, List, Optional

from tpu_operator import consts
from tpu_operator.kube import errors
from tpu_operator.kube.client import Client

log = logging.getLogger(__name__)

HEALTHY = "Healthy"
UNHEALTHY = "Unhealthy"


class HealthMonitorAgent:
    def __init__(
        self,
        client: Optional[Client],
        node_name: str,
        install_dir: str = consts.LIBTPU_INSTALL_DIR,
        socket_dir: str = "/var/lib/kubelet/device-plugins",
        health_dir: str = consts.HEALTH_DIR,
        interval: float = 30.0,
        active_probes: str = "auto",
        expected_chips: Optional[int] = None,
        recorder=None,
    ):
        if active_probes not in ("auto", "on", "off"):
            raise ValueError(f"active_probes must be auto/on/off, got {active_probes!r}")
        self.client = client
        self.node_name = node_name
        self.install_dir = install_dir
        self.socket_dir = socket_dir
        self.health_dir = health_dir
        self.interval = interval
        self.active_probes = active_probes
        self._expected_chips = expected_chips
        if recorder is None and client is not None:
            from tpu_operator.kube.events import EventRecorder

            recorder = EventRecorder(client, "", component="tpu-health-monitor")
        self.recorder = recorder
        self._last_verdict: Optional[str] = None

    # -- probes ---------------------------------------------------------------

    def expected_chips(self, node: Optional[dict] = None) -> Optional[int]:
        """How many chips this node should have: the TFD chips-per-node
        label, else the accelerator catalog (both count PHYSICAL chips).
        Deliberately NOT the google.com/tpu allocatable — device-plugin
        time-slicing replicas inflate it, which would brand every shared
        chip's phantom replicas Unhealthy and auto-repair a healthy node.
        Recomputed each pass (a late-arriving TFD label must win); None
        when the node is unreadable/unrecognized (presence-only then)."""
        if self._expected_chips is not None:
            return self._expected_chips
        if node is None:
            if self.client is None:
                return None
            node = self.client.get_or_none("v1", "Node", self.node_name)
            if node is None:
                return None
        raw = (node["metadata"].get("labels") or {}).get(consts.TFD_CHIPS_PER_NODE_LABEL)
        try:
            if raw is not None:
                return int(raw)
        except ValueError:
            pass
        from tpu_operator.nodeinfo import tpu_info

        info = tpu_info(node)
        return info.chips_per_node if info is not None else None

    def probe_chips(self, node: Optional[dict] = None) -> Optional[Dict[str, str]]:
        """Per-chip verdicts from the device inventory: present devices
        are Healthy, expected-but-absent indices are Unhealthy. None when
        the probe machinery itself failed (indeterminate)."""
        try:
            from tpu_operator.native import tpuinfo

            devices = tpuinfo.probe().get("devices", [])
        except Exception:  # noqa: BLE001 — probe failure is indeterminate
            return None
        verdicts = {os.path.basename(d): HEALTHY for d in devices}
        expected = self.expected_chips(node)
        if expected:
            for i in range(expected):
                verdicts.setdefault(f"accel{i}", UNHEALTHY)
        return verdicts

    def probe_libtpu(self) -> bool:
        """The installer ready-marker the validator's libtpu component
        also gates on — losing it means workloads would load a stale or
        missing libtpu.so."""
        return os.path.exists(os.path.join(self.install_dir, consts.LIBTPU_CTR_READY_FILE))

    def probe_plugin_socket(self) -> bool:
        from tpu_operator.agents.device_plugin_agent import PLUGIN_SOCKET_NAME

        return os.path.exists(os.path.join(self.socket_dir, PLUGIN_SOCKET_NAME))

    def probe_matmul(self) -> Optional[bool]:
        """Cheap matmul sanity burst: does the runtime still deliver
        compute on this node's chips? Returns None (indeterminate) when
        the probe is off, or fails in ``auto`` mode — an unacquirable
        chip usually means a tenant owns it (the single-client runtime
        rejects a second client), which is not unhealth."""
        if self.active_probes == "off":
            return None
        try:
            from tpu_operator.workloads.matmul_bench import matmul_tflops

            report = matmul_tflops(size=256, iters=2)
            return report["tflops"] > 0
        except Exception as e:  # noqa: BLE001
            if self.active_probes == "auto":
                log.info("health: matmul probe skipped (chip busy or unavailable): %s", e)
                return None
            log.warning("health: matmul probe failed: %s", e)
            return False

    def probe(self, node: Optional[dict] = None) -> Optional[dict]:
        """One full probe pass -> report, or None when the chip inventory
        itself was indeterminate (change nothing this tick)."""
        chips = self.probe_chips(node)
        if chips is None:
            return None
        reasons: List[str] = []
        missing = sorted(c for c, v in chips.items() if v != HEALTHY)
        if missing:
            reasons.append(f"missing devices: {','.join(missing)}")
        if not chips:
            reasons.append("no TPU devices visible")
        if not self.probe_libtpu():
            reasons.append("libtpu install marker missing")
        if not self.probe_plugin_socket():
            reasons.append("device-plugin socket absent")
        matmul = self.probe_matmul()
        if matmul is False:
            reasons.append("matmul sanity probe failed")
        verdict = consts.HEALTH_DEGRADED if reasons else consts.HEALTH_HEALTHY
        return {"verdict": verdict, "chips": chips, "reasons": reasons}

    # -- publication ----------------------------------------------------------

    def write_verdicts_file(self, report: dict) -> None:
        """Atomic write so the device plugin never reads a torn file."""
        os.makedirs(self.health_dir, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=self.health_dir, prefix=".verdicts-")
        with os.fdopen(fd, "w") as f:
            json.dump({"verdict": report["verdict"], "chips": report["chips"],
                       "reasons": report["reasons"]}, f)
        os.replace(tmp, os.path.join(self.health_dir, consts.HEALTH_VERDICTS_FILE))

    def _set_condition(self, node: dict, report: dict) -> None:
        """TPUHealthy node condition via the status subresource (the node
        problem-detector convention; a failed write is best-effort — the
        label is the load-bearing signal). Operates on the node object
        the caller already holds — no extra GET per tick."""
        now = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        healthy = report["verdict"] == consts.HEALTH_HEALTHY
        cond = {
            "type": consts.TPU_HEALTH_CONDITION,
            "status": "True" if healthy else "False",
            "reason": "ProbesPassed" if healthy else "ProbeFailed",
            "message": "; ".join(report["reasons"]) or "all health probes passed",
            "lastTransitionTime": now,
        }
        conds = node.setdefault("status", {}).setdefault("conditions", [])
        existing = next((c for c in conds if c.get("type") == cond["type"]), None)
        if existing is not None:
            if existing.get("status") == cond["status"] and existing.get("message") == cond["message"]:
                return
            cond["lastTransitionTime"] = (
                existing.get("lastTransitionTime", now)
                if existing.get("status") == cond["status"]
                else now
            )
            conds[conds.index(existing)] = cond
        else:
            conds.append(cond)
        try:
            self.client.update_status(node)  # tpuop-lint: kinds=v1/Node
        except errors.ApiError as e:
            log.debug("health: condition publish skipped: %s", e)

    def apply_once(self) -> bool:
        """One probe + publish pass; returns True when anything changed.
        The node is fetched ONCE and threaded through the probe (expected
        chips), the label/annotation write, and the condition write."""
        node = (
            self.client.get_or_none("v1", "Node", self.node_name)
            if self.client is not None
            else None
        )
        report = self.probe(node)
        if report is None:
            return False  # indeterminate: keep current state
        self.write_verdicts_file(report)
        if self.client is None or node is None:
            return False
        labels = node["metadata"].setdefault("labels", {})
        annotations = node["metadata"].setdefault("annotations", {})
        chips_json = json.dumps(report["chips"], sort_keys=True)
        previous = labels.get(consts.TPU_HEALTH_LABEL)
        changed = (
            previous != report["verdict"]
            or annotations.get(consts.TPU_HEALTH_CHIPS_ANNOTATION) != chips_json
        )
        # a first-ever healthy verdict is not a transition — only flips
        # (and a node BORN degraded) warrant an Event
        transitioned = previous != report["verdict"] and (
            previous is not None or report["verdict"] == consts.HEALTH_DEGRADED
        )
        if changed:
            labels[consts.TPU_HEALTH_LABEL] = report["verdict"]
            annotations[consts.TPU_HEALTH_CHIPS_ANNOTATION] = chips_json
            if previous != report["verdict"]:
                # the remediation grace period is measured from this stamp
                annotations[consts.TPU_HEALTH_SINCE_ANNOTATION] = str(int(time.time()))
            try:
                # use the server's response (fresh resourceVersion) for
                # the follow-up condition write
                node = self.client.update(node) or node  # tpuop-lint: kinds=v1/Node
            except errors.Conflict:
                return False  # node moved under us; next tick retries
        self._set_condition(node, report)
        if transitioned and self.recorder is not None:
            degraded = report["verdict"] == consts.HEALTH_DEGRADED
            self.recorder.event(
                node,
                "Warning" if degraded else "Normal",
                "TPUHealthDegraded" if degraded else "TPUHealthRestored",
                f"node {self.node_name}: {report['verdict']}"
                + (f" ({'; '.join(report['reasons'])})" if report["reasons"] else ""),
            )
        self._last_verdict = report["verdict"]
        return changed

    def run_forever(self) -> None:
        while True:
            try:
                self.apply_once()
            except errors.ApiError as e:
                log.warning("health-monitor: %s", e)
            time.sleep(self.interval)


def main() -> int:
    logging.basicConfig(level=logging.INFO)
    node_name = os.environ.get("NODE_NAME", "")
    if not node_name:
        log.error("NODE_NAME required")
        return 1
    from tpu_operator.kube.http_client import HttpClient

    try:
        interval = float(os.environ.get("HEALTH_CHECK_INTERVAL", "30").strip())
    except ValueError:
        log.warning(
            "invalid HEALTH_CHECK_INTERVAL %r; using 30s",
            os.environ.get("HEALTH_CHECK_INTERVAL"),
        )
        interval = 30.0
    active = os.environ.get("TPU_HEALTH_ACTIVE_PROBES", "auto").strip().lower()
    if active not in ("auto", "on", "off"):
        log.warning("invalid TPU_HEALTH_ACTIVE_PROBES %r; using auto", active)
        active = "auto"
    HealthMonitorAgent(
        HttpClient.in_cluster(),
        node_name,
        install_dir=os.environ.get("LIBTPU_INSTALL_DIR", consts.LIBTPU_INSTALL_DIR),
        socket_dir=os.environ.get("KUBELET_SOCKET_DIR", "/var/lib/kubelet/device-plugins"),
        health_dir=os.environ.get("HEALTH_DIR", consts.HEALTH_DIR),
        interval=interval,
        active_probes=active,
    ).run_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
