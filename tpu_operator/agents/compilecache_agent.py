"""tpu-compile-cache agent: the elected-node half of the prewarm loop.

The compile-cache controller elects ONE in-service node per generation
with unsatisfied prewarm demand by stamping
``consts.COMPILE_CACHE_ELECTED_LABEL`` — and the prewarm DaemonSet's
nodeSelector includes that label, so this agent only ever runs on an
elected node, holding the node's chips through the ``google.com/tpu``
extended resource for exactly the compile window.

The loop per tick:

  1. read the own Node (election label + generation labels);
  2. read the ``tpu-compile-cache`` ConfigMap: prewarm requests for this
     generation whose content address already has a valid record for
     (generation, topology, model hash, libtpu version) are CACHE HITS:
     zero writes, nothing re-compiles (the compile-once fleet-wide
     contract; a rebooted elected node lands here);
  3. otherwise compile: bind JAX's persistent compilation cache (real
     TPU — the executable serializes to the node cache directory), run
     the serving engine's warmup step, and publish the measured
     duration as the generation's record plus a prewarm ack.

The controller notices the published record, clears the election label
(which descheduled this pod), and the serving controller clears its
satisfied request — the new replica's worker pod then resolves a cache
hit in its own warmup step and starts warm.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Callable, Optional

from tpu_operator import consts
from tpu_operator.kube import errors
from tpu_operator.kube.client import Client
from tpu_operator.nodeinfo import tpu_info
from tpu_operator.workloads.autotune import runtime_fingerprint
from tpu_operator.workloads.compilecache import (
    CompileCacheStore,
    bind_persistent_cache,
    cache_record,
    entry_key,
    parse_entry,
    parse_requests,
)

log = logging.getLogger(__name__)


def default_warm_fn(request: dict, version: str) -> float:
    """The real prewarm: compile the serving engine's programs (decode +
    chunked prefill + page gather — exactly the warmup step a worker
    runs) and return the measured duration. On real TPU the persistent
    cache directory keeps the serialized executables; on the CPU sim the
    measured duration IS the asset."""
    from tpu_operator.workloads.serving import DecodeEngine, ServingModelConfig

    bind_persistent_cache()
    cfg = ServingModelConfig()
    engine = DecodeEngine(cfg)
    started = time.perf_counter()
    engine.warmup(min(cfg.prefill_chunk, cfg.max_seq // 4))
    return time.perf_counter() - started


class CompileCacheAgent:
    def __init__(
        self,
        client: Client,
        node_name: str,
        namespace: str = consts.DEFAULT_OPERATOR_NAMESPACE,
        interval: float = 60.0,
        warm_fn: Optional[Callable[[dict, str], float]] = None,
    ):
        self.client = client
        self.node_name = node_name
        self.namespace = namespace
        self.interval = interval
        # injectable for tests/smokes; the default is the real compile
        self.warm_fn = warm_fn or default_warm_fn
        self._stop = False

    # -- one pass -------------------------------------------------------------

    def reconcile_once(self) -> str:
        """Returns the pass outcome (tests and logs read it):
        ``not-elected`` | ``no-generation`` | ``no-requests`` |
        ``cache-hit`` | ``prewarmed``."""
        node = self.client.get_or_none("v1", "Node", self.node_name)
        if node is None:
            return "not-elected"
        labels = node["metadata"].get("labels") or {}
        if labels.get(consts.COMPILE_CACHE_ELECTED_LABEL) != consts.COMPILE_CACHE_ELECTED:
            # the DaemonSet nodeSelector should make this unreachable,
            # but a just-cleared label can race the pod teardown
            return "not-elected"
        info = tpu_info(node)
        generation = info.generation if info else ""
        if not generation or generation == "unknown":
            log.warning(
                "compilecache: node %s has no recognizable TPU generation",
                self.node_name,
            )
            return "no-generation"
        version = runtime_fingerprint()
        cm = self.client.get_or_none(
            "v1", "ConfigMap", consts.COMPILE_CACHE_CONFIGMAP, self.namespace
        )
        data = (cm or {}).get("data") or {}
        requests = parse_requests(data.get(consts.COMPILE_PREWARM_REQUEST_KEY))
        mine = {
            rid: r for rid, r in requests.items()
            if r.get("generation") == generation
        }
        if not mine:
            return "no-requests"
        entry = parse_entry(data.get(entry_key(generation)))
        pending = {
            rid: r for rid, r in mine.items()
            if cache_record(
                entry, r.get("topology", ""), r.get("model", ""), version
            ) is None
        }
        if not pending:
            # compile-once: every requested executable is already cached
            # for this toolchain — a rebooted elected node issues ZERO
            # writes
            return "cache-hit"
        store = CompileCacheStore(self.client, self.namespace, version)
        for rid in sorted(pending):
            request = pending[rid]
            log.info(
                "compilecache: prewarming %s on %s (libtpu %s)",
                rid, self.node_name, version,
            )
            seconds = self.warm_fn(request, version)
            store.publish(
                generation, request.get("topology", ""),
                request.get("model", ""), seconds,
                source="prewarm", serving=request.get("serving", ""),
                node=self.node_name,
            )
            store.ack(rid, self.node_name, seconds, "prewarmed")
        return "prewarmed"

    # -- loop -----------------------------------------------------------------

    def run_forever(self) -> None:
        while not self._stop:
            try:
                outcome = self.reconcile_once()
                log.info("compilecache: pass outcome %s", outcome)
            except errors.ApiError as e:
                log.warning("compilecache: pass failed: %s", e)
            except Exception:  # noqa: BLE001 — a compile crash must not kill the pod
                log.exception("compilecache: prewarm failed")
            time.sleep(self.interval)

    def stop(self) -> None:
        self._stop = True


def _float_env(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)).strip())
    except ValueError:
        log.warning("invalid %s %r; using %s", name, os.environ.get(name), default)
        return default


def main() -> int:
    logging.basicConfig(level=logging.INFO)
    import argparse

    p = argparse.ArgumentParser("tpu-compile-cache")
    p.add_argument(
        "--oneshot", action="store_true",
        help="run one reconcile pass and exit (image smoke / debugging)",
    )
    args = p.parse_args()
    from tpu_operator.kube.http_client import HttpClient

    client = HttpClient.in_cluster()
    agent = CompileCacheAgent(
        client,
        node_name=os.environ.get("NODE_NAME", ""),
        namespace=os.environ.get(
            consts.OPERATOR_NAMESPACE_ENV, consts.DEFAULT_OPERATOR_NAMESPACE
        ),
        interval=_float_env("COMPILE_CACHE_INTERVAL", 60.0),
    )
    if args.oneshot:
        print(json.dumps({"outcome": agent.reconcile_once()}))
        return 0
    agent.run_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
