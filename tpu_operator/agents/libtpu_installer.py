"""libtpu-installer: the driver-container payload.

Reference: the nvidia driver container (assets/state-driver
0500_daemonset.yaml `command: ["nvidia-driver"]`) compiles + loads kernel
modules; libtpu is a userspace library, so the TPU equivalent is an
atomic versioned install onto the host path that the device plugin mounts
into workload containers:

  1. locate libtpu.so (LIBTPU_PATH env, the bundled pip package, or an
     explicit --source)
  2. copy to <install-dir>/libtpu-<version>.so, atomically repoint the
     libtpu.so symlink (no torn reads for running pods)
  3. write the version file + the installer ready marker the validator's
     libtpu component checks (consts.LIBTPU_CTR_READY_FILE)
  4. keep running (DaemonSet semantics); the startupProbe checks the
     marker
"""

from __future__ import annotations

import argparse
import hashlib
import logging
import os
import shutil
import tempfile
import time
from typing import Optional

from tpu_operator import consts

log = logging.getLogger(__name__)


def find_libtpu(source: Optional[str] = None) -> str:
    """Resolve the libtpu.so shipped in this image."""
    candidates = [source, os.environ.get("LIBTPU_PATH")]
    try:
        import libtpu  # the pip package bundles the .so

        candidates.append(os.path.join(os.path.dirname(libtpu.__file__), "libtpu.so"))
    except ImportError:
        pass
    candidates.append("/usr/lib/libtpu.so")
    for path in candidates:
        if path and os.path.exists(path):
            return path
    raise FileNotFoundError(f"no libtpu.so found (checked {[c for c in candidates if c]})")


def file_digest(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def install(source: str, install_dir: str, version: str = "") -> dict:
    """Idempotent atomic install; returns a report."""
    os.makedirs(install_dir, exist_ok=True)
    digest = file_digest(source)
    version = version or digest[:12]
    versioned = os.path.join(install_dir, f"libtpu-{version}.so")
    link = os.path.join(install_dir, "libtpu.so")
    changed = False
    if not os.path.exists(versioned) or file_digest(versioned) != digest:
        fd, tmp = tempfile.mkstemp(dir=install_dir, prefix=".libtpu-")
        os.close(fd)
        shutil.copyfile(source, tmp)
        os.replace(tmp, versioned)
        changed = True
    # atomically repoint the symlink (or replace a plain file from older
    # installs)
    tmp_link = os.path.join(install_dir, ".libtpu.so.tmp")
    try:
        os.remove(tmp_link)
    except FileNotFoundError:
        pass
    os.symlink(os.path.basename(versioned), tmp_link)
    os.replace(tmp_link, link)
    with open(os.path.join(install_dir, "version"), "w") as f:
        f.write(version + "\n")
    with open(os.path.join(install_dir, consts.LIBTPU_CTR_READY_FILE), "w") as f:
        f.write(digest + "\n")
    log.info("libtpu %s installed at %s (changed=%s)", version, link, changed)
    return {"version": version, "digest": digest, "path": link, "changed": changed}


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser("libtpu-installer")
    p.add_argument("--install-dir", default=os.environ.get("LIBTPU_INSTALL_DIR", consts.LIBTPU_INSTALL_DIR))
    p.add_argument("--source", default=None)
    p.add_argument("--version", default=os.environ.get("LIBTPU_VERSION", ""))
    p.add_argument("--oneshot", action="store_true", help="install and exit (tests/manual)")
    args = p.parse_args(argv)
    report = install(find_libtpu(args.source), args.install_dir, args.version)
    log.info("install report: %s", report)
    if args.oneshot:
        return 0
    # DaemonSet long-run: periodically re-verify (self-heal if the host
    # path is wiped, e.g. node image upgrade)
    while True:
        time.sleep(60)
        try:
            install(find_libtpu(args.source), args.install_dir, args.version)
        except (OSError, FileNotFoundError) as e:
            log.warning("re-verify failed: %s", e)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
