"""tpu-feature-discovery agent (the GFD analog).

Reference: gpu-feature-discovery (templated by assets/gpu-feature-discovery)
publishes per-node GPU attribute labels. This agent derives TPU attributes
for its node — from the GKE-provided labels plus, when available, the
native ``tpuinfo`` device probe — and patches them onto the Node as
``tpu.google.com/*`` labels (BASELINE config 3).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional

from tpu_operator import consts
from tpu_operator.kube import errors
from tpu_operator.kube.client import Client
from tpu_operator.kube.objects import deep_copy
from tpu_operator.nodeinfo import tfd_labels, tpu_info

log = logging.getLogger(__name__)


class TFDAgent:
    def __init__(self, client: Client, node_name: str, interval: float = 60.0):
        self.client = client
        self.node_name = node_name
        self.interval = interval

    def discover(self) -> Optional[dict]:
        """Labels to publish for this node ({} = strip ours, None =
        indeterminate, change nothing). The GKE labels are the source of
        truth for slice identity; the native probe (native/tpuinfo)
        contributes the locally-visible chip count when present.

        tpu_info's bootstrap fallback reads the tpu.google.com labels this
        very agent publishes — so discovery here must start from the
        GKE-only view, or a node whose GKE label disappeared would keep
        looking like a TPU node off our own stale publication forever. The
        fallback view is consulted only when local hardware actually
        exists (the self-managed regime, where the node-discovery
        bootstrap owns the base labels and this agent enriches them)."""
        node = self.client.get("v1", "Node", self.node_name)
        gke_view = deep_copy(node)
        gke_labels = gke_view["metadata"].get("labels") or {}
        for key in consts.TFD_LABELS:
            gke_labels.pop(key, None)
        info = tpu_info(gke_view)
        chips = self._probe_local_chips()  # probe ONCE; reused below
        if info is None and chips:
            info = tpu_info(node)  # discovery-published base labels
        if info is None:
            if chips is None and tpu_info(node) is not None:
                # no GKE identity, probe failed, but discovery labels
                # exist: indeterminate — never strip on a bad probe tick
                return None
            return {}
        labels = tfd_labels(info)
        if chips:  # successful probe that saw chips; 0 keeps catalog value
            labels[consts.TFD_CHIPS_PER_NODE_LABEL] = str(chips)
        return labels

    @staticmethod
    def _probe_local_chips() -> Optional[int]:
        """Locally visible chip count; None when the probe machinery
        failed (distinct from a successful probe seeing 0 chips — only
        the latter may justify treating hardware as absent)."""
        try:
            from tpu_operator.native import tpuinfo

            return int(tpuinfo.probe().get("chip_count") or 0)
        except Exception:  # noqa: BLE001 — native probe is best-effort
            return None

    def apply_once(self) -> bool:
        """Patch the node when discovery differs from current labels."""
        want = self.discover()
        if want is None:
            return False  # indeterminate probe tick: keep current state
        try:
            node = self.client.get("v1", "Node", self.node_name)
        except errors.NotFound:
            return False
        labels = node["metadata"].setdefault("labels", {})
        changed = False
        for key, value in want.items():
            if labels.get(key) != value:
                labels[key] = value
                changed = True
        if not want:
            for key in consts.TFD_LABELS:
                if key in labels:
                    del labels[key]
                    changed = True
        if changed:
            try:
                self.client.update(node)
            except errors.Conflict:
                return False
        return changed

    def run_forever(self) -> None:
        while True:
            try:
                self.apply_once()
            except errors.ApiError as e:
                log.warning("tfd: %s", e)
            time.sleep(self.interval)


def main() -> int:
    logging.basicConfig(level=logging.INFO)
    node_name = os.environ.get("NODE_NAME", "")
    if not node_name:
        log.error("NODE_NAME required")
        return 1
    from tpu_operator.kube.http_client import HttpClient

    try:
        interval = float(os.environ.get("TFD_SLEEP_INTERVAL", "60").strip())
    except ValueError:
        log.warning("invalid TFD_SLEEP_INTERVAL %r; using 60s", os.environ.get("TFD_SLEEP_INTERVAL"))
        interval = 60.0
    TFDAgent(HttpClient.in_cluster(), node_name, interval=interval).run_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
