"""tpu-feature-discovery agent (the GFD analog).

Reference: gpu-feature-discovery (templated by assets/gpu-feature-discovery)
publishes per-node GPU attribute labels. This agent derives TPU attributes
for its node — from the GKE-provided labels plus, when available, the
native ``tpuinfo`` device probe — and patches them onto the Node as
``tpu.google.com/*`` labels (BASELINE config 3).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional

from tpu_operator import consts
from tpu_operator.kube import errors
from tpu_operator.kube.client import Client
from tpu_operator.nodeinfo import tfd_labels, tpu_info

log = logging.getLogger(__name__)


class TFDAgent:
    def __init__(self, client: Client, node_name: str, interval: float = 60.0):
        self.client = client
        self.node_name = node_name
        self.interval = interval

    def discover(self) -> dict:
        """Labels to publish for this node. The GKE labels are the source
        of truth for slice identity; the native probe (native/tpuinfo)
        contributes the locally-visible chip count when present."""
        node = self.client.get("v1", "Node", self.node_name)
        info = tpu_info(node)
        if info is None:
            return {}
        labels = tfd_labels(info)
        chips = self._probe_local_chips()
        if chips is not None:
            labels[consts.TFD_CHIPS_PER_NODE_LABEL] = str(chips)
        return labels

    @staticmethod
    def _probe_local_chips() -> Optional[int]:
        try:
            from tpu_operator.native import tpuinfo

            report = tpuinfo.probe()
            return report["chip_count"] if report.get("chip_count") else None
        except Exception:  # noqa: BLE001 — native probe is best-effort
            return None

    def apply_once(self) -> bool:
        """Patch the node when discovery differs from current labels."""
        want = self.discover()
        try:
            node = self.client.get("v1", "Node", self.node_name)
        except errors.NotFound:
            return False
        labels = node["metadata"].setdefault("labels", {})
        changed = False
        for key, value in want.items():
            if labels.get(key) != value:
                labels[key] = value
                changed = True
        if not want:
            for key in consts.TFD_LABELS:
                if key in labels:
                    del labels[key]
                    changed = True
        if changed:
            try:
                self.client.update(node)
            except errors.Conflict:
                return False
        return changed

    def run_forever(self) -> None:
        while True:
            try:
                self.apply_once()
            except errors.ApiError as e:
                log.warning("tfd: %s", e)
            time.sleep(self.interval)


def main() -> int:
    logging.basicConfig(level=logging.INFO)
    node_name = os.environ.get("NODE_NAME", "")
    if not node_name:
        log.error("NODE_NAME required")
        return 1
    from tpu_operator.kube.http_client import HttpClient

    try:
        interval = float(os.environ.get("TFD_SLEEP_INTERVAL", "60").strip())
    except ValueError:
        log.warning("invalid TFD_SLEEP_INTERVAL %r; using 60s", os.environ.get("TFD_SLEEP_INTERVAL"))
        interval = 60.0
    TFDAgent(HttpClient.in_cluster(), node_name, interval=interval).run_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
