"""TPU node attribute extraction.

Analog of ``internal/nodeinfo`` (node_info.go:34-57, attributes.go:43) —
but where the reference derives attributes from NFD's PCI scan
(pci-10de 0x10de = NVIDIA vendor id, state_manager.go:113-117), TPU nodes
are recognized by the labels GKE stamps on TPU node pools
(``cloud.google.com/gke-tpu-accelerator``, ``-topology``) and attributes
come from a built-in accelerator catalog.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional

from tpu_operator import consts
from tpu_operator.kube.objects import ObjectDict


@dataclasses.dataclass(frozen=True)
class AcceleratorInfo:
    """Facts about one GKE TPU accelerator family."""

    gke_type: str  # cloud.google.com/gke-tpu-accelerator value
    generation: str  # v4 / v5e / v5p / v6e
    chips_per_host: int  # maximum chips attached to one host VM
    topology_dims: int  # 2 = 2D mesh (v5e/v6e), 3 = 3D torus (v4/v5p)


# The accelerator catalog. Values follow Cloud TPU published system
# architecture (chips per VM / topology family per generation).
ACCELERATORS: Dict[str, AcceleratorInfo] = {
    "tpu-v4-podslice": AcceleratorInfo("tpu-v4-podslice", "v4", 4, 3),
    "tpu-v5-lite-podslice": AcceleratorInfo("tpu-v5-lite-podslice", "v5e", 4, 2),
    "tpu-v5-lite-device": AcceleratorInfo("tpu-v5-lite-device", "v5e", 8, 2),
    "tpu-v5p-slice": AcceleratorInfo("tpu-v5p-slice", "v5p", 4, 3),
    "tpu-v6e-slice": AcceleratorInfo("tpu-v6e-slice", "v6e", 4, 2),
}


def parse_topology(topology: str) -> List[int]:
    """'4x4' -> [4, 4]; '2x2x2' -> [2, 2, 2]. Empty/invalid -> []."""
    if not topology:
        return []
    try:
        dims = [int(p) for p in topology.lower().split("x")]
    except ValueError:
        return []
    return dims if all(d > 0 for d in dims) else []


@dataclasses.dataclass
class TPUNodeInfo:
    """Attributes of one TPU node, derived from its labels."""

    node_name: str
    accelerator_type: str  # GKE accelerator type
    topology: str  # e.g. "4x4"
    generation: str
    chips_in_slice: int  # product of topology dims
    chips_per_node: int
    slice_hosts: int  # hosts forming the slice
    nodepool: str

    @property
    def multi_host(self) -> bool:
        return self.slice_hosts > 1


def tpu_info(node: ObjectDict) -> Optional[TPUNodeInfo]:
    """None when the node carries no GKE TPU accelerator label."""
    labels = node.get("metadata", {}).get("labels", {}) or {}
    acc_type = labels.get(consts.GKE_TPU_ACCELERATOR_LABEL, "")
    if not acc_type:
        return None
    acc = ACCELERATORS.get(acc_type)
    topology = labels.get(consts.GKE_TPU_TOPOLOGY_LABEL, "")
    dims = parse_topology(topology)
    chips_in_slice = math.prod(dims) if dims else 0
    chips_per_host = acc.chips_per_host if acc else 4
    chips_per_node = min(chips_in_slice, chips_per_host) if chips_in_slice else chips_per_host
    slice_hosts = max(1, math.ceil(chips_in_slice / chips_per_host)) if chips_in_slice else 1
    return TPUNodeInfo(
        node_name=node["metadata"]["name"],
        accelerator_type=acc_type,
        topology=topology,
        generation=acc.generation if acc else "unknown",
        chips_in_slice=chips_in_slice,
        chips_per_node=chips_per_node,
        slice_hosts=slice_hosts,
        nodepool=labels.get(consts.GKE_NODEPOOL_LABEL, ""),
    )


def is_tpu_node(node: ObjectDict) -> bool:
    return tpu_info(node) is not None


def tfd_labels(info: TPUNodeInfo) -> Dict[str, str]:
    """The labels tpu-feature-discovery publishes for one node
    (BASELINE config 3)."""
    return {
        consts.TFD_ACCELERATOR_TYPE_LABEL: info.accelerator_type,
        consts.TFD_TOPOLOGY_LABEL: info.topology,
        consts.TFD_CHIPS_PER_NODE_LABEL: str(info.chips_per_node),
        consts.TFD_SLICE_HOSTS_LABEL: str(info.slice_hosts),
        consts.TFD_TPU_GENERATION_LABEL: info.generation,
    }
