"""TPU node attribute extraction.

Analog of ``internal/nodeinfo`` (node_info.go:34-57, attributes.go:43) —
but where the reference derives attributes from NFD's PCI scan
(pci-10de 0x10de = NVIDIA vendor id, state_manager.go:113-117), TPU nodes
are recognized by EITHER of two label sources, checked in order:

1. the labels GKE stamps on TPU node pools
   (``cloud.google.com/gke-tpu-accelerator``, ``-topology``), or
2. the vendor-neutral ``tpu.google.com/{accelerator-type,topology}``
   labels published by this operator's own node-discovery DaemonSet
   (agents/node_discovery_agent.py) from the native device probe —
   the NFD-analog bootstrap that makes self-managed (non-GKE) TPU-VM
   clusters work: nothing on such clusters stamps the GKE labels, so
   recognizing only source 1 would leave the operator cloud-locked
   (the reference's NFD-based labelling works on any cluster,
   state_manager.go:481-581).

Attributes come from a built-in accelerator catalog either way.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional

from tpu_operator import consts
from tpu_operator.kube.objects import ObjectDict


@dataclasses.dataclass(frozen=True)
class AcceleratorInfo:
    """Facts about one GKE TPU accelerator family."""

    gke_type: str  # cloud.google.com/gke-tpu-accelerator value
    generation: str  # v4 / v5e / v5p / v6e
    chips_per_host: int  # maximum chips attached to one host VM
    topology_dims: int  # 2 = 2D mesh (v5e/v6e), 3 = 3D torus (v4/v5p)


# The accelerator catalog. Values follow Cloud TPU published system
# architecture (chips per VM / topology family per generation).
ACCELERATORS: Dict[str, AcceleratorInfo] = {
    "tpu-v4-podslice": AcceleratorInfo("tpu-v4-podslice", "v4", 4, 3),
    "tpu-v5-lite-podslice": AcceleratorInfo("tpu-v5-lite-podslice", "v5e", 4, 2),
    "tpu-v5-lite-device": AcceleratorInfo("tpu-v5-lite-device", "v5e", 8, 2),
    "tpu-v5p-slice": AcceleratorInfo("tpu-v5p-slice", "v5p", 4, 3),
    "tpu-v6e-slice": AcceleratorInfo("tpu-v6e-slice", "v6e", 4, 2),
}


def parse_topology(topology: str) -> List[int]:
    """'4x4' -> [4, 4]; '2x2x2' -> [2, 2, 2]. Empty/invalid -> []."""
    if not topology:
        return []
    try:
        dims = [int(p) for p in topology.lower().split("x")]
    except ValueError:
        return []
    return dims if all(d > 0 for d in dims) else []


@dataclasses.dataclass
class TPUNodeInfo:
    """Attributes of one TPU node, derived from its labels."""

    node_name: str
    accelerator_type: str  # GKE accelerator type
    topology: str  # e.g. "4x4"
    generation: str
    chips_in_slice: int  # product of topology dims
    chips_per_node: int
    slice_hosts: int  # hosts forming the slice
    nodepool: str
    # which label set identified the node: "gke" (cloud.google.com/*) or
    # "discovery" (tpu.google.com/* from the node-discovery bootstrap).
    # Selectors built from this info MUST use the same set — the other
    # one does not exist on the node (nodepool.NodePool.selector).
    label_source: str = "gke"

    @property
    def multi_host(self) -> bool:
        return self.slice_hosts > 1


def tpu_info(node: ObjectDict) -> Optional[TPUNodeInfo]:
    """None when the node carries neither the GKE accelerator label nor
    the operator-published discovery label (see module docstring)."""
    labels = node.get("metadata", {}).get("labels", {}) or {}
    source = "gke"
    acc_type = labels.get(consts.GKE_TPU_ACCELERATOR_LABEL, "")
    topology = labels.get(consts.GKE_TPU_TOPOLOGY_LABEL, "")
    if not acc_type:
        # bootstrap path: labels the node-discovery DaemonSet published
        # from the native device probe on a non-GKE cluster
        source = "discovery"
        acc_type = labels.get(consts.TFD_ACCELERATOR_TYPE_LABEL, "")
        topology = labels.get(consts.TFD_TOPOLOGY_LABEL, "")
    if not acc_type:
        return None
    acc = ACCELERATORS.get(acc_type)
    dims = parse_topology(topology)
    chips_in_slice = math.prod(dims) if dims else 0
    # the probe-published local chip count beats catalog defaults when the
    # accelerator type is unknown to the catalog (self-managed bootstrap)
    chips_per_host = acc.chips_per_host if acc else _probed_chips(labels) or 4
    chips_per_node = min(chips_in_slice, chips_per_host) if chips_in_slice else chips_per_host
    slice_hosts = max(1, math.ceil(chips_in_slice / chips_per_host)) if chips_in_slice else 1
    return TPUNodeInfo(
        node_name=node["metadata"]["name"],
        accelerator_type=acc_type,
        topology=topology,
        generation=acc.generation if acc else "unknown",
        chips_in_slice=chips_in_slice,
        chips_per_node=chips_per_node,
        slice_hosts=slice_hosts,
        nodepool=labels.get(consts.GKE_NODEPOOL_LABEL, ""),
        label_source=source,
    )


def _probed_chips(labels: Dict[str, str]) -> int:
    """The local chip count the discovery agent published, or 0."""
    try:
        return max(0, int(labels.get(consts.TFD_CHIPS_PER_NODE_LABEL, "0")))
    except ValueError:
        return 0


def is_tpu_node(node: ObjectDict) -> bool:
    return tpu_info(node) is not None


def tfd_labels(info: TPUNodeInfo) -> Dict[str, str]:
    """The labels tpu-feature-discovery publishes for one node
    (BASELINE config 3)."""
    return {
        consts.TFD_ACCELERATOR_TYPE_LABEL: info.accelerator_type,
        consts.TFD_TOPOLOGY_LABEL: info.topology,
        consts.TFD_CHIPS_PER_NODE_LABEL: str(info.chips_per_node),
        consts.TFD_SLICE_HOSTS_LABEL: str(info.slice_hosts),
        consts.TFD_TPU_GENERATION_LABEL: info.generation,
    }
