"""tpu-operator: a TPU-native Kubernetes operator.

A from-scratch rebuild of the capabilities of the NVIDIA GPU Operator
(reference: elezar/gpu-operator v24.3.0) for Google Cloud TPU nodes. One
cluster-scoped ClusterPolicy CRD drives an ordered state machine that
provisions the whole TPU software stack: libtpu installation, the Cloud TPU
device plugin, tpu-feature-discovery node labels, a slice/topology manager
for multi-host gang scheduling, a libtpu metrics exporter, and an in-cluster
validator whose workload check is a JAX ``jax.lax.psum`` allreduce over ICI.

Layout mirrors the reference's architecture (see SURVEY.md):

- ``kube/``        controller-runtime equivalent (clients, informers, manager)
- ``api/``         CRD types: ClusterPolicy v1, TPUSlice v1alpha1
- ``render/``      manifest template renderer (reference: internal/render)
- ``state/``       state engine v2 (reference: internal/state)
- ``controllers/`` ClusterPolicy / TPUSlice / Upgrade reconcilers
- ``validator/``   node validator operand + JAX payloads
- ``tfd/``         tpu-feature-discovery operand (replaces GFD)
- ``sliceman/``    slice/topology manager operand (replaces mig-manager)
- ``deviceplugin/``kubelet device plugin for google.com/tpu
- ``metrics_exporter/`` libtpu metrics exporter (replaces dcgm-exporter)
"""

from tpu_operator.version import __version__

__all__ = ["__version__"]
