"""Deployment chart rendering: values -> install manifests.

Reference: the Helm chart ``deployments/gpu-operator`` — values.yaml feeds
templates/operator.yaml (the operator Deployment) and
templates/clusterpolicy.yaml (the CR), with CRDs shipped alongside
(crds/). Rendering uses the same jinja2 engine as the operand states, so
``tpuop-cfg render --values deploy/values.yaml | kubectl apply -f -`` is
the helm-install analog.
"""

from __future__ import annotations

import os
from typing import List

import yaml

from tpu_operator.api.common import ImageSpec
from tpu_operator.api.crds import all_crds
from tpu_operator.render import Renderer
from tpu_operator.utils import deep_merge

CHART_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "deploy")


def render_chart(values: dict, chart_dir: str = CHART_DIR) -> List[dict]:
    """CRDs first (like helm's crds/ handling), then templated objects.

    User values deep-merge over the chart's default values.yaml — helm
    semantics — so a partial overrides file produces the same install
    through this path and through ``helm install -f``."""
    defaults_file = os.path.join(chart_dir, "values.yaml")
    if os.path.exists(defaults_file):
        with open(defaults_file) as f:
            values = deep_merge(yaml.safe_load(f) or {}, values or {})
    operator = dict(
        {
            "repository": "gcr.io/tpu-operator",
            "image": "tpu-operator",
            "version": "1.0.0",
            "imagePullPolicy": "IfNotPresent",
            "imagePullSecrets": [],
            "replicas": 1,
            "leaderElect": True,
            "resources": None,
            "extraLabels": {},
        },
        **(values.get("operator") or {}),
    )
    cp_spec = values.get("clusterPolicy") or {}
    webhook = dict(
        {"enabled": False, "failurePolicy": "Fail", "caBundle": "", "tlsCrt": "", "tlsKey": ""},
        **(values.get("webhook") or {}),
    )
    data = {
        "namespace": values.get("namespace", "tpu-operator"),
        "operator": operator,
        "operator_image": ImageSpec.from_dict(operator).image_path("OPERATOR_IMAGE"),
        "cluster_policy_spec": cp_spec,
        "psa_enabled": bool((cp_spec.get("psa") or {}).get("enabled")),
        "webhook": webhook,
    }
    renderer = Renderer([os.path.join(chart_dir, "templates")])
    return all_crds() + renderer.render_objects(data)
