"""helmlite: a minimal Go-template (Helm) renderer for chart verification.

The shipped Helm chart (``deploy/helm/tpu-operator``) is the user-facing
install path (reference: ``deployments/gpu-operator`` — Chart.yaml,
templates/operator.yaml, crds/). This environment carries no ``helm``
binary, so CI proves the chart correct by rendering it with this engine
and asserting object-for-object parity with ``chart.render_chart()``
(see tests/test_helm_chart.py).

The engine implements exactly the text/template + sprig subset the chart
uses — actions with trim markers, ``.Values``/``.Release`` paths,
``if``/``else``/``end``, pipelines, and the functions listed in
``_FUNCTIONS`` — and *raises* on anything else, so a chart edit that
outgrows the verifier fails loudly instead of silently diverging from
what real helm would render. Semantics follow Go:

  - ``{{-``/``-}}`` trim all adjacent whitespace including newlines
  - missing map keys evaluate to None (render as empty, falsey in ``if``)
  - truthiness: nil/false/0/""/empty collection are false
  - ``toYaml`` marshals with sorted keys (sigs.k8s.io/yaml behavior)
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, List, Optional, Tuple

import yaml

from tpu_operator.kube.objects import ObjectDict
from tpu_operator.utils import deep_merge


class HelmliteError(Exception):
    pass


# ---------------------------------------------------------------------------
# functions (sprig subset)
# ---------------------------------------------------------------------------


def _truthy(v: Any) -> bool:
    if v is None or v is False:
        return False
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return v != 0
    if isinstance(v, (str, list, dict, tuple)):
        return len(v) > 0
    return True


def _to_yaml(v: Any) -> str:
    return yaml.safe_dump(v, default_flow_style=False, sort_keys=True).rstrip("\n")


def _gostr(v: Any) -> str:
    """Stringify the way Go's text/template prints values: booleans are
    lowercase, nil is empty."""
    if v is None:
        return ""
    if v is True:
        return "true"
    if v is False:
        return "false"
    return str(v)


def _indent(n: Any, s: Any) -> str:
    pad = " " * int(n)
    return "\n".join(pad + line for line in _gostr(s).splitlines())


_FUNCTIONS = {
    "toYaml": _to_yaml,
    "indent": _indent,
    "nindent": lambda n, s: "\n" + _indent(n, s),
    "quote": lambda v: '"%s"' % _gostr(v).replace("\\", "\\\\").replace('"', '\\"'),
    "default": lambda d, v=None: v if _truthy(v) else d,
    "hasPrefix": lambda prefix, s: str(s).startswith(str(prefix)),
    "not": lambda v: not _truthy(v),
    "and": lambda *a: next((x for x in a if not _truthy(x)), a[-1]),
    "or": lambda *a: next((x for x in a if _truthy(x)), a[-1]),
    "eq": lambda a, b: a == b,
    "ne": lambda a, b: a != b,
}


# ---------------------------------------------------------------------------
# lexer / parser
# ---------------------------------------------------------------------------

_ACTION_RE = re.compile(r"\{\{(-?)\s*(.*?)\s*(-?)\}\}", re.DOTALL)


def _lex(source: str) -> List[Tuple[str, str]]:
    """Split into ('text', s) and ('action', body) tokens with Go trim
    semantics applied to the surrounding text."""
    tokens: List[Tuple[str, str]] = []
    pos = 0
    for m in _ACTION_RE.finditer(source):
        text = source[pos : m.start()]
        if m.group(1) == "-":
            text = text.rstrip()
        tokens.append(("text", text))
        tokens.append(("action", m.group(2)))
        pos = m.end()
        if m.group(3) == "-":
            # trim leading whitespace of the following text
            rest = source[pos:]
            stripped = rest.lstrip()
            pos += len(rest) - len(stripped)
    tokens.append(("text", source[pos:]))
    return [t for t in tokens if t[0] == "action" or t[1]]


class _Node:
    pass


class _Text(_Node):
    def __init__(self, s: str):
        self.s = s


class _Expr(_Node):
    def __init__(self, pipeline: str):
        self.pipeline = pipeline


class _If(_Node):
    def __init__(self):
        # list of (condition-pipeline or None for else, body nodes)
        self.branches: List[Tuple[Optional[str], List[_Node]]] = []


def _parse(tokens: List[Tuple[str, str]], i: int = 0, in_block: bool = False):
    nodes: List[_Node] = []
    while i < len(tokens):
        kind, body = tokens[i]
        if kind == "text":
            nodes.append(_Text(body))
            i += 1
            continue
        if body.startswith("/*"):
            i += 1
            continue
        word = body.split(None, 1)[0] if body else ""
        if word == "if":
            node = _If()
            cond = body[2:].strip()
            while True:
                sub, i, term = _parse(tokens, i + 1, in_block=True)
                node.branches.append((cond, sub))
                if term == "end":
                    break
                if term == "else":
                    # bare else: final branch with condition None
                    sub, i, term2 = _parse(tokens, i + 1, in_block=True)
                    node.branches.append((None, sub))
                    if term2 != "end":
                        raise HelmliteError(f"expected end after else, got {term2}")
                    break
                if term.startswith("else if"):
                    cond = term[len("else if") :].strip()
                    continue
                raise HelmliteError(f"unexpected block terminator {term!r}")
            nodes.append(node)
            i += 1
            continue
        if word in ("end", "else") or body.startswith("else if"):
            if not in_block:
                raise HelmliteError(f"unexpected {body!r} outside a block")
            return nodes, i, body
        if word in ("range", "with", "define", "template", "include", "block"):
            raise HelmliteError(
                f"helmlite does not implement {word!r} — extend _FUNCTIONS/_parse "
                "(and re-check against real helm) before using it in the chart"
            )
        nodes.append(_Expr(body))
        i += 1
    if in_block:
        raise HelmliteError("unterminated block (missing {{ end }})")
    return nodes, i, ""


# ---------------------------------------------------------------------------
# evaluation
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(r'"(?:[^"\\]|\\.)*"|\S+')


def _eval_atom(tok: str, ctx: Dict[str, Any]) -> Any:
    if tok.startswith('"') and tok.endswith('"'):
        return tok[1:-1].replace('\\"', '"').replace("\\\\", "\\")
    if tok in ("true", "false"):
        return tok == "true"
    if tok in ("nil", "null"):
        return None
    if re.fullmatch(r"-?\d+", tok):
        return int(tok)
    if re.fullmatch(r"-?\d+\.\d+", tok):
        return float(tok)
    if tok == ".":
        return ctx
    if tok.startswith("."):
        cur: Any = ctx
        for part in tok[1:].split("."):
            if not part:
                raise HelmliteError(f"bad path {tok!r}")
            if isinstance(cur, dict):
                cur = cur.get(part)
            else:
                cur = None
            if cur is None:
                return None
        return cur
    raise HelmliteError(f"cannot evaluate {tok!r}")


def _eval_segment(tokens: List[str], ctx: Dict[str, Any], piped: Any = ...) -> Any:
    head = tokens[0]
    if head in _FUNCTIONS:
        args = [_eval_atom(t, ctx) for t in tokens[1:]]
        if piped is not ...:
            args.append(piped)
        return _FUNCTIONS[head](*args)
    if len(tokens) != 1 or piped is not ...:
        raise HelmliteError(f"unknown function {head!r}")
    return _eval_atom(head, ctx)


def _eval_pipeline(pipeline: str, ctx: Dict[str, Any]) -> Any:
    value: Any = ...
    for segment in pipeline.split("|"):
        tokens = _TOKEN_RE.findall(segment.strip())
        if not tokens:
            raise HelmliteError(f"empty pipeline segment in {pipeline!r}")
        value = _eval_segment(tokens, ctx, value)
    return value


def _render_nodes(nodes: List[_Node], ctx: Dict[str, Any]) -> str:
    out: List[str] = []
    for node in nodes:
        if isinstance(node, _Text):
            out.append(node.s)
        elif isinstance(node, _Expr):
            out.append(_gostr(_eval_pipeline(node.pipeline, ctx)))
        elif isinstance(node, _If):
            for cond, body in node.branches:
                if cond is None or _truthy(_eval_pipeline(cond, ctx)):
                    out.append(_render_nodes(body, ctx))
                    break
    return "".join(out)


# ---------------------------------------------------------------------------
# chart rendering
# ---------------------------------------------------------------------------


def render_string(source: str, ctx: Dict[str, Any]) -> str:
    nodes, _, _ = _parse(_lex(source))
    return _render_nodes(nodes, ctx)


def template(
    chart_dir: str,
    values: Optional[dict] = None,
    release_name: str = "tpu-operator",
    namespace: str = "default",
) -> List[ObjectDict]:
    """``helm template`` equivalent: chart default values deep-merged with
    overrides, crds/ emitted first (helm installs them before templates),
    then every templates/*.yaml in lexical order."""
    values_file = os.path.join(chart_dir, "values.yaml")
    with open(values_file) as f:
        defaults = yaml.safe_load(f) or {}
    merged = deep_merge(defaults, values or {})
    chart_meta = {}
    chart_yaml = os.path.join(chart_dir, "Chart.yaml")
    if os.path.exists(chart_yaml):
        with open(chart_yaml) as f:
            chart_meta = yaml.safe_load(f) or {}
    ctx = {
        "Values": merged,
        "Release": {"Name": release_name, "Namespace": namespace, "Service": "Helm"},
        "Chart": {"Name": chart_meta.get("name", ""), "Version": chart_meta.get("version", "")},
    }
    objects: List[ObjectDict] = []
    crd_dir = os.path.join(chart_dir, "crds")
    if os.path.isdir(crd_dir):
        for name in sorted(os.listdir(crd_dir)):
            if not name.endswith((".yaml", ".yml")):
                continue
            with open(os.path.join(crd_dir, name)) as f:
                objects.extend(d for d in yaml.safe_load_all(f) if d)
    tmpl_dir = os.path.join(chart_dir, "templates")
    for name in sorted(os.listdir(tmpl_dir)):
        if not name.endswith((".yaml", ".yml")):
            continue
        with open(os.path.join(tmpl_dir, name)) as f:
            source = f.read()
        try:
            text = render_string(source, ctx)
        except HelmliteError as e:
            raise HelmliteError(f"{name}: {e}") from e
        try:
            docs = list(yaml.safe_load_all(text))
        except yaml.YAMLError as e:
            raise HelmliteError(f"{name}: rendered YAML invalid: {e}\n{text}") from e
        objects.extend(d for d in docs if d)
    return objects
