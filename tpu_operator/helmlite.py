"""helmlite: a minimal Go-template (Helm) renderer for chart verification.

The shipped Helm chart (``deploy/helm/tpu-operator``) is the user-facing
install path (reference: ``deployments/gpu-operator`` — Chart.yaml,
templates/operator.yaml, crds/). This environment carries no ``helm``
binary, so CI proves the chart correct by rendering it with this engine
and asserting object-for-object parity with ``chart.render_chart()``
(see tests/test_helm_chart.py).

The engine implements the text/template + sprig subset charts use —
actions with trim markers, ``.Values``/``.Release`` paths,
``if``/``else``/``end``, ``range`` (with ``$i, $v :=`` declarations and
``else``), ``with``, variables (``$x := ...``, ``$`` as the root
context), named templates (``define`` in ``*.tpl`` files, the ``include``
function and ``template`` action, ``block`` as define-with-default +
execute-in-place), pipelines with parenthesized sub-expressions, and the
functions listed in ``_FUNCTIONS`` — and *raises* on anything else, so a
chart edit that
outgrows the verifier fails loudly instead of silently diverging from
what real helm would render. Semantics follow Go:

  - ``{{-``/``-}}`` trim all adjacent whitespace including newlines
  - missing map keys evaluate to None (render as empty, falsey in ``if``)
  - truthiness: nil/false/0/""/empty collection are false
  - ``toYaml`` marshals with sorted keys (sigs.k8s.io/yaml behavior)
  - ``range`` over maps iterates keys in sorted order (text/template)
  - inside ``define`` bodies, ``$`` and ``.`` are the invocation argument
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, List, Optional, Tuple

import yaml

from tpu_operator.kube.objects import ObjectDict
from tpu_operator.utils import deep_merge


class HelmliteError(Exception):
    pass


# ---------------------------------------------------------------------------
# functions (sprig subset)
# ---------------------------------------------------------------------------


def _truthy(v: Any) -> bool:
    if v is None or v is False:
        return False
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return v != 0
    if isinstance(v, (str, list, dict, tuple)):
        return len(v) > 0
    return True


def _to_yaml(v: Any) -> str:
    return yaml.safe_dump(v, default_flow_style=False, sort_keys=True).rstrip("\n")


def _gostr(v: Any) -> str:
    """Stringify the way Go's text/template prints values: booleans are
    lowercase, nil is empty."""
    if v is None:
        return ""
    if v is True:
        return "true"
    if v is False:
        return "false"
    return str(v)


def _indent(n: Any, s: Any) -> str:
    pad = " " * int(n)
    return "\n".join(pad + line for line in _gostr(s).splitlines())


def _quote(v: Any) -> str:
    return '"%s"' % _gostr(v).replace("\\", "\\\\").replace('"', '\\"')


def _printf(fmt: Any, *args: Any) -> str:
    """Go fmt verbs → python %-formatting for the subset charts use."""
    out = []
    arg_iter = iter(args)
    i, s = 0, str(fmt)
    while i < len(s):
        ch = s[i]
        if ch != "%":
            out.append(ch)
            i += 1
            continue
        if i + 1 >= len(s):
            raise HelmliteError("printf: trailing % in " + repr(fmt))
        # optional width[.precision] between % and the verb (Go fmt):
        # %5d, %.2f, %8.3f, %-10s
        j = i + 1
        while j < len(s) and (s[j].isdigit() or s[j] in ".-"):
            j += 1
        if j >= len(s):
            raise HelmliteError("printf: trailing format spec in " + repr(fmt))
        spec, verb = s[i + 1 : j], s[j]
        if spec and not re.fullmatch(r"-?\d*(\.\d*)?", spec):  # Go: "%.f" = precision 0
            # a malformed spec must fail the engine's error contract
            # (HelmliteError), not escape as ValueError from %-formatting
            raise HelmliteError(f"printf: malformed spec %{spec}{verb} in {fmt!r}")
        i = j + 1
        if verb == "%":
            if spec:
                raise HelmliteError(f"printf: malformed %% spec in {fmt!r}")
            out.append("%")
            continue
        try:
            arg = next(arg_iter)
        except StopIteration:
            raise HelmliteError(f"printf: not enough args for {fmt!r}") from None
        if verb in ("s", "v"):
            out.append(("%" + spec + "s") % _gostr(arg))
        elif verb == "d":
            if isinstance(arg, bool) or not isinstance(arg, int):
                raise HelmliteError(f"printf: %d wants an integer, got {arg!r}")
            out.append(("%" + spec + "d") % arg)
        elif verb == "f":
            if isinstance(arg, bool) or not isinstance(arg, (int, float)):
                raise HelmliteError(f"printf: %f wants a number, got {arg!r}")
            # Go's %f defaults to 6 decimals, same as python's
            out.append(("%" + spec + "f") % float(arg))
        elif verb == "q":
            if spec:
                raise HelmliteError(f"printf: %q takes no spec in {fmt!r}")
            out.append(_quote(arg))
        else:
            raise HelmliteError(f"printf: unsupported verb %{verb} in {fmt!r}")
    return "".join(out)


def _golen(v: Any) -> int:
    if not isinstance(v, (str, list, dict, tuple)):
        # Go errors on len of untyped nil / non-collections; silently
        # answering 0 would let the chart diverge from real helm
        raise HelmliteError(f"len of non-collection {type(v).__name__}")
    return len(v)


def _required(msg: Any, v: Any = None) -> Any:
    if v is None or v == "":
        raise HelmliteError(f"required value missing: {_gostr(msg)}")
    return v


def _sprig_dict(*kv: Any) -> dict:
    if len(kv) % 2:
        raise HelmliteError(f"dict wants key/value pairs, got {len(kv)} args")
    return {str(kv[i]): kv[i + 1] for i in range(0, len(kv), 2)}


def _sprig_merge(dst: Any, *srcs: Any) -> dict:
    """sprig merge: deep-merge sources into dst with dst taking
    precedence (leftmost wins). Returns a new dict; arguments are not
    mutated (sprig mutates dst — charts here never rely on that)."""
    out: dict = {}
    for m in reversed((dst,) + srcs):
        if not isinstance(m, dict):
            raise HelmliteError(f"merge wants dicts, got {type(m).__name__}")
        out = deep_merge(out, m)
    return out


_FUNCTIONS = {
    "toYaml": _to_yaml,
    "indent": _indent,
    "nindent": lambda n, s: "\n" + _indent(n, s),
    "quote": _quote,
    "default": lambda d, v=None: v if _truthy(v) else d,
    # sprig coalesce: first non-empty argument, nil when all are empty
    # (empty per Go truthiness — the chart's guard for nested knobs a
    # partial values file may omit, e.g. clusterPolicy.healthMonitor.*)
    "coalesce": lambda *a: next((x for x in a if _truthy(x)), None),
    # _gostr: a missing key (None) must compare as "", not "None"
    "hasPrefix": lambda prefix, s: _gostr(s).startswith(str(prefix)),
    "hasSuffix": lambda suffix, s: _gostr(s).endswith(str(suffix)),
    "not": lambda v: not _truthy(v),
    "and": lambda *a: next((x for x in a if not _truthy(x)), a[-1]),
    "or": lambda *a: next((x for x in a if _truthy(x)), a[-1]),
    "eq": lambda a, b: a == b,
    "ne": lambda a, b: a != b,
    # sprig string/flow helpers charts lean on
    "printf": _printf,
    "required": _required,
    "lower": lambda s: _gostr(s).lower(),
    "upper": lambda s: _gostr(s).upper(),
    "title": lambda s: _gostr(s).title(),
    "trim": lambda s: _gostr(s).strip(),
    "trunc": lambda n, s: _gostr(s)[: int(n)] if int(n) >= 0 else _gostr(s)[int(n):],
    "trimPrefix": lambda prefix, s: _gostr(s).removeprefix(str(prefix)),
    "trimSuffix": lambda suffix, s: _gostr(s).removesuffix(str(suffix)),
    "replace": lambda old, new, s: _gostr(s).replace(str(old), str(new)),
    "contains": lambda needle, s: str(needle) in _gostr(s),
    "toString": _gostr,
    "len": _golen,
    # sprig: ternary trueVal falseVal cond (cond usually piped in)
    "ternary": lambda t, f, cond: t if _truthy(cond) else f,
    # sprig dict helpers
    "hasKey": lambda d, k: isinstance(d, dict) and str(k) in d,
    "dict": _sprig_dict,
    "merge": _sprig_merge,
}


# ---------------------------------------------------------------------------
# lexer / parser
# ---------------------------------------------------------------------------

_ACTION_RE = re.compile(r"\{\{(-?)\s*(.*?)\s*(-?)\}\}", re.DOTALL)


def _lex(source: str) -> List[Tuple[str, str]]:
    """Split into ('text', s) and ('action', body) tokens with Go trim
    semantics applied to the surrounding text."""
    tokens: List[Tuple[str, str]] = []
    pos = 0
    for m in _ACTION_RE.finditer(source):
        text = source[pos : m.start()]
        if m.group(1) == "-":
            text = text.rstrip()
        tokens.append(("text", text))
        tokens.append(("action", m.group(2)))
        pos = m.end()
        if m.group(3) == "-":
            # trim leading whitespace of the following text
            rest = source[pos:]
            stripped = rest.lstrip()
            pos += len(rest) - len(stripped)
    tokens.append(("text", source[pos:]))
    return [t for t in tokens if t[0] == "action" or t[1]]


class _Node:
    pass


class _Text(_Node):
    def __init__(self, s: str):
        self.s = s


class _Expr(_Node):
    def __init__(self, pipeline: str):
        self.pipeline = pipeline


class _If(_Node):
    def __init__(self):
        # list of (condition-pipeline or None for else, body nodes)
        self.branches: List[Tuple[Optional[str], List[_Node]]] = []


class _Range(_Node):
    def __init__(self, var_names: List[str], pipeline: str, body, else_body):
        self.var_names = var_names  # [] | [$v] | [$i, $v]
        self.pipeline = pipeline
        self.body = body
        self.else_body = else_body


class _With(_Node):
    def __init__(self, pipeline: str, body, else_body):
        self.pipeline = pipeline
        self.body = body
        self.else_body = else_body


class _Assign(_Node):
    def __init__(self, name: str, pipeline: str, declare: bool):
        self.name = name  # without the $
        self.pipeline = pipeline
        self.declare = declare  # := (new block-local) vs = (existing var)


class _TemplateCall(_Node):
    def __init__(self, name: str, pipeline: Optional[str]):
        self.name = name
        self.pipeline = pipeline


def _parse_block_with_else(tokens, i, defines):
    """Parse a body that may carry one {{ else }}; returns
    (body, else_body, next_i)."""
    body, i, term = _parse(tokens, i + 1, in_block=True, defines=defines)
    else_body: List[_Node] = []
    if term == "else":
        else_body, i, term = _parse(tokens, i + 1, in_block=True, defines=defines)
    if term != "end":
        raise HelmliteError(f"expected end, got {term!r}")
    return body, else_body, i


def _split_range_decl(decl: str) -> Tuple[List[str], str]:
    if ":=" in decl:
        left, _, pipeline = decl.partition(":=")
        names = []
        for raw in left.split(","):
            raw = raw.strip()
            if not raw.startswith("$"):
                raise HelmliteError(f"range variable {raw!r} must start with $")
            names.append(raw[1:])
        if len(names) > 2:
            raise HelmliteError(f"range declares at most 2 variables: {decl!r}")
        return names, pipeline.strip()
    return [], decl.strip()


def _parse(tokens: List[Tuple[str, str]], i: int = 0, in_block: bool = False, defines=None):
    nodes: List[_Node] = []
    while i < len(tokens):
        kind, body = tokens[i]
        if kind == "text":
            nodes.append(_Text(body))
            i += 1
            continue
        if body.startswith("/*"):
            i += 1
            continue
        word = body.split(None, 1)[0] if body else ""
        if word == "if":
            node = _If()
            cond = body[2:].strip()
            while True:
                sub, i, term = _parse(tokens, i + 1, in_block=True, defines=defines)
                node.branches.append((cond, sub))
                if term == "end":
                    break
                if term == "else":
                    # bare else: final branch with condition None
                    sub, i, term2 = _parse(tokens, i + 1, in_block=True, defines=defines)
                    node.branches.append((None, sub))
                    if term2 != "end":
                        raise HelmliteError(f"expected end after else, got {term2}")
                    break
                if term.startswith("else if"):
                    cond = term[len("else if") :].strip()
                    continue
                raise HelmliteError(f"unexpected block terminator {term!r}")
            nodes.append(node)
            i += 1
            continue
        if word == "range":
            names, pipeline = _split_range_decl(body[len("range") :].strip())
            rng_body, else_body, i = _parse_block_with_else(tokens, i, defines)
            nodes.append(_Range(names, pipeline, rng_body, else_body))
            i += 1
            continue
        if word == "with":
            with_body, else_body, i = _parse_block_with_else(tokens, i, defines)
            nodes.append(_With(body[len("with") :].strip(), with_body, else_body))
            i += 1
            continue
        if word == "define":
            name = body[len("define") :].strip()
            if not (name.startswith('"') and name.endswith('"')):
                raise HelmliteError(f"define name must be quoted: {body!r}")
            sub, i, term = _parse(tokens, i + 1, in_block=True, defines=defines)
            if term != "end":
                raise HelmliteError(f"expected end after define, got {term!r}")
            if defines is None:
                raise HelmliteError("define outside a template file context")
            defines[name[1:-1]] = sub
            i += 1
            continue
        if word == "template":
            rest = body[len("template") :].strip()
            m = re.match(r'^"((?:[^"\\]|\\.)*)"\s*(.*)$', rest)
            if not m:
                raise HelmliteError(f"template name must be quoted: {body!r}")
            nodes.append(_TemplateCall(m.group(1), m.group(2).strip() or None))
            i += 1
            continue
        if word in ("end", "else") or body.startswith("else if"):
            if not in_block:
                raise HelmliteError(f"unexpected {body!r} outside a block")
            return nodes, i, body
        if word == "block":
            # Go: {{ block "name" pipeline }}body{{ end }} is shorthand for
            # define + execute-in-place, with the body as the DEFAULT: a
            # template defined elsewhere under the same name overrides it
            # (helm's override idiom), hence setdefault, not assignment
            m = re.match(r'^block\s+"((?:[^"\\]|\\.)*)"\s+(.+)$', body, re.DOTALL)
            if not m:
                raise HelmliteError(f"malformed block action: {body!r}")
            sub, i, term = _parse(tokens, i + 1, in_block=True, defines=defines)
            if term != "end":
                raise HelmliteError(f"expected end after block, got {term!r}")
            if defines is None:
                raise HelmliteError("block outside a template file context")
            defines.setdefault(m.group(1), sub)
            nodes.append(_TemplateCall(m.group(1), m.group(2).strip()))
            i += 1
            continue
        m = re.match(r"^\$([\w]+)\s*(:=|=)\s*(.+)$", body)
        if m:
            nodes.append(_Assign(m.group(1), m.group(3).strip(), m.group(2) == ":="))
            i += 1
            continue
        nodes.append(_Expr(body))
        i += 1
    if in_block:
        raise HelmliteError("unterminated block (missing {{ end }})")
    return nodes, i, ""


# ---------------------------------------------------------------------------
# evaluation
# ---------------------------------------------------------------------------


class _VarFrame:
    """One block's variable bindings, chained to the enclosing block —
    Go semantics: ``:=`` declares in the current block, ``=`` assigns to
    the nearest enclosing declaration (and errors if none exists)."""

    def __init__(self, parent: Optional["_VarFrame"] = None):
        self.map: Dict[str, Any] = {}
        self.parent = parent

    def lookup(self, name: str) -> Any:
        frame = self
        while frame is not None:
            if name in frame.map:
                return frame.map[name]
            frame = frame.parent
        raise HelmliteError(f"undefined variable ${name}")

    def declare(self, name: str, value: Any) -> None:
        self.map[name] = value

    def assign(self, name: str, value: Any) -> None:
        frame = self
        while frame is not None:
            if name in frame.map:
                frame.map[name] = value
                return
            frame = frame.parent
        raise HelmliteError(f"cannot assign to undeclared variable ${name} (use :=)")


class _Scope:
    """Evaluation scope: the current dot, ``$`` (set at template start),
    the variable frame chain, and the chart's named templates."""

    def __init__(self, dot: Any, root: Any, variables: Optional[_VarFrame] = None,
                 defines: Optional[Dict[str, list]] = None):
        self.dot = dot
        self.root = root
        self.vars = variables if variables is not None else _VarFrame()
        self.defines = defines if defines is not None else {}

    def child(self, dot: Any) -> "_Scope":
        # block bodies see the outer variables through the frame chain;
        # their own declarations stay block-local (Go scoping)
        return _Scope(dot, self.root, _VarFrame(self.vars), self.defines)


def _walk(base: Any, path: str, full: str) -> Any:
    cur = base
    for part in path.split("."):
        if not part:
            raise HelmliteError(f"bad path {full!r}")
        if isinstance(cur, dict):
            cur = cur.get(part)
        else:
            cur = None
        if cur is None:
            return None
    return cur


def _eval_atom(tok: str, scope: _Scope) -> Any:
    if len(tok) >= 2 and tok.startswith("(") and tok.endswith(")"):
        # parenthesized sub-pipeline: a full pipeline in argument position
        return _eval_pipeline(tok[1:-1], scope)
    if len(tok) >= 2 and tok.startswith('"') and tok.endswith('"'):
        return tok[1:-1].replace('\\"', '"').replace("\\\\", "\\")
    if tok in ("true", "false"):
        return tok == "true"
    if tok in ("nil", "null"):
        return None
    if re.fullmatch(r"-?\d+", tok):
        return int(tok)
    if re.fullmatch(r"-?\d+\.\d+", tok):
        return float(tok)
    if tok == ".":
        return scope.dot
    if tok == "$":
        return scope.root
    if tok.startswith("$."):
        return _walk(scope.root, tok[2:], tok)
    if tok.startswith("$"):
        name, _, path = tok[1:].partition(".")
        base = scope.vars.lookup(name)
        return _walk(base, path, tok) if path else base
    if tok.startswith("."):
        return _walk(scope.dot, tok[1:], tok)
    raise HelmliteError(f"cannot evaluate {tok!r}")


def _eval_segment(tokens: List[str], scope: _Scope, piped: Any = ...) -> Any:
    head = tokens[0]
    if head == "include":
        args = [_eval_atom(t, scope) for t in tokens[1:]]
        if piped is not ...:
            args.append(piped)
        if len(args) != 2:
            raise HelmliteError(f"include wants (name, context), got {len(args)} args")
        return _render_define(args[0], args[1], scope)
    if head in _FUNCTIONS:
        args = [_eval_atom(t, scope) for t in tokens[1:]]
        if piped is not ...:
            args.append(piped)
        return _FUNCTIONS[head](*args)
    if len(tokens) != 1 or piped is not ...:
        raise HelmliteError(f"unknown function {head!r}")
    return _eval_atom(head, scope)


def _split_pipeline(pipeline: str) -> List[str]:
    """Split on '|' outside string literals and parentheses
    ('{{ eq .x "|" }}' and '{{ and (eq .a 1 | not) .b }}' must not split
    inside the quoted argument / the parenthesized sub-pipeline)."""
    segments: List[str] = []
    current: List[str] = []
    in_string = False
    depth = 0
    i = 0
    while i < len(pipeline):
        ch = pipeline[i]
        if in_string:
            current.append(ch)
            if ch == "\\" and i + 1 < len(pipeline):
                current.append(pipeline[i + 1])
                i += 1
            elif ch == '"':
                in_string = False
        elif ch == '"':
            in_string = True
            current.append(ch)
        elif ch == "(":
            depth += 1
            current.append(ch)
        elif ch == ")":
            depth -= 1
            current.append(ch)
        elif ch == "|" and depth == 0:
            segments.append("".join(current))
            current = []
        else:
            current.append(ch)
        i += 1
    if in_string:
        raise HelmliteError(f"unterminated string literal in {pipeline!r}")
    if depth:
        raise HelmliteError(f"unbalanced parentheses in {pipeline!r}")
    segments.append("".join(current))
    return segments


def _segment_tokens(segment: str) -> List[str]:
    """Tokenize one pipeline segment: string literals and parenthesized
    sub-pipelines each form ONE token (the latter evaluated recursively
    by ``_eval_atom``)."""
    tokens: List[str] = []
    s = segment.strip()
    i, n = 0, len(s)
    while i < n:
        ch = s[i]
        if ch.isspace():
            i += 1
            continue
        if ch == '"':
            j = i + 1
            while j < n and s[j] != '"':
                j += 2 if s[j] == "\\" else 1
            if j >= n:
                raise HelmliteError(f"unterminated string literal in {segment!r}")
            tokens.append(s[i : j + 1])
            i = j + 1
            continue
        if ch == "(":
            depth, j, in_str = 1, i + 1, False
            while j < n and depth:
                c = s[j]
                if in_str:
                    if c == "\\":
                        j += 1
                    elif c == '"':
                        in_str = False
                elif c == '"':
                    in_str = True
                elif c == "(":
                    depth += 1
                elif c == ")":
                    depth -= 1
                j += 1
            if depth:
                raise HelmliteError(f"unbalanced parentheses in {segment!r}")
            tokens.append(s[i:j])
            i = j
            continue
        if ch == ")":
            raise HelmliteError(f"unbalanced parentheses in {segment!r}")
        j = i
        while j < n and not s[j].isspace() and s[j] not in '()"':
            j += 1
        tokens.append(s[i:j])
        i = j
    return tokens


def _eval_pipeline(pipeline: str, scope: _Scope) -> Any:
    value: Any = ...
    for segment in _split_pipeline(pipeline):
        tokens = _segment_tokens(segment)
        if not tokens:
            raise HelmliteError(f"empty pipeline segment in {pipeline!r}")
        value = _eval_segment(tokens, scope, value)
    return value


def _render_define(name: str, arg: Any, scope: _Scope) -> str:
    if name not in scope.defines:
        raise HelmliteError(f"no template defined with name {name!r}")
    # Go: inside a template invocation, both . and $ are the argument,
    # and the variable scope starts fresh
    return _render_nodes(scope.defines[name], _Scope(arg, arg, None, scope.defines))


def _render_nodes(nodes: List[_Node], scope: _Scope) -> str:
    out: List[str] = []
    for node in nodes:
        if isinstance(node, _Text):
            out.append(node.s)
        elif isinstance(node, _Expr):
            out.append(_gostr(_eval_pipeline(node.pipeline, scope)))
        elif isinstance(node, _Assign):
            value = _eval_pipeline(node.pipeline, scope)
            if node.declare:
                scope.vars.declare(node.name, value)
            else:
                scope.vars.assign(node.name, value)
        elif isinstance(node, _TemplateCall):
            arg = _eval_pipeline(node.pipeline, scope) if node.pipeline else None
            out.append(_render_define(node.name, arg, scope))
        elif isinstance(node, _If):
            for cond, body in node.branches:
                if cond is None or _truthy(_eval_pipeline(cond, scope)):
                    # if-bodies are blocks too: declarations stay local
                    out.append(_render_nodes(body, scope.child(scope.dot)))
                    break
        elif isinstance(node, _With):
            val = _eval_pipeline(node.pipeline, scope)
            if _truthy(val):
                out.append(_render_nodes(node.body, scope.child(val)))
            elif node.else_body:
                # else bodies are blocks too: declarations stay local
                out.append(_render_nodes(node.else_body, scope.child(scope.dot)))
        elif isinstance(node, _Range):
            val = _eval_pipeline(node.pipeline, scope)
            if isinstance(val, dict):
                items = [(k, val[k]) for k in sorted(val)]  # text/template order
            elif isinstance(val, (list, tuple)):
                items = list(enumerate(val))
            elif val is None:
                items = []
            else:
                raise HelmliteError(f"range over non-iterable {type(val).__name__}")
            if not items:
                if node.else_body:
                    # else bodies are blocks too: declarations stay local
                    out.append(_render_nodes(node.else_body, scope.child(scope.dot)))
                continue
            for key, elem in items:
                body_scope = scope.child(elem)
                if len(node.var_names) == 1:
                    body_scope.vars.declare(node.var_names[0], elem)
                elif len(node.var_names) == 2:
                    body_scope.vars.declare(node.var_names[0], key)
                    body_scope.vars.declare(node.var_names[1], elem)
                out.append(_render_nodes(node.body, body_scope))
    return "".join(out)


# ---------------------------------------------------------------------------
# chart rendering
# ---------------------------------------------------------------------------


def render_string(
    source: str, ctx: Dict[str, Any], defines: Optional[Dict[str, list]] = None
) -> str:
    defines = defines if defines is not None else {}
    nodes, _, _ = _parse(_lex(source), defines=defines)
    return _render_nodes(nodes, _Scope(ctx, ctx, None, defines))


def load_defines(source: str, defines: Dict[str, list]) -> None:
    """Collect {{ define }} blocks from a helper file (_helpers.tpl) into
    the shared chart-wide template namespace (helm semantics)."""
    nodes, _, _ = _parse(_lex(source), defines=defines)
    for node in nodes:
        if isinstance(node, _Text):
            if node.s.strip():
                raise HelmliteError(
                    f"helper files must only define templates; found output text {node.s.strip()[:40]!r}"
                )
        else:
            # an expression/if/range at the top level of a .tpl would be
            # rendered by real helm but silently lost here — fail loudly
            raise HelmliteError(
                f"helper files must only define templates; found {type(node).__name__} action"
            )


def template(
    chart_dir: str,
    values: Optional[dict] = None,
    release_name: str = "tpu-operator",
    namespace: str = "default",
) -> List[ObjectDict]:
    """``helm template`` equivalent: chart default values deep-merged with
    overrides, crds/ emitted first (helm installs them before templates),
    then every templates/*.yaml in lexical order."""
    values_file = os.path.join(chart_dir, "values.yaml")
    with open(values_file) as f:
        defaults = yaml.safe_load(f) or {}
    merged = deep_merge(defaults, values or {})
    chart_meta = {}
    chart_yaml = os.path.join(chart_dir, "Chart.yaml")
    if os.path.exists(chart_yaml):
        with open(chart_yaml) as f:
            chart_meta = yaml.safe_load(f) or {}
    ctx = {
        "Values": merged,
        "Release": {"Name": release_name, "Namespace": namespace, "Service": "Helm"},
        "Chart": {"Name": chart_meta.get("name", ""), "Version": chart_meta.get("version", "")},
    }
    objects: List[ObjectDict] = []
    crd_dir = os.path.join(chart_dir, "crds")
    if os.path.isdir(crd_dir):
        for name in sorted(os.listdir(crd_dir)):
            if not name.endswith((".yaml", ".yml")):
                continue
            with open(os.path.join(crd_dir, name)) as f:
                objects.extend(d for d in yaml.safe_load_all(f) if d)
    tmpl_dir = os.path.join(chart_dir, "templates")
    defines: Dict[str, list] = {}
    for name in sorted(os.listdir(tmpl_dir)):
        if name.endswith(".tpl"):
            with open(os.path.join(tmpl_dir, name)) as f:
                try:
                    load_defines(f.read(), defines)
                except HelmliteError as e:
                    raise HelmliteError(f"{name}: {e}") from e
    for name in sorted(os.listdir(tmpl_dir)):
        if not name.endswith((".yaml", ".yml")):
            continue
        with open(os.path.join(tmpl_dir, name)) as f:
            source = f.read()
        try:
            text = render_string(source, ctx, defines)
        except HelmliteError as e:
            raise HelmliteError(f"{name}: {e}") from e
        try:
            docs = list(yaml.safe_load_all(text))
        except yaml.YAMLError as e:
            raise HelmliteError(f"{name}: rendered YAML invalid: {e}\n{text}") from e
        objects.extend(d for d in docs if d)
    return objects
