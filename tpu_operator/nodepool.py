"""Node pool partitioning for per-pool DaemonSet fan-out.

Reference: ``internal/state/nodepool.go:55-132`` partitions GPU nodes by
os/kernel/rhcos so each pool gets its own driver DaemonSet. The TPU
equivalent: libtpu versions must match across every host of a slice, and
slice topology determines gang size — so nodes partition by
(accelerator type, topology, GKE node pool).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List

from tpu_operator import consts
from tpu_operator.kube.objects import ObjectDict
from tpu_operator.nodeinfo import TPUNodeInfo, tpu_info


@dataclasses.dataclass
class NodePool:
    name: str  # stable, DNS-safe pool key
    accelerator_type: str
    topology: str
    gke_nodepool: str
    node_names: List[str]
    info: TPUNodeInfo  # representative node's attributes

    @property
    def selector(self) -> Dict[str, str]:
        """nodeSelector matching exactly this pool's nodes — built from
        the label set that actually identified them: GKE labels on GKE,
        the discovery-published tpu.google.com labels on self-managed
        clusters (where no cloud.google.com/* label exists, so a GKE
        selector would match zero nodes and every per-pool TPUSlice
        DaemonSet would hang unscheduled)."""
        if self.info.label_source == "discovery":
            sel = {consts.TFD_ACCELERATOR_TYPE_LABEL: self.accelerator_type}
            if self.topology:
                sel[consts.TFD_TOPOLOGY_LABEL] = self.topology
            return sel
        sel = {consts.GKE_TPU_ACCELERATOR_LABEL: self.accelerator_type}
        if self.topology:
            sel[consts.GKE_TPU_TOPOLOGY_LABEL] = self.topology
        if self.gke_nodepool:
            sel[consts.GKE_NODEPOOL_LABEL] = self.gke_nodepool
        return sel


def _pool_name(info: TPUNodeInfo) -> str:
    parts = [info.accelerator_type]
    if info.topology:
        parts.append(info.topology.replace("x", "-"))
    if info.nodepool:
        parts.append(info.nodepool)
    return "-".join(parts).lower()


def get_node_pools(nodes: List[ObjectDict]) -> List[NodePool]:
    """reference: getNodePools nodepool.go:55-132."""
    pools: Dict[str, NodePool] = {}
    for node in nodes:
        info = tpu_info(node)
        if info is None:
            continue
        key = _pool_name(info)
        pool = pools.get(key)
        if pool is None:
            pools[key] = NodePool(
                name=key,
                accelerator_type=info.accelerator_type,
                topology=info.topology,
                gke_nodepool=info.nodepool,
                node_names=[info.node_name],
                info=info,
            )
        else:
            pool.node_names.append(info.node_name)
    for pool in pools.values():
        pool.node_names.sort()
    return sorted(pools.values(), key=lambda p: p.name)
