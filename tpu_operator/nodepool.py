"""Node pool partitioning for per-pool DaemonSet fan-out.

Reference: ``internal/state/nodepool.go:55-132`` partitions GPU nodes by
os/kernel/rhcos so each pool gets its own driver DaemonSet. The TPU
equivalent: libtpu versions must match across every host of a slice, and
slice topology determines gang size — so nodes partition by
(accelerator type, topology, GKE node pool).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List

from tpu_operator import consts
from tpu_operator.kube.objects import ObjectDict
from tpu_operator.nodeinfo import TPUNodeInfo, tpu_info


@dataclasses.dataclass
class NodePool:
    name: str  # stable, DNS-safe pool key
    accelerator_type: str
    topology: str
    gke_nodepool: str
    node_names: List[str]
    info: TPUNodeInfo  # representative node's attributes

    @property
    def selector(self) -> Dict[str, str]:
        """nodeSelector matching exactly this pool's nodes — built from
        the label set that actually identified them: GKE labels on GKE,
        the discovery-published tpu.google.com labels on self-managed
        clusters (where no cloud.google.com/* label exists, so a GKE
        selector would match zero nodes and every per-pool TPUSlice
        DaemonSet would hang unscheduled)."""
        if self.info.label_source == "discovery":
            sel = {consts.TFD_ACCELERATOR_TYPE_LABEL: self.accelerator_type}
            if self.topology:
                sel[consts.TFD_TOPOLOGY_LABEL] = self.topology
            return sel
        sel = {consts.GKE_TPU_ACCELERATOR_LABEL: self.accelerator_type}
        if self.topology:
            sel[consts.GKE_TPU_TOPOLOGY_LABEL] = self.topology
        if self.gke_nodepool:
            sel[consts.GKE_NODEPOOL_LABEL] = self.gke_nodepool
        return sel


def _pool_name(info: TPUNodeInfo) -> str:
    parts = [info.accelerator_type]
    if info.topology:
        parts.append(info.topology.replace("x", "-"))
    if info.nodepool:
        parts.append(info.nodepool)
    return "-".join(parts).lower()


def get_node_pools(nodes: List[ObjectDict]) -> List[NodePool]:
    """reference: getNodePools nodepool.go:55-132.

    Fully deterministic in the node SET, independent of input order:
    pools sort by name, members sort by name, and the representative
    ``info`` is always the lexicographically-first member's (it used to
    be whichever node the informer listed first, so gang worker ids and
    placement decisions could differ across re-lists of the same
    cluster)."""
    infos: Dict[str, Dict[str, TPUNodeInfo]] = {}
    for node in nodes:
        info = tpu_info(node)
        if info is None:
            continue
        infos.setdefault(_pool_name(info), {})[info.node_name] = info
    pools: List[NodePool] = []
    for key in sorted(infos):
        members = infos[key]
        names = sorted(members)
        representative = members[names[0]]
        pools.append(
            NodePool(
                name=key,
                accelerator_type=representative.accelerator_type,
                topology=representative.topology,
                gke_nodepool=representative.nodepool,
                node_names=names,
                info=representative,
            )
        )
    return pools
