"""Fleet data-plane aggregation: gang rollups + healthy-fleet compute.

The top layer of the telemetry pipeline. Workloads record per-host step
timing (workloads/telemetry.py), the slice manager publishes each
gang's merged artifact onto its gang ConfigMap
(``consts.GANG_TELEMETRY_ANNOTATION``); this aggregator — run from the
health reconciler's pass, so it rides the same cadence and informer
caches — reads those artifacts and the node labels back into the
fleet-level series:

    tpu_operator_gang_step_seconds{slice}      gang-median step time
    tpu_operator_gang_straggler_ratio{slice}   slowest host vs gang median
    tpu_operator_fleet_healthy_tflops          deliverable compute now
    tpu_operator_perf_degraded_nodes           grey failures in the fleet

Straggler detection: a gang whose ratio exceeds
``consts.GANG_STRAGGLER_RATIO`` gets a ``PerfDegraded`` Event naming
the slowest host — the operator-side pointer from "this gang is slow"
to "this is the node to look at", before (or alongside) the exporter's
own floor breach on that host.

``tpu_operator_fleet_healthy_tflops`` prices each in-service node at
its generation's MEASURED roof (tpu_operator/perf.py), not published
peak: the gauge answers "how much compute can this fleet actually
deliver right now", the calibration input the capacity planner
(ROADMAP item 4) and serving autoscaler (item 1) consume.
"""

from __future__ import annotations

import json
import logging
from typing import Dict, List, Optional, Set

from tpu_operator import consts
from tpu_operator.controllers.operator_metrics import get_metrics
from tpu_operator.kube import errors
from tpu_operator.kube.client import Client
from tpu_operator.kube.events import EventRecorder
from tpu_operator.nodeinfo import tpu_info
from tpu_operator.perf import measured_roofs

log = logging.getLogger(__name__)

# the slice manager stamps this on everything it owns; gang ConfigMaps
# are found by it (import kept value-only to avoid a module cycle)
_MANAGED_BY = {"app.kubernetes.io/managed-by": "tpu-slice-manager"}


def node_in_service(labels: dict) -> bool:
    """Whether a node's chips count toward deliverable fleet compute:
    not health-degraded, not mid-repair/quarantined, and not flagged by
    the exporter's perf-floor breach (a slow chip delivers less than its
    roof by definition — pricing it at the roof would overstate the
    fleet exactly when a grey failure is eating it)."""
    from tpu_operator.placement.engine import labels_unavailable

    return not labels_unavailable(labels)


class FleetTelemetryAggregator:
    def __init__(self, client: Client, namespace: str, recorder: Optional[EventRecorder] = None):
        self.client = client
        self.namespace = namespace
        self.recorder = recorder or EventRecorder(client, namespace)
        self.metrics = get_metrics()
        self._gang_series: Set[str] = set()  # label values published
        self._stragglers_flagged: Set[str] = set()  # event dedup per episode

    # -- one aggregation pass ------------------------------------------------

    def sync(self) -> dict:
        """Read gang artifacts + node labels, publish the fleet series.
        Returns a summary dict (tests and the telemetry must-gather
        artifact read it)."""
        summary = {
            "gangs": {},
            "stragglers": [],
            "fleet_healthy_tflops": 0.0,
            "perf_degraded_nodes": [],
        }
        self._sync_gangs(summary)
        self._sync_fleet(summary)
        return summary

    def _sync_gangs(self, summary: dict) -> None:
        try:
            cms = self.client.list(
                "v1", "ConfigMap", self.namespace, label_selector=_MANAGED_BY
            )
        except errors.ApiError as e:
            log.debug("fleet telemetry: gang ConfigMap list failed: %s", e)
            return
        live: Set[str] = set()
        for cm in cms:
            raw = (cm["metadata"].get("annotations") or {}).get(
                consts.GANG_TELEMETRY_ANNOTATION
            )
            if not raw:
                continue
            try:
                artifact = json.loads(raw)
            except ValueError:
                log.warning(
                    "fleet telemetry: malformed gang artifact on %s",
                    cm["metadata"]["name"],
                )
                continue
            # gang ConfigMaps are named <slice>-gang; the slice name is
            # the series key (matches the placement labels' gang id)
            slice_name = cm["metadata"]["name"]
            if slice_name.endswith("-gang"):
                slice_name = slice_name[: -len("-gang")]
            step = float(artifact.get("gang_step_p50_s") or 0.0)
            ratio = float(artifact.get("straggler_ratio") or 0.0)
            self.metrics.gang_step_seconds.labels(slice_name).set(step)
            self.metrics.gang_straggler_ratio.labels(slice_name).set(ratio)
            live.add(slice_name)
            summary["gangs"][slice_name] = {
                "step_p50_s": step,
                "straggler_ratio": ratio,
                "slowest_host": artifact.get("slowest_host", ""),
            }
            if ratio > consts.GANG_STRAGGLER_RATIO:
                summary["stragglers"].append(slice_name)
                if slice_name not in self._stragglers_flagged:
                    self.recorder.event(
                        cm, "Warning", "PerfDegraded",
                        f"gang {slice_name}: straggler ratio {ratio:.2f} "
                        f"(> {consts.GANG_STRAGGLER_RATIO}), slowest host "
                        f"{artifact.get('slowest_host', '?')} — one member is "
                        "dragging every peer's step time",
                    )
                    self._stragglers_flagged.add(slice_name)
            else:
                self._stragglers_flagged.discard(slice_name)
        # a torn-down gang's series goes with it: a frozen last value
        # would keep a straggler alert firing for a gang that no longer
        # exists (same discipline as the fragmentation gauge)
        for gone in self._gang_series - live:
            try:
                self.metrics.gang_step_seconds.remove(gone)
                self.metrics.gang_straggler_ratio.remove(gone)
            except KeyError:
                pass
            self._stragglers_flagged.discard(gone)
        self._gang_series = live

    def _sync_fleet(self, summary: dict) -> None:
        roofs = measured_roofs()
        try:
            nodes: List[dict] = self.client.list(
                "v1", "Node", label_selector={consts.TPU_PRESENT_LABEL: "true"}
            )
        except errors.ApiError as e:
            log.debug("fleet telemetry: node list failed: %s", e)
            return
        total = 0.0
        degraded: List[str] = []
        for node in nodes:
            labels = node["metadata"].get("labels") or {}
            if labels.get(consts.TPU_PERF_LABEL) == consts.PERF_DEGRADED:
                degraded.append(node["metadata"]["name"])
            if not node_in_service(labels):
                continue
            info = tpu_info(node)
            if info is None:
                continue
            roof = roofs.get(info.generation, {}).get("matmul_tflops")
            if roof:
                total += roof * max(1, info.chips_per_node)
        self.metrics.fleet_healthy_tflops.set(round(total, 1))
        self.metrics.perf_degraded_nodes.set(len(degraded))
        summary["fleet_healthy_tflops"] = round(total, 1)
        summary["perf_degraded_nodes"] = sorted(degraded)
