"""ICI fabric analyzer: link-level series + edge-aware blame.

The top layer of the fabric telemetry pipeline (workloads/fabric.py
measures, the slice manager publishes, this ingests). Runs from the
health reconciler's pass — same cadence and informer caches as the
fleet aggregator — reading each gang's published fabric artifact
(``consts.GANG_FABRIC_ANNOTATION``) back into:

    tpu_operator_ici_link_bandwidth_gbps{pool,edge}   measured GB/s
    tpu_operator_ici_link_degraded{pool,edge}         1 while slow/cut

and running **blame assignment**, the decision PR 7 could not make: a
slow link and a slow chip both read as one straggling host at host
granularity, so remediation used to quarantine a healthy node while
the bad cable kept poisoning whichever gang landed across it next.
With per-edge measurements the two separate:

  - **host blame** — ``consts.FABRIC_HOST_BLAME_EDGES`` or more
    degraded edges sharing one endpoint indict that host's ICI
    interface, not N independent cables failing at once: the host gets
    the ``tpu.google.com/perf=degraded`` label and enters the existing
    grey-failure repair FSM (cordon → … → revalidate), exactly the
    PR 7 path a floor-breaching chip takes.
  - **link blame** — a degraded edge whose endpoints are otherwise
    healthy indicts the cable: it is recorded in the per-pool
    link-health ConfigMap (``consts.LINK_HEALTH_CONFIGMAP``), BOTH
    endpoints stay in service and schedulable, and the placement
    engine — which consumes the link map as unavailable-edge input —
    re-places any gang straddling the edge and routes new blocks
    around it.

A recorded link clears when a later artifact measures that same edge
healthy again (a re-seated cable proves itself the same way it was
convicted); its series go when the record does, and a drained pool
takes every series and record with it. Stale artifacts — a re-placed
gang's ConfigMap still carries the OLD block's matrix until a fresh
probe runs — are detected by membership: every artifact member must
still be placed in that gang, or the matrix describes links the gang
no longer runs on and is skipped wholesale.
"""

from __future__ import annotations

import json
import logging
from typing import Dict, List, Optional, Set, Tuple

from tpu_operator import consts
from tpu_operator.controllers.operator_metrics import get_metrics
from tpu_operator.kube import errors
from tpu_operator.kube.client import Client
from tpu_operator.kube.events import EventRecorder
from tpu_operator.kube.objects import new_object
from tpu_operator.nodepool import get_node_pools

log = logging.getLogger(__name__)

# the slice manager stamps this on every gang object it owns (kept
# value-only to avoid a module cycle, same as fleet_telemetry)
_MANAGED_BY = {"app.kubernetes.io/managed-by": "tpu-slice-manager"}


def parse_link_map(cm: Optional[dict]) -> Dict[str, Dict[str, dict]]:
    """{pool: {edge: record}} from the link-health ConfigMap; malformed
    pool entries degrade to empty rather than poisoning the pass."""
    out: Dict[str, Dict[str, dict]] = {}
    if cm is None:
        return out
    for pool, raw in (cm.get("data") or {}).items():
        try:
            parsed = json.loads(raw)
        except (TypeError, ValueError):
            log.warning("fabric: malformed link-health entry for pool %s", pool)
            continue
        edges = (parsed or {}).get("edges")
        if isinstance(edges, dict):
            out[pool] = {str(k): dict(v) for k, v in edges.items() if isinstance(v, dict)}
    return out


def degraded_link_pairs(client, namespace: str) -> List[Tuple[str, str]]:
    """Severed ICI edges from the link-health ConfigMap as sorted
    node-name pairs — the degraded-links input every consumer (placement
    replan, TPUJob gang state, TPUServing routing/victim scoring) feeds
    the engine. A MISSING or malformed map means no cuts (nothing was
    ever recorded) — but a failed READ propagates and aborts the
    caller's pass like any other input read: planning with "no cuts"
    because the apiserver 500'd could seat a fresh gang straight across
    a known-degraded link."""
    cm = client.get_or_none(
        "v1", "ConfigMap", consts.LINK_HEALTH_CONFIGMAP, namespace
    )
    edges = []
    for pool_edges in parse_link_map(cm).values():
        for edge in pool_edges:
            a, _, b = edge.partition("|")
            if a and b:
                edges.append((a, b))
    return sorted(edges)


class FabricTelemetryAggregator:
    def __init__(self, client: Client, namespace: str, recorder: Optional[EventRecorder] = None):
        self.client = client
        self.namespace = namespace
        self.recorder = recorder or EventRecorder(
            client, namespace, component="tpu-fabric-telemetry"
        )
        self.metrics = get_metrics()
        self._link_series: Set[Tuple[str, str]] = set()  # (pool, edge) published
        self._link_events: Set[str] = set()  # edge keys evented this episode
        self._host_events: Set[str] = set()

    # -- one analysis pass ---------------------------------------------------

    def sync(self) -> dict:
        """Ingest every gang fabric artifact, assign blame, maintain the
        link-health map + series. Returns a summary dict (tests and the
        fabric must-gather artifact read it)."""
        summary: dict = {
            "gangs": {},
            "degraded_edges": [],
            "link_blamed": [],
            "host_blamed": [],
            "stale_artifacts": [],
            "link_map": {},
        }
        try:
            nodes = self.client.list(
                "v1", "Node", label_selector={consts.TPU_PRESENT_LABEL: "true"}
            )
            cms = self.client.list(
                "v1", "ConfigMap", self.namespace, label_selector=_MANAGED_BY
            )
        except errors.ApiError as e:
            log.debug("fabric telemetry: list failed: %s", e)
            return summary
        node_by_name = {n["metadata"]["name"]: n for n in nodes}
        pool_of: Dict[str, str] = {}
        for pool in get_node_pools(nodes):
            for name in pool.node_names:
                pool_of[name] = pool.name

        link_map = self._load_link_map()
        # (pool, edge) -> {"bw_gbps", "degraded", "axis", "gang"}
        measured: Dict[Tuple[str, str], dict] = {}

        for cm in cms:
            raw = (cm["metadata"].get("annotations") or {}).get(
                consts.GANG_FABRIC_ANNOTATION
            )
            if not raw:
                continue
            slice_name = cm["metadata"]["name"]
            if slice_name.endswith("-gang"):
                slice_name = slice_name[: -len("-gang")]
            try:
                artifact = json.loads(raw)
            except ValueError:
                log.warning("fabric: malformed artifact on %s", cm["metadata"]["name"])
                continue
            self._ingest_artifact(
                slice_name, artifact, node_by_name, pool_of, link_map,
                measured, summary, cm,
            )

        self._prune_drained_pools(link_map, set(pool_of.values()))
        self._store_link_map(link_map)
        self._publish_series(measured, link_map)
        # episode bookkeeping: once a blamed host's label clears (repair
        # completed, or the node left), its Event dedup entry goes too —
        # a LATER second ICI failure is a new episode and must event
        # again, the same lifecycle _link_events follows
        self._host_events = {
            host for host in self._host_events
            if (node_by_name.get(host, {}).get("metadata", {}).get("labels") or {})
            .get(consts.TPU_PERF_LABEL) == consts.PERF_DEGRADED
        }
        summary["link_map"] = {
            pool: sorted(edges) for pool, edges in sorted(link_map.items())
        }
        return summary

    # -- per-gang ingestion --------------------------------------------------

    def _ingest_artifact(
        self,
        slice_name: str,
        artifact: dict,
        node_by_name: Dict[str, dict],
        pool_of: Dict[str, str],
        link_map: Dict[str, Dict[str, dict]],
        measured: Dict[Tuple[str, str], dict],
        summary: dict,
        cm: dict,
    ) -> None:
        members = [str(m) for m in (artifact.get("members") or [])]
        edges = artifact.get("edges") or {}
        if not members or not isinstance(edges, dict) or not edges:
            return
        if self._artifact_stale(slice_name, members, node_by_name):
            summary["stale_artifacts"].append(slice_name)
            return
        pool = pool_of.get(members[0], "")
        if not pool:
            return
        bws = sorted(
            float(meta.get("bw_gbps") or 0.0) for meta in edges.values()
        )
        median = bws[len(bws) // 2]
        floor = median * consts.FABRIC_LINK_DEGRADED_FRACTION
        degraded_edges: List[str] = []
        endpoint_counts: Dict[str, int] = {}
        for edge, meta in sorted(edges.items()):
            bw = float(meta.get("bw_gbps") or 0.0)
            # a one-edge gang has no peers to compare against; the
            # median of >=2 edges is the pool-relative reference
            is_degraded = len(edges) >= 2 and bw < floor
            measured[(pool, edge)] = {
                "bw_gbps": bw,
                "degraded": is_degraded,
                "axis": str(meta.get("axis") or ""),
                "gang": slice_name,
            }
            if is_degraded:
                degraded_edges.append(edge)
                for host in edge.split("|"):
                    endpoint_counts[host] = endpoint_counts.get(host, 0) + 1
            elif edge in link_map.get(pool, {}):
                # the cable proved itself healthy again: clear the record
                del link_map[pool][edge]
                self._link_events.discard(edge)

        host_blamed = {
            host for host, count in endpoint_counts.items()
            if count >= consts.FABRIC_HOST_BLAME_EDGES
        }
        for host in sorted(host_blamed):
            self._blame_host(host, node_by_name.get(host), degraded_edges)
            summary["host_blamed"].append(host)
        for edge in degraded_edges:
            summary["degraded_edges"].append(edge)
            if any(host in host_blamed for host in edge.split("|")):
                continue  # the endpoint is the story, not this cable
            record = {
                "bw_gbps": measured[(pool, edge)]["bw_gbps"],
                "median_gbps": round(median, 3),
                "axis": measured[(pool, edge)]["axis"],
                "gang": slice_name,
            }
            link_map.setdefault(pool, {})[edge] = record
            summary["link_blamed"].append(edge)
            if edge not in self._link_events:
                self.recorder.event(
                    cm, "Warning", "IciLinkDegraded",
                    f"gang {slice_name}: ICI link {edge} measured "
                    f"{record['bw_gbps']:.1f} GB/s against a gang median of "
                    f"{median:.1f} — blaming the link (single slow edge, both "
                    "endpoints otherwise healthy); recording it in "
                    f"{consts.LINK_HEALTH_CONFIGMAP} and re-placing gangs "
                    "around it. Both endpoint hosts stay in service.",
                )
                self._link_events.add(edge)
        summary["gangs"][slice_name] = {
            "pool": pool,
            "edges": len(edges),
            "median_gbps": round(median, 3),
            "degraded": sorted(degraded_edges),
            "worst_edge": artifact.get("worst_edge", ""),
        }

    @staticmethod
    def _artifact_stale(
        slice_name: str, members: List[str], node_by_name: Dict[str, dict]
    ) -> bool:
        """A fabric matrix describes the links of the block its gang ran
        on WHEN PROBED. After a re-place the gang ConfigMap (same name)
        still carries the old matrix; blaming from it would convict
        links the gang no longer touches — and an old matrix whose
        members were ALL torn down (labels nulled) must not sneak back
        in as an "implicit gang". Freshness test: every member exists;
        when the slice name maps to a live placement (some node carries
        its owner label), the artifact's member set must BE that
        placement's current member set; only a slice with no placement
        anywhere (a true whole-pool implicit gang) falls back to the
        existence-only test."""
        for member in members:
            if member not in node_by_name:
                return True
        # slice names are "tpu-slice-<owner>" for both placed gangs
        # (owner = the placement label value) and implicit pool gangs
        # (owner = the pool name, which no node ever carries as a
        # placement label). Hash-truncated long names fall through to
        # the implicit branch — conservative, and such names never
        # collide with a real owner label value anyway.
        owner = slice_name
        if owner.startswith("tpu-slice-"):
            owner = owner[len("tpu-slice-"):]
        placed = {
            name for name, node in node_by_name.items()
            if (node["metadata"].get("labels") or {}).get(consts.PLACEMENT_LABEL)
            == owner
        }
        if placed:
            return set(members) != placed
        # no node carries this owner: implicit gang — but members that
        # belong to some OTHER placement prove the block moved on
        return any(
            (node_by_name[m]["metadata"].get("labels") or {}).get(
                consts.PLACEMENT_LABEL
            )
            for m in members
        )

    def _blame_host(self, host: str, node: Optional[dict], degraded_edges: List[str]) -> None:
        """Multiple slow edges share this endpoint: indict the host's ICI
        interface and hand it to the grey-failure FSM via the exporter's
        own label — the analyzer never clears it; recovery is the repair
        FSM's job (revalidation demands the perf signal clear), exactly
        as for a floor-breaching chip. One known asymmetry: after the
        FSM's reinstall, a restarted exporter with healthy node-LOCAL
        probes may clear the label even though the ICI interface is
        still bad — the host then uncordons, the next gang placed on it
        re-indicts it, and the episode repeats. Each re-entry burns the
        shared retry budget, so a genuinely bad interface terminates in
        quarantine (the right call for hardware only a tech can fix)
        rather than churning forever."""
        if node is None:
            return
        labels = node["metadata"].get("labels") or {}
        touching = [e for e in degraded_edges if host in e.split("|")]
        if labels.get(consts.TPU_PERF_LABEL) != consts.PERF_DEGRADED:
            try:
                self.client.patch(
                    "v1", "Node", host,
                    {"metadata": {"labels": {
                        consts.TPU_PERF_LABEL: consts.PERF_DEGRADED
                    }}},
                )
            except errors.ApiError as e:
                log.warning("fabric: host blame label on %s failed: %s", host, e)
                return
            # keep the pass's cached node current: the end-of-sync event
            # bookkeeping reads this same dict and must see the label it
            # just published, not the pre-patch snapshot
            node["metadata"].setdefault("labels", {})[
                consts.TPU_PERF_LABEL
            ] = consts.PERF_DEGRADED
        if host not in self._host_events:
            self.recorder.event(
                node, "Warning", "IciHostDegraded",
                f"node {host}: {len(touching)} degraded ICI edges share this "
                f"endpoint ({', '.join(touching)}) — blaming the host's ICI "
                "interface, not the cables; entering the grey-failure repair "
                "FSM.",
            )
            self._host_events.add(host)

    # -- link-health map persistence -----------------------------------------

    def _load_link_map(self) -> Dict[str, Dict[str, dict]]:
        # a failed READ must propagate and abort the pass (sync's caller
        # isolates it): treating a 500 as "no records" would diff {}
        # against the previous pass's map and overwrite every standing
        # link blame with an empty ConfigMap — erasing the cut the
        # placement engine is routing around. Only NotFound (nothing
        # ever recorded) means an empty map.
        cm = self.client.get_or_none(
            "v1", "ConfigMap", consts.LINK_HEALTH_CONFIGMAP, self.namespace
        )
        self._stored_map = parse_link_map(cm)
        return {pool: dict(edges) for pool, edges in self._stored_map.items()}

    def _store_link_map(self, link_map: Dict[str, Dict[str, dict]]) -> None:
        link_map = {pool: edges for pool, edges in link_map.items() if edges}
        stored = {
            pool: edges
            for pool, edges in getattr(self, "_stored_map", {}).items()
            if edges
        }
        if link_map == stored:
            return  # nothing changed: no write, no watch echo
        data = {
            pool: json.dumps({"edges": edges}, sort_keys=True)
            for pool, edges in sorted(link_map.items())
        }
        cm = new_object(
            "v1", "ConfigMap", consts.LINK_HEALTH_CONFIGMAP, self.namespace,
            labels={"app.kubernetes.io/managed-by": consts.OPERATOR_NAME},
            data=data,
        )
        try:
            self.client.apply(cm)
        except errors.ApiError as e:
            log.warning("fabric: link-health map write failed: %s", e)

    def _prune_drained_pools(
        self, link_map: Dict[str, Dict[str, dict]], live_pools: Set[str]
    ) -> None:
        """A drained pool's records (and series) go with it: a frozen
        last value would keep the link alert firing for hardware that
        no longer exists."""
        for pool in list(link_map):
            if pool not in live_pools:
                for edge in link_map[pool]:
                    self._link_events.discard(edge)
                del link_map[pool]

    # -- series --------------------------------------------------------------

    def _publish_series(
        self,
        measured: Dict[Tuple[str, str], dict],
        link_map: Dict[str, Dict[str, dict]],
    ) -> None:
        live: Set[Tuple[str, str]] = set()
        for (pool, edge), info in measured.items():
            self.metrics.ici_link_bandwidth.labels(pool, edge).set(info["bw_gbps"])
            self.metrics.ici_link_degraded.labels(pool, edge).set(
                1 if info["degraded"] else 0
            )
            live.add((pool, edge))
        # recorded-but-unmeasured links (no live gang straddles the cut
        # anymore — that is the point) keep firing from the record
        for pool, edges in link_map.items():
            for edge, record in edges.items():
                if (pool, edge) in live:
                    continue
                self.metrics.ici_link_bandwidth.labels(pool, edge).set(
                    float(record.get("bw_gbps") or 0.0)
                )
                self.metrics.ici_link_degraded.labels(pool, edge).set(1)
                live.add((pool, edge))
        for pool, edge in self._link_series - live:
            try:
                self.metrics.ici_link_bandwidth.remove(pool, edge)
                self.metrics.ici_link_degraded.remove(pool, edge)
            except KeyError:
                pass
        self._link_series = live
