"""TPUSlice reconciler.

Reference: ``controllers/nvidiadriver_controller.go:75-207`` — per-CR
libtpu deployment: require a ClusterPolicy to exist, validate node-selector
disjointness, partition the CR's nodes into pools, sync the per-pool
DaemonSet state, publish conditions, requeue 5s while NotReady.
"""

from __future__ import annotations

import logging
from typing import List, Optional

from tpu_operator import consts
from tpu_operator.api.clusterpolicy import (
    CLUSTER_POLICY_API_VERSION,
    CLUSTER_POLICY_KIND,
    ClusterPolicy,
)
from tpu_operator.api.tpuslice import (
    TPU_SLICE_API_VERSION,
    TPU_SLICE_KIND,
    TPUSlice,
)
from tpu_operator.catalog import InfoCatalog
from tpu_operator.controllers.status import publish_status
from tpu_operator.controllers.tpuslice_validator import ValidationError, validate_node_selectors
from tpu_operator.kube import errors, trace
from tpu_operator.kube.cached import CachedReadClient
from tpu_operator.kube.client import Client
from tpu_operator.kube.controller import Controller, Request, Result, generation_changed
from tpu_operator.kube.objects import ObjectDict, matches_selector
from tpu_operator.nodepool import get_node_pools
from tpu_operator.state.skel import SyncStates
from tpu_operator.states.tpuslice_state import TPUSliceLibtpuState

log = logging.getLogger(__name__)


class TPUSliceReconciler:
    def __init__(self, client: Client, namespace: str = consts.DEFAULT_OPERATOR_NAMESPACE):
        self.client = client
        self.namespace = namespace

    def reconcile(self, req: Request) -> Result:
        obj = self.client.get_or_none(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, req.name)
        if obj is None:
            return Result()  # GC via ownerReferences
        ts = TPUSlice.from_unstructured(obj)

        # a ClusterPolicy must exist (reference: nvidiadriver_controller.go:102-125)
        cps = self.client.list(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND)
        if not cps:
            self._status(obj, "notReady", reason="NoClusterPolicy",
                         message="no ClusterPolicy found; TPUSlice requires one")
            return Result(requeue_after=consts.REQUEUE_NOT_READY_SECONDS)
        cps.sort(key=lambda o: (o["metadata"].get("creationTimestamp", ""), o["metadata"]["name"]))
        cp = ClusterPolicy.from_unstructured(cps[0])
        if not cp.spec.libtpu.use_slice_crd():
            # without this gate the ClusterPolicy's own libtpu state and the
            # per-CR DaemonSets would both install libtpu on the same nodes
            # (reference: the UseNvidiaDriverCRD gate)
            self._status(
                obj, "notReady", error=True, reason="TPUSliceCRDDisabled",
                message="ClusterPolicy spec.libtpu.useTPUSliceCRD is not true; "
                        "TPUSlice CRs are inactive",
            )
            return Result(requeue_after=consts.REQUEUE_NOT_READY_SECONDS)

        all_nodes = self.client.list("v1", "Node")
        try:
            validate_node_selectors(self.client, ts, all_nodes)
        except ValidationError as e:
            self._status(obj, "notReady", error=True, reason="NodeSelectorConflict", message=str(e))
            return Result(requeue_after=consts.REQUEUE_NOT_READY_SECONDS)

        selector = ts.spec.get_node_selector()
        nodes = [
            n for n in all_nodes
            if matches_selector(n["metadata"].get("labels"), selector)
        ]
        pools = get_node_pools(nodes)
        catalog = InfoCatalog(
            cluster_policy=cp,
            namespace=self.namespace,
            tpu_slice=ts,
            node_pools=pools,
            has_tpu_nodes=bool(pools),
        )
        state = TPUSliceLibtpuState(ts)
        with trace.span("sync-pools", pools=len(pools)):
            result = state.sync(self.client, catalog, owner=obj)
        if result.state == SyncStates.ERROR:
            self._status(obj, "notReady", error=True, reason="SyncError", message=result.error or "")
            return Result(requeue=True)
        if result.state == SyncStates.NOT_READY:
            self._status(obj, "notReady", reason="DaemonSetsNotReady",
                         message="libtpu DaemonSets are not ready on all pools")
            return Result(requeue_after=consts.REQUEUE_NOT_READY_SECONDS)
        self._status(obj, "ready", reason="Ready",
                     message=f"libtpu deployed on {len(pools)} node pool(s)")
        return Result()

    def _status(self, obj: ObjectDict, state: str, reason: str = "", message: str = "", error: bool = False):
        publish_status(self.client, obj, state, reason, message, error)


# fixed shard fan-out for TPUSlice queues: enough for worker isolation,
# small enough that the per-shard metric children stay bounded
TPUSLICE_SHARDS = 4


def slice_shard(obj: ObjectDict) -> str:
    """The queue shard a TPUSlice's work rides on: a STABLE hash of the
    CR name. Deliberately NOT the slice's pool — a slice's pool changes
    over its life (placement writes status.pool, admins re-pin
    spec.pool), and a shard key derived from mutable state would let the
    same slice sit queued on two shards and reconcile CONCURRENTLY
    (racing DaemonSet creates, last-writer-wins status), with requeues
    pinned to the stale shard forever. Name-hash routing keeps the old
    per-name serialization exactly (same name → same queue, always)
    while one wedged slice's worker can no longer starve the other
    shards' slices."""
    import zlib

    name = obj["metadata"]["name"]
    return f"h{zlib.crc32(name.encode()) % TPUSLICE_SHARDS}"


def setup_with_manager(mgr, reconciler: TPUSliceReconciler) -> Controller:
    """reference: SetupWithManager nvidiadriver_controller.go:238+ — watch
    TPUSlice (generation-gated), ClusterPolicy, Nodes, and owned
    DaemonSets. Requests are sharded by a stable name hash (see
    ``slice_shard``) so slices get isolated queues + workers without
    ever losing per-name serialization."""
    ctrl = Controller(
        "tpuslice", reconciler, coalesce_window=consts.NODE_EVENT_COALESCE_SECONDS
    )
    reconciler.client = CachedReadClient(reconciler.client, mgr)

    def to_sharded_request(obj: ObjectDict) -> List[Request]:
        return [Request(name=obj["metadata"]["name"], shard=slice_shard(obj))]

    def map_to_all_slices(_obj) -> List[Request]:
        try:
            slices = reconciler.client.list(TPU_SLICE_API_VERSION, TPU_SLICE_KIND)
        except errors.ApiError:
            return []
        return [req for s in slices for req in to_sharded_request(s)]

    def owned_daemonset(event_type, old, new) -> bool:
        refs = new["metadata"].get("ownerReferences", [])
        return any(r.get("kind") == TPU_SLICE_KIND for r in refs)

    def node_changed(event_type, old: Optional[ObjectDict], new: ObjectDict) -> bool:
        if event_type != "MODIFIED" or old is None:
            return True
        return old["metadata"].get("labels") != new["metadata"].get("labels")

    ctrl.watch(
        mgr.informer_for(TPU_SLICE_API_VERSION, TPU_SLICE_KIND),
        mapper=to_sharded_request, predicate=generation_changed,
    )
    ctrl.watch(mgr.informer_for(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND), mapper=map_to_all_slices)
    ctrl.watch(mgr.informer_for("v1", "Node"), mapper=map_to_all_slices, predicate=node_changed)
    ctrl.watch(mgr.informer_for("apps/v1", "DaemonSet"), mapper=map_to_all_slices, predicate=owned_daemonset)
    mgr.add_controller(ctrl)
    return ctrl
