"""TPUJob reconciler: elastic fault-tolerant training lifecycle.

The job layer over the placement stack (ROADMAP item 3). One TPUJob owns
one TPUSlice (``<job>-slice``) and the controller drives the whole
lifecycle as a bounded FSM persisted in ``status.job``::

    Pending → Placing → Running ⇄ Checkpointing → Growing → Resuming
                  ↑         │
                  │         └─ gang broken ─→ Shrinking ─→ Resuming
                  └──────────── nothing placeable (backoff) ──→ Failed

Every decision recomputes from cluster state (the slice's placement
status, node service labels, the link-health map, the job progress
ConfigMap), so a restarted operator re-derives the same world — the
engine-room convention every other controller here follows.

**Shrink** fires on any of the three out-of-service signals (health FSM
verdict, grey-failure perf label, fabric link cut through the block) or
on preemption — all of which surface as "the owned slice is no longer
Scheduled on an in-service gang". The controller asks the torus
allocator for the largest placeable sub-block of the desired shape
(clean fit, never preemption — ``placement.engine.largest_placeable_shape``)
bounded below by ``spec.gang.minShape``, patches the slice's placement
shape to it, and the gang resumes from the newest good checkpoint on a
re-derived mesh. **Grow** fires when the desired shape becomes placeable
again (capacity healed): the controller first drives a checkpoint
barrier through the progress ConfigMap (zero steps lost on a planned
resize), then patches the shape back up.

**Quarantine**: attempts that make no progress — nothing placeable at or
above the min shape, or the trainer erroring on resume — burn a
full-jitter backoff budget (``kube/backoff.py``, the same bounded-retry
pattern the health controller quarantines through). The budget resets
when the job reaches Running; exhaustion parks the job in ``Failed``
with an Event instead of crash-looping through the placement queue.
"""

from __future__ import annotations

import logging
import math
import random
import time
from typing import Dict, List, Optional, Tuple

from tpu_operator import consts
from tpu_operator.api.tpujob import (
    TERMINAL_PHASES,
    TPU_JOB_API_VERSION,
    TPU_JOB_KIND,
    JobPhase,
    TPUJob,
)
from tpu_operator.api.tpuslice import (
    TPU_SLICE_API_VERSION,
    TPU_SLICE_KIND,
    new_tpu_slice,
)
from tpu_operator.controllers.operator_metrics import get_metrics
from tpu_operator.kube import errors, trace
from tpu_operator.kube.backoff import RetryBudget
from tpu_operator.kube.cached import CachedReadClient
from tpu_operator.kube.client import Client
from tpu_operator.kube.controller import Controller, Request, Result, generation_changed
from tpu_operator.kube.events import EventRecorder
from tpu_operator.kube.objects import ObjectDict
from tpu_operator.placement.engine import (
    PlacementPhase,
    labels_unavailable,
    largest_placeable_shape,
)
from tpu_operator.placement.torus import parse_shape

log = logging.getLogger(__name__)

JOB_MANAGER = "tpu-job-controller"


def _shape_str(shape: Tuple[int, int, int]) -> str:
    return "x".join(str(d) for d in shape)


def _volume(shape: Tuple[int, int, int]) -> int:
    return math.prod(shape)


class JobReconciler:
    def __init__(self, client: Client, namespace: str = consts.DEFAULT_OPERATOR_NAMESPACE):
        self.client = client
        self.namespace = namespace
        self.recorder = EventRecorder(client, namespace, component=JOB_MANAGER)
        self.metrics = get_metrics()
        # full-jitter needs a private RNG so tests/drills can seed it
        self.rng = random.Random()
        # jobs with live labelled series, so deletion retires them (O005)
        from tpu_operator.kube import racecheck

        self._series_lock = racecheck.lock("JobReconciler._series_lock")
        self._job_series: set = set()
        self._pod_set = None  # lazy: the manager swaps the client post-init

    @property
    def pods(self):
        """The worker-pod converger (the pod data plane's control-plane
        half), bound to whatever client the reconciler currently holds."""
        from tpu_operator.dataplane.pods import WorkerPodSet

        if self._pod_set is None or self._pod_set.client is not self.client:
            self._pod_set = WorkerPodSet(self.client, self.namespace)
        return self._pod_set

    # -- worker pods ---------------------------------------------------------

    def _converge_workers(
        self, obj: ObjectDict, job: TPUJob, gang_nodes: List[str], shape: str
    ) -> None:
        """One worker Pod per gang member, pinned to its node. The gang
        hash (job + shape + member set) rides every worker's env: a
        re-place renders different hashes, the convergence loop replaces
        the pods, and the new generation re-runs the rendezvous — stale
        check-ins from the old generation can never complete it."""
        from tpu_operator.dataplane.pods import job_worker_name
        from tpu_operator.utils import object_hash

        gang_hash = object_hash(
            {"job": job.name, "shape": shape, "nodes": list(gang_nodes)}
        )[:12]
        count = len(gang_nodes)
        workers = []
        for index, node_name in enumerate(gang_nodes):
            env = {
                consts.WORKER_ENV_JOB_NAME: job.name,
                consts.WORKER_ENV_WORKER_INDEX: str(index),
                consts.WORKER_ENV_WORKER_COUNT: str(count),
                consts.WORKER_ENV_GANG_HASH: gang_hash,
                consts.WORKER_ENV_NAMESPACE: self.namespace,
            }
            if job.spec.checkpoint.dir:
                env[consts.WORKER_ENV_CHECKPOINT_DIR] = job.spec.checkpoint.dir
            node = self.client.get_or_none("v1", "Node", node_name)
            chips = self._int(
                (((node or {}).get("status") or {}).get("capacity") or {})
                .get(consts.TPU_RESOURCE_NAME)
            )
            workers.append({
                "name": job_worker_name(job.name, index),
                "env": env,
                "node": node_name,
                "chips": chips,
            })
        self.pods.converge(obj, consts.POD_MAIN_JOB_WORKER, workers)
        # a shrink leaves high-index workers behind: sweep them (owned only)
        self.pods.sweep(TPU_JOB_KIND, job.name, live=[w["name"] for w in workers])

    # -- series hygiene ------------------------------------------------------

    def _export(self, job: str, step: int, epoch: int, hosts: int, restarts: int) -> None:
        with self._series_lock:
            self._job_series.add(job)
        self.metrics.job_step.labels(job).set(step)
        self.metrics.job_epoch.labels(job).set(epoch)
        self.metrics.job_gang_hosts.labels(job).set(hosts)
        self.metrics.job_restarts.labels(job).set(restarts)

    def _retire_series(self, job: str) -> None:
        with self._series_lock:
            if job not in self._job_series:
                return
            self._job_series.discard(job)
        for gauge in (
            self.metrics.job_step,
            self.metrics.job_epoch,
            self.metrics.job_gang_hosts,
            self.metrics.job_restarts,
        ):
            try:
                gauge.remove(job)
            except KeyError:
                pass

    # -- cluster reads -------------------------------------------------------

    def _progress(self, job: str) -> dict:
        cm = self.client.get_or_none(
            "v1", "ConfigMap", job + consts.JOB_PROGRESS_SUFFIX, self.namespace
        )
        return (cm or {}).get("data") or {}

    def _degraded_links(self) -> List[tuple]:
        from tpu_operator.controllers.fabric_telemetry import degraded_link_pairs

        return degraded_link_pairs(self.client, self.namespace)

    # -- slice management ----------------------------------------------------

    def _slice_spec(self, job: TPUJob, shape: str) -> dict:
        return {
            "placement": {
                "shape": shape,
                "priority": job.spec.gang.priority,
                "preemptionPolicy": job.spec.gang.preemption_policy,
                **({"pool": job.spec.gang.pool} if job.spec.gang.pool else {}),
            }
        }

    def _ensure_slice(self, obj: ObjectDict, job: TPUJob, shape: str) -> Optional[ObjectDict]:
        """Create the owned TPUSlice (or converge its placement shape).
        Returns the live slice, or None when the create/patch must
        retry."""
        name = job.name + consts.JOB_SLICE_SUFFIX
        slice_obj = self.client.get_or_none(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, name)
        tenant = (obj["metadata"].get("labels") or {}).get(consts.TENANT_LABEL) or ""
        if slice_obj is None:
            body = new_tpu_slice(name, self._slice_spec(job, shape))
            if tenant:
                # the job's tenant rides onto the owned slice so the
                # fair-share engine accounts the gang to the right quota
                body["metadata"].setdefault("labels", {})[consts.TENANT_LABEL] = tenant
            body["metadata"]["ownerReferences"] = [{
                "apiVersion": TPU_JOB_API_VERSION,
                "kind": TPU_JOB_KIND,
                "name": job.name,
                "uid": obj["metadata"].get("uid", ""),
            }]
            try:
                return self.client.create(body)  # tpuop-lint: kinds=tpu.google.com/v1alpha1/TPUSlice
            except errors.AlreadyExists:
                return self.client.get_or_none(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, name)
            except errors.ApiError as e:
                log.warning("job %s: slice create failed: %s", job.name, e)
                return None
        desired_placement = self._slice_spec(job, shape)["placement"]
        current = (slice_obj.get("spec") or {}).get("placement") or {}
        if any(current.get(k) != v for k, v in desired_placement.items()):
            try:
                self.client.patch(  # tpuop-lint: kinds=tpu.google.com/v1alpha1/TPUSlice
                    TPU_SLICE_API_VERSION, TPU_SLICE_KIND, name,
                    {"spec": self._slice_spec(job, shape)},
                )
            except errors.ApiError as e:
                log.warning("job %s: slice shape patch failed: %s", job.name, e)
                return None
            slice_obj = self.client.get_or_none(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, name)
        if slice_obj is not None:
            held = (slice_obj["metadata"].get("labels") or {}).get(consts.TENANT_LABEL) or ""
            if held != tenant:
                # re-tenanted job: converge the slice label (None clears)
                try:
                    self.client.patch(  # tpuop-lint: kinds=tpu.google.com/v1alpha1/TPUSlice
                        TPU_SLICE_API_VERSION, TPU_SLICE_KIND, name,
                        {"metadata": {"labels": {consts.TENANT_LABEL: tenant or None}}},
                    )
                except errors.ApiError as e:
                    log.warning("job %s: slice tenant patch failed: %s", job.name, e)
                    return None
        return slice_obj

    def _delete_slice(self, job_name: str, owned_only: bool = False) -> None:
        """Tear down the job's owned slice. ``owned_only`` (the
        job-vanished sweep path) verifies the TPUJob ownerReference
        first: a request name that never was a job (a foreign
        ``*-progress`` ConfigMap, a mistyped name) must not delete a
        user's coincidentally-named TPUSlice."""
        name = job_name + consts.JOB_SLICE_SUFFIX
        if owned_only:
            obj = self.client.get_or_none(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, name)
            if obj is None or not any(
                ref.get("kind") == TPU_JOB_KIND and ref.get("name") == job_name
                for ref in obj["metadata"].get("ownerReferences") or []
            ):
                return
        try:
            self.client.delete(  # tpuop-lint: kinds=tpu.google.com/v1alpha1/TPUSlice
                TPU_SLICE_API_VERSION, TPU_SLICE_KIND, name
            )
        except errors.NotFound:
            pass
        except errors.ApiError as e:
            log.debug("job %s: slice delete deferred: %s", job_name, e)

    # -- gang health ---------------------------------------------------------

    def _gang_state(self, slice_obj: Optional[ObjectDict], links: List[tuple]) -> dict:
        """What the owned slice's world looks like: scheduled?, member
        nodes, out-of-service members (with which signal), a link cut
        inside the block, a preemption verdict."""
        state = {
            "scheduled": False, "nodes": [], "out": {}, "cut": "",
            "preempted": False, "unschedulable": False, "message": "",
        }
        if slice_obj is None:
            return state
        placement = (slice_obj.get("status") or {}).get("placement") or {}
        state["message"] = str(placement.get("message") or "")
        phase = placement.get("phase")
        state["scheduled"] = phase == PlacementPhase.SCHEDULED
        state["unschedulable"] = phase == PlacementPhase.UNSCHEDULABLE
        state["preempted"] = "preempted" in state["message"]
        nodes = list(placement.get("nodes") or [])
        state["nodes"] = nodes
        members = set(nodes)
        for name in nodes:
            node = self.client.get_or_none("v1", "Node", name)
            if node is None:
                state["out"][name] = "node-gone"
                continue
            labels = node["metadata"].get("labels") or {}
            if not labels_unavailable(labels):
                continue
            if labels.get(consts.TPU_PERF_LABEL) == consts.PERF_DEGRADED:
                state["out"][name] = "grey-failure"
            elif labels.get(consts.REPAIR_STATE_LABEL):
                state["out"][name] = f"repair:{labels[consts.REPAIR_STATE_LABEL]}"
            else:
                state["out"][name] = "host-health"
        for a, b in links:
            if a in members and b in members:
                state["cut"] = f"{a}|{b}"
                break
        return state

    @staticmethod
    def _classify_cause(gang: dict) -> str:
        if gang["out"]:
            node, signal = sorted(gang["out"].items())[0]
            return f"{signal} ({node})"
        if gang["cut"]:
            return f"link-cut ({gang['cut']})"
        if gang["preempted"]:
            return "preemption"
        if gang["unschedulable"]:
            return "unschedulable"
        return "re-placed"

    # -- status --------------------------------------------------------------

    def _publish(self, obj: ObjectDict, block: dict) -> bool:
        current = (obj.get("status") or {}).get("job") or {}
        if current == block:
            return True
        body = dict(block)
        for stale in current:
            if stale not in body:
                body[stale] = None  # merge patch: null removes stale keys
        try:
            self.client.patch_status(  # tpuop-lint: kinds=tpu.google.com/v1alpha1/TPUJob
                TPU_JOB_API_VERSION, TPU_JOB_KIND, obj["metadata"]["name"],
                {"status": {"job": body, "state": block.get("phase", "")}},
            )
        except errors.NotFound:
            return True
        except errors.ApiError as e:
            log.debug("job status publish for %s failed: %s", obj["metadata"]["name"], e)
            return False
        return True

    def _request_progress_key(self, job_name: str, key: str, token: str) -> bool:
        """Write one controller-owned key into the progress ConfigMap
        (the checkpoint/restart handshakes). The gang owns the CM's
        lifecycle; until it exists there is nobody to handshake with."""
        try:
            self.client.patch(
                "v1", "ConfigMap", job_name + consts.JOB_PROGRESS_SUFFIX,
                {"data": {key: token}}, self.namespace,
            )
        except errors.NotFound:
            return False
        except errors.ApiError as e:
            log.debug("job %s: progress key %s write failed: %s", job_name, key, e)
            return False
        return True

    # -- reconcile -----------------------------------------------------------

    def reconcile(self, req: Request) -> Result:
        obj = self.client.get_or_none(TPU_JOB_API_VERSION, TPU_JOB_KIND, req.name)
        if obj is None:
            # deleted: retire series; the owned slice/progress CM are
            # GC'd via ownerReferences on a real apiserver, and swept
            # here for stores without cascade (ownership verified — the
            # request name may never have been a job)
            self._retire_series(req.name)
            self._delete_slice(req.name, owned_only=True)
            self.pods.sweep(TPU_JOB_KIND, req.name)
            return Result()
        job = TPUJob.from_unstructured(obj)
        prior = dict(job.status.job or {})
        phase = prior.get("phase") or JobPhase.PENDING
        if phase in TERMINAL_PHASES:
            return Result()

        # -- validate the elasticity contract once per pass
        desired = parse_shape(job.spec.gang.shape)
        min_shape = parse_shape(job.spec.gang.min_shape or job.spec.gang.shape)
        if desired is None or min_shape is None or _volume(min_shape) > _volume(desired):
            block = dict(prior)
            self._fail(
                obj, block,
                f"invalid gang spec: shape={job.spec.gang.shape!r} "
                f"minShape={job.spec.gang.min_shape!r}",
            )
            self._export(req.name, self._int(block.get("step")),
                         self._int(block.get("epoch")), 0,
                         self._int(block.get("restarts")))
            return Result(requeue=not self._publish(obj, block))
        budget = RetryBudget(
            retry_limit=job.spec.backoff.retry_limit,
            base_delay_seconds=job.spec.backoff.base_seconds,
            max_delay_seconds=job.spec.backoff.max_seconds,
        )

        # -- world state
        progress = self._progress(job.name)
        step = self._int(progress.get(consts.JOB_PROGRESS_STEP), self._int(prior.get("step")))
        epoch = self._int(progress.get(consts.JOB_PROGRESS_EPOCH), self._int(prior.get("epoch")))
        ckpt_step = self._int(
            progress.get(consts.JOB_PROGRESS_CHECKPOINT_STEP),
            self._int(prior.get("checkpointStep")),
        )
        world = self._int(progress.get(consts.JOB_PROGRESS_WORLD))
        pstatus = progress.get(consts.JOB_PROGRESS_STATUS, "")

        block = {
            "phase": phase,
            "step": step,
            "epoch": epoch,
            "checkpointStep": ckpt_step,
            "desiredShape": _shape_str(desired),
            "shape": prior.get("shape") or _shape_str(desired),
            "hosts": 0,
            "restarts": self._int(prior.get("restarts")),
            "totalRestarts": self._int(prior.get("totalRestarts")),
            "shrinks": list(prior.get("shrinks") or []),
            "causes": list(prior.get("causes") or []),
        }
        if prior.get("nextAttemptAt"):
            block["nextAttemptAt"] = prior["nextAttemptAt"]
        if prior.get("message"):
            block["message"] = prior["message"]
        if prior.get("barrier"):
            block["barrier"] = prior["barrier"]
        if prior.get("barrierSeq"):
            block["barrierSeq"] = prior["barrierSeq"]
        # defrag-migration bookkeeping: the request token last honored
        # (so a stale defragRequest never re-migrates) and the one a
        # barrier is currently in flight for
        if prior.get("defragHandled"):
            block["defragHandled"] = prior["defragHandled"]
        if prior.get("defragPending"):
            block["defragPending"] = prior["defragPending"]
        # the risk scorer's planned-migration twin (riskMigrateRequest):
        # same ledger shape, same stale-token rule
        if prior.get("riskHandled"):
            block["riskHandled"] = prior["riskHandled"]
        if prior.get("riskPending"):
            block["riskPending"] = prior["riskPending"]

        # -- completion first: a finished job frees its capacity
        if pstatus == consts.JOB_PROGRESS_COMPLETE and step >= job.spec.workload.steps:
            block.update(phase=JobPhase.SUCCEEDED, hosts=0, message="")
            block.pop("nextAttemptAt", None)
            self._delete_slice(job.name)
            self.pods.sweep(TPU_JOB_KIND, job.name)
            self.recorder.normal(
                obj, "JobSucceeded",
                f"training complete at step {step} (checkpoint epoch {epoch})",
            )
            ok = self._publish(obj, block)
            self._export(job.name, step, epoch, 0, 0)
            return Result(requeue=not ok)

        # -- converge the owned slice to the current target shape
        target_str = block["shape"]
        target = parse_shape(target_str) or desired
        slice_obj = self._ensure_slice(obj, job, target_str)
        if slice_obj is None:
            block["phase"] = JobPhase.PLACING  # create/patch retried next pass
            self._publish(obj, block)
            return Result(requeue=True)
        links = self._degraded_links()
        gang = self._gang_state(slice_obj, links)
        healthy = gang["scheduled"] and not gang["out"] and not gang["cut"]
        block["hosts"] = len(gang["nodes"]) if healthy else 0

        with trace.span(
            "job-fsm", phase=phase, healthy=healthy, step=step, shape=target_str
        ):
            if healthy:
                result = self._reconcile_healthy(
                    obj, job, block, budget, desired, target, world, pstatus,
                    progress, gang["nodes"],
                )
            else:
                result = self._reconcile_broken(
                    obj, job, block, budget, desired, min_shape, gang, links
                )
        self._export(
            job.name, block["step"], block["epoch"], block["hosts"], block["restarts"]
        )
        ok = self._publish(obj, block)
        if not ok:
            return Result(requeue=True)
        if block["phase"] in TERMINAL_PHASES:
            return Result()
        return result

    # -- the healthy half ----------------------------------------------------

    def _reconcile_healthy(
        self,
        obj: ObjectDict,
        job: TPUJob,
        block: dict,
        budget: RetryBudget,
        desired: Tuple[int, int, int],
        target: Tuple[int, int, int],
        world: int,
        pstatus: str,
        progress: dict,
        gang_nodes: List[str],
    ) -> Result:
        phase = block["phase"]
        hosts = block["hosts"]

        # a healthy placed gang always has its worker pods converged —
        # idempotent (hash match = no-op), and any generation change
        # (re-place, resize) re-renders them with a fresh gang hash
        self._converge_workers(obj, job, gang_nodes, _shape_str(target))

        if pstatus == consts.JOB_PROGRESS_FAILED:
            # the gang is placed but training errored: restart from the
            # newest good checkpoint, against the budget
            return self._charge_attempt(
                obj, job, block, budget,
                cause=f"trainer-error: {progress.get(consts.JOB_PROGRESS_ERROR, '')}".strip(),
                restart=True,
            )

        if phase == JobPhase.CHECKPOINTING:
            token = str(block.get("barrier") or "")
            ack = progress.get(consts.JOB_PROGRESS_CHECKPOINT_ACK, "")
            if token.startswith(("defrag-", "risk-")):
                # a planned-migration barrier — the defrag controller's
                # consolidation move or the risk scorer's walk-off-the-
                # dying-host move: checkpoint first, THEN tear the gang
                # down so the placement engine re-seats it — the move
                # loses zero steps, exactly like a planned grow
                if ack == token:
                    self._teardown_gang(gang_nodes)
                    # tear the data plane down in the SAME pass: the
                    # re-place can land this very pass, and a surviving
                    # old-generation worker would otherwise run (and the
                    # next generation re-execute) steps past the barrier
                    # checkpoint — lost work on a planned move
                    self._converge_workers(obj, job, [], _shape_str(target))
                    # lift the barrier key: the runner HOLDS at a
                    # planned-migration barrier (zero steps past the
                    # checkpoint), and the next pod generation reads the
                    # same CM — a stale token would hold it at a barrier
                    # nobody owns
                    self._request_progress_key(
                        job.name, consts.JOB_CHECKPOINT_REQUEST, ""
                    )
                    if token.startswith("defrag-"):
                        block["defragHandled"] = str(
                            block.pop("defragPending", "") or ""
                        )
                        why = "defrag migration"
                    else:
                        block["riskHandled"] = str(
                            block.pop("riskPending", "") or ""
                        )
                        why = "predicted-failure migration"
                    block.pop("barrier", None)
                    block["phase"] = JobPhase.RESUMING
                    block["message"] = ""
                    self.recorder.normal(
                        obj, "JobMigrating",
                        f"{why}: checkpointed at step {block['step']}, "
                        "gang torn down for re-placement",
                    )
                return Result(requeue_after=consts.JOB_RESYNC_SECONDS)
            if not token or target == desired:
                # lost/landed barrier: drop back to Running (the grow
                # check re-fires next pass if capacity still allows)
                block["phase"] = JobPhase.RUNNING
                block.pop("barrier", None)
                block.pop("defragPending", None)
                block.pop("riskPending", None)
            elif ack == token:
                # barrier satisfied: grow — zero steps past the barrier.
                # Re-verify first: capacity may have vanished while the
                # gang checkpointed, and a blind grow would bounce the
                # job through Unschedulable for nothing.
                block.pop("barrier", None)
                if self._placeable(job, desired, _volume(desired), exclude_self=True):
                    self._record_resize(
                        obj, job, block, _shape_str(desired), "grow",
                        cause="capacity healed",
                    )
                else:
                    block["phase"] = JobPhase.RUNNING
            return Result(requeue_after=consts.JOB_RESYNC_SECONDS)

        if phase in (
            JobPhase.PENDING, JobPhase.PLACING, JobPhase.SHRINKING,
            JobPhase.GROWING, JobPhase.RESUMING,
        ):
            # placed; wait for the gang to train at this world size
            if world == hosts and pstatus == consts.JOB_PROGRESS_RUNNING:
                if phase != JobPhase.PENDING and block["restarts"]:
                    self.recorder.normal(
                        obj, "JobResumed",
                        f"resumed at step {block['step']} on {hosts} host(s)",
                    )
                block["phase"] = JobPhase.RUNNING
                block["restarts"] = 0  # progress resets the failure streak
                block.pop("nextAttemptAt", None)
                block["message"] = ""
                if phase != JobPhase.RUNNING:
                    self.recorder.normal(
                        obj, "JobPlaced",
                        f"gang of {hosts} host(s) placed as "
                        f"{_shape_str(target)}; training",
                    )
            else:
                block["phase"] = (
                    JobPhase.RESUMING
                    if phase in (JobPhase.SHRINKING, JobPhase.GROWING, JobPhase.RESUMING)
                    else JobPhase.PLACING
                )
            return Result(requeue_after=consts.JOB_RESYNC_SECONDS)

        # phase == RUNNING: look for a grow opportunity
        if target != desired:
            grown = self._placeable(job, desired, _volume(desired), exclude_self=True)
            if grown is not None:
                # monotonic sequence persisted in status: the token can
                # never repeat, so a stale checkpointAck from an EARLIER
                # grow can never satisfy this barrier (ack == token with
                # no fresh checkpoint would lose up to a cadence of
                # steps on a planned resize)
                seq = self._int(block.get("barrierSeq")) + 1
                token = f"grow-{seq}-{block['step']}"
                if self._request_progress_key(
                    job.name, consts.JOB_CHECKPOINT_REQUEST, token
                ):
                    block["barrierSeq"] = seq
                    block["phase"] = JobPhase.CHECKPOINTING
                    block["barrier"] = token
                    self.recorder.normal(
                        obj, "JobGrowing",
                        f"capacity healed: checkpointing before growing "
                        f"{_shape_str(target)} -> {_shape_str(desired)}",
                    )
        # still RUNNING (no grow barrier fired): honor a pending defrag
        # migration request — same barrier machinery, same monotonic
        # sequence, `defrag-` token prefix routes the ack to the
        # teardown-and-re-place arm instead of the slice-shape patch.
        # A token already honored (status.job.defragHandled) is stale:
        # executing it twice would checkpoint-cycle the gang for nothing.
        defrag_req = str(progress.get(consts.JOB_DEFRAG_REQUEST, "") or "")
        if (
            block["phase"] == JobPhase.RUNNING
            and defrag_req
            and defrag_req != str(block.get("defragHandled") or "")
        ):
            seq = self._int(block.get("barrierSeq")) + 1
            token = f"defrag-{seq}-{block['step']}"
            if self._request_progress_key(
                job.name, consts.JOB_CHECKPOINT_REQUEST, token
            ):
                block["barrierSeq"] = seq
                block["phase"] = JobPhase.CHECKPOINTING
                block["barrier"] = token
                block["defragPending"] = defrag_req
                self.recorder.normal(
                    obj, "JobMigrating",
                    "defrag migration requested: checkpointing before "
                    "re-placing the gang",
                )
        # ... and the risk scorer's predicted-failure migration — the
        # SAME barrier machinery with a `risk-` token prefix, so a host
        # the telemetry says is dying is walked away from with zero
        # lost steps. Honored tokens land in status.job.riskHandled;
        # redelivery of one is stale and never migrates twice.
        risk_req = str(progress.get(consts.JOB_RISK_MIGRATE_REQUEST, "") or "")
        if (
            block["phase"] == JobPhase.RUNNING
            and risk_req
            and risk_req != str(block.get("riskHandled") or "")
        ):
            seq = self._int(block.get("barrierSeq")) + 1
            token = f"risk-{seq}-{block['step']}"
            if self._request_progress_key(
                job.name, consts.JOB_CHECKPOINT_REQUEST, token
            ):
                block["barrierSeq"] = seq
                block["phase"] = JobPhase.CHECKPOINTING
                block["barrier"] = token
                block["riskPending"] = risk_req
                self.recorder.normal(
                    obj, "JobMigrating",
                    "predicted host failure: checkpointing before "
                    "re-placing the gang off the risky host",
                )
        return Result(requeue_after=consts.JOB_RESYNC_SECONDS)

    def _teardown_gang(self, gang_nodes: List[str]) -> None:
        """Clear the gang's assignment labels so the placement engine
        re-seats it (labels are the source of truth; a partial clear is
        a broken gang the next pass finishes tearing down — the same
        level-triggered repair the engine is built on)."""
        from tpu_operator.controllers.placement_controller import (
            clear_assignment_labels,
        )

        clear_assignment_labels(self.client, gang_nodes)

    # -- the broken half -----------------------------------------------------

    def _reconcile_broken(
        self,
        obj: ObjectDict,
        job: TPUJob,
        block: dict,
        budget: RetryBudget,
        desired: Tuple[int, int, int],
        min_shape: Tuple[int, int, int],
        gang: dict,
        links: List[tuple],
    ) -> Result:
        cause = self._classify_cause(gang)
        # a broken gang re-places regardless, which IS a migration: any
        # defrag or risk request outstanding or mid-barrier is thereby
        # satisfied (without this, a fault during the barrier window
        # would replay the migration — a spurious checkpoint cycle —
        # once healthy)
        progress = self._progress(job.name)
        defrag_req = str(progress.get(consts.JOB_DEFRAG_REQUEST, "") or "")
        if defrag_req:
            block["defragHandled"] = defrag_req
        block.pop("defragPending", None)
        risk_req = str(progress.get(consts.JOB_RISK_MIGRATE_REQUEST, "") or "")
        if risk_req:
            block["riskHandled"] = risk_req
        block.pop("riskPending", None)
        barrier_req = str(progress.get(consts.JOB_CHECKPOINT_REQUEST, "") or "")
        if barrier_req.startswith(("defrag-", "risk-")):
            # the runner holds at a planned-migration barrier; with the
            # gang broken the re-place satisfies it, so lift the key or
            # the next generation parks at a barrier nobody owns
            self._request_progress_key(job.name, consts.JOB_CHECKPOINT_REQUEST, "")
        best = self._placeable(
            job, desired, _volume(min_shape), exclude_self=True, links=links
        )
        if best is None:
            # nothing at or above the min shape places: burn the budget
            return self._charge_attempt(
                obj, job, block, budget,
                cause=f"{cause}; no placeable block >= {_shape_str(min_shape)}",
            )
        best_str = _shape_str(best)
        target_str = block["shape"]
        if best_str != target_str:
            kind = (
                "shrink"
                if _volume(best) < _volume(parse_shape(target_str) or desired)
                else "grow"
            )
            self._record_resize(obj, job, block, best_str, kind, cause=cause)
        elif block["phase"] == JobPhase.PENDING:
            block["phase"] = JobPhase.PLACING  # fresh job waiting for admission
        elif block["phase"] != JobPhase.PLACING:
            # same shape still places: the placement engine re-places it
            # by itself; just track the transition
            block["phase"] = JobPhase.PLACING
            block["message"] = f"re-placing after {cause}"
            self._note_cause(block, f"step {block['step']}: {cause}")
        return Result(requeue_after=consts.JOB_RESYNC_SECONDS)

    # -- shared transitions --------------------------------------------------

    def _placeable(
        self,
        job: TPUJob,
        desired: Tuple[int, int, int],
        min_volume: int,
        exclude_self: bool = False,
        links: Optional[List[tuple]] = None,
    ) -> Optional[Tuple[int, int, int]]:
        try:
            slices = self.client.list(TPU_SLICE_API_VERSION, TPU_SLICE_KIND)
            nodes = self.client.list("v1", "Node")
        except errors.ApiError as e:
            log.warning("job %s: allocator inputs unreadable: %s", job.name, e)
            return None
        return largest_placeable_shape(
            slices, nodes, desired, min_volume,
            degraded_links=links if links is not None else self._degraded_links(),
            pool=job.spec.gang.pool,
            exclude=[job.name + consts.JOB_SLICE_SUFFIX] if exclude_self else [],
        )

    def _record_resize(
        self, obj: ObjectDict, job: TPUJob, block: dict, new_shape: str,
        kind: str, cause: str,
    ) -> None:
        """Patch the owned slice to ``new_shape`` and book the resize in
        status (shrink history + cause log)."""
        try:
            self.client.patch(  # tpuop-lint: kinds=tpu.google.com/v1alpha1/TPUSlice
                TPU_SLICE_API_VERSION, TPU_SLICE_KIND,
                job.name + consts.JOB_SLICE_SUFFIX,
                {"spec": self._slice_spec(job, new_shape)},
            )
        except errors.ApiError as e:
            log.warning("job %s: %s to %s failed: %s", job.name, kind, new_shape, e)
            return
        old = block["shape"]
        block["shape"] = new_shape
        block["phase"] = JobPhase.SHRINKING if kind == "shrink" else JobPhase.GROWING
        block["message"] = ""
        history = list(block.get("shrinks") or [])
        history.append({
            "step": block["step"], "from": old, "to": new_shape,
            "kind": kind, "cause": cause,
        })
        block["shrinks"] = history[-consts.JOB_HISTORY_LIMIT:]
        if kind == "shrink":
            self._note_cause(block, f"step {block['step']}: {cause}")
        event_type = "Warning" if kind == "shrink" else "Normal"
        self.recorder.event(
            obj, event_type, "JobShrunk" if kind == "shrink" else "JobGrown",
            f"{kind} {old} -> {new_shape} ({cause}); resuming from "
            f"checkpoint epoch {block['epoch']} (step {block['checkpointStep']})",
        )

    def _note_cause(self, block: dict, cause: str) -> None:
        causes = list(block.get("causes") or [])
        if not causes or causes[-1] != cause:
            causes.append(cause)
        block["causes"] = causes[-consts.JOB_CAUSES_LIMIT:]

    def _charge_attempt(
        self,
        obj: ObjectDict,
        job: TPUJob,
        block: dict,
        budget: RetryBudget,
        cause: str,
        restart: bool = False,
    ) -> Result:
        """One failed attempt against the retry budget, gated by the
        persisted next-attempt time so event-driven wakeups can't burn
        the budget faster than the backoff schedule."""
        now = time.time()
        next_at = self._float(block.get("nextAttemptAt"))
        if now < next_at:
            return Result(requeue_after=min(next_at - now, consts.JOB_RESYNC_SECONDS))
        attempts = self._int(block.get("restarts"))
        if budget.exhausted(attempts):
            self._fail(
                obj, block, f"retry budget exhausted ({attempts} attempts): {cause}"
            )
            return Result()
        attempts += 1
        delay = budget.delay(attempts, self.rng)
        block["restarts"] = attempts
        block["totalRestarts"] = self._int(block.get("totalRestarts")) + 1
        block["nextAttemptAt"] = round(now + delay, 3)
        block["message"] = cause
        self._note_cause(block, f"step {block['step']}: {cause}")
        if restart:
            token = str(block["totalRestarts"])
            self._request_progress_key(job.name, consts.JOB_RESTART_REQUEST, token)
            block["phase"] = JobPhase.RESUMING
            self.recorder.warning(
                obj, "JobRestarted",
                f"restart {attempts}/{budget.retry_limit} after {cause}; "
                f"resuming from checkpoint epoch {block['epoch']}",
            )
        else:
            block["phase"] = JobPhase.PLACING
        return Result(requeue_after=max(delay, 0.01))

    def _fail(self, obj: ObjectDict, block: dict, message: str) -> None:
        """Terminal quarantine: mutate ``block`` to Failed, tear the
        owned slice down (a dead job never holds capacity or
        placement-queue slots), and record the Event. The caller's
        single status publish/export tail does the writing — one
        tpujobs/status patch per quarantine, not two."""
        block["phase"] = JobPhase.FAILED
        block["hosts"] = 0
        block["message"] = message
        block.pop("nextAttemptAt", None)
        block.pop("barrier", None)
        block.pop("defragPending", None)
        block.pop("riskPending", None)
        self._delete_slice(obj["metadata"]["name"])
        self.pods.sweep(TPU_JOB_KIND, obj["metadata"]["name"])
        self.recorder.warning(obj, "JobFailed", f"quarantined: {message}")

    @staticmethod
    def _int(value, default: int = 0) -> int:
        try:
            return int(value)
        except (TypeError, ValueError):
            return default

    @staticmethod
    def _float(value, default: float = 0.0) -> float:
        try:
            return float(value)
        except (TypeError, ValueError):
            return default


def setup_with_manager(mgr, reconciler: JobReconciler) -> Controller:
    ctrl = Controller("tpujob", reconciler)
    reconciler.client = CachedReadClient(reconciler.client, mgr)

    def map_owned_slice(obj: ObjectDict) -> List[Request]:
        # ONLY slices carrying a TPUJob ownerReference map back to a
        # job: a user's standalone TPUSlice that merely happens to end
        # in "-slice" is not this controller's to reconcile (or sweep)
        for ref in obj["metadata"].get("ownerReferences") or []:
            if ref.get("kind") == TPU_JOB_KIND:
                return [Request(name=ref["name"])]
        return []

    def placement_status_changed(event_type, old, new) -> bool:
        if event_type != "MODIFIED" or old is None:
            return True
        return (
            (old.get("status") or {}).get("placement")
            != (new.get("status") or {}).get("placement")
        )

    def map_progress_cm(obj: ObjectDict) -> List[Request]:
        name = obj["metadata"]["name"]
        if not name.endswith(consts.JOB_PROGRESS_SUFFIX):
            return []
        return [Request(name=name[: -len(consts.JOB_PROGRESS_SUFFIX)])]

    def progress_changed(event_type, old, new) -> bool:
        if not new["metadata"]["name"].endswith(consts.JOB_PROGRESS_SUFFIX):
            return False
        if event_type != "MODIFIED" or old is None:
            return True
        return (old.get("data") or {}) != (new.get("data") or {})

    def map_to_all_jobs(_obj) -> List[Request]:
        try:
            jobs = reconciler.client.list(TPU_JOB_API_VERSION, TPU_JOB_KIND)
        except errors.ApiError:
            return []
        return [Request(name=j["metadata"]["name"]) for j in jobs]

    def service_labels_changed(event_type, old, new) -> bool:
        """Node events that can break or heal a gang: the out-of-service
        signals plus assignment-label churn."""
        keys = (
            consts.TPU_HEALTH_LABEL,
            consts.REPAIR_STATE_LABEL,
            consts.TPU_PERF_LABEL,
            consts.PLACEMENT_LABEL,
        )
        if event_type != "MODIFIED" or old is None:
            return True
        old_labels = old["metadata"].get("labels") or {}
        new_labels = new["metadata"].get("labels") or {}
        return any(old_labels.get(k) != new_labels.get(k) for k in keys)

    ctrl.watch(
        mgr.informer_for(TPU_JOB_API_VERSION, TPU_JOB_KIND), predicate=generation_changed
    )
    ctrl.watch(
        mgr.informer_for(TPU_SLICE_API_VERSION, TPU_SLICE_KIND),
        mapper=map_owned_slice, predicate=placement_status_changed,
    )
    ctrl.watch(
        mgr.informer_for("v1", "ConfigMap", reconciler.namespace),
        mapper=map_progress_cm, predicate=progress_changed,
    )
    ctrl.watch(
        mgr.informer_for("v1", "Node"),
        mapper=map_to_all_jobs, predicate=service_labels_changed,
    )
    mgr.add_controller(ctrl)
    return ctrl
