"""ClusterPolicy reconciler — the primary control loop.

Reference: ``controllers/clusterpolicy_controller.go:94-235`` +
``state_manager.go`` — fetch the singleton CR, re-detect cluster facts,
label TPU nodes with per-operand deploy gates, sync the ordered operand
states, then publish status/conditions with the reference's requeue
semantics (5s while NotReady, 45s poll while the cluster has no TPU
nodes).
"""

from __future__ import annotations

import logging
from typing import List, Optional

from tpu_operator import clusterinfo, consts
from tpu_operator.api.clusterpolicy import (
    CLUSTER_POLICY_API_VERSION,
    CLUSTER_POLICY_KIND,
    ClusterPolicy,
    State,
)
from tpu_operator.catalog import InfoCatalog
from tpu_operator.controllers.operator_metrics import get_metrics
from tpu_operator.controllers.status import publish_status
from tpu_operator.kube import errors
from tpu_operator.kube import retry as kube_retry
from tpu_operator.kube import trace
from tpu_operator.kube.cached import CachedReadClient
from tpu_operator.kube.client import Client
from tpu_operator.kube.controller import Controller, Request, Result, generation_changed
from tpu_operator.kube.echo import WriteEchoFilter
from tpu_operator.kube.events import EventRecorder
from tpu_operator.kube.objects import ObjectDict, metadata_patch
from tpu_operator.nodeinfo import is_tpu_node
from tpu_operator.state import StateManager, SyncStates
from tpu_operator.states import new_cluster_policy_states

log = logging.getLogger(__name__)

# the per-operand deploy gates stamped onto TPU nodes
# (reference: gpuStateLabels state_manager.go:86-111)
OPERAND_DEPLOY_KEYS = {
    "state-libtpu": consts.COMMON_DEPLOY_LABEL_PREFIX + "libtpu",
    "state-device-plugin": consts.COMMON_DEPLOY_LABEL_PREFIX + "device-plugin",
    "state-operator-validation": consts.COMMON_DEPLOY_LABEL_PREFIX + "operator-validation",
    "state-tpu-feature-discovery": consts.COMMON_DEPLOY_LABEL_PREFIX + "tfd",
    "state-slice-manager": consts.COMMON_DEPLOY_LABEL_PREFIX + "slice-manager",
    "state-metrics-exporter": consts.COMMON_DEPLOY_LABEL_PREFIX + "metrics-exporter",
    "state-node-status-exporter": consts.COMMON_DEPLOY_LABEL_PREFIX + "node-status-exporter",
    "state-health-monitor": consts.COMMON_DEPLOY_LABEL_PREFIX + "health-monitor",
    "state-autotuner": consts.COMMON_DEPLOY_LABEL_PREFIX + "autotuner",
    "state-compile-cache": consts.COMMON_DEPLOY_LABEL_PREFIX + "compile-cache",
}


class ClusterPolicyReconciler:
    def __init__(self, client: Client, namespace: str = consts.DEFAULT_OPERATOR_NAMESPACE):
        self.client = client
        self.namespace = namespace
        self.state_manager = StateManager(new_cluster_policy_states())
        self.metrics = get_metrics()
        self.recorder = EventRecorder(client, namespace)
        # wired by setup_with_manager: cache-backed node reads (read-only
        # snapshots, no apiserver round-trip per reconcile)
        self.node_informer = None
        # post-write label state per node, consulted by the node-watch
        # predicate so our own label sweep's echo events don't re-enqueue
        # the reconcile that produced them
        self.echo_filter = WriteEchoFilter()
        # live cluster facts: recomputed only when a node event lands
        # (reference: clusterinfo live mode, clusterinfo.go:83-125)
        self.cluster_info = clusterinfo.LiveClusterInfo(client)

    def _nodes(self):
        if self.node_informer is not None and self.node_informer.has_synced():
            return self.node_informer.cached(copy=False)
        return self.client.list("v1", "Node")

    # -- reconcile -----------------------------------------------------------

    def reconcile(self, req: Request) -> Result:
        obj = self.client.get_or_none(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, req.name)
        if obj is None:
            return Result()  # deleted; operands are GC'd via ownerReferences

        # singleton guard (reference: clusterpolicy_controller.go:121-126):
        # the oldest CR wins, any other instance is marked ignored
        if not self._is_primary(obj):
            self._update_status(obj, State.IGNORED, reason="MultipleClusterPolicies",
                                message="only the oldest ClusterPolicy is reconciled")
            return Result()

        cp = ClusterPolicy.from_unstructured(obj)

        # init: cluster facts from the live cache (recomputed only after a
        # node event) + label nodes every reconcile (reference: init()
        # state_manager.go:753-895 recomputes each pass; live mode is the
        # v2 improvement clusterinfo.go:83-125 offers)
        nodes = self._nodes()
        info = self.cluster_info.get(
            nodes=nodes, default_runtime=cp.spec.operator.default_runtime
        )
        catalog = InfoCatalog(
            cluster_policy=cp,
            namespace=self.namespace,
            runtime=info.container_runtime,
            kubernetes_version=info.kubernetes_version,
            has_tpu_nodes=info.tpu_node_count > 0,
        )
        try:
            with trace.span("label-nodes"):
                self._label_tpu_nodes(cp)
                self._apply_psa_labels(cp)
        except errors.ApiError as e:
            log.warning("node labelling failed: %s", e)
            self.metrics.record_failure()
            return Result(requeue=True)
        self.metrics.tpu_nodes_total.set(info.tpu_node_count)

        with trace.span("sync-states"):
            results = self.state_manager.sync_state(self.client, catalog, owner=obj)
        not_ready = [n for n, r in results.states.items() if r.state == SyncStates.NOT_READY]
        errored = [n for n, r in results.states.items() if r.state == SyncStates.ERROR]
        self.metrics.operand_states_not_ready.set(len(not_ready) + len(errored))

        if errored:
            self.metrics.record_failure()
            self._update_status(
                obj, State.NOT_READY, error=True, reason="OperandError",
                message=f"states errored: {', '.join(sorted(errored))}",
            )
            return Result(requeue=True)  # rate-limited backoff

        if not_ready:
            self.metrics.record_success()
            self._update_status(
                obj, State.NOT_READY, reason="OperandNotReady",
                message=f"waiting on states: {', '.join(sorted(not_ready))}",
            )
            return Result(requeue_after=consts.REQUEUE_NOT_READY_SECONDS)

        self.metrics.record_success()
        if not catalog.has_tpu_nodes:
            # ready with zero accelerator nodes (BASELINE config 1), but keep
            # polling for TPU nodes to appear (reference: 45s NFD poll,
            # clusterpolicy_controller.go:199)
            self._update_status(obj, State.READY, reason="NoTPUNodes",
                                message="no TPU nodes in cluster; operands idle")
            return Result(requeue_after=consts.REQUEUE_NO_TPU_NODES_SECONDS)
        self._update_status(obj, State.READY, reason="Ready",
                            message="all operand states are ready")
        if self._api_degraded():
            # keep re-checking so the Degraded condition CLEARS once the
            # apiserver recovers — a quiet Ready cluster generates no
            # events to trigger the reconcile that would clear it
            return Result(requeue_after=consts.REQUEUE_DEGRADED_SECONDS)
        # slow heartbeat so a degradation that BEGINS while Ready and
        # quiet (failing watch reconnects enqueue nothing) still gets a
        # reconcile to surface it; a healthy pass costs zero writes
        return Result(requeue_after=consts.READY_RESYNC_SECONDS)

    # -- helpers -------------------------------------------------------------

    def _is_primary(self, obj: ObjectDict) -> bool:
        all_cps = self.client.list(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND)
        if not all_cps:
            return True
        all_cps.sort(key=lambda o: (o["metadata"].get("creationTimestamp", ""), o["metadata"]["name"]))
        return all_cps[0]["metadata"]["name"] == obj["metadata"]["name"]

    def _api_resilience(self):
        return kube_retry.resilience_of(self.client)

    def _api_degraded(self) -> bool:
        res = self._api_resilience()
        return bool(res) and res.degraded()

    def _update_status(
        self,
        obj: ObjectDict,
        state: str,
        reason: str = "",
        message: str = "",
        error: bool = False,
    ) -> None:
        """reference: updateCRState clusterpolicy_controller.go:237."""
        previous = obj.get("status", {}).get("state")
        res = self._api_resilience()
        degraded = res.degraded() if res is not None else None
        if degraded:
            # the condition message must be BYTE-STABLE while degraded
            # (live counters in it would defeat publish_status's
            # write-on-change dedup and produce a status write per 5s
            # requeue against the already-struggling apiserver); the
            # volatile detail goes to the log + must-gather instead
            broken = res.breaker.state != kube_retry.CircuitBreaker.CLOSED
            detail = "apiserver requests failing; breaker " + ("open" if broken else "closed")
            log.warning("apiserver degraded: %s", res.describe())
        else:
            detail = ""
        publish_status(
            self.client, obj, state, reason, message, error,
            extra={"namespace": self.namespace},
            degraded=degraded,
            degraded_detail=detail,
        )
        if previous != state:
            # kubectl-describe visibility for every state transition
            event_type = "Warning" if error else "Normal"
            self.recorder.event(obj, event_type, reason or state, message or f"state: {state}")

    def _apply_psa_labels(self, cp: ClusterPolicy) -> None:
        """Pod Security Admission labels on the operand namespace when
        psa.enabled (reference: setPodSecurityLabelsForNamespace
        state_manager.go:600-648 — operands run privileged). Written as a
        metadata-only merge patch: the old full-object update re-sent the
        whole Namespace and could Conflict with unrelated writers."""
        ns = self.client.get_or_none("v1", "Namespace", self.namespace)
        if ns is None:
            return
        labels = ns["metadata"].get("labels") or {}
        annotations = ns["metadata"].get("annotations") or {}
        marker = "tpu.google.com/psa-labels-managed"
        keys = (
            "pod-security.kubernetes.io/enforce",
            "pod-security.kubernetes.io/audit",
            "pod-security.kubernetes.io/warn",
        )
        label_delta: dict = {}
        annotation_delta: dict = {}
        if cp.spec.psa.is_enabled():
            for k in keys:
                if labels.get(k) != "privileged":
                    label_delta[k] = "privileged"
            if annotations.get(marker) != "true":
                annotation_delta[marker] = "true"
        elif annotations.get(marker) == "true":
            # revert ONLY what the operator wrote (the marker proves it);
            # admin-set PSA labels are never touched
            for k in keys:
                if labels.get(k) == "privileged":
                    label_delta[k] = None
            annotation_delta[marker] = None
        body = metadata_patch(labels=label_delta, annotations=annotation_delta)
        if body:
            self.client.patch("v1", "Namespace", self.namespace, body)

    def _enabled_operand_keys(self, cp: ClusterPolicy) -> List[str]:
        catalog = InfoCatalog(cluster_policy=cp, namespace=self.namespace)
        return [
            OPERAND_DEPLOY_KEYS[s.name]
            for s in self.state_manager.states
            if s.name in OPERAND_DEPLOY_KEYS and s.is_enabled(catalog)
        ]

    def _label_tpu_nodes(self, cp: ClusterPolicy) -> None:
        """reference: labelGPUNodes state_manager.go:481-581 — stamp
        tpu.present + per-operand deploy labels on TPU nodes, strip all our
        labels from nodes that no longer have TPUs. Existing explicit values
        (e.g. a hand-set \"false\" opt-out) are left alone.

        Each changed node gets ONE apply-set write (the server-side-apply
        analog, ``Client.apply_set``): the sweep declares the complete
        owned label set per node under the labeller's field-manager
        identity, and the SERVER converges it — removals derive from the
        on-object ownership record (restart-safe, no read-modify-write),
        foreign values (a hand-set opt-out) are never stolen, and a no-op
        apply costs the server nothing. Changed nodes are written through
        the shared write fan-out pool so the sweep's wall time is the
        concurrent window, not N serial round-trips — one slow PATCH
        can't stall the reconcile."""
        from tpu_operator.kube.objects import apply_set_merge
        from tpu_operator.kube.writers import shared_fanout

        enabled_keys = set(self._enabled_operand_keys(cp))
        manager = consts.APPLY_SET_MANAGER_LABELLER
        calls = []
        for node in self._nodes():
            # cache snapshots are read-only: compute the declaration,
            # never mutate
            labels = node["metadata"].get("labels") or {}
            desired: dict = {}
            if is_tpu_node(node):
                desired[consts.TPU_PRESENT_LABEL] = "true"
                desired[consts.TPU_WORKLOAD_CONFIG_LABEL] = consts.DEFAULT_WORKLOAD_CONFIG
                workload = labels.get(
                    consts.TPU_WORKLOAD_CONFIG_LABEL, consts.DEFAULT_WORKLOAD_CONFIG
                )
                for key in OPERAND_DEPLOY_KEYS.values():
                    if key in enabled_keys and workload == consts.WORKLOAD_CONFIG_CONTAINER:
                        desired[key] = "true"
            # client-side no-op skip: the cache already reflects the
            # declaration, so a settled sweep writes nothing (O(changes))
            new_labels, _, changed = apply_set_merge(
                node["metadata"], manager, desired
            )
            # legacy cleanup: our labels written before the apply-set
            # record existed carry no ownership the apply can remove —
            # any undeclared ours-key that survived the apply (a de-TPU'd
            # node's whole set, or a DISABLED operand's gate stamped by a
            # pre-record operator version) strips via an explicit delta,
            # preserving the old unconditional-removal semantics
            ours = (
                consts.TPU_PRESENT_LABEL, consts.TPU_WORKLOAD_CONFIG_LABEL,
                *OPERAND_DEPLOY_KEYS.values(),
            )
            leftover = {
                key: None for key in ours
                if key in new_labels and key not in desired
            }
            if not changed and not leftover:
                continue
            name = node["metadata"]["name"]
            after = {k: v for k, v in new_labels.items() if k not in leftover}
            # record BEFORE the write: the in-memory client delivers the
            # watch event synchronously inside the call, so a record made
            # after would miss its own echo. A failed write leaves a
            # record for a label state that never materializes — harmless
            # by the filter's advisory design.
            self.echo_filter.record(name, after)
            if changed:
                calls.append(self._apply_call(name, manager, desired))
            if leftover:
                calls.append(self._strip_call(name, leftover))
        if not calls:
            return
        first_error = None
        for _, err in shared_fanout().map(calls, verb="apply_set", kind="Node"):
            if err is not None and first_error is None:
                first_error = err
        if first_error is not None:
            # surface ONE failure so the reconcile requeues (the rest of
            # the sweep still landed — level-triggered repair finishes it)
            raise first_error

    def _apply_call(self, name: str, manager: str, desired: dict):
        def call():
            try:
                self.client.apply_set("v1", "Node", name, manager, labels=desired)
            except errors.NotFound:
                # node deleted while the sweep ran (cache trails the
                # watch): skip it, the rest of the sweep must still land
                pass

        return call

    def _strip_call(self, name: str, delta: dict):
        def call():
            try:
                self.client.patch("v1", "Node", name, {"metadata": {"labels": delta}})
            except errors.NotFound:
                pass

        return call


def node_labels_changed(event_type: str, old: Optional[ObjectDict], new: ObjectDict) -> bool:
    """Watch predicate (reference: node predicates
    clusterpolicy_controller.go:283-341): care about node add/delete and
    label changes only."""
    if event_type != "MODIFIED" or old is None:
        return True
    return old["metadata"].get("labels") != new["metadata"].get("labels")


def setup_with_manager(
    mgr, reconciler: ClusterPolicyReconciler, cached_reads: bool = True
) -> Controller:
    """reference: SetupWithManager clusterpolicy_controller.go:352-407 —
    watch the CR (generation-gated), Node label events, and owned
    DaemonSets, all funnelled into requests for every ClusterPolicy.
    ``cached_reads=False`` keeps reads on the wire client (bench uses it
    to measure what the informer caches save)."""
    # node-event bursts (every node in a sweep delivers one event, all
    # mapping to the same CP request) coalesce into one reconcile
    ctrl = Controller(
        "clusterpolicy", reconciler,
        coalesce_window=consts.NODE_EVENT_COALESCE_SECONDS,
    )
    if cached_reads:
        # reads via the manager's informer caches, writes direct — the
        # reference reconciler reads exclusively through controller-runtime's
        # cache (clusterpolicy_controller.go:352-407); without this every
        # sync pass re-LISTs all owned kinds per state
        reconciler.client = CachedReadClient(reconciler.client, mgr)

    def map_to_all_cps(_obj) -> List[Request]:
        try:
            cps = reconciler.client.list(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND)
        except errors.ApiError:
            return []
        return [Request(name=cp["metadata"]["name"]) for cp in cps]

    def node_event(event_type, old, new) -> bool:
        if not node_labels_changed(event_type, old, new):
            return False
        # drop the echo of our own label writes: at N nodes one sweep
        # otherwise re-delivers N MODIFIED events that re-enqueue the very
        # reconcile that produced them
        if event_type == "MODIFIED" and reconciler.echo_filter.is_echo(new):
            return False
        return True

    ctrl.watch(mgr.informer_for(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND), predicate=generation_changed)
    node_informer = mgr.informer_for("v1", "Node")
    ctrl.watch(node_informer, mapper=map_to_all_cps, predicate=node_event)
    reconciler.node_informer = node_informer
    reconciler.cluster_info.attach(node_informer)

    def owned_daemonset(event_type, old, new) -> bool:
        refs = new["metadata"].get("ownerReferences", [])
        return any(r.get("kind") == CLUSTER_POLICY_KIND for r in refs)

    ctrl.watch(mgr.informer_for("apps/v1", "DaemonSet"), mapper=map_to_all_cps, predicate=owned_daemonset)
    mgr.add_controller(ctrl)
    return ctrl
