"""Tenancy reconciler: TPUQuota accounting, status, and observability.

The placement engine *enforces* fairness (the DRF admission order and
the preemption economy live in ``placement/engine.py`` +
``tenancy/fairshare.py``); this controller makes it *visible*. One
fleet-wide pass per quota/placement change:

- parses every TPUQuota (malformed specs go ``Invalid`` and grant
  nothing — fail closed), builds the same :class:`FairSharePolicy` the
  engine plans with,
- accounts per-tenant usage from published placement statuses
  (``tenancy.fairshare.usage_from_slices`` — the same rollup the engine
  recomputes mid-pass from its own plan),
- publishes each quota's accounting block (used/guaranteed/borrowed
  chips, weighted dominant share, protection state) as a key-scoped
  status patch, and
- exports the ``tpu_operator_tenant_*`` gauges, retiring a tenant's
  series when its quota is deleted and no usage remains (O005 — a
  deleted tenant must not export its last value forever).

The p99 time-to-place gauge reads the ``tpu-tenancy-ledger`` sample
ring the placement controller books. That read is ADVISORY here — an
unreadable ledger only skips the p99 export this pass; the fail-closed
K003 contract binds the ledger's *writer* (a booking that cannot read
the ledger must not reset the audit trail), not this gauge.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional

from tpu_operator import consts
from tpu_operator.api.tpuquota import TPU_QUOTA_API_VERSION, TPU_QUOTA_KIND
from tpu_operator.api.tpuslice import TPU_SLICE_API_VERSION, TPU_SLICE_KIND
from tpu_operator.controllers.operator_metrics import get_metrics
from tpu_operator.kube import errors
from tpu_operator.kube.cached import CachedReadClient
from tpu_operator.kube.client import Client
from tpu_operator.kube.controller import Controller, Request, Result
from tpu_operator.kube.events import EventRecorder
from tpu_operator.tenancy.fairshare import (
    FairSharePolicy,
    parse_quota,
    capacity_by_generation,
    usage_from_slices,
)
from tpu_operator.tenancy.ledger import place_p99, read_ledger

log = logging.getLogger(__name__)

TENANCY_MANAGER = "tpu-tenancy"

# the whole fleet accounts as one unit; every watch event maps here
TENANCY_REQUEST = Request(name="tenancy-accounting")


class TenancyReconciler:
    def __init__(
        self,
        client: Client,
        namespace: str = consts.DEFAULT_OPERATOR_NAMESPACE,
        recorder: Optional[EventRecorder] = None,
    ):
        self.client = client
        self.namespace = namespace
        self.recorder = recorder or EventRecorder(
            client, namespace, component=TENANCY_MANAGER
        )
        self.metrics = get_metrics()
        self._now = time.time
        from tpu_operator.kube import racecheck

        # gauge-series bookkeeping shares the reconciler across the
        # controller's workers and the metrics endpoint
        self._series_lock = racecheck.lock("TenancyReconciler._series_lock")
        self._tenant_series: set = set()

    def reconcile(self, req: Request) -> Result:
        try:
            quotas = self.client.list(TPU_QUOTA_API_VERSION, TPU_QUOTA_KIND)
            slices = self.client.list(TPU_SLICE_API_VERSION, TPU_SLICE_KIND)
            nodes = self.client.list("v1", "Node")
        except errors.ApiError as e:
            # fail closed: partial inputs would publish wrong accounting
            # (a missing slice list reads as a tenant holding nothing)
            log.warning("tenancy: input list failed, pass aborted: %s", e)
            return Result(requeue=True)
        entries = {}
        for obj in quotas:
            entries[obj["metadata"]["name"]] = parse_quota(obj)
        valid = [e for e in entries.values() if e is not None]
        policy = FairSharePolicy(valid, capacity_by_generation(nodes)) if valid else None
        used = usage_from_slices(slices, nodes)
        ledger = read_ledger(self.client, self.namespace)  # advisory here
        statuses_ok = True
        for obj in quotas:
            desired = self._desired_status(obj, entries[obj["metadata"]["name"]], policy, used)
            if not self._publish_status(obj, desired):
                statuses_ok = False
        self._publish_series(policy, used, ledger)
        if not statuses_ok:
            return Result(requeue=True)
        # placements move without any quota/slice spec event mapping
        # here (label-only re-tenanting, node churn shifting capacity)
        return Result(requeue_after=consts.TENANCY_RESYNC_SECONDS)

    # -- status --------------------------------------------------------------

    def _desired_status(
        self,
        obj: dict,
        entry,
        policy: Optional[FairSharePolicy],
        used: Dict[str, Dict[str, int]],
    ) -> dict:
        if entry is None or policy is None:
            return {
                "state": "Invalid",
                "tenancy": {
                    "reason": "malformed spec: tenant must be non-empty, weight "
                              "positive and finite, guaranteed a map of "
                              "generation to non-negative integer chips",
                },
            }
        tenant = entry.tenant
        return {
            "state": "Active",
            "tenancy": {
                "tenant": tenant,
                "weight": entry.weight,
                "guaranteed": entry.guaranteed_map,
                "used": policy.level_usage(used, tenant),
                "usedChips": sum(policy.level_usage(used, tenant).values()),
                "borrowedChips": policy.borrowed_chips(tenant, used),
                "dominantShare": round(policy.dominant_share(tenant, used), 6),
                "weightedShare": round(policy.weighted_share(tenant, used), 6),
                "withinGuarantee": policy.within_guarantee(tenant, used),
            },
        }

    def _publish_status(self, obj: dict, desired: dict) -> bool:
        name = obj["metadata"]["name"]
        current = obj.get("status") or {}
        if (current.get("state"), current.get("tenancy") or {}) == (
            desired["state"], desired["tenancy"]
        ):
            return True
        if desired["state"] == "Invalid" and current.get("state") != "Invalid":
            self.recorder.event(
                obj, "Warning", "TPUQuotaInvalid",
                "TPUQuota spec is malformed and grants nothing (fail closed): "
                + str(desired["tenancy"].get("reason") or ""),
            )
        try:
            self.client.patch_status(  # tpuop-lint: kinds=tpu.google.com/v1alpha1/TPUQuota
                TPU_QUOTA_API_VERSION, TPU_QUOTA_KIND, name,
                {"status": desired},
            )
        except errors.NotFound:
            return True  # deleted mid-pass; the delete event re-enqueues
        except errors.ApiError as e:
            log.debug("tenancy status publish for %s failed: %s", name, e)
            return False
        return True

    # -- metrics -------------------------------------------------------------

    def _publish_series(
        self,
        policy: Optional[FairSharePolicy],
        used: Dict[str, Dict[str, int]],
        ledger: Optional[dict],
    ) -> None:
        """Per-tenant gauges for every declared tenant plus every tenant
        actually holding chips; series no longer in that set retire
        (O005) — deleting the last TPUQuota retires everything."""
        live: set = set()
        if policy is not None:
            live.update(policy.quotas)
            live.update(used)
        for tenant in sorted(live):
            self.metrics.tenant_used_chips.labels(tenant).set(
                sum(policy.level_usage(used, tenant).values())
            )
            self.metrics.tenant_fair_share.labels(tenant).set(
                round(policy.weighted_share(tenant, used), 6)
            )
            self.metrics.tenant_borrowed_chips.labels(tenant).set(
                policy.borrowed_chips(tenant, used)
            )
            p99 = place_p99(ledger, tenant) if ledger else None
            if p99 is not None:
                self.metrics.tenant_place_p99.labels(tenant).set(p99)
        with self._series_lock:
            gone = self._tenant_series - live
            self._tenant_series = live
        for tenant in gone:
            for gauge in (
                self.metrics.tenant_used_chips,
                self.metrics.tenant_fair_share,
                self.metrics.tenant_borrowed_chips,
                self.metrics.tenant_place_p99,
            ):
                try:
                    gauge.remove(tenant)
                except KeyError:
                    pass


def setup_with_manager(mgr, reconciler: TenancyReconciler) -> Controller:
    ctrl = Controller("tenancy", reconciler)
    reconciler.client = CachedReadClient(reconciler.client, mgr)

    def map_to_pass(_obj) -> List[Request]:
        return [TENANCY_REQUEST]

    def quota_changed(event_type, old, new) -> bool:
        """Re-account when the quota itself changed (or appeared/went
        away) — this controller's own status echoes must not loop."""
        if event_type != "MODIFIED" or old is None:
            return True
        return (old.get("spec") or {}) != (new.get("spec") or {})

    def placement_changed(event_type, old, new) -> bool:
        """Slice events matter when the published placement block moved
        (usage changed) or the slice was re-tenanted."""
        if event_type != "MODIFIED" or old is None:
            return True
        if ((old.get("status") or {}).get("placement")
                != (new.get("status") or {}).get("placement")):
            return True
        old_tenant = (old["metadata"].get("labels") or {}).get(consts.TENANT_LABEL)
        new_tenant = (new["metadata"].get("labels") or {}).get(consts.TENANT_LABEL)
        return old_tenant != new_tenant

    ctrl.watch(
        mgr.informer_for(TPU_QUOTA_API_VERSION, TPU_QUOTA_KIND),
        mapper=map_to_pass, predicate=quota_changed,
    )
    ctrl.watch(
        mgr.informer_for(TPU_SLICE_API_VERSION, TPU_SLICE_KIND),
        mapper=map_to_pass, predicate=placement_changed,
    )
    mgr.add_controller(ctrl)
    return ctrl
