"""TPUSlice node-selector conflict validation.

Reference: ``internal/validator/validator.go:31-90`` — a node may be
selected by at most one NVIDIADriver CR; overlapping CRs fail validation
before any DaemonSet is rendered.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

from tpu_operator.api.tpuslice import TPU_SLICE_API_VERSION, TPU_SLICE_KIND, TPUSlice
from tpu_operator.kube.client import Client
from tpu_operator.kube.objects import matches_selector


class ValidationError(Exception):
    pass


def selected_nodes(client: Client, tpu_slice: TPUSlice, nodes: Optional[List[dict]] = None) -> Set[str]:
    """reference: getNVIDIADriverSelectedNodes validator.go:60-90. Pass
    ``nodes`` to reuse one Node list across CRs (a reconcile would
    otherwise pay O(CRs x nodes) API reads)."""
    selector = tpu_slice.spec.get_node_selector()
    if nodes is None:
        nodes = client.list("v1", "Node")
    return {
        node["metadata"]["name"]
        for node in nodes
        if matches_selector(node["metadata"].get("labels"), selector)
    }


def validate_node_selectors(client: Client, tpu_slice: TPUSlice, nodes: Optional[List[dict]] = None) -> None:
    """Raise when this CR's selected nodes overlap another TPUSlice CR's
    (reference: Validate validator.go:31-58)."""
    if nodes is None:
        nodes = client.list("v1", "Node")
    mine = selected_nodes(client, tpu_slice, nodes)
    conflicts: Dict[str, List[str]] = {}
    for other_obj in client.list(TPU_SLICE_API_VERSION, TPU_SLICE_KIND):
        other = TPUSlice.from_unstructured(other_obj)
        if other.name == tpu_slice.name:
            continue
        overlap = mine & selected_nodes(client, other, nodes)
        if overlap:
            conflicts[other.name] = sorted(overlap)
    if conflicts:
        detail = "; ".join(f"{name}: {nodes}" for name, nodes in sorted(conflicts.items()))
        raise ValidationError(
            f"TPUSlice {tpu_slice.name} selects nodes already selected by other CRs: {detail}"
        )
