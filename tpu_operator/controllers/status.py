"""Shared CR status publisher used by both reconcilers.

reference: updateCRState (clusterpolicy_controller.go:237) + the
internal/conditions updaters, as one helper so state/reason/message
transitions are detected and persisted identically for every CRD.
"""

from __future__ import annotations

import copy
import logging
from typing import Optional

from tpu_operator.controllers import conditions
from tpu_operator.kube import errors
from tpu_operator.kube.client import Client
from tpu_operator.kube.objects import ObjectDict

log = logging.getLogger(__name__)


def publish_status(
    client: Client,
    obj: ObjectDict,
    state: str,
    reason: str = "",
    message: str = "",
    error: bool = False,
    extra: Optional[dict] = None,
    degraded: Optional[bool] = None,
    degraded_detail: str = "",
) -> None:
    """Set status.state + Ready/Error conditions, writing only on change.
    The before-image is snapshotted up front — the condition helpers mutate
    in place, so comparing against a live alias would always say
    'unchanged' and swallow reason/message transitions.

    The write is a merge patch against the status subresource carrying
    only the keys this publisher owns (state/conditions/extra): no
    resourceVersion travels, so it can never Conflict with the other
    status writers (health block, upgrade block) and never clobbers their
    keys — the full-object update_status it replaces did both."""
    status = obj.setdefault("status", {})
    before = copy.deepcopy(status)
    conds = status.setdefault("conditions", [])
    if error:
        conditions.set_error(conds, reason, message)
    elif state == "ready":
        conditions.set_ready(conds, reason, message)
    else:
        conditions.set_not_ready(conds, reason or "NotReady", message)
    if degraded is not None:
        # apiserver-connectivity signal (kube/retry.ApiResilience): set
        # while the client is riding out 429/5xx storms or an outage on
        # retries + breaker + cached reads, cleared on recovery. None
        # (in-memory clients, no resilience state) writes nothing.
        conditions.set_degraded(conds, degraded, degraded_detail)
    status["state"] = state
    status.update(extra or {})
    if status == before:
        # byte-identical to what is already on the CR: no write, and the
        # caller (see ClusterPolicyReconciler._update_status) emits no
        # Event either — a quiet steady state costs zero status traffic
        return
    delta = {"conditions": status["conditions"], "state": state}
    delta.update(extra or {})
    md = obj["metadata"]
    try:
        client.patch_status(  # tpuop-lint: kinds=tpu.google.com/v1/ClusterPolicy,tpu.google.com/v1alpha1/TPUSlice
            obj["apiVersion"], obj["kind"], md["name"], {"status": delta}, md.get("namespace")
        )
    except errors.NotFound:
        # CR deleted between read and publish; its reconcile is moot
        log.debug("status publish skipped for deleted %s", md.get("name"))
