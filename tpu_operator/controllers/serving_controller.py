"""TPUServing reconciler: traffic-driven elastic serving.

The demand-driven layer over the placement stack (ROADMAP item 1). One
TPUServing owns one TPUSlice per replica (``<serving>-replica-<i>``) and
the controller drives the replica count from observed load::

    demand (load CM: arrival rate, queue depth, measured TTFT)
      + SLO signals (PR 7 gang step-time artifacts vs spec.slo)
        -> desired replicas -> TPUSlice create/delete
           -> placement engine admits priority-then-FIFO
    routing weights (controller-owned load-CM key) exclude replicas
    whose PR 8 fabric artifact / link-health record shows degraded edges

Every decision recomputes from cluster state (the replicas' placement
statuses, node service labels, the link-health map, the load ConfigMap),
so a restarted operator re-derives the same world — the engine-room
convention every other controller here follows.

**Scale-up** is immediate: a burst is exactly when capacity is needed,
and the placement engine's priority-then-FIFO admission is the queue.
**Scale-down** is hysteretic: demand must sit below the *shrunk*
capacity (with headroom) for a full cooldown before one replica is
retired per pass — a diurnal lull shrinks the fleet, a burst's trailing
edge doesn't flap it. The victim is the replica whose removal most
*reduces* ``tpu_operator_torus_fragmentation``
(``placement.engine.scale_down_victim`` — the allocator's own scoring
replayed minus each candidate): the fleet-level perf optimization that
keeps the big contiguous blocks open for the next scale-up or training
job.

**Quarantine**: autoscaler passes in which a wanted replica stays
unplaceable burn a full-jitter backoff budget (``kube/backoff.py``, the
same bounded-retry pattern the TPUJob FSM quarantines through) behind a
persisted ``nextAttemptAt`` gate, so watch-event storms can't outrun the
schedule; exhaustion parks the serving in ``Failed`` with an Event.
"""

from __future__ import annotations

import json
import logging
import math
import random
import time
from typing import Dict, List, Optional, Tuple

from tpu_operator import consts
from tpu_operator.api.tpuserving import (
    SERVING_TERMINAL_PHASES,
    TPU_SERVING_API_VERSION,
    TPU_SERVING_KIND,
    ServingPhase,
    TPUServing,
)
from tpu_operator.api.tpuslice import (
    TPU_SLICE_API_VERSION,
    TPU_SLICE_KIND,
    new_tpu_slice,
)
from tpu_operator.controllers.operator_metrics import get_metrics
from tpu_operator.kube import errors, trace
from tpu_operator.kube.backoff import RetryBudget
from tpu_operator.kube.cached import CachedReadClient
from tpu_operator.kube.client import Client
from tpu_operator.kube.controller import Controller, Request, Result, generation_changed
from tpu_operator.kube.events import EventRecorder
from tpu_operator.kube.objects import ObjectDict
from tpu_operator.placement.engine import (
    PlacementPhase,
    labels_unavailable,
    pick_scale_down_victim,
    scale_down_scores,
)

log = logging.getLogger(__name__)

SERVING_MANAGER = "tpu-serving-controller"


def replica_name(serving: str, index: int) -> str:
    return f"{serving}{consts.SERVING_REPLICA_INFIX}{index}"


class ServingReconciler:
    def __init__(self, client: Client, namespace: str = consts.DEFAULT_OPERATOR_NAMESPACE):
        self.client = client
        self.namespace = namespace
        self.recorder = EventRecorder(client, namespace, component=SERVING_MANAGER)
        self.metrics = get_metrics()
        # full-jitter needs a private RNG so tests/drills can seed it
        self.rng = random.Random()
        # servings with live labelled series, so deletion retires them
        # (O005); the racecheck factory instruments it under TPUOP_RACECHECK
        from tpu_operator.kube import racecheck

        self._series_lock = racecheck.lock("ServingReconciler._series_lock")
        self._serving_series: set = set()
        self._pod_set = None  # lazy: the manager swaps the client post-init

    @property
    def pods(self):
        """The worker-pod converger (the pod data plane's control-plane
        half), bound to whatever client the reconciler currently holds."""
        from tpu_operator.dataplane.pods import WorkerPodSet

        if self._pod_set is None or self._pod_set.client is not self.client:
            self._pod_set = WorkerPodSet(self.client, self.namespace)
        return self._pod_set

    # -- series hygiene ------------------------------------------------------

    def _export(
        self, serving: str, replicas: int, tokens_per_s: float,
        ttft_p99: float, queue_depth: int,
        kv_hit_ratio: float = 0.0, handoff_bytes: float = 0.0,
        pools: Optional[Dict[str, int]] = None,
    ) -> None:
        with self._series_lock:
            self._serving_series.add(serving)
        self.metrics.serving_replicas.labels(serving).set(replicas)
        self.metrics.serving_tokens_per_s.labels(serving).set(tokens_per_s)
        self.metrics.serving_ttft_p99.labels(serving).set(ttft_p99)
        self.metrics.serving_queue_depth.labels(serving).set(queue_depth)
        self.metrics.serving_kv_hit_ratio.labels(serving).set(kv_hit_ratio)
        self.metrics.serving_kv_handoff_bytes.labels(serving).set(handoff_bytes)
        # both pool series always exist (0 with disaggregation off), so
        # retirement can remove a fixed label set
        pools = pools or {}
        for pool in (consts.SERVING_POOL_PREFILL, consts.SERVING_POOL_DECODE):
            self.metrics.serving_pool_replicas.labels(serving, pool).set(
                pools.get(pool, 0))

    def _retire_series(self, serving: str) -> None:
        with self._series_lock:
            if serving not in self._serving_series:
                return
            self._serving_series.discard(serving)
        for gauge in (
            self.metrics.serving_replicas,
            self.metrics.serving_tokens_per_s,
            self.metrics.serving_ttft_p99,
            self.metrics.serving_queue_depth,
            self.metrics.serving_kv_hit_ratio,
            self.metrics.serving_kv_handoff_bytes,
        ):
            try:
                gauge.remove(serving)
            except KeyError:
                pass
        for pool in (consts.SERVING_POOL_PREFILL, consts.SERVING_POOL_DECODE):
            try:
                self.metrics.serving_pool_replicas.remove(serving, pool)
            except KeyError:
                pass

    # -- cluster reads -------------------------------------------------------

    def _load(self, serving: str) -> dict:
        cm = self.client.get_or_none(
            "v1", "ConfigMap", serving + consts.SERVING_LOAD_SUFFIX, self.namespace
        )
        return (cm or {}).get("data") or {}

    def _degraded_links(self) -> List[tuple]:
        from tpu_operator.controllers.fabric_telemetry import degraded_link_pairs

        return degraded_link_pairs(self.client, self.namespace)

    def _owned_replicas(
        self, serving: str, infix: Optional[str] = None
    ) -> Optional[List[ObjectDict]]:
        """Every TPUSlice carrying a TPUServing ownerReference naming
        this serving — index order, so scale decisions are stable.
        ``infix`` narrows to one pool's slices (``-replica-`` for the
        decode/aggregated set, ``-prefill-`` for the prefill pool); the
        default returns them all (the deletion sweep).

        Fails CLOSED: a transient list failure returns ``None`` (callers
        abort the pass and requeue), never the empty list — this read
        gates replica deletion and the deleted-serving sweep, and an
        impersonated "no replicas" would leak every owned slice forever
        (sweep sees nothing, and no requeue would ever retry)."""
        try:
            slices = self.client.list(TPU_SLICE_API_VERSION, TPU_SLICE_KIND)
        except errors.ApiError:
            return None
        owned = []
        for obj in slices:
            if any(
                ref.get("kind") == TPU_SERVING_KIND and ref.get("name") == serving
                for ref in obj["metadata"].get("ownerReferences") or []
            ):
                if infix is not None and not obj["metadata"]["name"].startswith(
                        serving + infix):
                    continue
                owned.append(obj)
        prefix = serving + (infix or consts.SERVING_REPLICA_INFIX)

        def index_of(obj: ObjectDict) -> int:
            name = obj["metadata"]["name"]
            try:
                return int(name[len(prefix):]) if name.startswith(prefix) else 1 << 30
            except ValueError:
                return 1 << 30

        return sorted(owned, key=lambda o: (index_of(o), o["metadata"]["name"]))

    def _gang_annotation(self, slice_name: str, annotation: str) -> Optional[dict]:
        cm = self.client.get_or_none(
            "v1", "ConfigMap", f"{slice_name}-gang", self.namespace
        )
        raw = ((cm or {}).get("metadata") or {}).get("annotations", {}).get(annotation)
        if not raw:
            return None
        try:
            parsed = json.loads(raw)
        except ValueError:
            return None
        return parsed if isinstance(parsed, dict) else None

    # -- replica state -------------------------------------------------------

    def _replica_state(self, obj: ObjectDict, links: List[tuple]) -> dict:
        """One replica's world: placed?, members, out-of-service members,
        a link cut through its block, fabric-artifact exclusion."""
        placement = (obj.get("status") or {}).get("placement") or {}
        nodes = list(placement.get("nodes") or [])
        state = {
            "name": obj["metadata"]["name"],
            "scheduled": placement.get("phase") == PlacementPhase.SCHEDULED,
            "unschedulable": placement.get("phase") == PlacementPhase.UNSCHEDULABLE,
            "nodes": nodes,
            "out": [],
            "cut": "",
            "fabric_degraded": False,
        }
        members = set(nodes)
        for name in nodes:
            node = self.client.get_or_none("v1", "Node", name)
            if node is None or labels_unavailable(node["metadata"].get("labels") or {}):
                state["out"].append(name)
        for a, b in links:
            if a in members and b in members:
                state["cut"] = f"{a}|{b}"
                break
        if state["scheduled"] and not state["cut"]:
            # the PR 8 fabric artifact: a replica whose own matrix shows
            # an edge below the degraded fraction of its median is
            # excluded from routing even before the analyzer records the
            # link (stale artifacts — disjoint members — are skipped,
            # the fabric analyzer's convention)
            artifact = self._gang_annotation(
                state["name"], consts.GANG_FABRIC_ANNOTATION
            )
            if artifact and set(artifact.get("members") or []) <= members:
                median = float(artifact.get("median_edge_gbps") or 0.0)
                worst = float(artifact.get("min_edge_gbps") or 0.0)
                if median > 0 and worst < consts.FABRIC_LINK_DEGRADED_FRACTION * median:
                    state["fabric_degraded"] = True
        state["ready"] = bool(state["scheduled"] and not state["out"] and not state["cut"])
        state["routable"] = bool(state["ready"] and not state["fabric_degraded"])
        return state

    def _step_time_breach(self, states: List[dict], slo_step: float) -> bool:
        """The PR 7 gang step-time artifacts as the overload signal: any
        routable replica whose gang-median decode step exceeds the SLO
        means the fleet is saturated even when the rate math still
        fits."""
        if slo_step <= 0:
            return False
        for state in states:
            if not state["routable"]:
                continue
            artifact = self._gang_annotation(
                state["name"], consts.GANG_TELEMETRY_ANNOTATION
            )
            if artifact and float(artifact.get("gang_step_p50_s") or 0.0) > slo_step:
                return True
        return False

    # -- autoscaling ---------------------------------------------------------

    def _autoscale(
        self, serving: TPUServing, block: dict, load: dict,
        states: List[dict], now: float,
    ) -> Tuple[int, str]:
        """Desired replica count + the reason string booked into the
        decision history. Scale-ups are immediate; scale-downs wait for
        headroom + cooldown (hysteresis)."""
        spec = serving.spec.replicas
        lo, hi = max(0, spec.min), max(max(0, spec.min), spec.max)
        current = self._int(block.get("desired"), lo)
        current = min(max(current, lo), hi)
        rate = self._float(load.get(consts.SERVING_LOAD_ARRIVAL_RATE))
        queue_depth = self._int(load.get(consts.SERVING_LOAD_QUEUE_DEPTH))
        ttft_p99 = self._float(load.get(consts.SERVING_LOAD_TTFT_P99))
        capacity = max(spec.target_rps, 1e-6)
        need = max(lo, min(hi, math.ceil(rate / capacity))) if rate > 0 else lo
        reason = f"arrival rate {rate:.1f} rps / {capacity:g} rps per replica"
        ready = sum(1 for s in states if s["ready"])
        slo_breached = (
            ttft_p99 > serving.spec.slo.ttft_p99_seconds
            or queue_depth > capacity  # > a replica-second of backlog
            or self._step_time_breach(states, serving.spec.slo.step_seconds)
        )
        if slo_breached and ready >= current:
            # rate math says "fits" but the SLO disagrees: add one
            need = max(need, min(hi, current + 1))
            reason = (
                f"SLO breach (ttft_p99 {ttft_p99:.2f}s, queue {queue_depth})"
            )
        disagg = serving.spec.disaggregation
        dec_tps = self._float(load.get(consts.SERVING_LOAD_DECODE_TOKENS_PER_S))
        if (
            disagg.enabled and disagg.decode_tokens_per_s_floor > 0
            and 0 < dec_tps < disagg.decode_tokens_per_s_floor
            and ready >= current and current + 1 > need
        ):
            # the decode pool's own signal: aggregate decode throughput
            # sagging below the floor under load adds a decode replica
            # even when the arrival-rate math still fits
            need = min(hi, current + 1)
            reason = (
                f"decode throughput {dec_tps:.1f} tok/s below floor "
                f"{disagg.decode_tokens_per_s_floor:g}"
            )
        if need > current:
            block.pop("lowSince", None)
            return need, f"scale up {current} -> {need}: {reason}"
        if need < current:
            # hysteresis: demand must fit the shrunk set with headroom,
            # and sit there for the whole cooldown
            shrunk_capacity = (
                (current - 1) * capacity * consts.SERVING_SCALE_DOWN_HEADROOM
            )
            fits = rate <= shrunk_capacity and queue_depth == 0 and not slo_breached
            if not fits:
                block.pop("lowSince", None)
                return current, ""
            low_since = self._float(block.get("lowSince"))
            if not low_since:
                block["lowSince"] = round(now, 3)
                return current, ""
            cooldown = max(0.0, spec.cooldown_seconds)
            cooled = now - low_since >= cooldown
            since_last = now - self._float(block.get("lastScaleAt"))
            if cooled and since_last >= cooldown:
                block.pop("lowSince", None)
                # one replica per pass: the next pass re-evaluates
                return current - 1, (
                    f"scale down {current} -> {current - 1}: lull "
                    f"({rate:.1f} rps fits {current - 1} replica(s) "
                    f"with headroom)"
                )
            return current, ""
        block.pop("lowSince", None)
        return current, ""

    # -- replica management --------------------------------------------------

    def _slice_spec(self, serving: TPUServing) -> dict:
        model = serving.spec.model
        return {
            "placement": {
                "shape": model.shape,
                "priority": model.priority,
                "preemptionPolicy": "Never",
                **({"pool": model.pool} if model.pool else {}),
            }
        }

    def _create_slice(
        self, obj: ObjectDict, serving_name: str, name: str, spec: dict
    ) -> bool:
        body = new_tpu_slice(name, spec)
        tenant = (obj["metadata"].get("labels") or {}).get(consts.TENANT_LABEL) or ""
        if tenant:
            # the serving's tenant rides onto every replica slice so the
            # fair-share engine accounts replicas to the right quota
            body["metadata"].setdefault("labels", {})[consts.TENANT_LABEL] = tenant
        body["metadata"]["ownerReferences"] = [{
            "apiVersion": TPU_SERVING_API_VERSION,
            "kind": TPU_SERVING_KIND,
            "name": serving_name,
            "uid": obj["metadata"].get("uid", ""),
        }]
        try:
            self.client.create(body)  # tpuop-lint: kinds=tpu.google.com/v1alpha1/TPUSlice
        except errors.AlreadyExists:
            return True
        except errors.ApiError as e:
            log.warning("serving %s: replica create failed: %s", serving_name, e)
            return False
        return True

    def _create_replica(self, obj: ObjectDict, serving: TPUServing, index: int) -> bool:
        return self._create_slice(
            obj, serving.name,
            replica_name(serving.name, index), self._slice_spec(serving),
        )

    def _delete_replica(self, name: str) -> bool:
        try:
            self.client.delete(  # tpuop-lint: kinds=tpu.google.com/v1alpha1/TPUSlice
                TPU_SLICE_API_VERSION, TPU_SLICE_KIND, name
            )
        except errors.NotFound:
            pass
        except errors.ApiError as e:
            log.warning("serving replica %s delete failed: %s", name, e)
            return False
        return True

    # -- the prefill pool (disaggregation) -----------------------------------

    def _prefill_slice_spec(self, serving: TPUServing) -> dict:
        model = serving.spec.model
        disagg = serving.spec.disaggregation
        pool = disagg.prefill_pool or model.pool
        return {
            "placement": {
                "shape": disagg.prefill_shape or model.shape,
                "priority": model.priority,
                "preemptionPolicy": "Never",
                **({"pool": pool} if pool else {}),
            }
        }

    def _reconcile_prefill(
        self, obj: ObjectDict, serving: TPUServing, block: dict,
        load: dict, links: List[tuple], now: float,
    ) -> List[dict]:
        """Converge the prefill pool on ITS OWN signal: the router's
        measured prefill TTFT p99 against the SLO target. A breach adds
        a prefill replica immediately; TTFT sitting comfortably inside
        (half the target) retires the highest-index one per cooldown —
        the decode pool's rate/throughput math never touches this count."""
        disagg = serving.spec.disaggregation
        lo = max(0, disagg.prefill_min)
        hi = max(max(1, lo), disagg.prefill_max)
        current = self._int(block.get("prefillDesired"), -1)
        current = min(max(current if current >= 0 else lo, lo), hi)
        ttft = self._float(load.get(consts.SERVING_LOAD_PREFILL_TTFT_P99))
        target = serving.spec.slo.ttft_p99_seconds
        desired = current
        reason = ""
        if ttft > target and current < hi:
            desired = current + 1
            reason = (f"prefill scale up {current} -> {desired}: prefill "
                      f"TTFT p99 {ttft:.3f}s > {target:g}s")
        elif ttft and ttft < 0.5 * target and current > lo:
            cooldown = max(0.0, serving.spec.replicas.cooldown_seconds)
            if now - self._float(block.get("lastPrefillScaleAt")) >= cooldown:
                desired = current - 1
                reason = (f"prefill scale down {current} -> {desired}: "
                          f"prefill TTFT p99 {ttft:.3f}s well inside {target:g}s")
        if reason:
            block["lastPrefillScaleAt"] = round(now, 3)
            self._note_decision(block, "prefill-scale", reason)
            self.recorder.normal(obj, "ServingPrefillScaled", reason)
        block["prefillDesired"] = desired
        replicas = self._owned_replicas(
            serving.name, infix=consts.SERVING_PREFILL_INFIX)
        if replicas is None:
            # fail closed: no create/retire against an unreadable pool
            # (the resync pass retries with a real view)
            return []
        if len(replicas) < desired:
            have = {o["metadata"]["name"] for o in replicas}
            for index in range(hi):
                if len(have) >= desired:
                    break
                name = f"{serving.name}{consts.SERVING_PREFILL_INFIX}{index}"
                if name in have:
                    continue
                if not self._create_slice(
                        obj, serving.name, name, self._prefill_slice_spec(serving)):
                    break
                have.add(name)
            refreshed = self._owned_replicas(
                serving.name, infix=consts.SERVING_PREFILL_INFIX)
            if refreshed is not None:
                replicas = refreshed
        elif len(replicas) > desired:
            # one per pass, highest index first (prefill replicas hold no
            # session KV, so victim choice is free — keep indexes dense)
            victim = replicas[-1]["metadata"]["name"]
            if self._delete_replica(victim):
                self._note_decision(block, "prefill-victim", f"retired {victim}")
                replicas = replicas[:-1]
        return [self._replica_state(o, links) for o in replicas]

    def _sweep_owned(self, serving: str) -> bool:
        """Deleted serving: tear down every ownerRef-verified replica
        slice (real apiservers cascade via ownerReferences; the fake
        store is swept here — ownership verified, so a user's standalone
        TPUSlice can never be collateral). Returns False when the owned
        set was unreadable — the caller must requeue, or the replicas
        leak with nothing left to retrigger the sweep."""
        owned = self._owned_replicas(serving)
        if owned is None:
            return False
        for obj in owned:
            self._delete_replica(obj["metadata"]["name"])
        return True

    def _pick_victim(
        self, serving: TPUServing, replicas: List[ObjectDict], links: List[tuple]
    ) -> Tuple[Optional[str], dict]:
        """The fragmentation-aware scale-down choice, with the score map
        for the decision record."""
        candidates = [o["metadata"]["name"] for o in replicas]
        try:
            slices = self.client.list(TPU_SLICE_API_VERSION, TPU_SLICE_KIND)
            nodes = self.client.list("v1", "Node")
        except errors.ApiError as e:
            log.warning("serving %s: victim scoring inputs unreadable: %s",
                        serving.name, e)
            return None, {}
        scores = scale_down_scores(slices, nodes, candidates, degraded_links=links)
        return pick_scale_down_victim(scores), scores

    # -- worker pods ---------------------------------------------------------

    @staticmethod
    def _replica_index(slice_name: str) -> int:
        try:
            return int(slice_name.rsplit("-", 1)[1])
        except (IndexError, ValueError):
            return 0

    def _converge_workers(
        self, obj: ObjectDict, serving: TPUServing,
        states: List[dict], prefill_states: List[dict],
    ) -> Dict[str, str]:
        """One worker Pod per ready replica, pinned to the replica's
        first gang node. Returns {replica slice name: pod name}. A
        replica that stops being ready loses its pod on the next pass
        (swept — its engine's KV dies with the gang, which is exactly
        what a real node loss costs)."""
        from tpu_operator.dataplane.pods import serving_worker_name

        disagg = serving.spec.disaggregation
        workers: List[dict] = []
        pod_names: Dict[str, str] = {}

        def add(state: dict, pool: str, pool_env: str) -> None:
            name = serving_worker_name(
                serving.name, pool, self._replica_index(state["name"]))
            pod_names[state["name"]] = name
            workers.append({
                "name": name,
                "env": {
                    consts.WORKER_ENV_SERVING_NAME: serving.name,
                    consts.WORKER_ENV_REPLICA_NAME: state["name"],
                    consts.WORKER_ENV_POOL: pool_env,
                    consts.WORKER_ENV_NAMESPACE: self.namespace,
                    # compile-cache addressing: the worker's warmup step
                    # resolves (and on a miss, publishes) its record
                    consts.WORKER_ENV_GENERATION: serving.spec.model.generation or "",
                    consts.WORKER_ENV_TOPOLOGY: serving.spec.model.shape or "",
                },
                "node": state["nodes"][0] if state["nodes"] else "",
            })

        for state in states:
            if state["ready"]:
                add(state, consts.SERVING_POOL_DECODE,
                    consts.SERVING_POOL_DECODE if disagg.enabled else "")
        for state in prefill_states:
            if state["ready"]:
                add(state, consts.SERVING_POOL_PREFILL,
                    consts.SERVING_POOL_PREFILL)
        self.pods.converge(obj, consts.POD_MAIN_SERVING_WORKER, workers)
        self.pods.sweep(
            TPU_SERVING_KIND, serving.name, live=[w["name"] for w in workers])
        return pod_names

    # -- status --------------------------------------------------------------

    def _publish(self, obj: ObjectDict, block: dict) -> bool:
        current = (obj.get("status") or {}).get("serving") or {}
        if current == block:
            return True
        body = dict(block)
        for stale in current:
            if stale not in body:
                body[stale] = None  # merge patch: null removes stale keys
        try:
            self.client.patch_status(  # tpuop-lint: kinds=tpu.google.com/v1alpha1/TPUServing
                TPU_SERVING_API_VERSION, TPU_SERVING_KIND, obj["metadata"]["name"],
                {"status": {"serving": body, "state": block.get("phase", "")}},
            )
        except errors.NotFound:
            return True
        except errors.ApiError as e:
            log.debug("serving status publish for %s failed: %s",
                      obj["metadata"]["name"], e)
            return False
        return True

    def _publish_routing(
        self, serving: str, routing: Dict[str, float],
        pools: Optional[Dict[str, dict]] = None,
    ) -> None:
        """The controller-owned load-CM keys the router consumes. Created
        on first use so routing exists before the first traffic tick;
        the traffic side owns the demand keys (disjoint sets on one CM,
        merge-patch semantics — the job progress CM convention)."""
        from tpu_operator.kube.objects import new_object

        name = serving + consts.SERVING_LOAD_SUFFIX
        data = {consts.SERVING_ROUTING_KEY: json.dumps(routing, sort_keys=True)}
        if pools is not None:
            data[consts.SERVING_POOLS_KEY] = json.dumps(pools, sort_keys=True)
        try:
            self.client.patch("v1", "ConfigMap", name, {"data": data}, self.namespace)
        except errors.NotFound:
            try:
                self.client.create(  # tpuop-lint: kinds=v1/ConfigMap
                    new_object("v1", "ConfigMap", name, self.namespace, data=data)
                )
            except (errors.AlreadyExists, errors.ApiError):
                pass
        except errors.ApiError as e:
            log.debug("serving %s: routing publish failed: %s", serving, e)

    # -- AOT prewarm ---------------------------------------------------------

    def _compile_cache_data(self) -> Optional[dict]:
        """The compile-cache CM's data; {} before first use, None when
        the API is unreachable — prewarm scheduling FAILS CLOSED on
        None (no decisions against an impersonated empty cache)."""
        try:
            cm = self.client.get_or_none(
                "v1", "ConfigMap", consts.COMPILE_CACHE_CONFIGMAP, self.namespace
            )
        except errors.ApiError:
            return None
        return (cm or {}).get("data") or {}

    def _write_prewarm_requests(self, requests: Dict[str, dict]) -> None:
        """The one compile-cache key this controller owns: the prewarm
        request map (the agent acks under its own disjoint key)."""
        from tpu_operator.kube.objects import new_object

        data = {consts.COMPILE_PREWARM_REQUEST_KEY: json.dumps(
            {"requests": requests}, sort_keys=True)}
        try:
            self.client.patch(
                "v1", "ConfigMap", consts.COMPILE_CACHE_CONFIGMAP,
                {"data": data}, self.namespace,
            )
        except errors.NotFound:
            try:
                self.client.create(  # tpuop-lint: kinds=v1/ConfigMap
                    new_object("v1", "ConfigMap", consts.COMPILE_CACHE_CONFIGMAP,
                               self.namespace, data=data)
                )
            except (errors.AlreadyExists, errors.ApiError):
                pass
        except errors.ApiError as e:
            log.debug("serving: prewarm request publish failed: %s", e)

    def _reconcile_prewarm(
        self, obj: ObjectDict, serving: TPUServing, block: dict
    ) -> None:
        """AOT prewarm scheduling: this serving's replicas imply an
        imminent (generation, shape, model) — when the fleet compile
        cache has no record for it, publish a prewarm request so the
        elected agent compiles BEFORE the next replica's worker boots
        (its warmup step then resolves a cache hit). Idempotent:
        an already-requested or already-cached key writes nothing, so
        steady state is zero writes; a satisfied request is cleared
        once (the request map is this controller's key)."""
        from tpu_operator.workloads.compilecache import (
            entry_key,
            model_descriptor_hash,
            parse_entry,
            parse_requests,
            record_key,
            request_id,
        )

        generation = serving.spec.model.generation
        if not generation:
            return  # no generation hint: nothing to address the cache by
        topology = serving.spec.model.shape
        model_hash = model_descriptor_hash()
        data = self._compile_cache_data()
        if data is None:
            return  # fail closed (K003): unreadable cache schedules nothing
        rid = request_id(generation, topology, model_hash)
        requests = parse_requests(data.get(consts.COMPILE_PREWARM_REQUEST_KEY))
        entry = parse_entry(data.get(entry_key(generation)))
        # presence-based: the compile-cache controller DELETES entries
        # invalidated by a libtpu bump, so presence converges on
        # validity — and a stale record is re-requested right after
        records = (entry or {}).get("records")
        cached = isinstance(records, dict) and record_key(topology, model_hash) in records
        if cached:
            if rid in requests:
                remaining = {k: v for k, v in requests.items() if k != rid}
                self._write_prewarm_requests(remaining)
                self._note_decision(
                    block, "prewarm", f"{rid} cached; prewarm request cleared")
            return
        if rid in requests:
            return  # requested, compile in flight: zero writes
        requests[rid] = {
            "generation": generation,
            "topology": topology,
            "model": model_hash,
            "serving": serving.name,
        }
        self._write_prewarm_requests(requests)
        detail = (
            f"requested compile prewarm for {rid} (cold cache: the next "
            f"replica would pay the full XLA compile)"
        )
        self._note_decision(block, "prewarm", detail)
        self.recorder.normal(obj, "ServingPrewarmRequested", detail)

    def _note_decision(self, block: dict, action: str, detail: str) -> None:
        decisions = list(block.get("decisions") or [])
        decisions.append({"step": self._int(block.get("passes")), "action": action,
                          "reason": detail})
        block["decisions"] = decisions[-consts.SERVING_DECISIONS_LIMIT:]

    def _fail(self, obj: ObjectDict, block: dict, message: str) -> None:
        """Terminal quarantine: a serving that cannot place its replicas
        stops holding placement-queue slots; the caller's single status
        publish tail does the writing."""
        block["phase"] = ServingPhase.FAILED
        block["ready"] = 0
        block["message"] = message
        block.pop("nextAttemptAt", None)
        self._sweep_owned(obj["metadata"]["name"])
        self.pods.sweep(TPU_SERVING_KIND, obj["metadata"]["name"])
        self.recorder.warning(obj, "ServingFailed", f"quarantined: {message}")

    # -- reconcile -----------------------------------------------------------

    def reconcile(self, req: Request) -> Result:
        obj = self.client.get_or_none(TPU_SERVING_API_VERSION, TPU_SERVING_KIND, req.name)
        if obj is None:
            self._retire_series(req.name)
            swept = self._sweep_owned(req.name)
            self.pods.sweep(TPU_SERVING_KIND, req.name)
            # an unreadable owned set MUST requeue: the serving is gone,
            # so nothing else will ever retrigger this sweep
            return Result(requeue=not swept)
        serving = TPUServing.from_unstructured(obj)
        prior = dict(serving.status.serving or {})
        phase = prior.get("phase") or ServingPhase.PENDING
        if phase in SERVING_TERMINAL_PHASES:
            return Result()

        block = {
            "phase": phase,
            "desired": self._int(prior.get("desired"), -1),
            "ready": 0,
            "routable": 0,
            "passes": self._int(prior.get("passes")) + 1,
            "restarts": self._int(prior.get("restarts")),
            "decisions": list(prior.get("decisions") or []),
        }
        for carry in ("nextAttemptAt", "lastScaleAt", "lowSince", "message"):
            if prior.get(carry):
                block[carry] = prior[carry]

        # -- validate the footprint once per pass
        from tpu_operator.placement.torus import parse_shape

        spec = serving.spec
        if (
            parse_shape(spec.model.shape) is None
            or spec.replicas.min < 0
            or spec.replicas.max < max(1, spec.replicas.min)
            or spec.replicas.target_rps <= 0
        ):
            self._fail(
                obj, block,
                f"invalid serving spec: shape={spec.model.shape!r} "
                f"replicas=[{spec.replicas.min}, {spec.replicas.max}] "
                f"targetRps={spec.replicas.target_rps}",
            )
            self._export(req.name, 0, 0.0, 0.0, 0)
            return Result(requeue=not self._publish(obj, block))
        budget = RetryBudget(
            retry_limit=spec.backoff.retry_limit,
            base_delay_seconds=spec.backoff.base_seconds,
            max_delay_seconds=spec.backoff.max_seconds,
        )
        if block["desired"] < 0:
            block["desired"] = spec.replicas.min

        # -- world state
        load = self._load(serving.name)
        links = self._degraded_links()
        replicas = self._owned_replicas(
            serving.name, infix=consts.SERVING_REPLICA_INFIX)
        if replicas is None:
            # transient list failure: abort before any scale decision —
            # acting on an impersonated empty set would delete/recreate
            # replicas against a world that isn't real
            return Result(requeue=True)
        states = [self._replica_state(o, links) for o in replicas]
        now = time.time()

        with trace.span(
            "serving-autoscale", phase=phase,
            replicas=len(replicas), desired=block["desired"],
        ):
            result = self._reconcile_scaling(
                obj, serving, block, budget, load, links, replicas, states, now
            )
        ttft_p99 = self._float(load.get(consts.SERVING_LOAD_TTFT_P99))
        pools_block = block.get("pools") or {}
        self._export(
            serving.name, block["ready"],
            self._float(load.get(consts.SERVING_LOAD_TOKENS_PER_S)),
            ttft_p99,
            self._int(load.get(consts.SERVING_LOAD_QUEUE_DEPTH)),
            kv_hit_ratio=self._float(load.get(consts.SERVING_LOAD_KV_HIT_RATIO)),
            handoff_bytes=self._float(load.get(consts.SERVING_LOAD_HANDOFF_BYTES)),
            pools={
                consts.SERVING_POOL_PREFILL: self._int(
                    (pools_block.get(consts.SERVING_POOL_PREFILL) or {}).get("ready")),
                consts.SERVING_POOL_DECODE: self._int(
                    (pools_block.get(consts.SERVING_POOL_DECODE) or {}).get("ready"),
                    block["ready"]),
            },
        )
        ok = self._publish(obj, block)
        if not ok:
            return Result(requeue=True)
        if block["phase"] in SERVING_TERMINAL_PHASES:
            return Result()
        return result

    def _reconcile_scaling(
        self,
        obj: ObjectDict,
        serving: TPUServing,
        block: dict,
        budget: RetryBudget,
        load: dict,
        links: List[tuple],
        replicas: List[ObjectDict],
        states: List[dict],
        now: float,
    ) -> Result:
        desired, reason = self._autoscale(serving, block, load, states, now)
        prior_desired = self._int(block.get("desired"))
        block["desired"] = desired
        if reason:
            self._note_decision(block, "scale-up" if desired > prior_desired
                                else "scale-down", reason)
            block["lastScaleAt"] = round(now, 3)
            if desired > prior_desired:
                self.recorder.normal(obj, "ServingScaledUp", reason)

        # -- converge the replica set to `desired`
        if len(replicas) < desired:
            have = {o["metadata"]["name"] for o in replicas}
            index = 0
            created = 0
            while len(have) + created < desired and index < desired + len(have):
                name = replica_name(serving.name, index)
                if name not in have:
                    if self._create_replica(obj, serving, index):
                        created += 1
                    else:
                        break
                index += 1
        elif len(replicas) > desired:
            victim, scores = self._pick_victim(serving, replicas, links)
            if victim is not None and self._delete_replica(victim):
                after, delta = scores.get(victim, (0.0, 0.0))
                detail = (
                    f"retired {victim}: fragmentation delta {delta:+.4f} "
                    f"(-> {after:.4f}) is the best of "
                    f"{{{', '.join(f'{n}: {scores[n][1]:+.4f}' for n in sorted(scores))}}}"
                )
                self._note_decision(block, "victim", detail)
                self.recorder.normal(obj, "ServingScaledDown", detail)
                replicas = [o for o in replicas if o["metadata"]["name"] != victim]
                states = [s for s in states if s["name"] != victim]

        # -- AOT prewarm: make sure the compile this serving's next
        # replica needs is already in the fleet cache
        self._reconcile_prewarm(obj, serving, block)

        # -- the prefill pool converges on its own signal
        disagg = serving.spec.disaggregation
        prefill_states: List[dict] = []
        if disagg.enabled:
            prefill_states = self._reconcile_prefill(
                obj, serving, block, load, links, now)
        else:
            block.pop("prefillDesired", None)
            block.pop("lastPrefillScaleAt", None)

        # -- worker pods: one per placed replica, in both pools
        pod_names = self._converge_workers(obj, serving, states, prefill_states)

        # -- routing: ready replicas minus fabric-excluded ones; a worker
        # pod the kubelet has marked Failed is unroutable even when its
        # replica slice is healthy (the engine behind it is dead)
        phases = self.pods.worker_phases(TPU_SERVING_KIND, serving.name)
        routing: Dict[str, float] = {}
        for state in states:
            weight = 1.0 if state["routable"] else 0.0
            if phases.get(pod_names.get(state["name"], "")) == "Failed":
                weight = 0.0
            routing[state["name"]] = weight
            if state["fabric_degraded"]:
                self.recorder.warning(
                    obj, "ServingReplicaExcluded",
                    f"replica {state['name']} excluded from routing: fabric "
                    f"artifact shows a degraded ICI edge",
                )
        prefill_ready = sum(1 for s in prefill_states if s["ready"])
        pools = None
        if disagg.enabled:
            pools = {
                consts.SERVING_POOL_PREFILL: {
                    "desired": self._int(block.get("prefillDesired")),
                    "ready": prefill_ready,
                },
                consts.SERVING_POOL_DECODE: {
                    "desired": self._int(block.get("desired")),
                    "ready": sum(1 for s in states if s["ready"]),
                },
            }
            block["pools"] = pools
        else:
            block.pop("pools", None)
        self._publish_routing(serving.name, routing, pools)
        ready = sum(1 for s in states if s["ready"])
        routable = sum(1 for s in states if s["routable"])
        block["ready"] = ready
        block["routable"] = routable
        block["replicas"] = {
            s["name"]: (
                "Serving" if s["routable"]
                else "Excluded" if s["ready"]
                else "Broken" if s["out"] or s["cut"]
                else "Unschedulable" if s["unschedulable"]
                else "Placing"
            )
            for s in states
        }
        slo = serving.spec.slo
        ttft_p99 = self._float(load.get(consts.SERVING_LOAD_TTFT_P99))
        block["slo"] = {
            "ttftP99": ttft_p99,
            "ttftTarget": slo.ttft_p99_seconds,
            "attained": bool(ttft_p99 <= slo.ttft_p99_seconds),
        }

        # -- placement starvation burns the budget ONLY while the service
        # is below its min-replica floor (actually down, nothing
        # placeable). A scale-UP shortfall above the floor — a burst
        # wants 3, the torus fits 2 — is a capacity note, never a
        # quarantine: exhausting the budget there would delete healthy,
        # traffic-serving replicas to punish the cluster for being full.
        wanted = self._int(block.get("desired"))
        floor = max(0, serving.spec.replicas.min)
        starved = next((s["name"] for s in states if s["unschedulable"]), "")
        if ready >= wanted:
            block["restarts"] = 0
            block.pop("nextAttemptAt", None)
            block["message"] = ""
        elif starved and ready < floor:
            charged = self._charge_attempt(
                obj, block, budget,
                cause=f"replica {starved} unplaceable with {ready}/{floor} "
                      f"min replicas ready",
            )
            if charged is not None:
                return charged
        elif starved:
            block["message"] = (
                f"replica {starved} unplaceable (capacity short; serving "
                f"{ready} >= min {floor}, not quarantining)"
            )

        # -- phase
        if block["phase"] != ServingPhase.FAILED:
            if wanted == 0:
                block["phase"] = ServingPhase.SERVING
            elif not states and wanted > 0:
                block["phase"] = ServingPhase.PENDING
            elif ready >= wanted and routable >= wanted:
                block["phase"] = ServingPhase.SERVING
            elif ready >= wanted and routable < wanted:
                block["phase"] = ServingPhase.DEGRADED
            else:
                block["phase"] = ServingPhase.SCALING
        return Result(requeue_after=consts.SERVING_RESYNC_SECONDS)

    def _charge_attempt(
        self, obj: ObjectDict, block: dict, budget: RetryBudget, cause: str
    ) -> Optional[Result]:
        """One failed placement attempt against the retry budget, gated
        by the persisted next-attempt time so event-driven wakeups can't
        burn the budget faster than the backoff schedule. Returns a
        Result when the gate parked or the budget exhausted; None when
        the pass should continue normally after charging."""
        next_at = self._float(block.get("nextAttemptAt"))
        now = time.time()
        if now < next_at:
            return Result(requeue_after=min(next_at - now, consts.SERVING_RESYNC_SECONDS))
        attempts = self._int(block.get("restarts"))
        if budget.exhausted(attempts):
            self._fail(
                obj, block,
                f"placement retry budget exhausted ({attempts} attempts): {cause}",
            )
            return Result()
        attempts += 1
        delay = budget.delay(attempts, self.rng)
        block["restarts"] = attempts
        block["nextAttemptAt"] = round(now + delay, 3)
        block["message"] = cause
        return None

    @staticmethod
    def _int(value, default: int = 0) -> int:
        try:
            return int(float(value))
        except (TypeError, ValueError):
            return default

    @staticmethod
    def _float(value, default: float = 0.0) -> float:
        try:
            return float(value)
        except (TypeError, ValueError):
            return default


def setup_with_manager(mgr, reconciler: ServingReconciler) -> Controller:
    ctrl = Controller("tpuserving", reconciler)
    reconciler.client = CachedReadClient(reconciler.client, mgr)

    def map_owned_slice(obj: ObjectDict) -> List[Request]:
        # ONLY slices carrying a TPUServing ownerReference map back: a
        # user's standalone TPUSlice named "*-replica-0" is not this
        # controller's to reconcile (or sweep)
        for ref in obj["metadata"].get("ownerReferences") or []:
            if ref.get("kind") == TPU_SERVING_KIND:
                return [Request(name=ref["name"])]
        return []

    def placement_status_changed(event_type, old, new) -> bool:
        if event_type != "MODIFIED" or old is None:
            return True
        return (
            (old.get("status") or {}).get("placement")
            != (new.get("status") or {}).get("placement")
        )

    def map_load_cm(obj: ObjectDict) -> List[Request]:
        name = obj["metadata"]["name"]
        if not name.endswith(consts.SERVING_LOAD_SUFFIX):
            return []
        return [Request(name=name[: -len(consts.SERVING_LOAD_SUFFIX)])]

    def load_changed(event_type, old, new) -> bool:
        if not new["metadata"]["name"].endswith(consts.SERVING_LOAD_SUFFIX):
            return False
        if event_type != "MODIFIED" or old is None:
            return True
        return (old.get("data") or {}) != (new.get("data") or {})

    def map_to_all_servings(_obj) -> List[Request]:
        try:
            servings = reconciler.client.list(TPU_SERVING_API_VERSION, TPU_SERVING_KIND)
        except errors.ApiError:
            return []
        return [Request(name=s["metadata"]["name"]) for s in servings]

    def service_labels_changed(event_type, old, new) -> bool:
        keys = (
            consts.TPU_HEALTH_LABEL,
            consts.REPAIR_STATE_LABEL,
            consts.TPU_PERF_LABEL,
            consts.PLACEMENT_LABEL,
        )
        if event_type != "MODIFIED" or old is None:
            return True
        old_labels = old["metadata"].get("labels") or {}
        new_labels = new["metadata"].get("labels") or {}
        return any(old_labels.get(k) != new_labels.get(k) for k in keys)

    ctrl.watch(
        mgr.informer_for(TPU_SERVING_API_VERSION, TPU_SERVING_KIND),
        predicate=generation_changed,
    )
    ctrl.watch(
        mgr.informer_for(TPU_SLICE_API_VERSION, TPU_SLICE_KIND),
        mapper=map_owned_slice, predicate=placement_status_changed,
    )
    ctrl.watch(
        mgr.informer_for("v1", "ConfigMap", reconciler.namespace),
        mapper=map_load_cm, predicate=load_changed,
    )
    ctrl.watch(
        mgr.informer_for("v1", "Node"),
        mapper=map_to_all_servings, predicate=service_labels_changed,
    )
    mgr.add_controller(ctrl)
    return ctrl
