"""Scheduled defragmentation: background gang migrations in idle windows.

The execution half of the capacity planner (``tpu_operator/planning/``).
Fragmentation is a measured series (``tpu_operator_torus_fragmentation``)
and the proposer math is the engine's own replay-minus-candidate helper
(``placement/engine.migration_scores`` — the SAME primitive the serving
controller's scale-down victim rides), so a proposal here is exactly
"what the next placement pass would do if this gang's assignment went
away". What this controller adds is the *discipline* around executing
one:

- **Idle windows only.** A pass proposes nothing while the placement
  engine has work in flight (any label delta or teardown in the
  replayed plan — a Queued gang, a broken gang, an orphaned label). An
  ``Unschedulable`` request does NOT block defrag: a parked gang is the
  *beneficiary* — a migration that seats one wins outright.
- **Demand headroom.** No migrations above
  ``consts.DEFRAG_UTILIZATION_HEADROOM`` fleet utilization: near-full
  is exactly when a checkpoint/drain cycle hurts most and helps least.
- **Budget + cooldown.** At most ``DEFRAG_MIGRATION_BUDGET`` migrations
  per ``DEFRAG_BUDGET_WINDOW_SECONDS``, never two within
  ``DEFRAG_COOLDOWN_SECONDS``, and never a second while one is in
  flight — defrag can slow down, it can never thrash.
- **Owner-safe execution.** A TPUJob gang migrates through the PR 13
  checkpoint barrier: this controller writes its one owned key
  (``consts.JOB_DEFRAG_REQUEST``) into the job's progress ConfigMap and
  the job controller checkpoints, tears the gang down, and resumes on
  the re-placed block. A TPUServing replica takes the drain-then-
  re-place path (assignment labels cleared; the serving router zeroes
  its weight the same pass, the engine re-seats it) — and only while
  the serving has another routable replica. Gangs owned by neither are
  NEVER touched.
- **Link-cut aware.** Every replay carries the fabric analyzer's
  link-health map, so a proposal can never seat a gang across a
  recorded cut.

Decisions (last ``DEFRAG_DECISIONS_LIMIT``, with predicted-vs-realized
fragmentation deltas) persist in the ``tpu-defrag-state`` ConfigMap —
restart-safe budget accounting, and the must-gather ``plan.txt``
evidence trail. Completed migrations emit a ``DefragMigrated`` Event
naming the source and destination blocks.
"""

from __future__ import annotations

import json
import logging
import time
from typing import Dict, List, Optional, Tuple

from tpu_operator import consts
from tpu_operator.api.tpujob import TPU_JOB_API_VERSION, TPU_JOB_KIND, JobPhase
from tpu_operator.api.tpuserving import TPU_SERVING_KIND
from tpu_operator.api.tpuslice import TPU_SLICE_API_VERSION, TPU_SLICE_KIND
from tpu_operator.controllers.operator_metrics import get_metrics
from tpu_operator.kube import errors, trace
from tpu_operator.kube.cached import CachedReadClient
from tpu_operator.kube.client import Client
from tpu_operator.kube.controller import Controller, Request, Result
from tpu_operator.kube.events import EventRecorder
from tpu_operator.kube.objects import ObjectDict, new_object
from tpu_operator.placement.engine import (
    PlacementEngine,
    PlacementPhase,
    migration_scores,
    pick_migration,
)
from tpu_operator.planning.model import predict_step_time
from tpu_operator.workloads.descriptor import reference_descriptor

log = logging.getLogger(__name__)

DEFRAG_MANAGER = "tpu-defrag-controller"

# the one request the whole pass maps to (the placement queue's shape)
DEFRAG_REQUEST = Request(name="defrag-pass")

# an in-flight migration whose gang never re-placed within this window
# is recorded failed (realized=None -> "abandoned") and stops blocking
IN_FLIGHT_TIMEOUT_SECONDS = 600.0


class DefragReconciler:
    def __init__(self, client: Client, namespace: str = consts.DEFAULT_OPERATOR_NAMESPACE):
        self.client = client
        self.namespace = namespace
        self.recorder = EventRecorder(client, namespace, component=DEFRAG_MANAGER)
        self.metrics = get_metrics()
        self._now = time.time  # tests pin the clock
        from tpu_operator.kube import racecheck

        self._series_lock = racecheck.lock("DefragReconciler._series_lock")
        self._util_pools: set = set()
        self._pred_generations: set = set()

    # -- series hygiene ------------------------------------------------------

    def _export_utilization(self, engine: PlacementEngine) -> Dict[str, float]:
        utilization: Dict[str, float] = {}
        for pool_name, (_, torus) in sorted(engine.pools.items()):
            utilization[pool_name] = torus.utilization()
            self.metrics.fleet_utilization.labels(pool_name).set(utilization[pool_name])
        with self._series_lock:
            gone = self._util_pools - set(utilization)
            self._util_pools = set(utilization)
        for pool_name in gone:
            try:
                self.metrics.fleet_utilization.remove(pool_name)
            except KeyError:
                pass
        return utilization

    def _export_predictions(self, engine: PlacementEngine) -> Dict[str, float]:
        """The analytical model's reference prediction per generation
        present in the fleet — the live calibration surface `tpuop-cfg
        plan` and dashboards read. Autotune winners fold in exactly as
        they do for the floors pipeline."""
        entries = self._autotune_entries()
        descriptor = reference_descriptor()
        predictions: Dict[str, float] = {}
        for pool, _ in engine.pools.values():
            gen = pool.info.generation
            if gen in predictions:
                continue
            prediction = predict_step_time(
                descriptor, gen, (2, 2, 1),
                chips_per_host=max(1, pool.info.chips_per_node),
                autotune_entries=entries,
            )
            predictions[gen] = round(prediction.step_seconds, 6)
            self.metrics.plan_predicted_step.labels(gen).set(predictions[gen])
        with self._series_lock:
            gone = self._pred_generations - set(predictions)
            self._pred_generations = set(predictions)
        for gen in gone:
            try:
                self.metrics.plan_predicted_step.remove(gen)
            except KeyError:
                pass
        return predictions

    def _autotune_entries(self) -> Optional[dict]:
        """The cached per-generation sweep entries (calibration input);
        None when the results CM is absent/unreadable — the model falls
        back to the static table, never raises."""
        try:
            cm = self.client.get_or_none(
                "v1", "ConfigMap", consts.AUTOTUNE_RESULTS_CONFIGMAP, self.namespace
            )
        except errors.ApiError:
            return None
        if cm is None:
            return None
        from tpu_operator.workloads.autotune import cached_entries

        return cached_entries(cm.get("data"))

    # -- persisted state -----------------------------------------------------

    def _read_state(self) -> Optional[dict]:
        """The budget/cooldown ledger. A transient READ failure returns
        None and the caller aborts the pass — a flaky apiserver must
        fail CLOSED, not reset the ledger and hand back the whole
        migration budget. Only a genuinely malformed blob (which a
        retry can never fix) starts fresh."""
        try:
            cm = self.client.get_or_none(
                "v1", "ConfigMap", consts.DEFRAG_STATE_CONFIGMAP, self.namespace
            )
        except errors.ApiError as e:
            log.warning("defrag: state CM unreadable, pass aborted: %s", e)
            return None
        raw = ((cm or {}).get("data") or {}).get(consts.DEFRAG_STATE_KEY)
        if not raw:
            return {"decisions": []}
        try:
            state = json.loads(raw)
        except ValueError:
            state = None  # malformed: start fresh, never crash the pass
        if not isinstance(state, dict) or not isinstance(state.get("decisions"), list):
            return {"decisions": []}
        return state

    def _write_state(self, state: dict) -> None:
        state["decisions"] = state.get("decisions", [])[-consts.DEFRAG_DECISIONS_LIMIT:]
        data = {consts.DEFRAG_STATE_KEY: json.dumps(state, sort_keys=True)}
        try:
            self.client.patch(
                "v1", "ConfigMap", consts.DEFRAG_STATE_CONFIGMAP,
                {"data": data}, self.namespace,
            )
        except errors.NotFound:
            try:
                self.client.create(  # tpuop-lint: kinds=v1/ConfigMap
                    new_object(
                        "v1", "ConfigMap", consts.DEFRAG_STATE_CONFIGMAP,
                        self.namespace, data=data,
                    )
                )
            except (errors.AlreadyExists, errors.ApiError) as e:
                log.debug("defrag state write raced/failed: %s", e)
        except errors.ApiError as e:
            log.debug("defrag state write failed: %s", e)

    # -- the pass ------------------------------------------------------------

    def reconcile(self, req: Request) -> Result:
        try:
            slices = self.client.list(TPU_SLICE_API_VERSION, TPU_SLICE_KIND)
            nodes = self.client.list("v1", "Node")
        except errors.ApiError as e:
            log.debug("defrag pass inputs unreadable: %s", e)
            return Result(requeue_after=consts.DEFRAG_REPLAN_SECONDS)
        links = self._degraded_links()
        if links is None:
            # a failed link-map read aborts the pass (the placement
            # controller's rule): proposing with "no cuts" could migrate
            # a gang ONTO a known-degraded link
            return Result(requeue_after=consts.DEFRAG_REPLAN_SECONDS)
        with trace.span("defrag-plan", slices=len(slices), nodes=len(nodes)):
            engine = PlacementEngine(slices, nodes, degraded_links=links)
            plan = engine.plan()
        utilization = self._export_utilization(engine)
        self._export_predictions(engine)
        if not engine.pools:
            return Result(requeue_after=consts.DEFRAG_REPLAN_SECONDS)

        state = self._read_state()
        if state is None:
            # ledger unreadable: fail closed (proposing against an empty
            # ledger would hand the whole migration budget back)
            return Result(requeue_after=consts.DEFRAG_REPLAN_SECONDS)
        now = self._now()
        slices_by_name = {s["metadata"]["name"]: s for s in slices}
        in_flight, dirty = self._settle_in_flight(state, plan, slices_by_name, now)

        busy = bool(plan.label_deltas or plan.teardowns)
        over_headroom = any(
            u >= consts.DEFRAG_UTILIZATION_HEADROOM for u in utilization.values()
        )
        if not (busy or over_headroom or in_flight) and self._budget_allows(state, now):
            with trace.span("defrag-propose"):
                proposal = self._propose(slices, nodes, slices_by_name, links)
            if proposal is not None:
                dirty = self._execute(proposal, slices_by_name, state, now) or dirty
        if dirty:
            # a quiet pass writes nothing (the fabric analyzer's rule):
            # an every-pass state rewrite would be a steady write load
            # for a controller that is idle almost always
            self._write_state(state)
        return Result(requeue_after=consts.DEFRAG_REPLAN_SECONDS)

    def _degraded_links(self) -> Optional[List[tuple]]:
        from tpu_operator.controllers.fabric_telemetry import degraded_link_pairs

        try:
            return degraded_link_pairs(self.client, self.namespace)
        except errors.ApiError as e:
            log.warning("defrag: link-health map unreadable, pass aborted: %s", e)
            return None

    # -- budget --------------------------------------------------------------

    def _budget_allows(self, state: dict, now: float) -> bool:
        executed = [
            d for d in state.get("decisions", []) if d.get("executed_at") is not None
        ]
        if executed:
            last = max(d["executed_at"] for d in executed)
            if now - last < consts.DEFRAG_COOLDOWN_SECONDS:
                return False
        window_start = now - consts.DEFRAG_BUDGET_WINDOW_SECONDS
        recent = sum(1 for d in executed if d["executed_at"] >= window_start)
        return recent < consts.DEFRAG_MIGRATION_BUDGET

    def _settle_in_flight(
        self, state: dict, plan, slices_by_name: dict, now: float
    ) -> Tuple[bool, bool]:
        """Book the realized outcome of the newest unsettled decision.
        Returns (in_flight, state_changed): in_flight blocks proposing
        while a migration is still moving."""
        changed = False
        decisions = state.get("decisions", [])
        for decision in reversed(decisions):
            if decision.get("settled"):
                continue
            name = decision.get("slice", "")
            obj = slices_by_name.get(name)
            status = ((obj or {}).get("status") or {}).get("placement") or {}
            scheduled = status.get("phase") == PlacementPhase.SCHEDULED
            moved = scheduled and (
                (str(status.get("origin") or ""), status.get("pool"))
                != (decision.get("source_origin"), decision.get("pool"))
                or list(status.get("nodes") or [])
                != list(decision.get("source_nodes") or [])
            )
            if moved:
                # realized on the SOURCE pool — the same pool the
                # proposal's predicted_frag was scored on (a cross-pool
                # re-seat must never difference two pools' numbers)
                realized = plan.fragmentation.get(
                    str(decision.get("pool") or ""), 0.0
                )
                changed = True
                decision["settled"] = True
                decision["realized_frag"] = realized
                decision["realized_delta"] = round(
                    realized - float(decision.get("frag_before") or 0.0), 4
                )
                decision["dest_origin"] = str(status.get("origin") or "")
                if obj is not None:
                    self.recorder.event(
                        obj, "Normal", "DefragMigrated",
                        f"gang {name} migrated from block "
                        f"{decision.get('source_origin') or '?'} to block "
                        f"{decision.get('dest_origin') or '?'} in pool "
                        f"{status.get('pool') or decision.get('pool') or '?'}; "
                        f"fragmentation {decision.get('frag_before')} -> {realized} "
                        f"(predicted {decision.get('predicted_frag')})",
                    )
                continue
            if obj is None or now - float(decision.get("executed_at") or 0.0) \
                    > IN_FLIGHT_TIMEOUT_SECONDS:
                changed = True
                decision["settled"] = True
                decision["realized_frag"] = None
                decision["abandoned"] = True
                continue
            return True, changed  # still moving: never overlap migrations
        return False, changed

    # -- proposing -----------------------------------------------------------

    def _migratable(self, slices_by_name: dict) -> Dict[str, Tuple[str, str]]:
        """slice name -> (owner kind, owner name) for every placed gang
        defrag may legally move: TPUJob-owned gangs whose job is Running
        with a live progress CM (somebody must answer the checkpoint
        barrier), and TPUServing replicas with at least one OTHER placed,
        in-service sibling (never drain the last routable replica).
        Everything else — no owner, foreign owner — is untouchable."""
        out: Dict[str, Tuple[str, str]] = {}
        for name, obj in slices_by_name.items():
            status = (obj.get("status") or {}).get("placement") or {}
            if status.get("phase") != PlacementPhase.SCHEDULED:
                continue
            owner = self._owner_of(obj)
            if owner is None:
                continue
            kind, owner_name = owner
            if kind == TPU_JOB_KIND and self._job_migratable(owner_name):
                out[name] = owner
            elif kind == TPU_SERVING_KIND and self._serving_sibling_placed(
                name, owner_name, slices_by_name
            ):
                out[name] = owner
        return out

    @staticmethod
    def _owner_of(obj: ObjectDict) -> Optional[Tuple[str, str]]:
        for ref in obj["metadata"].get("ownerReferences") or []:
            if ref.get("kind") in (TPU_JOB_KIND, TPU_SERVING_KIND) and ref.get("name"):
                return (str(ref["kind"]), str(ref["name"]))
        return None

    def _job_migratable(self, job_name: str) -> bool:
        job = self.client.get_or_none(TPU_JOB_API_VERSION, TPU_JOB_KIND, job_name)
        if job is None:
            return False
        block = (job.get("status") or {}).get("job") or {}
        if block.get("phase") != JobPhase.RUNNING:
            return False
        progress = self.client.get_or_none(
            "v1", "ConfigMap", job_name + consts.JOB_PROGRESS_SUFFIX, self.namespace
        )
        return progress is not None

    def _serving_sibling_placed(
        self, name: str, serving: str, slices_by_name: dict
    ) -> bool:
        """True when another replica of the same serving is placed AND
        in service (every member node healthy) — draining a gang whose
        only sibling is placed-but-dying would leave the serving with
        zero routable replicas for the whole re-place window. (A
        sibling whose router exclusion comes ONLY from a not-yet-blamed
        fabric artifact can slip through for one analyzer cadence; the
        analyzer's link/host blame lands in the link map / node labels,
        which this check and the replay both honor.)"""
        from tpu_operator.placement.engine import labels_unavailable

        for other_name, other in slices_by_name.items():
            if other_name == name:
                continue
            owner = self._owner_of(other)
            if owner != (TPU_SERVING_KIND, serving):
                continue
            status = (other.get("status") or {}).get("placement") or {}
            if status.get("phase") != PlacementPhase.SCHEDULED:
                continue
            members_healthy = True
            for node_name in status.get("nodes") or []:
                node = self.client.get_or_none("v1", "Node", node_name)
                if node is None or labels_unavailable(
                    node["metadata"].get("labels") or {}
                ):
                    members_healthy = False
                    break
            if members_healthy:
                return True
        return False

    def _propose(
        self, slices, nodes, slices_by_name: dict, links
    ) -> Optional[dict]:
        migratable = self._migratable(slices_by_name)
        if not migratable:
            return None
        scores = migration_scores(
            slices, nodes, sorted(migratable), degraded_links=links
        )
        best = pick_migration(scores)
        if best is None:
            return None
        entry = scores[best]
        if not entry["lands_pending"] and entry["frag_delta"] > -consts.DEFRAG_MIN_FRAG_GAIN:
            return None  # the improvement is noise: not worth a checkpoint
        kind, owner_name = migratable[best]
        return {"slice": best, "owner_kind": kind, "owner_name": owner_name, **entry}

    # -- executing -----------------------------------------------------------

    def _execute(
        self, proposal: dict, slices_by_name: dict, state: dict, now: float
    ) -> bool:
        """Returns True when the migration was requested and booked
        into the state ledger (the caller's write-needed signal)."""
        name = proposal["slice"]
        obj = slices_by_name.get(name)
        status = ((obj or {}).get("status") or {}).get("placement") or {}
        decision = {
            "slice": name,
            "owner_kind": proposal["owner_kind"],
            "owner_name": proposal["owner_name"],
            "pool": proposal["pool"],
            "dest_pool": proposal.get("dest_pool") or proposal["pool"],
            "frag_before": proposal["frag_before"],
            "predicted_frag": proposal["frag_after"],
            "predicted_delta": proposal["frag_delta"],
            "lands_pending": proposal["lands_pending"],
            "source_origin": str(status.get("origin") or ""),
            "source_nodes": list(status.get("nodes") or []),
            "predicted_dest_origin": proposal["origin"],
            "executed_at": None,
            "settled": False,
        }
        if proposal["owner_kind"] == TPU_JOB_KIND:
            ok = self._request_job_migration(proposal["owner_name"], state, now)
        else:
            ok = self._drain_serving_replica(decision["source_nodes"])
        if not ok:
            return False
        decision["executed_at"] = now
        state.setdefault("decisions", []).append(decision)
        self.metrics.defrag_migrations.inc()
        if obj is not None:
            self.recorder.event(
                obj, "Normal", "DefragProposed",
                f"migrating gang {name} off block "
                f"{decision['source_origin'] or '?'} (pool {proposal['pool']}): "
                f"predicted fragmentation {proposal['frag_before']} -> "
                f"{proposal['frag_after']}"
                + (
                    f"; seats pending {', '.join(proposal['lands_pending'])}"
                    if proposal["lands_pending"] else ""
                ),
            )
        return True

    def _request_job_migration(self, job_name: str, state: dict, now: float) -> bool:
        """The checkpoint-barrier path: bump our one owned key in the
        job's progress CM; the job controller drives checkpoint →
        teardown → re-place → resume and records the token it honored
        in status.job.defragHandled."""
        token = f"defrag-{int(now)}-{state.get('serial', 0)}"
        try:
            self.client.patch(
                "v1", "ConfigMap", job_name + consts.JOB_PROGRESS_SUFFIX,
                {"data": {consts.JOB_DEFRAG_REQUEST: token}}, self.namespace,
            )
        except (errors.NotFound, errors.ApiError) as e:
            log.debug("defrag: job %s migration request failed: %s", job_name, e)
            return False
        # bumped only on success: a failed request mutates nothing, so
        # the caller's skip-the-write-when-clean rule stays sound
        state["serial"] = int(state.get("serial", 0)) + 1
        return True

    def _drain_serving_replica(self, gang_nodes: List[str]) -> bool:
        """The drain-then-re-place path: clear the replica gang's
        assignment labels (the engine's source of truth). The serving
        router zeroes the replica's weight the moment it reads as
        unplaced, and the placement pass re-seats it into the replay's
        predicted block. A PARTIAL clear still counts executed (the
        engine finishes the teardown — level-triggered repair), but a
        sweep that cleared NOTHING must not book a migration, spend
        budget, or block defrag behind a phantom in-flight decision."""
        from tpu_operator.controllers.placement_controller import (
            clear_assignment_labels,
        )

        return clear_assignment_labels(self.client, gang_nodes) > 0


def setup_with_manager(mgr, reconciler: DefragReconciler) -> Controller:
    ctrl = Controller(
        "defrag", reconciler, coalesce_window=consts.NODE_EVENT_COALESCE_SECONDS
    )
    reconciler.client = CachedReadClient(reconciler.client, mgr)

    def map_to_pass(_obj) -> List[Request]:
        return [DEFRAG_REQUEST]

    def placement_changed(event_type, old, new) -> bool:
        """Only placement-status movement matters: the pass re-derives
        everything else, and its own state-CM writes must not re-enqueue
        it (the CM watch below is name-filtered to the link map)."""
        if event_type != "MODIFIED" or old is None:
            return True
        return (
            ((old.get("status") or {}).get("placement") or {})
            != ((new.get("status") or {}).get("placement") or {})
        )

    ctrl.watch(
        mgr.informer_for(TPU_SLICE_API_VERSION, TPU_SLICE_KIND),
        mapper=map_to_pass, predicate=placement_changed,
    )
    mgr.add_controller(ctrl)
    return ctrl
