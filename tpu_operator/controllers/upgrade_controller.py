"""Upgrade reconciler: drives rolling libtpu upgrades.

Reference: ``controllers/upgrade_controller.go:80-197`` — gated on the
ClusterPolicy's upgradePolicy.autoUpgrade flag (labels stripped when
disabled, :102-120), builds the per-node state from pods + labels, exports
progress metrics, applies the FSM, and re-plans every 2 minutes.
"""

from __future__ import annotations

import logging
from typing import List

from tpu_operator import consts
from tpu_operator.api.clusterpolicy import (
    CLUSTER_POLICY_API_VERSION,
    CLUSTER_POLICY_KIND,
    ClusterPolicy,
)
from tpu_operator.controllers.operator_metrics import get_metrics
from tpu_operator.kube import errors, trace
from tpu_operator.kube.cached import CachedReadClient
from tpu_operator.kube.client import Client
from tpu_operator.kube.controller import Controller, Request, Result
from tpu_operator.upgrade.fsm import (
    IN_PROGRESS,
    ClusterUpgradeStateManager,
    UpgradeState,
)

log = logging.getLogger(__name__)


class UpgradeReconciler:
    def __init__(self, client: Client, namespace: str = consts.DEFAULT_OPERATOR_NAMESPACE):
        self.client = client
        self.namespace = namespace
        self.state_manager = ClusterUpgradeStateManager(client, namespace)
        self.metrics = get_metrics()

    def reconcile(self, req: Request) -> Result:
        obj = self.client.get_or_none(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, req.name)
        if obj is None:
            return Result()
        cp = ClusterPolicy.from_unstructured(obj)
        policy = cp.spec.libtpu.upgrade_policy
        if not policy.auto_upgrade:
            self.state_manager.remove_upgrade_labels()
            # labels are gone: clear any stale progress block too
            self._publish_upgrade_status(req.name, self.state_manager.build_state())
            return Result()

        state = self.state_manager.build_state()
        self.metrics.upgrades_in_progress.set(state.count(*IN_PROGRESS))
        self.metrics.upgrades_done.set(state.count(UpgradeState.DONE))
        self.metrics.upgrades_failed.set(state.count(UpgradeState.FAILED))
        with trace.span("upgrade-fsm", nodes=len(state.nodes)):
            self.state_manager.apply_state(state, policy)
        # apply_state keeps the in-memory state current (every successful
        # transition writes node_state.state), so no re-list is needed
        self._publish_upgrade_status(req.name, state)

        # re-plan on a fixed cadence (reference: plannedRequeueInterval 2 min)
        return Result(requeue_after=consts.UPGRADE_REPLAN_SECONDS)

    def _publish_upgrade_status(self, cp_name: str, state) -> None:
        """Per-node upgrade progress in ClusterPolicy status (the
        reference exposes this via metrics only; kubectl-visible state is
        the natural home)."""
        upgrade = {
            "inProgress": state.count(*IN_PROGRESS),
            "done": state.count(UpgradeState.DONE),
            "failed": state.count(UpgradeState.FAILED),
            "pending": state.count(UpgradeState.UPGRADE_REQUIRED),
            "nodes": {n.name: n.state for n in state.nodes.values() if n.state},
        }
        obj = self.client.get_or_none(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, cp_name)
        if obj is None:
            return
        status = obj.get("status") or {}
        if not upgrade["nodes"]:
            if "upgrade" not in status:
                return
            want = None  # merge-patch null removes the block
        elif status.get("upgrade") == upgrade:
            return
        else:
            want = upgrade
        try:
            # upgrade-key-only status patch: can't conflict with (or
            # clobber) the ClusterPolicy reconciler's conditions writes
            self.client.patch_status(  # tpuop-lint: kinds=tpu.google.com/v1/ClusterPolicy
                CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, cp_name,
                {"status": {"upgrade": want}},
            )
        except errors.ApiError as e:
            log.debug("upgrade status publish skipped: %s", e)


def setup_with_manager(mgr, reconciler: UpgradeReconciler) -> Controller:
    ctrl = Controller("upgrade", reconciler)
    reconciler.client = CachedReadClient(reconciler.client, mgr)

    def map_to_all_cps(_obj) -> List[Request]:
        try:
            cps = reconciler.client.list(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND)
        except errors.ApiError:
            return []
        return [Request(name=cp["metadata"]["name"]) for cp in cps]

    ctrl.watch(mgr.informer_for(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND))

    def driver_pod(event_type, old, new) -> bool:
        labels = new["metadata"].get("labels") or {}
        return labels.get("app.kubernetes.io/component") == "libtpu-installer"

    ctrl.watch(mgr.informer_for("v1", "Pod", reconciler.namespace), mapper=map_to_all_cps, predicate=driver_pod)
    mgr.add_controller(ctrl)
    return ctrl
