"""Autotune reconciler: per-generation sweep election + winner folding.

The operator half of the kernel-autotuning loop (ROADMAP item 5; the
agent half is ``agents/autotune_agent.py``). Each pass:

1. **Elect** — group in-service TPU nodes by generation; for every
   generation whose cached sweep entry is missing, incomplete, or
   recorded under a different libtpu version, hold the election label
   (``consts.AUTOTUNE_ELECTED_LABEL``) on exactly ONE in-service node
   (lexicographically-first for determinism). The autotuner DaemonSet's
   nodeSelector includes the label, so electing a node IS scheduling
   the sweep pod — and clearing it (generation swept, or the elected
   node went out of service) tears the pod down and frees the chips.
   A swept generation holds no elections: a node joining it later is
   never elected and never re-sweeps.

2. **Fold** — parse the per-generation entries in the
   ``tpu-autotune-results`` ConfigMap and (a) tighten the
   ``tpu-perf-floors`` pipeline: measured TPU roofs replace
   ``perf.py``'s scaled guesses for every swept generation
   (``workloads.autotune.merge_winner_floors``; CPU/interpret entries
   publish configs but never floors), patched into the floors ConfigMap
   only when semantically different — the exporter's hot-reload picks
   the tightened floor up on its next probe cycle without a pod
   restart; (b) publish the compact winners blob
   (``winners.json``) that workloads resolve block shapes from via
   ``TPU_AUTOTUNE_JSON``.

Steady state is O(changes): valid entries everywhere -> no elections,
floors/winners semantically unchanged -> zero apiserver writes.
"""

from __future__ import annotations

import json
import logging
from typing import Dict, List, Optional

from tpu_operator import consts, images
from tpu_operator.api.clusterpolicy import (
    CLUSTER_POLICY_API_VERSION,
    CLUSTER_POLICY_KIND,
    ClusterPolicy,
)
from tpu_operator.controllers.operator_metrics import get_metrics
from tpu_operator.kube import errors, trace
from tpu_operator.kube.cached import CachedReadClient
from tpu_operator.kube.client import Client
from tpu_operator.kube.controller import Controller, Request, Result, generation_changed
from tpu_operator.kube.events import EventRecorder
from tpu_operator.kube.objects import ObjectDict
from tpu_operator.nodeinfo import tpu_info
from tpu_operator.workloads.autotune import (
    entry_key,
    entry_valid,
    merge_winner_floors,
    parse_entry,
    winners_blob,
)

log = logging.getLogger(__name__)


def libtpu_version_for(cp: ClusterPolicy) -> str:
    """The toolchain version sweeps must match: the libtpu image tag —
    the same value the autotuner DaemonSet injects as LIBTPU_VERSION, so
    the agent's recorded fingerprint and this converge; a rolling libtpu
    upgrade changes the tag and invalidates every cached sweep."""
    image = images.resolve("libtpu", cp.spec.libtpu)
    return image.rsplit(":", 1)[1] if ":" in image else image


class AutotuneReconciler:
    def __init__(self, client: Client, namespace: str = consts.DEFAULT_OPERATOR_NAMESPACE):
        self.client = client
        self.namespace = namespace
        self.metrics = get_metrics()
        self.recorder = EventRecorder(client, namespace)
        self._elected_events: set = set()  # (gen, node) election dedup
        self._roof_series: set = set()  # generations with a live roof gauge
        self._floors_folded: Dict[str, str] = {}  # gen -> version folded

    # -- reconcile -----------------------------------------------------------

    def reconcile(self, req: Request) -> Result:
        obj = self.client.get_or_none(
            CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, req.name
        )
        if obj is None:
            return Result()
        cp = ClusterPolicy.from_unstructured(obj)
        if not cp.spec.autotuner.is_enabled():
            with trace.span("autotune-elect"):
                self._clear_all_elections()
            # stale-series hygiene on disable: frozen gauges would keep
            # alerting on a sweep that will never happen, and a roof
            # series would export yesterday's measurement forever
            self.metrics.autotune_generations_swept.set(0)
            self.metrics.autotune_generations_pending.set(0)
            self._update_roof_series({})
            return Result()
        desired_version = libtpu_version_for(cp)
        try:
            nodes = self.client.list(
                "v1", "Node", label_selector={consts.TPU_PRESENT_LABEL: "true"}
            )
        except errors.ApiError as e:
            log.warning("autotune: node list failed: %s", e)
            return Result(requeue=True)
        cm = self.client.get_or_none(
            "v1", "ConfigMap", consts.AUTOTUNE_RESULTS_CONFIGMAP, self.namespace
        )
        data = (cm or {}).get("data") or {}
        groups = self._by_generation(nodes)
        cached_gens = {
            k[: -len(".json")]
            for k in data
            if k.endswith(".json") and k != consts.AUTOTUNE_WINNERS_KEY
        }
        entries = {
            gen: entry
            for gen in set(groups) | cached_gens
            if (entry := parse_entry(data.get(entry_key(gen)))) is not None
        }
        with trace.span("autotune-elect"):
            pending, kept = self._elect(
                obj, groups, entries, desired_version,
                claim_chips=max(1, cp.spec.autotuner.chips or 4),
            )
            self._clear_orphan_elections(kept)
        with trace.span("autotune-fold"):
            self._fold(obj, entries, desired_version, cm)
        swept = [g for g in groups if entry_valid(entries.get(g), desired_version)]
        self.metrics.autotune_generations_swept.set(len(swept))
        self.metrics.autotune_generations_pending.set(len(pending))
        if pending:
            # a crashed elected node / a sweep in flight: re-check on a
            # timer (the published entry also lands as a watch event)
            return Result(requeue_after=consts.AUTOTUNE_REPLAN_SECONDS)
        return Result()

    # -- helpers -------------------------------------------------------------

    @staticmethod
    def _labels(node: ObjectDict) -> dict:
        return node["metadata"].get("labels") or {}

    def _by_generation(self, nodes: List[ObjectDict]) -> Dict[str, List[ObjectDict]]:
        groups: Dict[str, List[ObjectDict]] = {}
        for node in nodes:
            info = tpu_info(node)
            if info is None or not info.generation or info.generation == "unknown":
                continue
            groups.setdefault(info.generation, []).append(node)
        return groups

    def _in_service(self, node: ObjectDict) -> bool:
        from tpu_operator.placement.engine import labels_unavailable

        return not labels_unavailable(self._labels(node))

    def _set_election(self, node_name: str, elected: bool) -> None:
        try:
            self.client.patch(
                "v1", "Node", node_name,
                {"metadata": {"labels": {
                    consts.AUTOTUNE_ELECTED_LABEL:
                        consts.AUTOTUNE_ELECTED if elected else None
                }}},
            )
        except errors.NotFound:
            pass  # node left while the pass ran

    def _clear_all_elections(self) -> None:
        """Autotuner disabled: no node may keep holding the election
        label (it schedules a chip-claiming pod)."""
        try:
            nodes = self.client.list(
                "v1", "Node",
                label_selector={consts.AUTOTUNE_ELECTED_LABEL: consts.AUTOTUNE_ELECTED},
            )
        except errors.ApiError:
            return
        for node in nodes:
            self._set_election(node["metadata"]["name"], False)

    def _clear_orphan_elections(self, kept: set) -> None:
        """Clear the election label from any node not designated this
        pass — a node that LEFT its generation grouping mid-sweep (lost
        accelerator labels, de-TPU'd) would otherwise hold the label
        (and its chip-claiming pod) forever, invisible to the
        per-generation convergence."""
        try:
            labelled = self.client.list(
                "v1", "Node",
                label_selector={consts.AUTOTUNE_ELECTED_LABEL: consts.AUTOTUNE_ELECTED},
            )
        except errors.ApiError:
            return
        for node in labelled:
            name = node["metadata"]["name"]
            if name not in kept:
                self._set_election(name, False)

    def _elect(
        self,
        cp_obj: ObjectDict,
        groups: Dict[str, List[ObjectDict]],
        entries: Dict[str, dict],
        desired_version: str,
        claim_chips: int = 4,
    ):
        """Converge the election labels; returns (generations still
        awaiting a sweep, node names whose election is kept)."""
        pending: List[str] = []
        kept: set = set()
        keep: Optional[str]
        for gen, gen_nodes in sorted(groups.items()):
            elected = [
                n for n in gen_nodes
                if self._labels(n).get(consts.AUTOTUNE_ELECTED_LABEL)
                == consts.AUTOTUNE_ELECTED
            ]
            if entry_valid(entries.get(gen), desired_version):
                # swept for this toolchain: a late-joining node is never
                # elected, a lingering election tears its pod down
                for node in elected:
                    self._set_election(node["metadata"]["name"], False)
                continue
            pending.append(gen)

            def schedulable(node) -> bool:
                # the sweep pod claims a FIXED google.com/tpu count
                # (spec.autotuner.chips): a node with fewer chips could
                # never schedule it, so electing it parks the sweep as
                # a Pending pod forever
                info = tpu_info(node)
                return info is not None and info.chips_per_node >= claim_chips

            def rank(node):
                # exact chip match first (exclusive ownership: the whole
                # host is claimed, no co-tenant skews the measurement),
                # then the smallest surplus, then name for determinism
                info = tpu_info(node)
                chips = info.chips_per_node if info else 0
                return (chips != claim_chips, chips, node["metadata"]["name"])

            eligible = sorted(
                (n for n in gen_nodes if self._in_service(n) and schedulable(n)),
                key=rank,
            )
            if not eligible:
                if any(self._in_service(n) for n in gen_nodes):
                    log.warning(
                        "autotune: generation %s has no node with >= %d "
                        "chips; lower spec.autotuner.chips to sweep it",
                        gen, claim_chips,
                    )
                for node in elected:
                    self._set_election(node["metadata"]["name"], False)
                continue
            live = sorted(
                (n for n in elected if self._in_service(n) and schedulable(n)),
                key=rank,
            )
            if live:
                keep = live[0]["metadata"]["name"]
            else:
                keep = eligible[0]["metadata"]["name"]
                self._set_election(keep, True)
                if (gen, keep) not in self._elected_events:
                    self.recorder.event(
                        cp_obj, "Normal", "AutotuneElected",
                        f"elected node {keep} to sweep kernel configs for "
                        f"generation {gen} (libtpu {desired_version})",
                    )
                    self._elected_events.add((gen, keep))
            kept.add(keep)
            for node in elected:
                name = node["metadata"]["name"]
                if name != keep:
                    self._set_election(name, False)
        return pending, kept

    # -- folding --------------------------------------------------------------

    def _fold(
        self,
        cp_obj: ObjectDict,
        entries: Dict[str, dict],
        desired_version: str,
        results_cm: Optional[ObjectDict],
    ) -> None:
        folded = {
            gen: entry for gen, entry in entries.items()
            if entry_valid(entry, desired_version)
        }
        self._fold_floors(cp_obj, folded, desired_version)
        self._publish_winners(entries, results_cm)
        self._update_roof_series(folded)

    def _fold_floors(
        self, cp_obj: ObjectDict, folded: Dict[str, dict], desired_version: str
    ) -> None:
        floors = merge_winner_floors(folded)
        cm = self.client.get_or_none(
            "v1", "ConfigMap", consts.PERF_FLOORS_CONFIGMAP, self.namespace
        )
        if cm is None:
            return  # pre-requisites has not rendered it yet
        current_blob = (cm.get("data") or {}).get(consts.PERF_FLOORS_KEY)
        try:
            current = json.loads(current_blob) if current_blob else {}
        except ValueError:
            current = {}
        if current == floors:
            return  # semantically settled: zero writes
        data = {consts.PERF_FLOORS_KEY: json.dumps(floors, sort_keys=True)}
        for gen, gen_floors in floors.items():
            data[gen] = json.dumps(gen_floors, sort_keys=True)
        self.client.patch(
            "v1", "ConfigMap", consts.PERF_FLOORS_CONFIGMAP, {"data": data},
            self.namespace,
        )
        for gen, entry in folded.items():
            if self._floors_folded.get(gen) != entry.get("libtpu_version"):
                matmul = floors.get(gen, {}).get("matmul_tflops")
                self.recorder.event(
                    cp_obj, "Normal", "AutotuneFloorsTightened",
                    f"generation {gen}: measured sweep roofs replace scaled "
                    f"guesses (matmul floor now {matmul} TFLOP/s, libtpu "
                    f"{entry.get('libtpu_version')})",
                )
                self._floors_folded[gen] = entry.get("libtpu_version", "")

    def _publish_winners(
        self, entries: Dict[str, dict], results_cm: Optional[ObjectDict]
    ) -> None:
        if results_cm is None or not entries:
            return
        blob = winners_blob(entries)
        current_raw = (results_cm.get("data") or {}).get(consts.AUTOTUNE_WINNERS_KEY)
        try:
            current = json.loads(current_raw) if current_raw else None
        except ValueError:
            current = None
        if current == blob:
            return
        self.client.patch(
            "v1", "ConfigMap", consts.AUTOTUNE_RESULTS_CONFIGMAP,
            {"data": {consts.AUTOTUNE_WINNERS_KEY: json.dumps(blob, sort_keys=True)}},
            self.namespace,
        )

    def _update_roof_series(self, folded: Dict[str, dict]) -> None:
        """Per-generation measured-roof gauge, with stale-series hygiene:
        an invalidated (toolchain-bumped) or vanished entry takes its
        series with it rather than exporting yesterday's roof forever."""
        live: set = set()
        for gen, entry in folded.items():
            best = None
            for packed in (entry.get("results", {}).get("matmul") or {}).values():
                rate = ((packed or {}).get("winner") or {}).get("rate")
                if isinstance(rate, (int, float)) and (best is None or rate > best):
                    best = float(rate)
            if best is not None and entry.get("platform") == "tpu":
                self.metrics.autotune_matmul_roof.labels(gen).set(round(best, 1))
                live.add(gen)
        for gone in self._roof_series - live:
            try:
                self.metrics.autotune_matmul_roof.remove(gone)
            except KeyError:
                pass
        self._roof_series = live


def setup_with_manager(mgr, reconciler: AutotuneReconciler) -> Controller:
    ctrl = Controller(
        "autotune", reconciler, coalesce_window=consts.NODE_EVENT_COALESCE_SECONDS
    )
    reconciler.client = CachedReadClient(reconciler.client, mgr)

    def map_to_all_cps(_obj) -> List[Request]:
        try:
            cps = reconciler.client.list(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND)
        except errors.ApiError:
            return []
        return [Request(name=cp["metadata"]["name"]) for cp in cps]

    ctrl.watch(
        mgr.informer_for(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND),
        predicate=generation_changed,
    )

    def autotune_labels_changed(event_type, old, new) -> bool:
        """Node events matter when election inputs changed: TPU identity,
        election state, or in-service state — our own election writes
        re-deliver, but the reconcile is idempotent and coalesced."""
        keys = (
            consts.TPU_PRESENT_LABEL,
            consts.AUTOTUNE_ELECTED_LABEL,
            consts.TPU_HEALTH_LABEL,
            consts.REPAIR_STATE_LABEL,
            consts.TPU_PERF_LABEL,
            consts.GKE_TPU_ACCELERATOR_LABEL,
            consts.TFD_ACCELERATOR_TYPE_LABEL,
        )
        if event_type != "MODIFIED" or old is None:
            return any(k in (new["metadata"].get("labels") or {}) for k in keys)
        old_labels = old["metadata"].get("labels") or {}
        new_labels = new["metadata"].get("labels") or {}
        return any(old_labels.get(k) != new_labels.get(k) for k in keys)

    ctrl.watch(
        mgr.informer_for("v1", "Node"),
        mapper=map_to_all_cps, predicate=autotune_labels_changed,
    )

    def results_changed(event_type, old, new) -> bool:
        """Only the results ConfigMap's DATA matters (a published sweep
        entry); our own winners.json write echoes here, but the next
        pass settles with zero writes."""
        if new["metadata"].get("name") != consts.AUTOTUNE_RESULTS_CONFIGMAP:
            return False
        if event_type != "MODIFIED" or old is None:
            return True
        return (old.get("data") or {}) != (new.get("data") or {})

    ctrl.watch(
        mgr.informer_for("v1", "ConfigMap"),
        mapper=map_to_all_cps, predicate=results_changed,
    )
    mgr.add_controller(ctrl)
    return ctrl
