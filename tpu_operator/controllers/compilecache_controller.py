"""Compile-cache reconciler: prewarm election + entry invalidation.

The operator half of the fleet compile cache (ROADMAP item 4; the store
vocabulary is ``workloads/compilecache.py``, the elected-node half is
``agents/compilecache_agent.py``). Each pass:

1. **Invalidate** — entries in the ``tpu-compile-cache`` ConfigMap
   recorded under a different libtpu version than the ClusterPolicy's
   current image tag are DELETED (one key-scoped patch per affected
   generation, exactly like ``tpu-autotune-results`` invalidation): a
   rolling libtpu upgrade makes every cached executable unloadable, and
   a deleted entry reads as a miss everywhere — the serving controller
   re-requests, the elected agent re-compiles ONCE per generation.

2. **Elect** — for every generation with unsatisfied prewarm demand
   (prewarm requests the serving controller published whose content
   address has no valid record), hold the election label
   (``consts.COMPILE_CACHE_ELECTED_LABEL``) on exactly one in-service
   node (the autotune election idiom: the prewarm DaemonSet's
   nodeSelector includes the label, so electing IS scheduling — and the
   pod, with the chips it claims, exists only for the compile window).
   Satisfied demand holds no election; orphaned elections are cleared.

3. **Export** — ``tpu_operator_compile_seconds{serving,generation}``
   from the valid cached records and the per-generation
   ``tpu_operator_compile_cache_{hits,misses}_total`` counters from the
   store's in-process accounting, with stale-series hygiene (O005): a
   record that leaves the cache takes its series with it.

Steady state is O(changes): every request satisfied -> no elections, no
stale entries -> zero apiserver writes.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Set, Tuple

from tpu_operator import consts
from tpu_operator.api.clusterpolicy import (
    CLUSTER_POLICY_API_VERSION,
    CLUSTER_POLICY_KIND,
    ClusterPolicy,
)
from tpu_operator.controllers.autotune_controller import libtpu_version_for
from tpu_operator.controllers.operator_metrics import get_metrics
from tpu_operator.kube import errors, trace
from tpu_operator.kube.cached import CachedReadClient
from tpu_operator.kube.client import Client
from tpu_operator.kube.controller import Controller, Request, Result, generation_changed
from tpu_operator.kube.events import EventRecorder
from tpu_operator.kube.objects import ObjectDict
from tpu_operator.nodeinfo import tpu_info
from tpu_operator.workloads import compilecache
from tpu_operator.workloads.compilecache import (
    cache_record,
    entry_key,
    entry_valid,
    parse_entry,
    parse_requests,
)

log = logging.getLogger(__name__)


class CompileCacheReconciler:
    def __init__(self, client: Client, namespace: str = consts.DEFAULT_OPERATOR_NAMESPACE):
        self.client = client
        self.namespace = namespace
        self.metrics = get_metrics()
        self.recorder = EventRecorder(client, namespace)
        self._elected_events: set = set()  # (gen, node) election dedup
        self._compile_series: Set[Tuple[str, str]] = set()  # (serving, gen)
        self._hit_series: Set[str] = set()
        self._miss_series: Set[str] = set()

    # -- reconcile -----------------------------------------------------------

    def reconcile(self, req: Request) -> Result:
        obj = self.client.get_or_none(
            CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, req.name
        )
        if obj is None:
            return Result()
        cp = ClusterPolicy.from_unstructured(obj)
        if not cp.spec.compile_cache.is_enabled():
            with trace.span("compilecache-elect"):
                self._clear_all_elections()
            # stale-series hygiene on disable: a frozen compile gauge
            # would export yesterday's cost forever
            self._update_series({})
            self._update_counter_series()
            return Result()
        desired_version = libtpu_version_for(cp)
        try:
            nodes = self.client.list(
                "v1", "Node", label_selector={consts.TPU_PRESENT_LABEL: "true"}
            )
        except errors.ApiError as e:
            log.warning("compilecache: node list failed: %s", e)
            return Result(requeue=True)
        cm = self.client.get_or_none(
            "v1", "ConfigMap", consts.COMPILE_CACHE_CONFIGMAP, self.namespace
        )
        data = (cm or {}).get("data") or {}
        groups = self._by_generation(nodes)
        entries = compilecache.cached_entries(data)
        with trace.span("compilecache-invalidate"):
            entries = self._invalidate_stale(obj, entries, desired_version)
        requests = parse_requests(data.get(consts.COMPILE_PREWARM_REQUEST_KEY))
        demand = self._unsatisfied(requests, entries, desired_version)
        with trace.span("compilecache-elect"):
            pending, kept = self._elect(obj, groups, demand, desired_version)
            self._clear_orphan_elections(kept)
        self._update_series(
            {g: e for g, e in entries.items() if entry_valid(e, desired_version)}
        )
        self._update_counter_series()
        if pending:
            # a crashed elected node / a compile in flight: re-check on
            # a timer (the published record also lands as a watch event)
            return Result(requeue_after=consts.COMPILE_CACHE_REPLAN_SECONDS)
        return Result()

    # -- helpers -------------------------------------------------------------

    @staticmethod
    def _labels(node: ObjectDict) -> dict:
        return node["metadata"].get("labels") or {}

    def _by_generation(self, nodes: List[ObjectDict]) -> Dict[str, List[ObjectDict]]:
        groups: Dict[str, List[ObjectDict]] = {}
        for node in nodes:
            info = tpu_info(node)
            if info is None or not info.generation or info.generation == "unknown":
                continue
            groups.setdefault(info.generation, []).append(node)
        return groups

    def _in_service(self, node: ObjectDict) -> bool:
        from tpu_operator.placement.engine import labels_unavailable

        return not labels_unavailable(self._labels(node))

    def _invalidate_stale(
        self, cp_obj: ObjectDict, entries: Dict[str, dict], desired_version: str
    ) -> Dict[str, dict]:
        """Delete entries recorded under a different libtpu version —
        ONE key-scoped patch per affected generation, so a rolling
        upgrade costs exactly one invalidation (and, downstream, one
        re-compile) per generation; valid entries are untouched."""
        live: Dict[str, dict] = {}
        for gen, entry in entries.items():
            if entry.get("libtpu_version") == desired_version:
                live[gen] = entry
                continue
            try:
                self.client.patch(
                    "v1", "ConfigMap", consts.COMPILE_CACHE_CONFIGMAP,
                    {"data": {entry_key(gen): None}}, self.namespace,
                )
            except errors.ApiError as e:
                log.warning("compilecache: invalidation of %s failed: %s", gen, e)
                continue
            self.recorder.event(
                cp_obj, "Normal", "CompileCacheInvalidated",
                f"generation {gen}: cached executables recorded under libtpu "
                f"{entry.get('libtpu_version')} invalidated (current "
                f"{desired_version})",
            )
        return live

    @staticmethod
    def _unsatisfied(
        requests: Dict[str, dict], entries: Dict[str, dict], desired_version: str
    ) -> Dict[str, List[dict]]:
        """Prewarm requests whose content address has no valid record,
        grouped by generation — the election demand."""
        out: Dict[str, List[dict]] = {}
        for _rid, request in sorted(requests.items()):
            gen = request.get("generation") or ""
            if not gen:
                continue
            record = cache_record(
                entries.get(gen), request.get("topology", ""),
                request.get("model", ""), desired_version,
            )
            if record is None:
                out.setdefault(gen, []).append(request)
        return out

    def _set_election(self, node_name: str, elected: bool) -> None:
        try:
            self.client.patch(
                "v1", "Node", node_name,
                {"metadata": {"labels": {
                    consts.COMPILE_CACHE_ELECTED_LABEL:
                        consts.COMPILE_CACHE_ELECTED if elected else None
                }}},
            )
        except errors.NotFound:
            pass  # node left while the pass ran

    def _clear_all_elections(self) -> None:
        try:
            nodes = self.client.list(
                "v1", "Node",
                label_selector={
                    consts.COMPILE_CACHE_ELECTED_LABEL: consts.COMPILE_CACHE_ELECTED
                },
            )
        except errors.ApiError:
            return
        for node in nodes:
            self._set_election(node["metadata"]["name"], False)

    def _clear_orphan_elections(self, kept: set) -> None:
        """Clear the election label from any node not designated this
        pass — a node that left its generation grouping mid-compile
        would otherwise hold the label (and its chip-claiming prewarm
        pod) forever."""
        try:
            labelled = self.client.list(
                "v1", "Node",
                label_selector={
                    consts.COMPILE_CACHE_ELECTED_LABEL: consts.COMPILE_CACHE_ELECTED
                },
            )
        except errors.ApiError:
            return
        for node in labelled:
            name = node["metadata"]["name"]
            if name not in kept:
                self._set_election(name, False)

    def _elect(
        self,
        cp_obj: ObjectDict,
        groups: Dict[str, List[ObjectDict]],
        demand: Dict[str, List[dict]],
        desired_version: str,
    ):
        """Converge election labels over generations with unsatisfied
        prewarm demand; returns (pending generations, kept node names).
        The autotune idiom: keep a live election if one exists, else
        elect the lexicographically-first in-service node."""
        pending: List[str] = []
        kept: set = set()
        for gen in sorted(demand):
            gen_nodes = groups.get(gen) or []
            elected = [
                n for n in gen_nodes
                if self._labels(n).get(consts.COMPILE_CACHE_ELECTED_LABEL)
                == consts.COMPILE_CACHE_ELECTED
            ]
            eligible = sorted(
                (n for n in gen_nodes if self._in_service(n)),
                key=lambda n: n["metadata"]["name"],
            )
            if not eligible:
                # demand with no node to serve it: requests outlive the
                # generation's nodes (drained pool) — hold no election
                for node in elected:
                    self._set_election(node["metadata"]["name"], False)
                continue
            pending.append(gen)
            live = [n for n in elected if self._in_service(n)]
            if live:
                keep = sorted(
                    live, key=lambda n: n["metadata"]["name"]
                )[0]["metadata"]["name"]
            else:
                keep = eligible[0]["metadata"]["name"]
                self._set_election(keep, True)
                if (gen, keep) not in self._elected_events:
                    self.recorder.event(
                        cp_obj, "Normal", "CompilePrewarmElected",
                        f"elected node {keep} to prewarm {len(demand[gen])} "
                        f"compile(s) for generation {gen} (libtpu "
                        f"{desired_version})",
                    )
                    self._elected_events.add((gen, keep))
            kept.add(keep)
            for node in elected:
                name = node["metadata"]["name"]
                if name != keep:
                    self._set_election(name, False)
        # elections held by generations whose demand vanished are
        # cleared by _clear_orphan_elections (they are not in `kept`)
        return pending, kept

    # -- metric export --------------------------------------------------------

    def _update_series(self, valid: Dict[str, dict]) -> None:
        """``compile_seconds{serving,generation}`` from the valid cached
        records, with stale-series hygiene: an invalidated or vanished
        record takes its series with it (O005)."""
        live: Set[Tuple[str, str]] = set()
        for gen, entry in valid.items():
            for record in (entry.get("records") or {}).values():
                if not isinstance(record, dict):
                    continue
                seconds = record.get("seconds")
                if not isinstance(seconds, (int, float)):
                    continue
                serving = record.get("serving") or record.get("source") or "prewarm"
                self.metrics.compile_seconds.labels(serving, gen).set(float(seconds))
                live.add((serving, gen))
        for gone in self._compile_series - live:
            try:
                self.metrics.compile_seconds.remove(*gone)
            except KeyError:
                pass
        self._compile_series = live

    def _update_counter_series(self) -> None:
        """Per-generation hit/miss counters from the store's in-process
        accounting (the sim runs workers in-process; on a real cluster
        the workers' own endpoints carry these), retiring series for
        generations whose counters reset away (O005)."""
        stats = compilecache.stats()
        live_hits: Set[str] = set()
        for gen, count in stats.get("hits", {}).items():
            self.metrics.compile_cache_hits.labels(gen).set(count)
            live_hits.add(gen)
        for gone in self._hit_series - live_hits:
            try:
                self.metrics.compile_cache_hits.remove(gone)
            except KeyError:
                pass
        self._hit_series = live_hits
        live_misses: Set[str] = set()
        for gen, count in stats.get("misses", {}).items():
            self.metrics.compile_cache_misses.labels(gen).set(count)
            live_misses.add(gen)
        for gone in self._miss_series - live_misses:
            try:
                self.metrics.compile_cache_misses.remove(gone)
            except KeyError:
                pass
        self._miss_series = live_misses


def setup_with_manager(mgr, reconciler: CompileCacheReconciler) -> Controller:
    ctrl = Controller(
        "compilecache", reconciler,
        coalesce_window=consts.NODE_EVENT_COALESCE_SECONDS,
    )
    reconciler.client = CachedReadClient(reconciler.client, mgr)

    def map_to_all_cps(_obj) -> List[Request]:
        try:
            cps = reconciler.client.list(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND)
        except errors.ApiError:
            return []
        return [Request(name=cp["metadata"]["name"]) for cp in cps]

    ctrl.watch(
        mgr.informer_for(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND),
        predicate=generation_changed,
    )

    def election_labels_changed(event_type, old, new) -> bool:
        """Node events matter when election inputs changed: TPU
        identity, election state, or in-service state."""
        keys = (
            consts.TPU_PRESENT_LABEL,
            consts.COMPILE_CACHE_ELECTED_LABEL,
            consts.TPU_HEALTH_LABEL,
            consts.REPAIR_STATE_LABEL,
            consts.TPU_PERF_LABEL,
            consts.GKE_TPU_ACCELERATOR_LABEL,
            consts.TFD_ACCELERATOR_TYPE_LABEL,
        )
        if event_type != "MODIFIED" or old is None:
            return any(k in (new["metadata"].get("labels") or {}) for k in keys)
        old_labels = old["metadata"].get("labels") or {}
        new_labels = new["metadata"].get("labels") or {}
        return any(old_labels.get(k) != new_labels.get(k) for k in keys)

    ctrl.watch(
        mgr.informer_for("v1", "Node"),
        mapper=map_to_all_cps, predicate=election_labels_changed,
    )

    def cache_changed(event_type, old, new) -> bool:
        """Only the cache ConfigMap's DATA matters (a published record
        or a new prewarm request); our own invalidation writes echo
        here, but the next pass settles with zero writes."""
        if new["metadata"].get("name") != consts.COMPILE_CACHE_CONFIGMAP:
            return False
        if event_type != "MODIFIED" or old is None:
            return True
        return (old.get("data") or {}) != (new.get("data") or {})

    ctrl.watch(
        mgr.informer_for("v1", "ConfigMap"),
        mapper=map_to_all_cps, predicate=cache_changed,
    )
    mgr.add_controller(ctrl)
    return ctrl
