"""Predictive health: per-host risk scoring + proactive migration.

Every other fault path in this operator is reactive — the job shrinks
*after* the host dies, the serving router excludes a replica *after*
the fabric verdict lands — and a hard failure costs a TPUJob up to a
full checkpoint cadence of lost steps. But the PR 7/8 telemetry
*precedes* hard failures: a dying host's straggler ratio climbs in the
gang artifact, its ICI edges decay into the link-health map, the
exporter's perf verdict flips, the repair FSM's retry counter grows.
This scorer — run from the health reconciler's pass like the fleet and
fabric aggregators, so it rides the same cadence and informer caches —
folds those precursors into one per-host score in [0, 1]:

    score = max(clamped sum of live signals, previous * RISK_DECAY)

    straggler   RISK_WEIGHT_STRAGGLER * (ratio - 1.0), capped at 1.0,
                only while the artifact is FRESH (the named slowest
                host still carries the publishing gang's placement
                label — the fabric analyzer's staleness convention:
                a re-placed gang's old artifact scores as NO signal)
    fabric      RISK_WEIGHT_FABRIC_EDGE per recorded degraded ICI edge
                touching the host
    grey        RISK_WEIGHT_GREY while the exporter's perf verdict is
                degraded
    repair      RISK_WEIGHT_REPAIR per recorded repair retry, capped

and publishes it to the ``tpu-node-risk`` ConfigMap (scores + budget
ledger + predicted-vs-realized migration log — restart-safe, and the
must-gather ``risk.txt`` evidence trail) and the
``tpu_operator_node_risk{node}`` gauge (retired when the host leaves
the fleet or its risk decays away).

Over ``RISK_THRESHOLD`` the scorer moves work off the host while it is
still alive, through the owners' own safe paths (the defrag
controller's execution discipline, re-used move for move):

- a TPUJob gang migrates behind the PR 13 checkpoint barrier: this
  controller writes its one owned progress-CM key
  (``consts.JOB_RISK_MIGRATE_REQUEST``) and the job controller drives
  checkpoint -> teardown -> re-place -> resume, so a *predicted*
  failure loses ZERO steps;
- a TPUServing replica takes the drain-then-re-place path — and only
  while another placed, in-service sibling keeps the serving routable;
- gangs owned by neither are NEVER touched.

False-positive governance: each planned migration charges the host's
persisted :class:`RetryBudget` (``attempts`` + ``nextAttemptAt`` in the
state CM — K005), at most one migration per pass fleet-wide, and never
a second while one is still settling. A host whose risk subsides
without dying settles ``realized=false`` and RELEASES its budget — a
noisy scorer decays back to quiet instead of thrashing a gang. Every
read that gates an action fails CLOSED (K003): an unreadable state CM
or input list aborts the pass, it never resets the ledger.
"""

from __future__ import annotations

import json
import logging
import random
import time
from typing import Dict, List, Optional, Tuple

from tpu_operator import consts
from tpu_operator.api.tpujob import TPU_JOB_API_VERSION, TPU_JOB_KIND, JobPhase
from tpu_operator.api.tpuserving import TPU_SERVING_KIND
from tpu_operator.api.tpuslice import TPU_SLICE_API_VERSION, TPU_SLICE_KIND
from tpu_operator.controllers.operator_metrics import get_metrics
from tpu_operator.kube import errors
from tpu_operator.kube.backoff import RetryBudget, read_attempts
from tpu_operator.kube.client import Client
from tpu_operator.kube.events import EventRecorder
from tpu_operator.kube.objects import ObjectDict, new_object
from tpu_operator.placement.engine import PlacementPhase, labels_unavailable

log = logging.getLogger(__name__)

RISK_MANAGER = "tpu-risk-scorer"

# the slice manager stamps this on every gang ConfigMap it owns (kept
# value-only to avoid a module cycle, same as fleet_telemetry)
_MANAGED_BY = {"app.kubernetes.io/managed-by": "tpu-slice-manager"}


class RiskScorer:
    def __init__(self, client: Client, namespace: str = consts.DEFAULT_OPERATOR_NAMESPACE,
                 recorder: Optional[EventRecorder] = None):
        self.client = client
        self.namespace = namespace
        self.recorder = recorder or EventRecorder(client, namespace, component=RISK_MANAGER)
        self.metrics = get_metrics()
        self._now = time.time  # tests pin the clock
        self.rng = random.Random()  # jitter only; decisions never ride it
        from tpu_operator.kube import racecheck

        self._series_lock = racecheck.lock("RiskScorer._series_lock")
        self._risk_series: set = set()

    @staticmethod
    def _float(raw) -> float:
        try:
            return float(raw or 0.0)
        except (TypeError, ValueError):
            return 0.0

    # -- one scoring pass ----------------------------------------------------

    def sync(self) -> dict:
        """Read the precursor telemetry, fold the per-host scores,
        publish series + state, and move work off hosts over the
        threshold. Returns a summary dict (tests and the risk
        must-gather artifact read it)."""
        summary: dict = {
            "scores": {}, "signals": {}, "stale": [],
            "migrated": [], "migrations": [],
        }
        try:
            nodes = self.client.list("v1", "Node")
            cms = self.client.list(
                "v1", "ConfigMap", self.namespace, label_selector=_MANAGED_BY
            )
            slices = self.client.list(TPU_SLICE_API_VERSION, TPU_SLICE_KIND)
        except errors.ApiError as e:
            # inputs unreadable: fail closed — no rescore, no action
            log.debug("risk: pass inputs unreadable: %s", e)
            return summary
        link_map = self._link_map()
        if link_map is None:
            return summary
        node_by_name = {n["metadata"]["name"]: n for n in nodes}
        slices_by_name = {s["metadata"]["name"]: s for s in slices}

        state = self._read_state()
        if state is None:
            # ledger unreadable: fail closed (acting against an empty
            # ledger would hand back every host's migration budget)
            return summary
        now = self._now()
        signals = self._collect_signals(cms, node_by_name, link_map, summary)
        changed = self._rescore(state, signals, node_by_name)
        scores = {
            host: self._float(entry.get("score"))
            for host, entry in (state.get("hosts") or {}).items()
        }
        self._publish_series(scores)
        summary["scores"] = scores
        summary["signals"] = signals
        in_flight, settled = self._settle(state, scores, node_by_name, now)
        changed = settled or changed
        if not in_flight:
            # never overlap planned migrations: the fleet absorbs one
            # checkpoint/drain at a time, and settlement is what tells
            # predicted from false alarm
            changed = self._act(
                state, scores, slices_by_name, node_by_name, now, summary
            ) or changed
        if changed:
            # a quiet pass writes nothing (the fabric analyzer's rule)
            self._write_state(state)
        summary["migrations"] = list(state.get("migrations") or [])
        return summary

    def _link_map(self) -> Optional[dict]:
        """The fabric analyzer's recorded per-edge verdicts. A missing
        map means no cuts; a failed READ returns None and aborts the
        pass (degraded edges both raise scores and gate where a
        re-placed gang may land — scoring without them fails open)."""
        from tpu_operator.controllers.fabric_telemetry import parse_link_map

        try:
            cm = self.client.get_or_none(
                "v1", "ConfigMap", consts.LINK_HEALTH_CONFIGMAP, self.namespace
            )
        except errors.ApiError as e:
            log.warning("risk: link-health map unreadable, pass aborted: %s", e)
            return None
        return parse_link_map(cm)

    # -- signals -------------------------------------------------------------

    def _collect_signals(
        self, cms: List[dict], node_by_name: Dict[str, dict],
        link_map: Dict[str, Dict[str, dict]], summary: dict,
    ) -> Dict[str, Dict[str, float]]:
        """host -> {signal: contribution} from the live telemetry.
        Absent, malformed, and STALE artifacts contribute nothing — a
        missing precursor is "no signal", never "crash" or "guess"."""
        signals: Dict[str, Dict[str, float]] = {}

        def add(host: str, key: str, value: float) -> None:
            if value <= 0.0 or host not in node_by_name:
                return
            parts = signals.setdefault(host, {})
            parts[key] = round(parts.get(key, 0.0) + value, 4)

        for cm in cms:
            raw = (cm["metadata"].get("annotations") or {}).get(
                consts.GANG_TELEMETRY_ANNOTATION
            )
            if not raw:
                continue
            try:
                artifact = json.loads(raw)
            except ValueError:
                continue  # malformed: no signal (fleet telemetry warns)
            if not isinstance(artifact, dict):
                continue
            slice_name = cm["metadata"]["name"]
            if slice_name.endswith("-gang"):
                slice_name = slice_name[: -len("-gang")]
            slowest = str(artifact.get("slowest_host") or "")
            ratio = self._float(artifact.get("straggler_ratio"))
            if not slowest or ratio <= consts.GANG_STRAGGLER_RATIO:
                continue
            if self._straggler_stale(slice_name, slowest, node_by_name):
                summary["stale"].append(slice_name)
                continue
            add(
                slowest, "straggler",
                min(1.0, consts.RISK_WEIGHT_STRAGGLER * (ratio - 1.0)),
            )
        for pool_edges in link_map.values():
            for edge in pool_edges:
                a, _, b = edge.partition("|")
                add(a, "fabric", consts.RISK_WEIGHT_FABRIC_EDGE)
                add(b, "fabric", consts.RISK_WEIGHT_FABRIC_EDGE)
        for name, node in node_by_name.items():
            meta = node["metadata"]
            labels = meta.get("labels") or {}
            if labels.get(consts.TPU_PERF_LABEL) == consts.PERF_DEGRADED:
                add(name, "grey", consts.RISK_WEIGHT_GREY)
            retries = read_attempts(
                meta.get("annotations"), consts.REPAIR_RETRIES_ANNOTATION
            )
            if retries:
                add(name, "repair", min(
                    consts.RISK_WEIGHT_REPAIR_CAP,
                    consts.RISK_WEIGHT_REPAIR * retries,
                ))
        return signals

    @staticmethod
    def _straggler_stale(
        slice_name: str, slowest: str, node_by_name: Dict[str, dict]
    ) -> bool:
        """The fabric analyzer's staleness convention applied to the
        gang artifact: after a re-place the gang ConfigMap (same name)
        still carries the old rollup, and scoring a host from it would
        convict a node the gang no longer runs on. Fresh iff the named
        slowest host exists AND still carries the publishing gang's
        placement label (gang CM names are ``<owner>-gang`` with the
        slice manager's ``tpu-slice-`` prefix ahead of the owner)."""
        node = node_by_name.get(slowest)
        if node is None:
            return True
        owner = slice_name
        if owner.startswith("tpu-slice-"):
            owner = owner[len("tpu-slice-"):]
        labels = node["metadata"].get("labels") or {}
        return labels.get(consts.PLACEMENT_LABEL) != owner

    # -- scoring -------------------------------------------------------------

    def _rescore(
        self, state: dict, signals: Dict[str, Dict[str, float]],
        node_by_name: Dict[str, dict],
    ) -> bool:
        """Fold this pass's signals into the persisted ledger:
        score = max(instant, previous * RISK_DECAY). A host below the
        floor (or gone from the fleet) leaves the ledger — and a host
        whose risk subsides below the threshold without dying releases
        its migration budget (the false-alarm decay contract)."""
        hosts: Dict[str, dict] = state.setdefault("hosts", {})
        changed = False
        for host in sorted(set(signals) | set(hosts)):
            if host not in node_by_name:
                if hosts.pop(host, None) is not None:
                    changed = True
                continue
            parts = signals.get(host) or {}
            instant = min(1.0, round(sum(parts.values()), 4))
            entry = hosts.get(host)
            prev = self._float((entry or {}).get("score"))
            score = round(max(instant, prev * consts.RISK_DECAY), 4)
            if score < consts.RISK_SCORE_FLOOR:
                if hosts.pop(host, None) is not None:
                    changed = True
                continue
            if entry is None:
                entry = hosts[host] = {}
                changed = True
            if entry.get("score") != score or entry.get("signals") != parts:
                entry["score"] = score
                entry["signals"] = parts
                changed = True
            if score < consts.RISK_THRESHOLD and (
                entry.get("attempts") or entry.get("nextAttemptAt")
            ):
                entry.pop("attempts", None)
                entry.pop("nextAttemptAt", None)
                changed = True
        return changed

    def _publish_series(self, scores: Dict[str, float]) -> None:
        """tpu_operator_node_risk{node}, retired with the ledger entry:
        a frozen last value would keep a dead or healed host reading
        risky forever (same discipline as the gang series)."""
        for host, score in sorted(scores.items()):
            self.metrics.node_risk.labels(host).set(score)
        with self._series_lock:
            gone = self._risk_series - set(scores)
            self._risk_series = set(scores)
        for host in gone:
            try:
                self.metrics.node_risk.remove(host)
            except KeyError:
                pass

    # -- persisted state -----------------------------------------------------

    def _read_state(self) -> Optional[dict]:
        """Scores + budget ledger + migration log. A transient READ
        failure returns None and the caller aborts the pass — a flaky
        apiserver must fail CLOSED, not reset the ledger and hand back
        every host's migration budget. Only a genuinely malformed blob
        (which a retry can never fix) starts fresh."""
        try:
            cm = self.client.get_or_none(
                "v1", "ConfigMap", consts.RISK_STATE_CONFIGMAP, self.namespace
            )
        except errors.ApiError as e:
            log.warning("risk: state CM unreadable, pass aborted: %s", e)
            return None
        raw = ((cm or {}).get("data") or {}).get(consts.RISK_STATE_KEY)
        if not raw:
            return {"hosts": {}, "migrations": []}
        try:
            state = json.loads(raw)
        except ValueError:
            state = None  # malformed: start fresh, never crash the pass
        if not isinstance(state, dict) or not isinstance(state.get("hosts"), dict):
            return {"hosts": {}, "migrations": []}
        state.setdefault("migrations", [])
        if not isinstance(state["migrations"], list):
            state["migrations"] = []
        return state

    def _write_state(self, state: dict) -> None:
        state["migrations"] = state.get("migrations", [])[-consts.RISK_MIGRATIONS_LIMIT:]
        data = {consts.RISK_STATE_KEY: json.dumps(state, sort_keys=True)}
        try:
            self.client.patch(
                "v1", "ConfigMap", consts.RISK_STATE_CONFIGMAP,
                {"data": data}, self.namespace,
            )
        except errors.NotFound:
            try:
                self.client.create(  # tpuop-lint: kinds=v1/ConfigMap
                    new_object(
                        "v1", "ConfigMap", consts.RISK_STATE_CONFIGMAP,
                        self.namespace, data=data,
                    )
                )
            except (errors.AlreadyExists, errors.ApiError) as e:
                log.debug("risk state write raced/failed: %s", e)
        except errors.ApiError as e:
            log.debug("risk state write failed: %s", e)

    # -- settlement ----------------------------------------------------------

    def _settle(
        self, state: dict, scores: Dict[str, float],
        node_by_name: Dict[str, dict], now: float,
    ) -> Tuple[bool, bool]:
        """Book predicted-vs-realized for every outstanding planned
        migration. Realized TRUE when the host did die (gone, or out of
        service); FALSE when its risk subsided past the grace window —
        which also releases the host's budget — or the prediction
        expired unresolved. Returns (in_flight, state_changed)."""
        changed = False
        in_flight = False
        for m in state.get("migrations", []):
            if m.get("settled"):
                continue
            host = str(m.get("host") or "")
            node = node_by_name.get(host)
            labels = ((node or {}).get("metadata") or {}).get("labels") or {}
            age = now - self._float(m.get("requested_at"))
            if node is None or labels_unavailable(labels):
                m["settled"] = True
                m["realized"] = True
                changed = True
                if node is not None:
                    self.recorder.event(
                        node, "Normal", "RiskRealized",
                        f"predicted failure of {host} realized "
                        f"{round(age, 1)}s after the planned migration of "
                        f"{m.get('owner_kind')}/{m.get('owner_name')} "
                        f"(score {m.get('score')})",
                    )
                continue
            subsided = scores.get(host, 0.0) < consts.RISK_THRESHOLD
            if subsided and age >= consts.RISK_SETTLE_GRACE_SECONDS:
                m["settled"] = True
                m["realized"] = False
                changed = True
                entry = (state.get("hosts") or {}).get(host)
                if entry:
                    entry.pop("attempts", None)
                    entry.pop("nextAttemptAt", None)
                self.recorder.event(
                    node, "Normal", "RiskFalseAlarm",
                    f"{host} outlived its risk signal (score "
                    f"{scores.get(host, 0.0)}); migration budget released",
                )
                continue
            if age > consts.RISK_SETTLE_TIMEOUT_SECONDS:
                m["settled"] = True
                m["realized"] = False
                changed = True
                continue
            in_flight = True
        return in_flight, changed

    # -- acting --------------------------------------------------------------

    def _act(
        self, state: dict, scores: Dict[str, float], slices_by_name: dict,
        node_by_name: Dict[str, dict], now: float, summary: dict,
    ) -> bool:
        """Move work off the riskiest eligible host — AT MOST ONE
        planned migration per pass, through the owner's own safe path,
        charged against the host's persisted budget."""
        risky = sorted(
            (h for h, s in scores.items() if s >= consts.RISK_THRESHOLD),
            key=lambda h: (-scores[h], h),
        )
        for host in risky:
            placed = self._slice_on(host, slices_by_name)
            if placed is None:
                continue
            slice_name, obj = placed
            owner = self._owner_of(obj)
            if owner is None:
                continue  # gangs owned by neither kind are never touched
            kind, owner_name = owner
            if kind == TPU_JOB_KIND:
                if not self._job_migratable(owner_name):
                    continue
            elif kind == TPU_SERVING_KIND:
                if not self._serving_sibling_placed(
                    slice_name, owner_name, slices_by_name
                ):
                    continue  # never drain the last routable replica
            else:
                continue
            entry = state.setdefault("hosts", {}).setdefault(host, {})
            if not self._charge_attempt(entry, now):
                continue
            # the charge is persisted whether or not the request lands:
            # the nextAttemptAt gate is exactly what keeps a failing
            # patch from being retried at watch-storm speed
            token = ""
            if kind == TPU_JOB_KIND:
                token = f"risk-{int(now)}-{int(state.get('serial', 0))}"
                ok = self._request_job_migration(owner_name, token)
                if ok:
                    state["serial"] = int(state.get("serial", 0)) + 1
            else:
                status = (obj.get("status") or {}).get("placement") or {}
                ok = self._drain_serving_replica(list(status.get("nodes") or []))
            if ok:
                state.setdefault("migrations", []).append({
                    "host": host,
                    "slice": slice_name,
                    "owner_kind": kind,
                    "owner_name": owner_name,
                    "token": token,
                    "score": scores[host],
                    "signals": dict(
                        ((state.get("hosts") or {}).get(host) or {}).get("signals")
                        or {}
                    ),
                    "requested_at": now,
                    "settled": False,
                    "realized": None,
                })
                self.metrics.risk_migrations.inc()
                summary["migrated"].append(host)
                self.recorder.event(
                    obj, "Normal",
                    "RiskMigrating" if kind == TPU_JOB_KIND else "RiskDraining",
                    f"host {host} risk {scores[host]} >= "
                    f"{consts.RISK_THRESHOLD}: moving {kind}/{owner_name} "
                    f"gang {slice_name} off it while it is still alive",
                )
            else:
                log.debug("risk: migration request for %s off %s failed",
                          owner_name, host)
            return True  # charged (and possibly moved): state is dirty
        return False

    def _charge_attempt(self, entry: dict, now: float) -> bool:
        """One unit of the host's migration budget. The persisted
        nextAttemptAt gate (floored at the base delay so two alarms in
        one precursor window can never both fire) is checked BEFORE the
        charge and re-armed with it — a watch-event storm or a
        crash-looping operator cannot burn the budget faster than the
        backoff schedule (K005)."""
        budget = RetryBudget(
            consts.RISK_MIGRATION_RETRY_LIMIT,
            consts.RISK_MIGRATION_BASE_SECONDS,
            consts.RISK_MIGRATION_MAX_SECONDS,
        )
        if now < self._float(entry.get("nextAttemptAt")):
            return False
        attempts = int(entry.get("attempts") or 0)
        if budget.exhausted(attempts):
            return False
        entry["attempts"] = attempts + 1
        delay = max(
            budget.base_delay_seconds, budget.delay(attempts + 1, self.rng)
        )
        entry["nextAttemptAt"] = round(now + delay, 3)
        return True

    # -- owner-safe execution (the defrag controller's discipline) -----------

    def _slice_on(
        self, host: str, slices_by_name: dict
    ) -> Optional[Tuple[str, ObjectDict]]:
        for name in sorted(slices_by_name):
            obj = slices_by_name[name]
            status = (obj.get("status") or {}).get("placement") or {}
            if status.get("phase") != PlacementPhase.SCHEDULED:
                continue
            if host in (status.get("nodes") or []):
                return name, obj
        return None

    @staticmethod
    def _owner_of(obj: ObjectDict) -> Optional[Tuple[str, str]]:
        for ref in obj["metadata"].get("ownerReferences") or []:
            if ref.get("kind") in (TPU_JOB_KIND, TPU_SERVING_KIND) and ref.get("name"):
                return (str(ref["kind"]), str(ref["name"]))
        return None

    def _job_migratable(self, job_name: str) -> bool:
        """Somebody must answer the checkpoint barrier: the job is
        Running and its progress CM is live."""
        job = self.client.get_or_none(TPU_JOB_API_VERSION, TPU_JOB_KIND, job_name)
        if job is None:
            return False
        block = (job.get("status") or {}).get("job") or {}
        if block.get("phase") != JobPhase.RUNNING:
            return False
        progress = self.client.get_or_none(
            "v1", "ConfigMap", job_name + consts.JOB_PROGRESS_SUFFIX, self.namespace
        )
        return progress is not None

    def _serving_sibling_placed(
        self, name: str, serving: str, slices_by_name: dict
    ) -> bool:
        """True when another replica of the same serving is placed AND
        in service — draining a gang whose only sibling is
        placed-but-dying would leave the serving unroutable for the
        whole re-place window (the defrag controller's exact rule)."""
        for other_name, other in slices_by_name.items():
            if other_name == name:
                continue
            if self._owner_of(other) != (TPU_SERVING_KIND, serving):
                continue
            status = (other.get("status") or {}).get("placement") or {}
            if status.get("phase") != PlacementPhase.SCHEDULED:
                continue
            members_healthy = True
            for node_name in status.get("nodes") or []:
                node = self.client.get_or_none("v1", "Node", node_name)
                if node is None or labels_unavailable(
                    node["metadata"].get("labels") or {}
                ):
                    members_healthy = False
                    break
            if members_healthy:
                return True
        return False

    def _request_job_migration(self, job_name: str, token: str) -> bool:
        """The checkpoint-barrier path: bump our one owned key in the
        job's progress CM; the job controller drives checkpoint ->
        teardown -> re-place -> resume and records the token it honored
        in status.job.riskHandled (redelivery never migrates twice)."""
        try:
            self.client.patch(
                "v1", "ConfigMap", job_name + consts.JOB_PROGRESS_SUFFIX,
                {"data": {consts.JOB_RISK_MIGRATE_REQUEST: token}}, self.namespace,
            )
        except (errors.NotFound, errors.ApiError) as e:
            log.debug("risk: job %s migration request failed: %s", job_name, e)
            return False
        return True

    def _drain_serving_replica(self, gang_nodes: List[str]) -> bool:
        """The drain-then-re-place path: clear the replica gang's
        assignment labels; the serving router zeroes its weight the
        same pass and the engine re-seats it — away from the risky
        host, because the engine's risk-aware scorer reads the same
        state CM this controller writes. A sweep that cleared NOTHING
        must not book a migration or spend budget."""
        from tpu_operator.controllers.placement_controller import (
            clear_assignment_labels,
        )

        return clear_assignment_labels(self.client, gang_nodes) > 0


def read_node_risk(client: Client, namespace: str) -> Optional[Dict[str, float]]:
    """The published per-host scores, for ADVISORY consumers (the
    placement engine's risk-aware scoring hook). Missing or malformed
    state reads as no scores; a failed READ returns None so callers
    that also gate destructive work can abort — the placement
    controller itself treats None as "place without risk bias", which
    only ever costs optimality, never safety."""
    try:
        cm = client.get_or_none(
            "v1", "ConfigMap", consts.RISK_STATE_CONFIGMAP, namespace
        )
    except errors.ApiError:
        return None
    raw = ((cm or {}).get("data") or {}).get(consts.RISK_STATE_KEY)
    if not raw:
        return {}
    try:
        state = json.loads(raw)
    except ValueError:
        return {}
    hosts = state.get("hosts") if isinstance(state, dict) else None
    if not isinstance(hosts, dict):
        return {}
    out: Dict[str, float] = {}
    for host, entry in hosts.items():
        try:
            score = float((entry or {}).get("score") or 0.0)
        except (TypeError, ValueError):
            continue
        if score > 0.0:
            out[str(host)] = score
    return out
