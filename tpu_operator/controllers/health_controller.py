"""Health remediation controller: bounded node auto-repair.

Consumes the health agent's ``tpu.google.com/tpu.health`` verdicts and
drives a per-node repair FSM — the GKE node-auto-repair analog the NVIDIA
reference stops short of (DCGM feeds metrics, nothing acts on them):

    (degraded) → cordon-required → eviction-required →
    reinstall-required → revalidate-required → uncordon-required → (healed)
                                        └─ retry budget exhausted → quarantined

Like the upgrade FSM (``tpu_operator/upgrade/fsm.py``, whose cordon/
eviction machinery this reuses by subclassing), every decision is
recomputed from cluster state each pass: the FSM lives entirely in node
labels/annotations and survives operator restarts. Evictions go through
pods/eviction so PodDisruptionBudgets are honored; a blocked eviction
parks the node until the remediation timeout quarantines it. Each repair
attempt burns one unit of the retry budget — a node that keeps flapping
lands in the ``quarantined`` terminal label (cordoned, operator hands
off to a human) instead of cycling forever.

Slice awareness: a degraded or in-repair host stamps
``tpu.google.com/slice.health=degraded`` on every peer of its
slice-manager gang (same accelerator node pool), so multi-host workloads
fail fast at scheduling instead of hanging on a sick gang member.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional

from tpu_operator import consts
from tpu_operator.api.clusterpolicy import (
    CLUSTER_POLICY_API_VERSION,
    CLUSTER_POLICY_KIND,
    ClusterPolicy,
    HealthMonitorSpec,
)
from tpu_operator.controllers.operator_metrics import get_metrics
from tpu_operator.kube import trace
from tpu_operator.kube import errors
from tpu_operator.kube.backoff import RetryBudget, read_attempts
from tpu_operator.kube.cached import CachedReadClient
from tpu_operator.kube.client import Client
from tpu_operator.kube.controller import Controller, Request, Result
from tpu_operator.kube.objects import ObjectDict, metadata_patch
from tpu_operator.upgrade.fsm import (
    DRIVER_POD_COMPONENT,
    DRIVER_POD_COMPONENT_LABEL,
    ClusterUpgradeStateManager,
)

log = logging.getLogger(__name__)


class RepairState:
    CORDON_REQUIRED = "cordon-required"
    EVICTION_REQUIRED = "eviction-required"
    REINSTALL_REQUIRED = "reinstall-required"
    REVALIDATE_REQUIRED = "revalidate-required"
    UNCORDON_REQUIRED = "uncordon-required"
    QUARANTINED = "quarantined"


IN_REPAIR = {
    RepairState.CORDON_REQUIRED,
    RepairState.EVICTION_REQUIRED,
    RepairState.REINSTALL_REQUIRED,
    RepairState.REVALIDATE_REQUIRED,
    RepairState.UNCORDON_REQUIRED,
}


def _labels(node: ObjectDict) -> dict:
    return node["metadata"].get("labels") or {}


def _annotations(node: ObjectDict) -> dict:
    return node["metadata"].get("annotations") or {}


class NodeRepairManager(ClusterUpgradeStateManager):
    """The repair FSM. Subclasses the upgrade state manager for its
    cordon/eviction/pod machinery (the reference pattern: one drain
    implementation shared by every node-walking controller); the state
    space and labels are its own."""

    # -- state bookkeeping ---------------------------------------------------

    def repair_nodes(self) -> List[ObjectDict]:
        """Nodes the FSM cares about: carrying a health verdict, a
        repair label (a node whose agent died mid-repair must still
        finish its walk), or the exporter's perf label (grey failures
        enter the same FSM). Existence-selector lists instead of a full
        node scan: cached reads ride the informer's label-key index, so
        the cost is O(nodes with a verdict), not O(cluster)."""
        seen: Dict[str, ObjectDict] = {}
        for selector in (
            consts.TPU_HEALTH_LABEL,
            consts.REPAIR_STATE_LABEL,
            consts.TPU_PERF_LABEL,
        ):
            for node in self.client.list("v1", "Node", label_selector=selector):
                seen[node["metadata"]["name"]] = node
        return sorted(seen.values(), key=lambda n: n["metadata"]["name"])

    def _set_repair_state(
        self,
        node: ObjectDict,
        new_state: str,
        retries: Optional[int] = None,
        next_attempt_at: Optional[float] = None,
    ) -> bool:
        """One atomic node write: state label + transition timestamp (+
        the retry counter when an attempt begins). Sent as a labels/
        annotations merge patch — no read-modify-write cycle, and no rv to
        Conflict on, so concurrent kubelet/agent writers of other fields
        can never bounce a repair transition."""
        name = node["metadata"]["name"]
        labels = _labels(node)
        annotation_delta: dict = {}
        label_delta: dict = {}
        if retries is not None:
            annotation_delta[consts.REPAIR_RETRIES_ANNOTATION] = str(retries)
        if next_attempt_at is not None:
            # rides the same atomic patch as the counter: the charge and
            # its backoff gate can never be observed apart
            annotation_delta[consts.REPAIR_NEXT_ATTEMPT_ANNOTATION] = str(
                round(next_attempt_at, 3)
            )
        if new_state:
            if labels.get(consts.REPAIR_STATE_LABEL) == new_state and retries is None:
                return True
            label_delta[consts.REPAIR_STATE_LABEL] = new_state
            # timestamp the transition so per-state timeouts survive
            # operator restarts (all FSM state lives in the cluster)
            annotation_delta[consts.REPAIR_STATE_SINCE_ANNOTATION] = str(int(time.time()))
        else:
            if consts.REPAIR_STATE_LABEL not in labels:
                return True
            label_delta[consts.REPAIR_STATE_LABEL] = None
            annotation_delta[consts.REPAIR_STATE_SINCE_ANNOTATION] = None
            # the trigger record goes with the state: the next episode
            # stamps its own reason
            annotation_delta[consts.REPAIR_REASON_ANNOTATION] = None
        body = metadata_patch(labels=label_delta, annotations=annotation_delta)
        try:
            live = self.client.patch("v1", "Node", name, body)
        except errors.NotFound:
            return False  # node gone; re-planned next pass
        node["metadata"] = live["metadata"]
        log.info("repair: node %s -> %s", node["metadata"]["name"], new_state or "(cleared)")
        event_type = "Warning" if new_state == RepairState.QUARANTINED else "Normal"
        self.recorder.event(
            live, event_type, "TPUNodeRepair",
            f"node {node['metadata']['name']}: {new_state or 'repair complete'}",
        )
        return True

    def _repair_expired(self, node: ObjectDict, timeout_seconds: int) -> bool:
        if not timeout_seconds:
            return False
        since = _annotations(node).get(consts.REPAIR_STATE_SINCE_ANNOTATION)
        if not since:
            return False
        try:
            return time.time() - float(since) > timeout_seconds
        except ValueError:
            return False

    def _retries(self, node: ObjectDict) -> int:
        return read_attempts(_annotations(node), consts.REPAIR_RETRIES_ANNOTATION)

    def _in_grace_period(self, node: ObjectDict, remediation) -> bool:
        """A node is left alone until its degradation has persisted past
        the grace period: a freshly joined node looks degraded while
        libtpu installs and the plugin registers, and cordoning it
        mid-provision would kill the install (and burn retry budget on
        every node join). The agent stamps health.since on transitions;
        when the label was set by something that did not (e.g. a manual
        kubectl label), the controller stamps it itself and waits."""
        grace = max(0, remediation.grace_period_seconds)
        if not grace:
            return False
        since = _annotations(node).get(consts.TPU_HEALTH_SINCE_ANNOTATION)
        if since is None:
            stamp = str(int(time.time()))
            try:
                live = self.client.patch(
                    "v1", "Node", node["metadata"]["name"],
                    {"metadata": {"annotations": {consts.TPU_HEALTH_SINCE_ANNOTATION: stamp}}},
                )
                node["metadata"] = live["metadata"]
            except errors.NotFound:
                pass
            return True
        try:
            return time.time() - float(since) < grace
        except ValueError:
            return False

    def _begin_or_quarantine(
        self, node: ObjectDict, remediation, reason: str = ""
    ) -> str:
        """Start one repair attempt against the retry budget, or park the
        node in the quarantined terminal state when the budget is spent.
        Used both on fresh degradation and when a revalidation times out
        (re-entering directly keeps the node under FSM ownership — the
        cordon is never orphaned on a node with no repair state).
        ``reason`` records which signal triggered the attempt ("health"
        or "perf") so revalidation knows what must clear; re-entries
        keep the recorded reason. The budget decision rides the shared
        bounded-retry helper (``kube/backoff.py``) — the same policy
        shape the TPUJob FSM quarantines through."""
        retries = self._retries(node)
        budget = RetryBudget(retry_limit=remediation.retry_limit)
        if budget.exhausted(retries):
            self._set_repair_state(node, RepairState.QUARANTINED)
            self._cordon(node, True)
            return RepairState.QUARANTINED
        # persisted backoff gate: a watch-event storm (or a crash-looping
        # operator) redelivers the same degradation many times per second;
        # without this stamp every delivery would burn one attempt and a
        # burst of duplicates could quarantine a node the schedule says
        # still has budget. Attempts arriving early leave the node in its
        # current state — the next pass after the stamp re-enters.
        next_at_raw = _annotations(node).get(consts.REPAIR_NEXT_ATTEMPT_ANNOTATION)
        if next_at_raw is not None:
            try:
                if time.time() < float(next_at_raw):
                    return _labels(node).get(consts.REPAIR_STATE_LABEL, "")
            except ValueError:
                pass  # mangled stamp degrades to "no gate", never a crash
        if reason and _annotations(node).get(consts.REPAIR_REASON_ANNOTATION) != reason:
            try:
                live = self.client.patch(
                    "v1", "Node", node["metadata"]["name"],
                    {"metadata": {"annotations": {consts.REPAIR_REASON_ANNOTATION: reason}}},
                )
                node["metadata"] = live["metadata"]
            except errors.NotFound:
                return ""
        if self._set_repair_state(
            node,
            RepairState.CORDON_REQUIRED,
            retries=retries + 1,
            next_attempt_at=time.time() + budget.delay(retries + 1),
        ):
            get_metrics().remediations_total.inc()
        return RepairState.CORDON_REQUIRED

    @staticmethod
    def _grey_degraded(labels: dict) -> bool:
        """The exporter's sustained perf-floor breach: the grey-failure
        signal that enters the same repair FSM as a failed health
        probe."""
        return labels.get(consts.TPU_PERF_LABEL) == consts.PERF_DEGRADED

    def _revalidated(self, node: ObjectDict) -> bool:
        """Whether the repair attempt healed what put the node in: a
        health-triggered repair needs the agent's explicit healthy
        verdict back (absence is indeterminate, not health); a
        perf-triggered one needs the exporter's breach label cleared —
        and neither passes while the OTHER signal reads degraded, so a
        chip that is now fast but failing probes (or vice versa) never
        uncordons."""
        labels = _labels(node)
        health = labels.get(consts.TPU_HEALTH_LABEL, "")
        if health == consts.HEALTH_DEGRADED or self._grey_degraded(labels):
            return False
        reason = _annotations(node).get(
            consts.REPAIR_REASON_ANNOTATION, consts.REPAIR_REASON_HEALTH
        )
        if reason == consts.REPAIR_REASON_PERF:
            return True  # perf label cleared, health not degraded
        return health == consts.HEALTH_HEALTHY

    # -- one idempotent pass -------------------------------------------------

    def apply_state(self, spec: HealthMonitorSpec) -> Dict[str, str]:  # type: ignore[override]
        """Advance every node by at most one repair step; returns the
        post-pass {node: repair state} map (health verdicts included for
        degraded nodes not yet in repair)."""
        remediation = spec.remediation
        # the pod index loads LAZILY, on the first node that actually
        # needs eviction/reinstall handling: the walker itself is already
        # O(sick nodes) via the label-indexed selector lists, and a quiet
        # pass (every pass, at steady state) must not pay an O(pods)
        # cluster scan — at 16k nodes that scan was the last O(cluster)
        # term in the health path
        pods_index: Dict[str, List[ObjectDict]] = {}
        pods_loaded = [False]

        def pods_on(node_name: str) -> List[ObjectDict]:
            if not pods_loaded[0]:
                pods_loaded[0] = True
                for pod in self.client.list("v1", "Pod"):
                    at = pod.get("spec", {}).get("nodeName")
                    if at and pod.get("status", {}).get("phase") not in ("Succeeded", "Failed"):
                        pods_index.setdefault(at, []).append(pod)
            return pods_index.get(node_name, [])

        states: Dict[str, str] = {}
        nodes = self.repair_nodes()
        for node in nodes:
            name = node["metadata"]["name"]
            state = _labels(node).get(consts.REPAIR_STATE_LABEL, "")
            health = _labels(node).get(consts.TPU_HEALTH_LABEL, "")

            if state == RepairState.QUARANTINED:
                # terminal: stays cordoned until a human intervenes
                self._cordon(node, True)
                states[name] = state
                continue

            if not state:
                if health == consts.HEALTH_DEGRADED:
                    if self._in_grace_period(node, remediation):
                        states[name] = health  # provisioning/flap grace
                    else:
                        states[name] = self._begin_or_quarantine(
                            node, remediation, reason=consts.REPAIR_REASON_HEALTH
                        )
                elif self._grey_degraded(_labels(node)):
                    # grey failure: the exporter only labels after N
                    # consecutive probe samples below floor, and a
                    # provisioning node has no successful probes to
                    # breach — the signal is pre-debounced, so the
                    # provisioning grace period does not apply
                    states[name] = self._begin_or_quarantine(
                        node, remediation, reason=consts.REPAIR_REASON_PERF
                    ) or consts.HEALTH_DEGRADED
                elif health:
                    states[name] = health
                continue

            if state == RepairState.CORDON_REQUIRED:
                self._cordon(node, True)
                self._set_repair_state(node, RepairState.EVICTION_REQUIRED)
                states[name] = RepairState.EVICTION_REQUIRED

            elif state == RepairState.EVICTION_REQUIRED:
                targets = [
                    p
                    for p in pods_on(name)
                    if not self._is_daemonset_pod(p) and self._consumes_tpu(p)
                ]
                blocked = self._evict_pods(targets, force=remediation.force)
                if not blocked:
                    # entry action for reinstall: kill the node's driver
                    # pods NOW so any Running driver pod seen later is the
                    # DaemonSet's fresh replacement (fresh libtpu install)
                    self._delete_driver_pods(pods_on(name))
                    self._set_repair_state(node, RepairState.REINSTALL_REQUIRED)
                    states[name] = RepairState.REINSTALL_REQUIRED
                elif self._repair_expired(node, remediation.timeout_seconds):
                    log.error("repair: node %s eviction blocked past timeout", name)
                    self._set_repair_state(node, RepairState.QUARANTINED)
                    states[name] = RepairState.QUARANTINED
                else:
                    states[name] = state

            elif state == RepairState.REINSTALL_REQUIRED:
                if self._fresh_driver_pod_running(pods_on(name)):
                    self._set_repair_state(node, RepairState.REVALIDATE_REQUIRED)
                    states[name] = RepairState.REVALIDATE_REQUIRED
                elif self._repair_expired(node, remediation.timeout_seconds):
                    # the DaemonSet never brought a driver pod back (e.g.
                    # libtpu operand broken/disabled): burn a retry rather
                    # than parking here unbounded
                    log.warning("repair: node %s driver pod never returned", name)
                    states[name] = self._begin_or_quarantine(node, remediation)
                else:
                    states[name] = state

            elif state == RepairState.REVALIDATE_REQUIRED:
                if self._revalidated(node):
                    self._set_repair_state(node, RepairState.UNCORDON_REQUIRED)
                    states[name] = RepairState.UNCORDON_REQUIRED
                elif self._repair_expired(node, remediation.timeout_seconds):
                    # the attempt failed to heal: re-enter directly
                    # against the retry budget (never drop to no-state
                    # while cordoned — a heal landing in that gap would
                    # leave the cordon orphaned forever)
                    log.warning("repair: node %s did not revalidate in time", name)
                    states[name] = self._begin_or_quarantine(node, remediation)
                else:
                    states[name] = state

            elif state == RepairState.UNCORDON_REQUIRED:
                self._cordon(node, False)
                self._set_repair_state(node, "")
                self.recorder.event(
                    node, "Normal", "TPUNodeRemediated",
                    f"node {name}: repair complete, uncordoned",
                )
                states[name] = ""

            else:
                log.warning("repair: node %s carries unknown state %r", name, state)
                states[name] = state

        self._sync_slice_health(nodes)
        return states

    def _delete_driver_pods(self, node_pods) -> None:
        for pod in node_pods:
            labels = pod["metadata"].get("labels") or {}
            if labels.get(DRIVER_POD_COMPONENT_LABEL) != DRIVER_POD_COMPONENT:
                continue
            md = pod["metadata"]
            # label match alone is spoofable: only the DaemonSet's own
            # pods are ours to bounce (a user pod wearing the component
            # label must never be collateral)
            if not any(
                ref.get("kind") == "DaemonSet"
                for ref in md.get("ownerReferences", [])
            ):
                continue
            try:
                self.client.delete("v1", "Pod", md["name"], md.get("namespace"))
            except errors.NotFound:
                pass

    def _fresh_driver_pod_running(self, node_pods) -> bool:
        """A Running, non-terminating driver pod — the DaemonSet's
        replacement after the entry-action delete, i.e. a fresh libtpu
        install pass."""
        for pod in node_pods:
            labels = pod["metadata"].get("labels") or {}
            if labels.get(DRIVER_POD_COMPONENT_LABEL) != DRIVER_POD_COMPONENT:
                continue
            if pod["metadata"].get("deletionTimestamp"):
                continue
            if pod.get("status", {}).get("phase") == "Running":
                return True
        return False

    # -- slice gang awareness ------------------------------------------------

    def _sync_slice_health(self, nodes: List[ObjectDict]) -> None:
        """Mark every member of a gang whose host is degraded/in-repair
        with the slice-health label; clear it when the gang is whole
        again. Gangs are keyed the way the slice manager pools nodes:
        the GKE node pool."""
        pools: Dict[str, List[ObjectDict]] = {}
        # selector list instead of a full node scan: the cached read rides
        # the informer's (tpu.present=true) label-pair index
        for node in self.client.list(
            "v1", "Node", label_selector={consts.TPU_PRESENT_LABEL: "true"}
        ):
            pool = _labels(node).get(consts.GKE_NODEPOOL_LABEL)
            if pool:
                pools.setdefault(pool, []).append(node)
        sick = set()
        for node in nodes:
            labels = _labels(node)
            if (
                labels.get(consts.TPU_HEALTH_LABEL) == consts.HEALTH_DEGRADED
                or labels.get(consts.REPAIR_STATE_LABEL)
                or self._grey_degraded(labels)
            ):
                pool = labels.get(consts.GKE_NODEPOOL_LABEL)
                if pool:
                    sick.add(pool)
        for pool, members in pools.items():
            # single-host pools have no gang to poison — but a pool that
            # SHRANK to one member must still clear a stale label
            want = (
                consts.HEALTH_DEGRADED if pool in sick and len(members) >= 2 else None
            )
            for member in members:
                labels = _labels(member)
                if want is None:
                    if consts.TPU_SLICE_HEALTH_LABEL not in labels:
                        continue
                else:
                    if labels.get(consts.TPU_SLICE_HEALTH_LABEL) == want:
                        continue
                try:
                    self.client.patch(
                        "v1", "Node", member["metadata"]["name"],
                        {"metadata": {"labels": {consts.TPU_SLICE_HEALTH_LABEL: want}}},
                    )
                except errors.NotFound:
                    pass  # member deleted mid-pass; next pass re-pools

    # -- monitoring-only mode ------------------------------------------------

    def observe_state(self) -> Dict[str, str]:
        """Remediation off, monitoring on: report health verdicts and
        keep the slice-gang labels honest WITHOUT driving any repair —
        observability (gauges, status.health, fail-fast gang labels)
        must not die with auto-repair."""
        states: Dict[str, str] = {}
        nodes = self.repair_nodes()
        for node in nodes:
            labels = _labels(node)
            health = labels.get(consts.TPU_HEALTH_LABEL, "")
            if health:
                states[node["metadata"]["name"]] = health
            elif self._grey_degraded(labels):
                # a grey failure counts as degraded in monitoring-only
                # mode too — the gauges and slice fail-fast labels must
                # not go blind with remediation off
                states[node["metadata"]["name"]] = consts.HEALTH_DEGRADED
        self._sync_slice_health(nodes)
        return states

    # -- cleanup -------------------------------------------------------------

    def remove_repair_labels(self, keep_slice_labels: bool = False) -> bool:
        """Remediation disabled: strip repair state and uncordon nodes we
        were mid-walk on. Quarantined nodes keep their cordon (a human
        opted them out of scheduling; disabling auto-repair must not
        silently re-admit a sick node) but lose the label so re-enabling
        starts clean. Returns True when cleanup fully converged (a
        Conflict leaves work behind and the caller should requeue —
        nothing else retriggers a reconcile for a node whose labels no
        longer change)."""
        clean = True
        for node in self.client.list("v1", "Node"):
            labels = node["metadata"].get("labels") or {}
            annotations = node["metadata"].get("annotations") or {}
            state = labels.get(consts.REPAIR_STATE_LABEL)
            slice_label = not keep_slice_labels and consts.TPU_SLICE_HEALTH_LABEL in labels
            retries = consts.REPAIR_RETRIES_ANNOTATION in annotations
            reason = consts.REPAIR_REASON_ANNOTATION in annotations
            next_at = consts.REPAIR_NEXT_ATTEMPT_ANNOTATION in annotations
            if not state and not slice_label and not retries and not reason and not next_at:
                continue
            label_delta: dict = {}
            if state:
                label_delta[consts.REPAIR_STATE_LABEL] = None
            if not keep_slice_labels and consts.TPU_SLICE_HEALTH_LABEL in labels:
                label_delta[consts.TPU_SLICE_HEALTH_LABEL] = None
            annotation_delta: dict = {}
            if consts.REPAIR_STATE_SINCE_ANNOTATION in annotations:
                annotation_delta[consts.REPAIR_STATE_SINCE_ANNOTATION] = None
            if reason:
                annotation_delta[consts.REPAIR_REASON_ANNOTATION] = None
            # the retry budget goes too: "re-enabling starts clean" — a
            # stale count would quarantine the node's first new fault
            if retries:
                annotation_delta[consts.REPAIR_RETRIES_ANNOTATION] = None
            if consts.REPAIR_NEXT_ATTEMPT_ANNOTATION in annotations:
                annotation_delta[consts.REPAIR_NEXT_ATTEMPT_ANNOTATION] = None
            try:
                self.client.patch(
                    "v1", "Node", node["metadata"]["name"],
                    metadata_patch(labels=label_delta, annotations=annotation_delta),
                )
            except errors.NotFound:
                continue
            except errors.ApiError:
                clean = False
                continue
            if state in IN_REPAIR:
                self._cordon(node, False)
        return clean


class HealthReconciler:
    def __init__(self, client: Client, namespace: str = consts.DEFAULT_OPERATOR_NAMESPACE):
        self.client = client
        self.namespace = namespace
        self.repair_manager = NodeRepairManager(client, namespace)
        self.metrics = get_metrics()
        from tpu_operator.controllers.fabric_telemetry import FabricTelemetryAggregator
        from tpu_operator.controllers.fleet_telemetry import FleetTelemetryAggregator
        from tpu_operator.controllers.risk import RiskScorer

        self.fleet_telemetry = FleetTelemetryAggregator(client, namespace)
        self.fabric_telemetry = FabricTelemetryAggregator(client, namespace)
        self.risk_scorer = RiskScorer(client, namespace)

    def _sync_fleet_telemetry(self) -> None:
        """Fleet data-plane rollups ride the health cadence: gang
        step-time/straggler series from the published gang artifacts,
        deliverable-TFLOP/s and grey-failure counts from node labels —
        and the fabric analyzer's link series + blame pass over the
        published fabric matrices. Never fatal to the repair pass —
        observability must not block remediation."""
        # setup_with_manager swaps self.client for the CachedReadClient
        # after construction: re-point the aggregators so their per-pass
        # ConfigMap/Node lists ride the informer caches, not the wire
        # (the fabric analyzer's writes — blame label, link map — pass
        # through the cache client to the wire; its blame decisions are
        # re-derived every pass, so cached read staleness is harmless)
        self.fleet_telemetry.client = self.client
        self.fabric_telemetry.client = self.client
        self.risk_scorer.client = self.client
        try:
            with trace.span("fleet-telemetry"):
                self.fleet_telemetry.sync()
        except Exception as e:  # noqa: BLE001
            log.warning("fleet telemetry sync failed: %s", e)
        try:
            with trace.span("fabric-telemetry"):
                self.fabric_telemetry.sync()
        except Exception as e:  # noqa: BLE001
            log.warning("fabric telemetry sync failed: %s", e)
        # predictive health rides the same cadence, AFTER the fabric
        # pass so this pass's blame (perf labels, link-map edges) is
        # already folded into the scores it acts on
        try:
            with trace.span("risk-scorer"):
                self.risk_scorer.sync()
        except Exception as e:  # noqa: BLE001
            log.warning("risk scorer sync failed: %s", e)

    def reconcile(self, req: Request) -> Result:
        obj = self.client.get_or_none(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, req.name)
        if obj is None:
            return Result()
        cp = ClusterPolicy.from_unstructured(obj)
        spec = cp.spec.health_monitor
        interval = float(spec.interval or consts.HEALTH_REPLAN_SECONDS)
        self._sync_fleet_telemetry()
        if not spec.is_enabled():
            clean = self.repair_manager.remove_repair_labels()
            self._publish_health_status(req.name, {})
            # nothing is tracked while disabled: stale gauge values would
            # keep alerts firing forever
            self.metrics.unhealthy_nodes.set(0)
            self.metrics.quarantined_nodes.set(0)
            # a conflicted cleanup must retry: nothing else retriggers a
            # reconcile for a node whose labels stop changing
            return Result() if clean else Result(requeue_after=interval)

        if not spec.remediation.enable:
            # monitoring-only: repair unwinds, but observability (gauges,
            # status.health, slice fail-fast labels) stays live
            clean = self.repair_manager.remove_repair_labels(keep_slice_labels=True)
            states = self.repair_manager.observe_state()
            degraded = [n for n, s in states.items() if s == consts.HEALTH_DEGRADED]
            self.metrics.unhealthy_nodes.set(len(degraded))
            self.metrics.quarantined_nodes.set(0)
            self._publish_health_status(req.name, states)
            return Result(requeue_after=interval)

        with trace.span("repair-fsm"):
            states = self.repair_manager.apply_state(spec)
        degraded = [n for n, s in states.items() if s == consts.HEALTH_DEGRADED]
        quarantined = [n for n, s in states.items() if s == RepairState.QUARANTINED]
        in_repair = [n for n, s in states.items() if s in IN_REPAIR]
        self.metrics.unhealthy_nodes.set(len(degraded) + len(in_repair) + len(quarantined))
        self.metrics.quarantined_nodes.set(len(quarantined))
        self._publish_health_status(req.name, states)
        # replan on the agent's own cadence: repair progress depends on
        # re-probes landing, not just cluster events
        return Result(requeue_after=interval)

    def _publish_health_status(self, cp_name: str, states: Dict[str, str]) -> None:
        """Per-node repair progress in ClusterPolicy status (same shape
        as the upgrade reconciler's block)."""
        interesting = {n: s for n, s in states.items() if s and s != consts.HEALTH_HEALTHY}
        health = {
            "degraded": sum(1 for s in states.values() if s == consts.HEALTH_DEGRADED),
            "remediating": sum(1 for s in states.values() if s in IN_REPAIR),
            "quarantined": sum(1 for s in states.values() if s == RepairState.QUARANTINED),
            "nodes": interesting,
        }
        obj = self.client.get_or_none(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, cp_name)
        if obj is None:
            return
        status = obj.get("status") or {}
        if not interesting:
            if "health" not in status:
                return
            want = None  # merge-patch null removes the block
        elif status.get("health") == health:
            return
        else:
            want = health
        try:
            # a health-key-only status patch: the ClusterPolicy reconciler's
            # concurrent conditions/state patch can neither conflict with
            # this write nor be clobbered by it
            self.client.patch_status(  # tpuop-lint: kinds=tpu.google.com/v1/ClusterPolicy
                CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, cp_name,
                {"status": {"health": want}},
            )
        except errors.ApiError as e:
            log.debug("health status publish skipped: %s", e)


def setup_with_manager(mgr, reconciler: HealthReconciler) -> Controller:
    ctrl = Controller(
        "health", reconciler, coalesce_window=consts.NODE_EVENT_COALESCE_SECONDS
    )
    reconciler.client = CachedReadClient(reconciler.client, mgr)

    def map_to_all_cps(_obj) -> List[Request]:
        try:
            cps = reconciler.client.list(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND)
        except errors.ApiError:
            return []
        return [Request(name=cp["metadata"]["name"]) for cp in cps]

    ctrl.watch(mgr.informer_for(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND))

    def health_labels_changed(event_type, old, new) -> bool:
        keys = (
            consts.TPU_HEALTH_LABEL,
            consts.REPAIR_STATE_LABEL,
            consts.TPU_PERF_LABEL,
        )
        if event_type != "MODIFIED" or old is None:
            return any(k in (new["metadata"].get("labels") or {}) for k in keys)
        old_labels = old["metadata"].get("labels") or {}
        new_labels = new["metadata"].get("labels") or {}
        return any(old_labels.get(k) != new_labels.get(k) for k in keys)

    ctrl.watch(mgr.informer_for("v1", "Node"), mapper=map_to_all_cps, predicate=health_labels_changed)
    mgr.add_controller(ctrl)
    return ctrl
