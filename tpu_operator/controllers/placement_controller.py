"""Placement reconciler: applies the engine's plan to the cluster.

The queue is global (admission order is priority-then-FIFO across ALL
TPUSlices), so every watch event maps to one synthetic request and each
reconcile replans the whole queue from cluster state — the same
level-triggered, recompute-everything shape as the health and upgrade
walkers. Idempotent: the assignment labels on nodes are the source of
truth, so a crash between label writes and status writes converges on
the next pass instead of double-booking.

Wire traffic per pass: one cached TPUSlice list, one cached Node list,
one labels-only merge patch per node whose assignment changed, and one
key-scoped status patch per slice whose placement block changed —
O(changes), not O(cluster).
"""

from __future__ import annotations

import logging
from typing import List, Optional

from tpu_operator import consts
from tpu_operator.api.tpuslice import TPU_SLICE_API_VERSION, TPU_SLICE_KIND
from tpu_operator.controllers.operator_metrics import get_metrics
from tpu_operator.kube import errors, trace
from tpu_operator.kube.cached import CachedReadClient
from tpu_operator.kube.client import Client
from tpu_operator.kube.controller import Controller, Request, Result
from tpu_operator.kube.events import EventRecorder
from tpu_operator.kube.objects import ObjectDict
from tpu_operator.placement.engine import PLACEMENT_MANAGER, Plan, PlacementEngine

log = logging.getLogger(__name__)

# the whole queue replans as one unit; every watch event maps here
QUEUE_REQUEST = Request(name="placement-queue")


class PlacementReconciler:
    def __init__(self, client: Client, namespace: str = consts.DEFAULT_OPERATOR_NAMESPACE):
        self.client = client
        self.namespace = namespace
        self.recorder = EventRecorder(client, namespace, component=PLACEMENT_MANAGER)
        self.metrics = get_metrics()
        self._frag_pools: set = set()

    def reconcile(self, req: Request) -> Result:
        slices = self.client.list(TPU_SLICE_API_VERSION, TPU_SLICE_KIND)
        nodes = self.client.list("v1", "Node")
        links = self._degraded_links()
        with trace.span("plan", slices=len(slices), nodes=len(nodes), links=len(links)):
            engine = PlacementEngine(slices, nodes, degraded_links=links)
            plan = engine.plan()
        with trace.span("apply-plan", deltas=len(plan.label_deltas)):
            self._apply_labels(plan)
            statuses_ok = self._publish_statuses(plan, {s["metadata"]["name"]: s for s in slices})
        self._record_events(plan, engine)
        self.metrics.placement_queue_depth.set(plan.queue_depth)
        for pool, frag in plan.fragmentation.items():
            self.metrics.torus_fragmentation.labels(pool).set(frag)
        for gone in self._frag_pools - set(plan.fragmentation):
            # a drained/deleted pool must stop exporting its last value
            try:
                self.metrics.torus_fragmentation.remove(gone)
            except KeyError:
                pass
        self._frag_pools = set(plan.fragmentation)
        if plan.teardowns or not statuses_ok:
            # a torn-down gang (preempted or degraded) re-places as soon
            # as the world settles; a failed status write retries — once
            # the labels have converged nothing else would re-enqueue it
            return Result(requeue=True)
        if plan.queue_depth:
            # pending work but nothing actionable: capacity can free up
            # without any event this controller watches mapping to it
            return Result(requeue_after=consts.PLACEMENT_REPLAN_SECONDS)
        return Result()

    def _degraded_links(self) -> List[tuple]:
        """Severed ICI edges from the fabric analyzer's link-health map
        (``consts.LINK_HEALTH_CONFIGMAP``): node-name pairs the engine
        treats as cutting contiguity. A MISSING or malformed map means
        no cuts (nothing was ever recorded) — but a failed read
        propagates and aborts the pass like any other input read:
        planning with "no cuts" because the apiserver 500'd could seat
        a fresh gang straight across a known-degraded link."""
        from tpu_operator.controllers.fabric_telemetry import parse_link_map

        cm = self.client.get_or_none(
            "v1", "ConfigMap", consts.LINK_HEALTH_CONFIGMAP, self.namespace
        )
        edges = []
        for pool_edges in parse_link_map(cm).values():
            for edge in pool_edges:
                a, _, b = edge.partition("|")
                if a and b:
                    edges.append((a, b))
        return sorted(edges)

    # -- plan application ----------------------------------------------------

    def _apply_labels(self, plan: Plan) -> None:
        # every delta is a real change by construction (assignments only
        # land on previously-free hosts, clears only on labelled ones),
        # so each is one labels-only merge patch with no read-back
        for node_name in sorted(plan.label_deltas):
            try:
                self.client.patch(
                    "v1", "Node", node_name,
                    {"metadata": {"labels": plan.label_deltas[node_name]}},
                )
            except errors.NotFound:
                pass  # node deleted mid-pass; next pass re-plans without it

    def _publish_statuses(self, plan: Plan, slices: dict) -> bool:
        ok = True
        for name in sorted(plan.statuses):
            desired = plan.statuses[name]
            obj = slices.get(name)
            if obj is None:
                continue
            current = (obj.get("status") or {}).get("placement") or {}
            if current == desired:
                continue
            if not desired:
                # the CR dropped its placement request: remove the block
                body = None
            else:
                # merge patch merges nested objects: stale keys the new
                # block no longer carries (message, origin, nodes) must be
                # nulled explicitly or they'd survive the phase transition
                body = dict(desired)
                for stale in current:
                    if stale not in body:
                        body[stale] = None
            try:
                self.client.patch_status(  # tpuop-lint: kinds=tpu.google.com/v1alpha1/TPUSlice
                    TPU_SLICE_API_VERSION, TPU_SLICE_KIND, name,
                    {"status": {"placement": body}},
                )
            except errors.NotFound:
                continue
            except errors.ApiError as e:
                ok = False  # caller requeues: status must converge too
                log.debug("placement status publish for %s failed: %s", name, e)
        return ok

    def _record_events(self, plan: Plan, engine: PlacementEngine) -> None:
        for slice_name, event_type, reason, message in plan.events:
            involved = engine.slices.get(slice_name)
            if involved is None:
                continue
            self.recorder.event(involved, event_type, reason, message)


def setup_with_manager(mgr, reconciler: PlacementReconciler) -> Controller:
    ctrl = Controller(
        "placement", reconciler, coalesce_window=consts.NODE_EVENT_COALESCE_SECONDS
    )
    reconciler.client = CachedReadClient(reconciler.client, mgr)

    def map_to_queue(_obj) -> List[Request]:
        return [QUEUE_REQUEST]

    def placement_changed(event_type, old, new) -> bool:
        """TPUSlice events matter when the placement request itself
        changed (spec) or the CR appeared/went away — status echoes of
        this controller's own writes must not re-enqueue the queue. A
        WIPED status on a slice that still requests placement (CRD
        structural pruning, manual status edit) does matter: a settled
        queue would otherwise never re-publish it. No echo loop — this
        controller's own writes always leave a non-empty block."""
        if event_type != "MODIFIED" or old is None:
            return True
        if (old.get("spec") or {}).get("placement") != (new.get("spec") or {}).get("placement"):
            return True
        return bool(
            (new.get("spec") or {}).get("placement")
            and (old.get("status") or {}).get("placement")
            and not (new.get("status") or {}).get("placement")
        )

    def node_changed(event_type, old: Optional[ObjectDict], new: ObjectDict) -> bool:
        """Node events matter when placement inputs changed: health /
        repair / coordinate / TPU identity / assignment labels. The echo
        of this controller's own assignment writes is dropped by the
        same-value check in _apply_labels, but filtering here saves the
        reconcile entirely for unrelated label churn."""
        if event_type != "MODIFIED" or old is None:
            return True
        keys = (
            consts.TPU_HEALTH_LABEL,
            consts.REPAIR_STATE_LABEL,
            consts.TPU_PERF_LABEL,
            consts.TORUS_COORDS_LABEL,
            consts.PLACEMENT_LABEL,
            consts.PLACEMENT_INDEX_LABEL,
            consts.PLACEMENT_TOPOLOGY_LABEL,
            consts.GKE_TPU_ACCELERATOR_LABEL,
            consts.GKE_TPU_TOPOLOGY_LABEL,
            consts.TFD_ACCELERATOR_TYPE_LABEL,
            consts.TFD_TOPOLOGY_LABEL,
        )
        old_labels = old["metadata"].get("labels") or {}
        new_labels = new["metadata"].get("labels") or {}
        return any(old_labels.get(k) != new_labels.get(k) for k in keys)

    def link_map_changed(event_type, old, new) -> bool:
        """The fabric analyzer's link-health map is a placement input: a
        newly severed (or healed) edge must replan the queue — a gang
        straddling the cut re-places, and a settled Unschedulable slice
        may fit once a cut heals. Only the one ConfigMap matters; data
        echoes with no change are dropped."""
        if (new["metadata"].get("name") != consts.LINK_HEALTH_CONFIGMAP
                or new["metadata"].get("namespace") != reconciler.namespace):
            return False
        if event_type != "MODIFIED" or old is None:
            return True
        return (old.get("data") or {}) != (new.get("data") or {})

    ctrl.watch(
        mgr.informer_for(TPU_SLICE_API_VERSION, TPU_SLICE_KIND),
        mapper=map_to_queue, predicate=placement_changed,
    )
    ctrl.watch(mgr.informer_for("v1", "Node"), mapper=map_to_queue, predicate=node_changed)
    ctrl.watch(
        mgr.informer_for("v1", "ConfigMap", reconciler.namespace),
        mapper=map_to_queue, predicate=link_map_changed,
    )
    mgr.add_controller(ctrl)
    return ctrl
