"""Placement reconciler: applies the engine's plan to the cluster.

Pool-sharded: node events map to a per-pool request (the pool-shard key
from ``kube/sharding.py``), and a pool request replans ONLY that pool —
the engine is fed the shard's node set from the sharded node view's
delta-maintained cache plus just the slices that touch the pool
(assigned there by labels, pinned there by spec, or last scheduled
there). Admission order is priority-then-FIFO across ALL TPUSlices, so
anything a pool pass cannot settle locally (an unpinned slice that
found no block, a teardown that may re-place elsewhere) defers to the
GLOBAL pass, which keeps the old recompute-everything shape and runs on
slice/link events, on the replan heartbeat, and whenever a pool pass
hands work up. Idempotent either way: the assignment labels on nodes
are the source of truth, so a crash between label writes and status
writes converges on the next pass instead of double-booking.

Wire traffic per pass: one cached TPUSlice list, the pool's cached node
set (no cluster-wide list on the pool path), one labels-only merge
patch per node whose assignment changed — fanned out through the shared
write pool — and one key-scoped status patch per slice whose placement
block changed: O(changes in the pool), not O(cluster).
"""

from __future__ import annotations

import calendar
import logging
import time
from typing import Dict, List, Optional

from tpu_operator import consts
from tpu_operator.api.tpuquota import TPU_QUOTA_API_VERSION, TPU_QUOTA_KIND
from tpu_operator.api.tpuslice import TPU_SLICE_API_VERSION, TPU_SLICE_KIND
from tpu_operator.controllers.operator_metrics import get_metrics
from tpu_operator.kube import errors, trace
from tpu_operator.kube.cached import CachedReadClient
from tpu_operator.kube.client import Client
from tpu_operator.kube.controller import Controller, Request, Result
from tpu_operator.kube.events import EventRecorder
from tpu_operator.kube.objects import ObjectDict
from tpu_operator.placement.engine import (
    PLACEMENT_MANAGER,
    Plan,
    PlacementEngine,
    PlacementPhase,
)

log = logging.getLogger(__name__)

# the whole queue replans as one unit; every watch event maps here
QUEUE_REQUEST = Request(name="placement-queue")

# informer index over TPUSlices by the pool they are pinned or last
# scheduled to — what keeps a pool pass's slice lookup O(matches)
SLICE_POOL_INDEX = "by-pool"


def _parse_k8s_time(stamp: str) -> Optional[float]:
    """metadata timestamps ("%Y-%m-%dT%H:%M:%SZ") → unix seconds."""
    try:
        return float(calendar.timegm(time.strptime(stamp, "%Y-%m-%dT%H:%M:%SZ")))
    except (TypeError, ValueError):
        return None


def clear_assignment_labels(client: Client, node_names) -> int:
    """Tear nodes out of their gang by clearing the assignment labels
    (``engine.assignment_clear_delta`` — the one spelling every
    teardown path shares): the job controller's checkpoint-barrier
    teardown and the defrag controller's drain-then-re-place both call
    here. Returns how many nodes no longer carry an assignment; the
    first real ApiError stops the sweep. A PARTIAL clear is safe (the
    engine reads it as a broken gang and finishes the teardown next
    pass) but ZERO progress is a failure the caller must not book as
    an executed migration. A vanished node counts as cleared — it
    holds no assignment anymore."""
    from tpu_operator.placement.engine import assignment_clear_delta

    delta = assignment_clear_delta()
    cleared = 0
    for node in node_names:
        try:
            client.patch("v1", "Node", node, {"metadata": {"labels": delta}})
        except errors.NotFound:
            cleared += 1
            continue
        except errors.ApiError as e:
            log.debug("assignment clear on %s failed: %s", node, e)
            return cleared
        cleared += 1
    return cleared


def slice_pool_index(obj: ObjectDict) -> List[str]:
    """Informer index fn: the pools a TPUSlice is pinned or last
    scheduled to."""
    spec_pool = str(((obj.get("spec") or {}).get("placement") or {}).get("pool") or "")
    status_pool = str(((obj.get("status") or {}).get("placement") or {}).get("pool") or "")
    return sorted({p for p in (spec_pool, status_pool) if p})


class PlacementReconciler:
    def __init__(self, client: Client, namespace: str = consts.DEFAULT_OPERATOR_NAMESPACE):
        self.client = client
        self.namespace = namespace
        self.recorder = EventRecorder(client, namespace, component=PLACEMENT_MANAGER)
        self.metrics = get_metrics()
        self._now = time.time  # tests pin the tenancy-ledger clock
        # fragmentation-series bookkeeping is shared by the global pass
        # and every pool-shard worker, which run CONCURRENTLY by design:
        # its mutations take a dedicated lock (metrics-only — no client
        # call ever runs under it). The label/status writes themselves
        # are deliberately NOT serialized across passes: the engine is
        # built for partial-write states (assignment labels are the
        # source of truth; crash-between-writes converges), so two
        # interleaved plans are just another partial state — each label
        # write is a single-owner assignment (last writer wins), the
        # losing gang reads as broken on the next pass and re-places,
        # and the chaos soak's zero-double-booked-hosts-after-quiesce
        # gate holds exactly because of this level-triggered repair.
        from tpu_operator.kube import racecheck

        self._frag_lock = racecheck.lock("PlacementReconciler._frag_lock")
        self._frag_pools: set = set()
        # wired by setup_with_manager: the pool-sharded node view (per-
        # pool delta-maintained caches) and the controller's enqueue hook
        # for handing pool-local leftovers to the global pass. Unwired
        # (direct reconciler use in tests/drills/bench), every request
        # takes the global path exactly as before.
        self.node_view = None
        self._enqueue = None
        self._drain_shard = None
        self._slice_informer = None  # pool-indexed TPUSlice cache

    def reconcile(self, req: Request) -> Result:
        if req.shard and self.node_view is not None and self.node_view.synced():
            return self._reconcile_pool(req.shard)
        slices = self.client.list(TPU_SLICE_API_VERSION, TPU_SLICE_KIND)
        nodes = self.client.list("v1", "Node")
        links = self._degraded_links()
        risk = self._node_risk()
        tenancy = self._tenancy(nodes)
        with trace.span("plan", slices=len(slices), nodes=len(nodes), links=len(links)):
            engine = PlacementEngine(
                slices, nodes, degraded_links=links, node_risk=risk, tenancy=tenancy
            )
            plan = engine.plan()
        with trace.span("apply-plan", deltas=len(plan.label_deltas)):
            self._apply_labels(plan)
            statuses_ok = self._publish_statuses(plan, {s["metadata"]["name"]: s for s in slices})
        self._record_events(plan, engine)
        tenancy_ok = self._book_tenancy(plan, engine, tenancy)
        self.metrics.placement_queue_depth.set(plan.queue_depth)
        for pool, frag in plan.fragmentation.items():
            self.metrics.torus_fragmentation.labels(pool).set(frag)
        # tracked set merges with pools the LIVE view still has nodes for:
        # a pool created after this pass's node snapshot (its pool pass
        # registered the gauge concurrently) must not be dropped from
        # tracking by a stale global replace — or its series could leak
        # forever once the pool later drains (the O005 stale-series class)
        live_pools = set(self.node_view.shards()) if self.node_view is not None else set()
        with self._frag_lock:
            keep = set(plan.fragmentation) | (self._frag_pools & live_pools)
            gone_pools = self._frag_pools - keep
            self._frag_pools = keep
        for gone in gone_pools:
            # a drained/deleted pool must stop exporting its last value
            # — and its queue shard (workers + labelled series) goes too.
            # The shard drain is guarded by the LIVE sharded view, not
            # this pass's (possibly stale) node snapshot: a pool that
            # (re)appeared mid-pass must keep its queue — and once the
            # view agrees the pool is empty, any request the drain drops
            # was a no-op replan of zero nodes anyway.
            try:
                self.metrics.torus_fragmentation.remove(gone)
            except KeyError:
                pass
            if (
                self._drain_shard is not None
                and self.node_view is not None
                and not self.node_view.nodes(gone)
            ):
                self._drain_shard(gone)
        if plan.teardowns or not statuses_ok or not tenancy_ok:
            # a torn-down gang (preempted or degraded) re-places as soon
            # as the world settles; a failed status or ledger write
            # retries — once the labels have converged nothing else
            # would re-enqueue it
            return Result(requeue=True)
        if plan.queue_depth:
            # pending work but nothing actionable: capacity can free up
            # without any event this controller watches mapping to it
            return Result(requeue_after=consts.PLACEMENT_REPLAN_SECONDS)
        return Result()

    def _reconcile_pool(self, shard: str) -> Result:
        """One pool's replan, fed by the sharded view's delta-maintained
        cache: same engine, same invariants, scoped inputs. Decisions a
        pool cannot make alone — admitting an UNPINNED slice that found
        no local block, re-homing a teardown — defer to the global pass
        (priority-then-FIFO admission is a cross-pool order)."""
        nodes = self.node_view.nodes(shard)
        if not nodes:
            # the pool drained out from under its shard: the global pass
            # owns the cleanup (fragmentation series, queue shard)
            self._request_global()
            return Result()
        assigned_here = {
            (n["metadata"].get("labels") or {}).get(consts.PLACEMENT_LABEL)
            for n in nodes
        } - {None, ""}
        relevant = self._slices_for_pool(shard, assigned_here)
        links = self._degraded_links()
        risk = self._node_risk()
        # pool-scoped policy: capacity/usage seen through this shard's
        # node set only. Ordering decisions a single pool cannot make
        # fairly (cross-pool dominant shares) defer to the global pass
        # the same way unpinned Unschedulable verdicts do
        tenancy = self._tenancy(nodes)
        with trace.span(
            "plan", pool=shard, slices=len(relevant), nodes=len(nodes), links=len(links)
        ):
            engine = PlacementEngine(
                relevant, nodes, degraded_links=links, node_risk=risk, tenancy=tenancy
            )
            plan = engine.plan()
        # a slice this pool couldn't seat may belong elsewhere: only a
        # slice PINNED TO THIS POOL gets its Unschedulable verdict
        # published here (the one case where this pool's view is
        # authoritative); everything else — unpinned, or pinned to a
        # different pool but dragged in by a stale status.pool — defers
        # to the global pass, which decides with every pool in view
        deferred = 0
        for name in list(plan.statuses):
            desired = plan.statuses[name]
            spec_pool = str(
                (((engine.slices.get(name) or {}).get("spec") or {})
                 .get("placement") or {}).get("pool") or ""
            )
            if (desired and desired.get("phase") == PlacementPhase.UNSCHEDULABLE
                    and spec_pool != shard):
                plan.statuses.pop(name)
                deferred += 1
        with trace.span("apply-plan", pool=shard, deltas=len(plan.label_deltas)):
            self._apply_labels(plan)
            statuses_ok = self._publish_statuses(
                plan, {s["metadata"]["name"]: s for s in relevant}
            )
        self._record_events(plan, engine)
        tenancy_ok = self._book_tenancy(plan, engine, tenancy)
        for pool, frag in plan.fragmentation.items():
            self.metrics.torus_fragmentation.labels(pool).set(frag)
        with self._frag_lock:
            self._frag_pools.update(plan.fragmentation)
        if plan.teardowns or deferred:
            # work only the global order can finish
            self._request_global()
        if not statuses_ok or not tenancy_ok:
            return Result(requeue=True)
        return Result()

    def _request_global(self) -> None:
        if self._enqueue is not None:
            self._enqueue(QUEUE_REQUEST)

    def _slices_for_pool(self, shard: str, assigned_here: set) -> List[ObjectDict]:
        """The slices a pool pass must see: pinned/last-scheduled to the
        pool (via the informer's ``by-pool`` index — O(matches), no
        all-slice scan per node event) plus the owners the pool's node
        labels name. Falls back to a filtered full list when the indexed
        informer isn't wired (direct reconciler use)."""
        informer = self._slice_informer
        if informer is None or not informer.has_synced():
            def touches_pool(obj) -> bool:
                name = obj["metadata"]["name"]
                spec_pool = str(((obj.get("spec") or {}).get("placement") or {}).get("pool") or "")
                status_pool = str(((obj.get("status") or {}).get("placement") or {}).get("pool") or "")
                return name in assigned_here or spec_pool == shard or status_pool == shard

            return [
                s for s in self.client.list(TPU_SLICE_API_VERSION, TPU_SLICE_KIND)
                if touches_pool(s)
            ]
        by_name = {
            s["metadata"]["name"]: s for s in informer.by_index(SLICE_POOL_INDEX, shard)
        }
        for owner in assigned_here:
            if owner not in by_name:
                obj = informer.get(owner)
                if obj is not None:
                    by_name[owner] = obj
        return [by_name[name] for name in sorted(by_name)]

    def _degraded_links(self) -> List[tuple]:
        """Severed ICI edges the engine treats as cutting contiguity
        (``fabric_telemetry.degraded_link_pairs`` — shared with the job
        and serving controllers so the three can never diverge on the
        link-map encoding)."""
        from tpu_operator.controllers.fabric_telemetry import degraded_link_pairs

        return degraded_link_pairs(self.client, self.namespace)

    def _node_risk(self) -> Dict[str, float]:
        """Per-host risk scores for the engine's risk-aware ranking
        hook (the risk scorer's published state CM). ADVISORY, unlike
        the link map: an unreadable or absent ledger reads as no bias —
        placing without it only costs optimality, never safety, so this
        read must not abort the pass (K003 applies to reads that gate
        destructive actions; ranking between equally-legal blocks is
        not one)."""
        from tpu_operator.controllers.risk import read_node_risk

        return read_node_risk(self.client, self.namespace) or {}

    def _tenancy(self, nodes: List[ObjectDict]):
        """The cluster's fair-share policy, built from its TPUQuota
        objects over the pass's node capacity (None with zero
        well-formed quotas — the byte-identical stock-admission path).
        UNLIKE the advisory risk read this fails CLOSED: a quota-blind
        pass could seat borrowers ahead of guaranteed tenants or evict
        a protected gang, so an ApiError propagates and the pass
        retries — the same contract as the slice/node lists."""
        from tpu_operator.tenancy.fairshare import (
            capacity_by_generation,
            policy_from_objects,
        )

        quotas = self.client.list(TPU_QUOTA_API_VERSION, TPU_QUOTA_KIND)
        return policy_from_objects(quotas, capacity_by_generation(nodes))

    def _book_tenancy(self, plan: Plan, engine: PlacementEngine, policy) -> bool:
        """Book the pass's preemption-economy decisions plus every
        newly-Scheduled gang's per-tenant time-to-place sample into the
        tpu-tenancy-ledger CM. Fail CLOSED (K003): an unreadable ledger
        returns False and the caller requeues — a cross-tenant eviction
        must never vanish from the audit trail. No-op without an active
        policy (the ledger only exists alongside quotas)."""
        if policy is None:
            return True
        from tpu_operator.tenancy.fairshare import resolve_tenant
        from tpu_operator.tenancy.ledger import book, read_ledger

        now = self._now()
        samples = []
        for name in sorted(plan.statuses):
            desired = plan.statuses[name] or {}
            if desired.get("phase") != PlacementPhase.SCHEDULED:
                continue
            obj = engine.slices.get(name)
            if obj is None:
                continue
            prior = (obj.get("status") or {}).get("placement") or {}
            if prior.get("phase") == PlacementPhase.SCHEDULED:
                continue  # already seated: not a fresh time-to-place
            created = _parse_k8s_time(obj["metadata"].get("creationTimestamp", ""))
            if created is None:
                continue
            tenant = resolve_tenant(obj) or consts.TENANT_DEFAULT
            samples.append((tenant, max(0.0, now - created)))
        if not plan.preemption_decisions and not samples:
            return True
        ledger = read_ledger(self.client, self.namespace)
        if ledger is None:
            return False
        return book(
            self.client, self.namespace, ledger,
            decisions=plan.preemption_decisions, samples=samples, now=now,
        )

    # -- plan application ----------------------------------------------------

    def _apply_labels(self, plan: Plan) -> None:
        # every delta is a real change by construction (assignments only
        # land on previously-free hosts, clears only on labelled ones),
        # so each is one labels-only merge patch with no read-back —
        # fanned out through the shared write pool so a gang-sized sweep
        # costs one concurrent window, not N serial round-trips
        from tpu_operator.kube.writers import shared_fanout

        def patch_call(node_name: str, delta: dict):
            def call():
                try:
                    self.client.patch(
                        "v1", "Node", node_name, {"metadata": {"labels": delta}}
                    )
                except errors.NotFound:
                    pass  # node deleted mid-pass; next pass re-plans without it

            return call

        calls = [
            patch_call(name, plan.label_deltas[name])
            for name in sorted(plan.label_deltas)
        ]
        for _, err in shared_fanout().map(calls, verb="patch", kind="Node"):
            if err is not None:
                raise err

    def _publish_statuses(self, plan: Plan, slices: dict) -> bool:
        ok = True
        for name in sorted(plan.statuses):
            desired = plan.statuses[name]
            obj = slices.get(name)
            if obj is None:
                continue
            current = (obj.get("status") or {}).get("placement") or {}
            if current == desired:
                continue
            if not desired:
                # the CR dropped its placement request: remove the block
                body = None
            else:
                # merge patch merges nested objects: stale keys the new
                # block no longer carries (message, origin, nodes) must be
                # nulled explicitly or they'd survive the phase transition
                body = dict(desired)
                for stale in current:
                    if stale not in body:
                        body[stale] = None
            try:
                self.client.patch_status(  # tpuop-lint: kinds=tpu.google.com/v1alpha1/TPUSlice
                    TPU_SLICE_API_VERSION, TPU_SLICE_KIND, name,
                    {"status": {"placement": body}},
                )
            except errors.NotFound:
                continue
            except errors.ApiError as e:
                ok = False  # caller requeues: status must converge too
                log.debug("placement status publish for %s failed: %s", name, e)
        return ok

    def _record_events(self, plan: Plan, engine: PlacementEngine) -> None:
        for slice_name, event_type, reason, message in plan.events:
            involved = engine.slices.get(slice_name)
            if involved is None:
                continue
            self.recorder.event(involved, event_type, reason, message)


def setup_with_manager(mgr, reconciler: PlacementReconciler) -> Controller:
    from tpu_operator.kube.sharding import ShardedNodeView

    ctrl = Controller(
        "placement", reconciler, coalesce_window=consts.NODE_EVENT_COALESCE_SECONDS
    )
    reconciler.client = CachedReadClient(reconciler.client, mgr)
    reconciler._enqueue = ctrl.enqueue
    reconciler._drain_shard = ctrl.drain_shard

    def map_to_queue(_obj) -> List[Request]:
        return [QUEUE_REQUEST]

    def placement_changed(event_type, old, new) -> bool:
        """TPUSlice events matter when the placement request itself
        changed (spec) or the CR appeared/went away — status echoes of
        this controller's own writes must not re-enqueue the queue. A
        WIPED status on a slice that still requests placement (CRD
        structural pruning, manual status edit) does matter: a settled
        queue would otherwise never re-publish it. No echo loop — this
        controller's own writes always leave a non-empty block."""
        if event_type != "MODIFIED" or old is None:
            return True
        if (old.get("spec") or {}).get("placement") != (new.get("spec") or {}).get("placement"):
            return True
        return bool(
            (new.get("spec") or {}).get("placement")
            and (old.get("status") or {}).get("placement")
            and not (new.get("status") or {}).get("placement")
        )

    def node_changed(event_type, old: Optional[ObjectDict], new: ObjectDict) -> bool:
        """Node events matter when placement inputs changed: health /
        repair / coordinate / TPU identity / assignment labels. The echo
        of this controller's own assignment writes is dropped by the
        same-value check in _apply_labels, but filtering here saves the
        reconcile entirely for unrelated label churn."""
        if event_type != "MODIFIED" or old is None:
            return True
        keys = (
            consts.TPU_HEALTH_LABEL,
            consts.REPAIR_STATE_LABEL,
            consts.TPU_PERF_LABEL,
            consts.TORUS_COORDS_LABEL,
            consts.PLACEMENT_LABEL,
            consts.PLACEMENT_INDEX_LABEL,
            consts.PLACEMENT_TOPOLOGY_LABEL,
            consts.GKE_TPU_ACCELERATOR_LABEL,
            consts.GKE_TPU_TOPOLOGY_LABEL,
            consts.TFD_ACCELERATOR_TYPE_LABEL,
            consts.TFD_TOPOLOGY_LABEL,
        )
        old_labels = old["metadata"].get("labels") or {}
        new_labels = new["metadata"].get("labels") or {}
        return any(old_labels.get(k) != new_labels.get(k) for k in keys)

    def link_map_changed(event_type, old, new) -> bool:
        """The fabric analyzer's link-health map is a placement input: a
        newly severed (or healed) edge must replan the queue — a gang
        straddling the cut re-places, and a settled Unschedulable slice
        may fit once a cut heals. Only the one ConfigMap matters; data
        echoes with no change are dropped."""
        if (new["metadata"].get("name") != consts.LINK_HEALTH_CONFIGMAP
                or new["metadata"].get("namespace") != reconciler.namespace):
            return False
        if event_type != "MODIFIED" or old is None:
            return True
        return (old.get("data") or {}) != (new.get("data") or {})

    def quota_changed(event_type, old, new) -> bool:
        """TPUQuota events replan the queue when the quota itself
        changed (spec) or the object appeared/went away — the tenancy
        controller's status-accounting echoes must not."""
        if event_type != "MODIFIED" or old is None:
            return True
        return (old.get("spec") or {}) != (new.get("spec") or {})

    slice_informer = mgr.informer_for(TPU_SLICE_API_VERSION, TPU_SLICE_KIND)
    slice_informer.add_index(SLICE_POOL_INDEX, slice_pool_index)
    reconciler._slice_informer = slice_informer
    ctrl.watch(slice_informer, mapper=map_to_queue, predicate=placement_changed)
    # fair-share inputs: adding/editing/deleting a TPUQuota reorders the
    # whole queue (and zero-quota clusters must replan back to stock)
    ctrl.watch(
        mgr.informer_for(TPU_QUOTA_API_VERSION, TPU_QUOTA_KIND),
        mapper=map_to_queue, predicate=quota_changed,
    )
    # node events route through the sharded view: each event enqueues its
    # POOL's request (one queue + worker pool per shard), and a node that
    # moves pools fans out as DELETED-on-old + ADDED-on-new, so both
    # affected pools replan. The view's per-shard caches are what the
    # pool pass plans from — per-pool deltas, no global node list.
    view = ShardedNodeView().attach(mgr.informer_for("v1", "Node"))
    reconciler.node_view = view

    def on_node_event(shard, event_type, old, new) -> None:
        if node_changed(event_type, old, new):
            ctrl.enqueue(Request(name=QUEUE_REQUEST.name, shard=shard))

    view.add_handler(on_node_event)
    ctrl.watch(
        mgr.informer_for("v1", "ConfigMap", reconciler.namespace),
        mapper=map_to_queue, predicate=link_map_changed,
    )
    mgr.add_controller(ctrl)
    return ctrl
