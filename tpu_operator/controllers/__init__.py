"""Controllers (reference: controllers/ — the three reconcilers)."""
