"""Operator self-metrics.

Reference: ``controllers/operator_metrics.go:29-201`` — Prometheus gauges /
counters on the controller-runtime registry, served from the manager's
:8080 metrics endpoint. Same metric names with the ``gpu``→``tpu`` swap.
"""

from __future__ import annotations

import time

import prometheus_client

_METRICS = None


class OperatorMetrics:
    def __init__(self, registry=None):
        reg = registry or prometheus_client.REGISTRY
        self.tpu_nodes_total = prometheus_client.Gauge(
            "tpu_operator_tpu_nodes_total",
            "Number of nodes with TPUs",
            registry=reg,
        )
        self.reconciliation_total = prometheus_client.Counter(
            "tpu_operator_reconciliation_total",
            "Total number of ClusterPolicy reconciliations",
            registry=reg,
        )
        self.reconciliation_failed = prometheus_client.Counter(
            "tpu_operator_reconciliation_failed_total",
            "Number of failed ClusterPolicy reconciliations",
            registry=reg,
        )
        self.reconciliation_status = prometheus_client.Gauge(
            "tpu_operator_reconciliation_status",
            "1 when the last reconciliation was fully successful",
            registry=reg,
        )
        self.reconciliation_last_success_ts = prometheus_client.Gauge(
            "tpu_operator_reconciliation_last_success_ts_seconds",
            "Timestamp (seconds since epoch) of the last successful reconciliation",
            registry=reg,
        )
        self.operand_states_not_ready = prometheus_client.Gauge(
            "tpu_operator_operand_states_not_ready",
            "Number of operand states not currently Ready",
            registry=reg,
        )
        self.upgrades_in_progress = prometheus_client.Gauge(
            "tpu_operator_libtpu_upgrades_in_progress",
            "Nodes currently upgrading libtpu",
            registry=reg,
        )
        self.upgrades_done = prometheus_client.Gauge(
            "tpu_operator_libtpu_upgrades_done",
            "Nodes that completed libtpu upgrade",
            registry=reg,
        )
        self.upgrades_failed = prometheus_client.Gauge(
            "tpu_operator_libtpu_upgrades_failed",
            "Nodes in libtpu upgrade-failed state",
            registry=reg,
        )
        self.unhealthy_nodes = prometheus_client.Gauge(
            "tpu_operator_unhealthy_nodes",
            "Nodes whose TPU health is degraded, in repair, or quarantined",
            registry=reg,
        )
        self.quarantined_nodes = prometheus_client.Gauge(
            "tpu_operator_quarantined_nodes",
            "Nodes parked in the quarantined terminal repair state",
            registry=reg,
        )
        self.remediations_total = prometheus_client.Counter(
            "tpu_operator_remediations_total",
            "Health remediation attempts started",
            registry=reg,
        )
        self.placement_queue_depth = prometheus_client.Gauge(
            "tpu_operator_placement_queue_depth",
            "TPUSlice placement requests not currently Scheduled "
            "(Queued + Unschedulable)",
            registry=reg,
        )
        self.torus_fragmentation = prometheus_client.Gauge(
            "tpu_operator_torus_fragmentation",
            "External fragmentation of a node pool's host torus "
            "(1 - largest free cube / free hosts)",
            ["pool"],
            registry=reg,
        )
        # apiserver-client resilience series, owned by the transport
        # layer (kube/retry.py) the same way apiserver_requests_total is
        # owned by http_client: process-wide on the default registry —
        # re-exported here so the operator's metric surface is complete
        # in one place and served from the manager's :8080 endpoint.
        from tpu_operator.kube import retry as _retry

        self.api_retries_total = _retry.retries_counter()
        self.api_breaker_state = _retry.breaker_state_gauge()

    def record_success(self):
        self.reconciliation_total.inc()
        self.reconciliation_status.set(1)
        self.reconciliation_last_success_ts.set(time.time())

    def record_failure(self):
        self.reconciliation_total.inc()
        self.reconciliation_failed.inc()
        self.reconciliation_status.set(0)


def get_metrics() -> OperatorMetrics:
    """Process-wide singleton (the default prometheus registry forbids
    duplicate registration)."""
    global _METRICS
    if _METRICS is None:
        _METRICS = OperatorMetrics()
    return _METRICS
