"""Operator self-metrics.

Reference: ``controllers/operator_metrics.go:29-201`` — Prometheus gauges /
counters on the controller-runtime registry, served from the manager's
:8080 metrics endpoint. Same metric names with the ``gpu``→``tpu`` swap.
"""

from __future__ import annotations

import time

import prometheus_client

_METRICS = None


def _get_or_create(kind, name: str, doc: str, labelnames=(), registry=None):
    """Idempotent collector construction: a second in-process ``Manager``
    (crash-recovery and leader-failover drills boot one, and so does any
    embedder that builds its own ``OperatorMetrics``) must not trip the
    registry's duplicate-registration ValueError — the existing collector
    is the same series and is simply reused."""
    reg = registry or prometheus_client.REGISTRY
    try:
        return kind(name, doc, labelnames, registry=reg)
    except ValueError:
        # prometheus_client indexes counters under the _total-stripped
        # name; probe both spellings before concluding the clash is real
        existing = reg._names_to_collectors.get(name)
        if existing is None and name.endswith("_total"):
            existing = reg._names_to_collectors.get(name[: -len("_total")])
        if existing is None:
            raise
        return existing


class OperatorMetrics:
    def __init__(self, registry=None):
        reg = registry or prometheus_client.REGISTRY
        self.tpu_nodes_total = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_tpu_nodes_total",
            "Number of nodes with TPUs",
            registry=reg,
        )
        self.reconciliation_total = _get_or_create(
            prometheus_client.Counter,
            "tpu_operator_reconciliation_total",
            "Total number of ClusterPolicy reconciliations",
            registry=reg,
        )
        self.reconciliation_failed = _get_or_create(
            prometheus_client.Counter,
            "tpu_operator_reconciliation_failed_total",
            "Number of failed ClusterPolicy reconciliations",
            registry=reg,
        )
        self.reconciliation_status = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_reconciliation_status",
            "1 when the last reconciliation was fully successful",
            registry=reg,
        )
        self.reconciliation_last_success_ts = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_reconciliation_last_success_ts_seconds",
            "Timestamp (seconds since epoch) of the last successful reconciliation",
            registry=reg,
        )
        self.operand_states_not_ready = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_operand_states_not_ready",
            "Number of operand states not currently Ready",
            registry=reg,
        )
        self.upgrades_in_progress = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_libtpu_upgrades_in_progress",
            "Nodes currently upgrading libtpu",
            registry=reg,
        )
        self.upgrades_done = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_libtpu_upgrades_done",
            "Nodes that completed libtpu upgrade",
            registry=reg,
        )
        self.upgrades_failed = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_libtpu_upgrades_failed",
            "Nodes in libtpu upgrade-failed state",
            registry=reg,
        )
        self.unhealthy_nodes = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_unhealthy_nodes",
            "Nodes whose TPU health is degraded, in repair, or quarantined",
            registry=reg,
        )
        self.quarantined_nodes = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_quarantined_nodes",
            "Nodes parked in the quarantined terminal repair state",
            registry=reg,
        )
        self.remediations_total = _get_or_create(
            prometheus_client.Counter,
            "tpu_operator_remediations_total",
            "Health remediation attempts started",
            registry=reg,
        )
        self.placement_queue_depth = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_placement_queue_depth",
            "TPUSlice placement requests not currently Scheduled "
            "(Queued + Unschedulable)",
            registry=reg,
        )
        self.torus_fragmentation = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_torus_fragmentation",
            "External fragmentation of a node pool's host torus "
            "(1 - largest free cube / free hosts)",
            ["pool"],
            registry=reg,
        )
        # gang-level data-plane rollups (controllers/fleet_telemetry.py
        # aggregates the per-gang step-time artifacts the slice manager
        # publishes, keyed by the placement labels)
        self.gang_step_seconds = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_gang_step_seconds",
            "Gang-median workload step time from the last published "
            "per-gang telemetry artifact",
            ["slice"],
            registry=reg,
        )
        self.gang_straggler_ratio = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_gang_straggler_ratio",
            "Slowest gang member's median step over the gang median "
            "(1.0 = uniform; sustained >1.25 flags a straggler)",
            ["slice"],
            registry=reg,
        )
        self.fleet_healthy_tflops = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_fleet_healthy_tflops",
            "Sum of measured-roof bf16 TFLOP/s across chips on nodes "
            "currently in service (health- and perf-excluded nodes "
            "subtracted) — the fleet's deliverable compute",
            registry=reg,
        )
        self.perf_degraded_nodes = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_perf_degraded_nodes",
            "Nodes carrying the exporter's sustained perf-floor-breach "
            "label (grey failures)",
            registry=reg,
        )
        # ICI fabric series (controllers/fabric_telemetry.py ingests the
        # per-gang fabric artifacts the slice manager publishes; edge =
        # "hostA|hostB", the canonical sorted pair)
        self.ici_link_bandwidth = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_ici_link_bandwidth_gbps",
            "Measured point-to-point ICI bandwidth of one torus link, "
            "from the last published gang fabric artifact",
            ["pool", "edge"],
            registry=reg,
        )
        self.ici_link_degraded = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_ici_link_degraded",
            "1 while the link's measured bandwidth sits below the "
            "degraded fraction of its gang's median edge (or the link "
            "is recorded in the pool's link-health map)",
            ["pool", "edge"],
            registry=reg,
        )
        # per-generation kernel autotuning (controllers/
        # autotune_controller.py folds the cached sweep entries)
        self.autotune_generations_swept = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_autotune_generations_swept",
            "TPU generations in the cluster with a valid cached kernel "
            "sweep for the current libtpu version",
            registry=reg,
        )
        self.autotune_generations_pending = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_autotune_generations_pending",
            "TPU generations awaiting a kernel sweep (election held or "
            "no eligible node)",
            registry=reg,
        )
        self.autotune_matmul_roof = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_autotune_matmul_roof_tflops",
            "Measured bf16 matmul roof from the generation's kernel "
            "sweep — the number that replaces perf.py's scaled guess "
            "(series retire when the entry is invalidated)",
            ["generation"],
            registry=reg,
        )
        # persistent compile cache (controllers/compilecache_controller
        # .py exports from the cached entries; series retire when the
        # record — or the generation — leaves the cache, O005)
        self.compile_seconds = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_compile_seconds",
            "Measured XLA compile (warmup) seconds recorded in the "
            "fleet compile cache for a serving's model on a generation "
            "(series retire when the record is invalidated)",
            ["serving", "generation"],
            registry=reg,
        )
        self.compile_cache_hits = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_compile_cache_hits_total",
            "Compile-cache hits observed per generation (warm starts: "
            "the warmup step resolved a cached executable record)",
            ["generation"],
            registry=reg,
        )
        self.compile_cache_misses = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_compile_cache_misses_total",
            "Compile-cache misses observed per generation (cold starts "
            "that paid — and then published — the full compile)",
            ["generation"],
            registry=reg,
        )
        # elastic training jobs (controllers/job_controller.py): per-job
        # bookkeeping gauges, removed when the TPUJob is deleted (O005)
        self.job_step = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_job_step",
            "Last train step the job's gang reported completing",
            ["job"],
            registry=reg,
        )
        self.job_epoch = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_job_checkpoint_epoch",
            "Newest checkpoint epoch in the job's store (the resume "
            "watermark: no step past it is ever lost)",
            ["job"],
            registry=reg,
        )
        self.job_gang_hosts = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_job_gang_hosts",
            "Hosts in the job's currently placed gang (0 while the gang "
            "is broken or being re-placed)",
            ["job"],
            registry=reg,
        )
        self.job_restarts = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_job_restarts",
            "Consecutive failed restart/re-place attempts charged against "
            "the job's retry budget (resets when the job reaches Running)",
            ["job"],
            registry=reg,
        )
        # traffic-driven serving (controllers/serving_controller.py):
        # per-serving rollups from the load ConfigMap + replica states,
        # removed when the TPUServing is deleted (O005)
        self.serving_replicas = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_serving_replicas",
            "Ready replicas of the serving (placed, in-service gangs; "
            "0 while every replica is placing or broken)",
            ["serving"],
            registry=reg,
        )
        self.serving_tokens_per_s = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_serving_tokens_per_s",
            "Aggregate decode throughput the serving's router last "
            "reported into the load ConfigMap",
            ["serving"],
            registry=reg,
        )
        self.serving_ttft_p99 = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_serving_ttft_p99_seconds",
            "Measured p99 time-to-first-token from the load ConfigMap "
            "(the SLO the autoscaler defends)",
            ["serving"],
            registry=reg,
        )
        self.serving_queue_depth = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_serving_queue_depth",
            "Requests waiting for a decode slot across the serving's "
            "replicas (sustained depth is the scale-up signal)",
            ["serving"],
            registry=reg,
        )
        # pod data plane (tpu_operator/dataplane/): router KV reuse and
        # disaggregated pool sizes, removed with the TPUServing (O005)
        self.serving_kv_hit_ratio = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_serving_kv_hit_ratio",
            "Fraction of routed requests that re-landed on a replica "
            "already holding their session or prefix KV pages (the "
            "KV-aware router's reuse signal, from the load ConfigMap)",
            ["serving"],
            registry=reg,
        )
        self.serving_pool_replicas = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_serving_pool_replicas",
            "Ready replicas of one disaggregated pool of the serving "
            "(pool = prefill | decode; absent while disaggregation is "
            "off)",
            ["serving", "pool"],
            registry=reg,
        )
        self.serving_kv_handoff_bytes = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_serving_kv_handoff_bytes",
            "Cumulative paged-KV bytes handed from the serving's prefill "
            "pool to its decode replicas, as last reported into the "
            "load ConfigMap",
            ["serving"],
            registry=reg,
        )
        # capacity planning & scheduled defragmentation (controllers/
        # defrag_controller.py rides the planning package): per-pool
        # utilization and the analytical model's reference prediction,
        # both retired with their pool/generation (O005)
        self.fleet_utilization = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_fleet_utilization",
            "Occupied fraction of a node pool's in-service hosts "
            "(out-of-service capacity is subtracted from the "
            "denominator) — the defrag controller's headroom signal",
            ["pool"],
            registry=reg,
        )
        self.defrag_migrations = _get_or_create(
            prometheus_client.Counter,
            "tpu_operator_defrag_migrations_total",
            "Gang migrations the defrag controller has executed "
            "(checkpoint-barrier moves for TPUJob gangs, "
            "drain-then-re-place for TPUServing replicas)",
            registry=reg,
        )
        self.plan_predicted_step = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_plan_predicted_step_seconds",
            "Analytical-model step-time prediction for the reference "
            "workload on one 2x2x1 block of the generation — the "
            "what-if engine's live calibration surface (series retire "
            "when the generation leaves the fleet)",
            ["generation"],
            registry=reg,
        )
        # predictive health (controllers/risk.py): the per-host risk
        # score folded from the precursor telemetry, retired when the
        # host leaves the fleet or its risk decays away (O005), plus
        # the planned-migration counter (the predictive twin of
        # defrag_migrations)
        self.node_risk = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_node_risk",
            "Per-host failure-risk score in [0, 1] folded from the "
            "precursor telemetry (gang straggler ratio, degraded ICI "
            "edges, grey-failure perf verdict, repair history) — the "
            "predictive-migration trigger at RISK_THRESHOLD",
            ["node"],
            registry=reg,
        )
        self.risk_migrations = _get_or_create(
            prometheus_client.Counter,
            "tpu_operator_risk_migrations_total",
            "Planned migrations the risk scorer has requested off "
            "hosts over the risk threshold (checkpoint-barrier moves "
            "for TPUJob gangs, drain-then-re-place for TPUServing "
            "replicas)",
            registry=reg,
        )
        # multi-tenant fairness (controllers/tenancy_controller.py):
        # per-tenant accounting over the fleet's TPUQuota objects —
        # series retire when a tenant's quota is deleted and no usage
        # remains (O005)
        self.tenant_used_chips = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_tenant_used_chips",
            "Chips a tenant currently holds across every generation "
            "(rollup of the tenant's level plus all descendants, from "
            "published placement statuses)",
            ["tenant"],
            registry=reg,
        )
        self.tenant_fair_share = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_tenant_fair_share",
            "Weighted dominant share (max over generations of "
            "used/capacity, divided by the tenant's TPUQuota weight) — "
            "the DRF quantity the admission queue equalizes",
            ["tenant"],
            registry=reg,
        )
        self.tenant_borrowed_chips = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_tenant_borrowed_chips",
            "Chips a tenant holds beyond its own guaranteed quota — "
            "reclaimable by cross-tenant preemption under the economy's "
            "legality rule",
            ["tenant"],
            registry=reg,
        )
        self.tenant_place_p99 = _get_or_create(
            prometheus_client.Gauge,
            "tpu_operator_tenant_p99_place_seconds",
            "p99 time-to-place over the tenant's recent gang placements "
            "(the tpu-tenancy-ledger sample ring) — the starvation "
            "signal the fair-share ordering bounds",
            ["tenant"],
            registry=reg,
        )
        # process-wide series owned by the layers that measure them —
        # transport resilience by kube/retry, wire request counts +
        # latency by kube/http_client, reconcile/queue/informer timing by
        # kube/trace — re-exported here so the operator's metric surface
        # is complete in one place and served from the manager's :8080
        # endpoint. (These live on the default registry regardless of
        # ``registry``; a custom registry gets only the operator-owned
        # series above, same as before.)
        from tpu_operator.kube import retry as _retry
        from tpu_operator.kube import trace as _trace
        from tpu_operator.kube.http_client import request_latency_histogram

        self.api_retries_total = _retry.retries_counter()
        self.api_breaker_state = _retry.breaker_state_gauge()
        self.reconcile_duration = _trace.reconcile_duration_histogram()
        self.workqueue_depth = _trace.queue_depth_gauge()
        self.workqueue_oldest_age = _trace.queue_oldest_age_gauge()
        self.workqueue_wait = _trace.queue_wait_histogram()
        self.informer_event_lag = _trace.informer_lag_histogram()
        self.apiserver_request_duration = request_latency_histogram()

    def record_success(self):
        self.reconciliation_total.inc()
        self.reconciliation_status.set(1)
        self.reconciliation_last_success_ts.set(time.time())

    def record_failure(self):
        self.reconciliation_total.inc()
        self.reconciliation_failed.inc()
        self.reconciliation_status.set(0)


def get_metrics() -> OperatorMetrics:
    """Process-wide singleton (the default prometheus registry forbids
    duplicate registration)."""
    global _METRICS
    if _METRICS is None:
        _METRICS = OperatorMetrics()
    return _METRICS
