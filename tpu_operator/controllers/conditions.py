"""Status condition updaters.

Reference: ``internal/conditions`` (conditions.go:31, clusterpolicy.go:32-101,
nvidiadriver.go:38-114) — set a ``Ready`` and an ``Error`` condition on the
CR status, meta/v1 semantics (lastTransitionTime only moves when status
flips).
"""

from __future__ import annotations

import time
from typing import List, Optional

READY = "Ready"
ERROR = "Error"
# apiserver-connectivity degradation (chaos/resilience work): set while
# the client's circuit breaker is not closed or request failures are
# landing inside the degraded window, cleared on recovery. Orthogonal to
# Ready — operands can be fully Ready while the control plane rides out
# a 429 storm on cached reads.
DEGRADED = "Degraded"


def _now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def set_condition(conditions: List[dict], type_: str, status: str, reason: str, message: str = "") -> List[dict]:
    """meta.SetStatusCondition semantics."""
    for cond in conditions:
        if cond.get("type") == type_:
            if cond.get("status") != status:
                cond["lastTransitionTime"] = _now()
            cond.update({"status": status, "reason": reason, "message": message})
            return conditions
    conditions.append(
        {
            "type": type_,
            "status": status,
            "reason": reason,
            "message": message,
            "lastTransitionTime": _now(),
        }
    )
    return conditions


def set_ready(conditions: Optional[List[dict]], reason: str = "Ready", message: str = "") -> List[dict]:
    conditions = conditions if conditions is not None else []
    set_condition(conditions, READY, "True", reason, message)
    set_condition(conditions, ERROR, "False", "NoError", "")
    return conditions


def set_not_ready(conditions: Optional[List[dict]], reason: str, message: str = "") -> List[dict]:
    conditions = conditions if conditions is not None else []
    set_condition(conditions, READY, "False", reason, message)
    set_condition(conditions, ERROR, "False", "NoError", "")
    return conditions


def set_error(conditions: Optional[List[dict]], reason: str, message: str) -> List[dict]:
    conditions = conditions if conditions is not None else []
    set_condition(conditions, READY, "False", reason, message)
    set_condition(conditions, ERROR, "True", reason, message)
    return conditions


def set_degraded(
    conditions: Optional[List[dict]], degraded: bool, message: str = ""
) -> List[dict]:
    conditions = conditions if conditions is not None else []
    if degraded:
        set_condition(conditions, DEGRADED, "True", "ApiserverDegraded", message)
    else:
        set_condition(conditions, DEGRADED, "False", "ApiserverHealthy", message)
    return conditions


def get_condition(conditions: List[dict], type_: str) -> Optional[dict]:
    for cond in conditions or []:
        if cond.get("type") == type_:
            return cond
    return None
