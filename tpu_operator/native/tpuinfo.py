"""ctypes wrapper for the native tpuinfo probe (native/tpuinfo.cc).

Self-builds with g++ on first use when the shared library is missing
(image builds run ``make -C native`` instead); falls back to a pure-Python
scan of the same device paths when no compiler is available.
"""

from __future__ import annotations

import ctypes
import glob
import json
import logging
import os
import subprocess
import threading
from typing import Optional

log = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_HERE, "libtpuinfo.so")
_SRC_PATH = os.path.join(os.path.dirname(os.path.dirname(_HERE)), "native", "tpuinfo.cc")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        if not os.path.exists(_SO_PATH):
            try:
                subprocess.run(
                    ["g++", "-O2", "-fPIC", "-Wall", "-std=c++17", "-shared",
                     "-o", _SO_PATH, _SRC_PATH],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
            except (OSError, subprocess.SubprocessError) as e:
                log.warning("tpuinfo native build failed (%s); using python fallback", e)
                _build_failed = True
                return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
            lib.tpuinfo_probe.argtypes = [ctypes.c_char_p, ctypes.c_int]
            lib.tpuinfo_probe.restype = ctypes.c_int
            lib.tpuinfo_fnv64.argtypes = [ctypes.c_char_p, ctypes.c_ulonglong]
            lib.tpuinfo_fnv64.restype = ctypes.c_ulonglong
            if hasattr(lib, "tpuinfo_chip_coords"):  # older prebuilt .so lacks it
                lib.tpuinfo_chip_coords.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
                lib.tpuinfo_chip_coords.restype = ctypes.c_int
            _lib = lib
            return lib
        except OSError as e:
            log.warning("tpuinfo load failed (%s); using python fallback", e)
            _build_failed = True
            return None


def _scan_root() -> str:
    """TPUINFO_SCAN_ROOT prefixes every probed path (same contract as the
    native probe): host-mounted-at-/host containers and simulated-device
    tests both point the scan at their root."""
    return os.environ.get("TPUINFO_SCAN_ROOT", "").rstrip("/")


def _python_probe() -> dict:
    root = _scan_root()
    devices = sorted(glob.glob(f"{root}/dev/accel*"))
    sys_devices = sorted(glob.glob(f"{root}/sys/class/accel/accel*"))
    vfio = [p for p in glob.glob(f"{root}/dev/vfio/*") if not p.endswith("/vfio")]
    return {
        "chip_count": max(len(devices), len(sys_devices)),
        "devices": devices,
        "vfio_groups": len(vfio),
    }


def probe() -> dict:
    """Device inventory: {"chip_count": N, "devices": [...], "vfio_groups": N}."""
    lib = _load()
    if lib is None:
        return _python_probe()
    buf = ctypes.create_string_buffer(64 * 1024)
    n = lib.tpuinfo_probe(buf, len(buf))
    if n < 0:
        return _python_probe()
    return json.loads(buf.value.decode())


def _python_chip_coords(chip_count: int) -> dict:
    bounds = None
    env = os.environ.get("TPU_CHIPS_PER_HOST_BOUNDS", "")
    if env:
        try:
            bx, by, bz = (int(p) for p in env.split(","))
            # same sanity cap as the native parser (tpuinfo.cc)
            if 0 < bx <= 64 and 0 < by <= 64 and 0 < bz <= 64 and bx * by * bz <= 4096:
                bounds = (bx, by, bz)
        except ValueError:
            pass
    if bounds is None:
        if chip_count <= 0:
            chip_count = _python_probe()["chip_count"]
        bounds = {8: (2, 4, 1), 4: (2, 2, 1), 2: (2, 1, 1)}.get(
            chip_count, (max(chip_count, 1), 1, 1)
        )
    bx, by, bz = bounds
    return {
        "bounds": [bx, by, bz],
        "coords": [[i % bx, (i // bx) % by, i // (bx * by)] for i in range(bx * by * bz)],
    }


def chip_coords(chip_count: int = 0) -> dict:
    """Per-chip (x,y,z) within this host's torus block, from the
    TPU_CHIPS_PER_HOST_BOUNDS contract (libtpu/GKE) or chip-count
    defaults: {"bounds": [x,y,z], "coords": [[x,y,z], ...]} indexed by
    local chip number (x fastest, libtpu's linearization)."""
    lib = _load()
    if lib is None or not hasattr(lib, "tpuinfo_chip_coords"):
        return _python_chip_coords(chip_count)
    buf = ctypes.create_string_buffer(64 * 1024)
    n = lib.tpuinfo_chip_coords(chip_count, buf, len(buf))
    if n < 0:
        return _python_chip_coords(chip_count)
    return json.loads(buf.value.decode())


def fnv64(data: bytes) -> int:
    """Native FNV-1a (same constants as tpu_operator.utils.fnv64a)."""
    lib = _load()
    if lib is None:
        from tpu_operator.utils import fnv64a

        return fnv64a(data)
    return int(lib.tpuinfo_fnv64(data, len(data)))
