"""Native components (C++), loaded over ctypes."""
