"""Operator version string (reference: internal/info/version.go)."""

__version__ = "0.1.0"
