from tpu_operator.render.render import Renderer, RenderError  # noqa: F401
