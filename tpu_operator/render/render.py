"""Manifest renderer.

Analog of the reference's ``internal/render``
(internal/render/render.go:49-151): walk a directory of templated YAML
manifests in lexical order, render each against a templating-data dict, and
decode every non-empty document into an unstructured object. Jinja2 stands
in for Go text/template+sprig; StrictUndefined gives the same
fail-on-missing-key behavior the reference relies on to catch bad render
data at sync time rather than apply time.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import jinja2
import yaml

from tpu_operator.kube.objects import ObjectDict

MANIFEST_SUFFIXES = (".yaml", ".yml", ".yaml.j2", ".yml.j2")


class RenderError(Exception):
    pass


def _to_yaml(value: Any, indent: int = 0) -> str:
    """Template filter mirroring the reference's custom ``yaml`` helper
    (render.go:64-75): dump a value as YAML, optionally indented so it can
    be spliced under a parent key."""
    dumped = yaml.safe_dump(value, default_flow_style=False, sort_keys=False).rstrip("\n")
    if indent:
        pad = " " * indent
        dumped = "\n".join(pad + line for line in dumped.splitlines())
    return dumped


class Renderer:
    """Renders all manifests under one or more directories."""

    def __init__(self, manifest_dirs: List[str]):
        self.manifest_dirs = list(manifest_dirs)
        self._env = jinja2.Environment(
            undefined=jinja2.StrictUndefined,
            trim_blocks=True,
            lstrip_blocks=True,
            keep_trailing_newline=True,
        )
        self._env.filters["to_yaml"] = _to_yaml

    def _manifest_files(self) -> List[str]:
        files: List[str] = []
        for directory in self.manifest_dirs:
            if not os.path.isdir(directory):
                raise RenderError(f"manifest dir not found: {directory}")
            entries = sorted(
                os.path.join(directory, f)
                for f in os.listdir(directory)
                if f.endswith(MANIFEST_SUFFIXES)
            )
            if not entries:
                raise RenderError(f"no manifests under {directory}")
            files.extend(entries)
        return files

    def render_objects(self, data: Optional[Dict[str, Any]] = None) -> List[ObjectDict]:
        """RenderObjects (render.go:77-151): all docs from all files, in
        file order, empty documents dropped."""
        data = data or {}
        objects: List[ObjectDict] = []
        for path in self._manifest_files():
            with open(path, "r") as f:
                source = f.read()
            try:
                text = self._env.from_string(source).render(**data)
            except jinja2.UndefinedError as e:
                raise RenderError(f"{path}: missing render data: {e}") from e
            except jinja2.TemplateError as e:
                raise RenderError(f"{path}: template error: {e}") from e
            try:
                docs = list(yaml.safe_load_all(text))
            except yaml.YAMLError as e:
                raise RenderError(f"{path}: rendered YAML invalid: {e}") from e
            for doc in docs:
                if not doc:
                    continue
                if "kind" not in doc or "apiVersion" not in doc:
                    raise RenderError(f"{path}: document missing kind/apiVersion")
                objects.append(doc)
        return objects
