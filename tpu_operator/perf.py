"""Per-generation performance roofs and floors.

The measured calibration layer between "published peak" and "alert
threshold". BENCH measured real sustained rates on v5e (185 bf16
TFLOP/s of a 197 published peak — 94% MXU utilization — and 665 GB/s
pallas-triad HBM bandwidth); other generations scale those measured
fractions onto their published peaks until someone benches them for
real. The floors the operator publishes (``default_floors``) sit at
``FLOOR_FRACTION`` of the measured roof: low enough that multi-tenant
jitter never trips them, high enough that a chip delivering 70% of what
its generation demonstrably sustains is a grey failure, not noise.

Consumers:
  - the perf-floors ConfigMap the pre-requisites state renders
    (``consts.PERF_FLOORS_CONFIGMAP``), read by the metrics exporter
    (grey-failure detection) and the validator (minTflops fallback);
  - ``controllers/fleet_telemetry`` (healthy-fleet TFLOP/s rollup);
  - the ROADMAP's capacity planner, which calibrates its analytical
    model against these same measured numbers.
"""

from __future__ import annotations

import json
from typing import Dict, Optional

# published dense bf16 peak TFLOP/s per chip. Deliberately a copy of
# workloads.matmul_bench.PEAK_TFLOPS rather than an import: that module
# imports jax at module scope and this one is loaded operator-side (the
# render path has no accelerator runtime). tests/test_telemetry.py pins
# the two tables equal.
PEAK_TFLOPS = {"v4": 275.0, "v5e": 197.0, "v5p": 459.0, "v6e": 918.0}

# measured on the v5e relay chip (BENCH rounds 3-6): sustained bf16
# matmul and pallas-triad HBM bandwidth under the two-point timing
# estimator — the numbers the utilization gauges already report against
MEASURED_V5E_TFLOPS = 185.0
MEASURED_V5E_TRIAD_GBPS = 665.0

# published HBM bandwidth per chip (GB/s) — scaled by the measured v5e
# triad fraction to estimate an achievable roof per generation
_PEAK_HBM_GBPS = {"v4": 1228.0, "v5e": 819.0, "v5p": 2765.0, "v6e": 1638.0}

# the fraction of published peak the v5e measurements demonstrated;
# applied to every generation's published numbers to seed its roof
_MXU_FRACTION = MEASURED_V5E_TFLOPS / PEAK_TFLOPS["v5e"]
_HBM_FRACTION = MEASURED_V5E_TRIAD_GBPS / _PEAK_HBM_GBPS["v5e"]

# floor = this fraction of the measured/derived roof: a sustained 30%
# shortfall against what the generation demonstrably sustains is a grey
# failure (the --telemetry-smoke scenario), not multi-tenant jitter
FLOOR_FRACTION = 0.7


def measured_roofs() -> Dict[str, Dict[str, float]]:
    """Per-generation achievable roofs: measured on v5e, measured-
    fraction-scaled published peaks elsewhere."""
    roofs: Dict[str, Dict[str, float]] = {}
    for gen in PEAK_TFLOPS:
        roofs[gen] = {
            "matmul_tflops": round(PEAK_TFLOPS[gen] * _MXU_FRACTION, 1),
            "triad_gbps": round(_PEAK_HBM_GBPS[gen] * _HBM_FRACTION, 1),
        }
    # the one generation with real measurements keeps them exactly
    roofs["v5e"] = {
        "matmul_tflops": MEASURED_V5E_TFLOPS,
        "triad_gbps": MEASURED_V5E_TRIAD_GBPS,
    }
    return roofs


def default_floors() -> Dict[str, Dict[str, float]]:
    """The floors the operator publishes: FLOOR_FRACTION of each roof."""
    return {
        gen: {probe: round(value * FLOOR_FRACTION, 1) for probe, value in roof.items()}
        for gen, roof in measured_roofs().items()
    }


def floors_json() -> str:
    """The ConfigMap's floors.json payload (sorted for stable renders)."""
    return json.dumps(default_floors(), sort_keys=True)


def floors_for(generation: str, floors_blob: Optional[str] = None) -> Dict[str, float]:
    """The floor map for one generation, from a floors.json blob (env /
    ConfigMap) falling back to the built-in defaults; {} when the
    generation is unknown or the blob is malformed (no floor -> no
    grey-failure detection, never a crash-looping exporter)."""
    table: Dict[str, Dict[str, float]] = {}
    if floors_blob:
        try:
            parsed = json.loads(floors_blob)
            if isinstance(parsed, dict):
                table = parsed
        except (ValueError, TypeError):
            table = {}
    if not table:
        table = default_floors()
    entry = table.get(generation)
    if not isinstance(entry, dict):
        return {}
    out: Dict[str, float] = {}
    for probe, value in entry.items():
        try:
            out[str(probe)] = float(value)
        except (TypeError, ValueError):
            continue
    return out
