"""Shared label / annotation / path constants.

TPU-native analog of the reference's internal/consts/consts.go:31-67. Where
the reference keys everything off ``nvidia.com/*`` labels fed by NFD's PCI
vendor detection (pci-10de), we key off the labels GKE already stamps on TPU
node pools (``cloud.google.com/gke-tpu-*``) plus our own
``tpu.google.com/*`` operator labels.
"""

# ---------------------------------------------------------------------------
# Node labels provided by the platform (GKE) — consumed, never written.
# ---------------------------------------------------------------------------
GKE_TPU_ACCELERATOR_LABEL = "cloud.google.com/gke-tpu-accelerator"
GKE_TPU_TOPOLOGY_LABEL = "cloud.google.com/gke-tpu-topology"
GKE_NODEPOOL_LABEL = "cloud.google.com/gke-nodepool"
OS_RELEASE_ID_LABEL = "feature.node.kubernetes.io/system-os_release.ID"
OS_RELEASE_VERSION_LABEL = "feature.node.kubernetes.io/system-os_release.VERSION_ID"
KERNEL_VERSION_LABEL = "feature.node.kubernetes.io/kernel-version.full"

# ---------------------------------------------------------------------------
# Node labels owned by the operator (reference: state_manager.go:50-117).
# ---------------------------------------------------------------------------
TPU_PRESENT_LABEL = "tpu.google.com/tpu.present"
TPU_WORKLOAD_CONFIG_LABEL = "tpu.google.com/tpu.workload.config"
COMMON_DEPLOY_LABEL_PREFIX = "tpu.google.com/tpu.deploy."

# Workload config values (reference: gpu-workload-configuration,
# state_manager.go:86-111). TPUs have no vGPU/passthrough; "container" is the
# only supported config today but the routing machinery is kept.
WORKLOAD_CONFIG_CONTAINER = "container"
DEFAULT_WORKLOAD_CONFIG = WORKLOAD_CONFIG_CONTAINER

# Labels written by tpu-feature-discovery (the GFD analog).
TFD_ACCELERATOR_TYPE_LABEL = "tpu.google.com/accelerator-type"
TFD_TOPOLOGY_LABEL = "tpu.google.com/topology"
TFD_CHIPS_PER_NODE_LABEL = "tpu.google.com/chips-per-node"
TFD_SLICE_HOSTS_LABEL = "tpu.google.com/slice-hosts"
TFD_TPU_GENERATION_LABEL = "tpu.google.com/generation"
TFD_LABELS = (
    TFD_ACCELERATOR_TYPE_LABEL,
    TFD_TOPOLOGY_LABEL,
    TFD_CHIPS_PER_NODE_LABEL,
    TFD_SLICE_HOSTS_LABEL,
    TFD_TPU_GENERATION_LABEL,
)

# Upgrade-state node label (reference: nvidia.com/gpu-driver-upgrade-state,
# vendor k8s-operator-libs/pkg/upgrade/consts.go).
UPGRADE_STATE_LABEL = "tpu.google.com/libtpu-upgrade-state"
UPGRADE_STATE_SINCE_ANNOTATION = "tpu.google.com/libtpu-upgrade-state-since"
UPGRADE_SKIP_DRAIN_POD_LABEL = "tpu.google.com/libtpu-upgrade-drain.skip"

# ---------------------------------------------------------------------------
# Node health (the DCGM-health → node-auto-repair analog). The health agent
# owns the health label/annotation/condition; the remediation controller
# owns the repair labels.
# ---------------------------------------------------------------------------
TPU_HEALTH_LABEL = "tpu.google.com/tpu.health"  # healthy | degraded
HEALTH_HEALTHY = "healthy"
HEALTH_DEGRADED = "degraded"
# JSON map of per-chip verdicts ({"accel0": "Healthy", ...}) published by
# the health agent alongside the summary label
TPU_HEALTH_CHIPS_ANNOTATION = "tpu.google.com/tpu.health.chips"
# when the current verdict was first observed (epoch seconds) — the
# remediation grace period is measured against it, so a node that is
# merely still PROVISIONING (libtpu installing, plugin not up yet) is
# not cordoned mid-install
TPU_HEALTH_SINCE_ANNOTATION = "tpu.google.com/tpu.health.since"
TPU_HEALTH_CONDITION = "TPUHealthy"  # node status condition type
# slice gang health: one degraded host marks every peer of its gang so
# multi-host workloads fail fast instead of hanging on a sick member
TPU_SLICE_HEALTH_LABEL = "tpu.google.com/slice.health"

# ---------------------------------------------------------------------------
# Topology-aware slice placement (tpu_operator/placement/). The placement
# controller owns the assignment labels; node discovery (or the platform)
# publishes the coordinate label; the slice manager consumes assignments.
# ---------------------------------------------------------------------------
# Host coordinate on the pool's ICI torus, "x-y-z" (e.g. "3-0-7"). On
# self-managed clusters node discovery derives it from TPU_WORKER_ID +
# the slice topology; absent coordinates degrade to a deterministic
# row-major layout over the pool's sorted node names.
TORUS_COORDS_LABEL = "tpu.google.com/torus-coords"
# Which TPUSlice placement owns this host (the gang the slice manager
# must materialize here) and the host's worker index within the placed
# block (row-major over the block shape: torus neighbors get adjacent
# worker ids, so gang hostlists follow the ICI wiring).
PLACEMENT_LABEL = "tpu.google.com/placement"
PLACEMENT_INDEX_LABEL = "tpu.google.com/placement-index"
# The placed block's CHIP topology (oriented host shape x per-host chip
# block, e.g. a 2x2x2-host block of 4-chip hosts -> "4x4x2"): what the
# slice manager advertises as TPU_TOPOLOGY in the gang env — a sub-block
# gang must not inherit the whole pool's topology
PLACEMENT_TOPOLOGY_LABEL = "tpu.google.com/placement-topology"
# re-plan cadence while placements are pending/unschedulable (capacity
# frees up without any watch event the queue predicate maps)
PLACEMENT_REPLAN_SECONDS = 15.0

# ---------------------------------------------------------------------------
# Data-plane telemetry & grey-failure detection. The metrics exporter
# compares its active probes against per-generation perf floors
# (published by the operator in the PERF_FLOORS_CONFIGMAP, seeded from
# the measured BENCH roofs in tpu_operator/perf.py) and on SUSTAINED
# breach stamps the perf label — a slow-but-alive chip leaves its gang
# the same way a dead one does (the health FSM's grey-failure path).
# ---------------------------------------------------------------------------
TPU_PERF_LABEL = "tpu.google.com/perf"  # degraded while below floor
PERF_DEGRADED = "degraded"
# rendered by the pre-requisites state (first in STATE_ORDER, so both
# consumers — exporter DaemonSet env and validator floors fallback —
# find it); per-generation JSON floor maps + one "floors.json" blob
PERF_FLOORS_CONFIGMAP = "tpu-perf-floors"
PERF_FLOORS_KEY = "floors.json"
# consecutive probe samples below floor before the exporter declares a
# sustained breach (one slow sample is noise — a co-tenant burst, a
# background compaction; N in a row over probe intervals is a grey
# failure)
PERF_BREACH_SAMPLES = 3
# gang step-time artifact: the merged per-host step report the slice
# manager publishes on the gang ConfigMap; the operator's fleet
# aggregation reads it back into the gang-level series
GANG_TELEMETRY_ANNOTATION = "tpu.google.com/gang-telemetry"
# slowest host's median step vs the gang median above this ratio is a
# straggler: a PerfDegraded Event fires and the rollup flags the gang
GANG_STRAGGLER_RATIO = 1.25

# ---------------------------------------------------------------------------
# ICI fabric telemetry (workloads/fabric.py -> controllers/
# fabric_telemetry.py). The fabric probe times every torus-axis link of
# a placed gang; the slice manager publishes the per-edge matrix beside
# the step-time artifact; the operator's fabric analyzer ingests it,
# assigns blame (link vs host), and feeds the placement engine's
# unavailable-EDGE support so gangs re-place around a bad cable instead
# of quarantining two healthy hosts.
# ---------------------------------------------------------------------------
# per-gang fabric artifact: edge bandwidth matrix + per-axis allreduce
# latency, published on the gang ConfigMap beside the telemetry artifact
GANG_FABRIC_ANNOTATION = "tpu.google.com/gang-fabric"
# per-pool link-health record the fabric analyzer maintains: one data
# key per pool, JSON {"edges": {"hostA|hostB": {...}}} — the placement
# controller reads it back as the engine's degraded-link input
LINK_HEALTH_CONFIGMAP = "tpu-link-health"
# an edge is degraded when its measured bandwidth falls below this
# fraction of the gang's median edge bandwidth — pool-relative, so the
# comparison self-calibrates per generation/payload instead of trusting
# a published point-to-point number nobody measured
FABRIC_LINK_DEGRADED_FRACTION = 0.5
# this many degraded edges sharing one endpoint indict the HOST (its
# ICI interface / chip, not N independent cables failing at once): the
# endpoint enters the perf-degraded grey-failure FSM. Below it, the
# LINK is blamed: recorded in the link-health map, both endpoints stay
# in service, and gangs straddling the edge re-place around it.
FABRIC_HOST_BLAME_EDGES = 2

# ---------------------------------------------------------------------------
# Per-generation kernel autotuning (workloads/autotune.py ->
# agents/autotune_agent.py -> controllers/autotune_controller.py). The
# controller elects ONE in-service node per un-swept TPU generation by
# label; the autotuner DaemonSet schedules only onto elected nodes (the
# label is in its nodeSelector, so the pod — and the chips it claims via
# the google.com/tpu resource — exists only for the sweep window), runs
# the sweep, and caches results per (generation, kernel family, shape
# class, libtpu version) in the results ConfigMap so a rebooted node or
# a late-joining node never re-sweeps. The controller folds measured
# winners into the perf-floors pipeline and publishes the winning
# configs for workloads to consume.
# ---------------------------------------------------------------------------
AUTOTUNE_ELECTED_LABEL = "tpu.google.com/autotune"
AUTOTUNE_ELECTED = "elected"
# per-generation sweep cache + published winners; data keys are
# "<generation>.json" entries plus the merged winners blob below
AUTOTUNE_RESULTS_CONFIGMAP = "tpu-autotune-results"
AUTOTUNE_WINNERS_KEY = "winners.json"
# the env workloads resolve tuned configs from (configMapKeyRef onto the
# winners blob; absent -> hand-swept defaults)
AUTOTUNE_ENV = "TPU_AUTOTUNE_JSON"
# re-check cadence while any generation is un-swept (the sweep finishes
# without any watch event the predicate maps once the agent publishes,
# but a crashed elected node must be re-elected on a timer)
AUTOTUNE_REPLAN_SECONDS = 30.0

# ---------------------------------------------------------------------------
# Persistent XLA compile cache + AOT prewarm (workloads/compilecache.py
# -> agents/compilecache_agent.py -> controllers/compilecache_controller
# .py). Compiled-executable records are content-addressed by
# (generation, topology, model descriptor hash, libtpu version): on real
# TPU the record fronts JAX's persistent compilation cache directory; on
# the CPU sim it records and replays measured warmup durations so cache
# hit vs miss stays an observable, benchable quantity. The serving
# controller writes prewarm REQUESTS (the one key it owns here) when an
# imminent scale-up implies an uncached key; the compile-cache
# controller elects one in-service node per generation with unsatisfied
# demand (the autotune election idiom — the label is in the DaemonSet's
# nodeSelector, so the prewarm pod exists only for the compile window);
# the elected agent compiles, publishes the record, and ACKs. Entries
# invalidate on libtpu image-tag change exactly like
# tpu-autotune-results; steady state is zero writes.
# ---------------------------------------------------------------------------
COMPILE_CACHE_ELECTED_LABEL = "tpu.google.com/compile-cache"
COMPILE_CACHE_ELECTED = "elected"
# per-generation compiled-executable records; data keys are
# "<generation>.json" entries plus the two handshake keys below
COMPILE_CACHE_CONFIGMAP = "tpu-compile-cache"
# prewarm handshake rides DISJOINT keys (the K002 convention): the
# serving controller owns the request map, the prewarm agent (via the
# workloads/compilecache publish helper) owns the ack map
COMPILE_PREWARM_REQUEST_KEY = "prewarm-requests.json"
COMPILE_PREWARM_ACK_KEY = "prewarm-acks.json"
# the directory JAX's persistent compilation cache is bound to on real
# TPU nodes (hostPath-backed on the DaemonSet; env-overridable)
COMPILE_CACHE_DIR_ENV = "TPU_COMPILE_CACHE_DIR"
COMPILE_CACHE_DIR_DEFAULT = "/var/cache/tpu-compile"
# re-check cadence while any prewarm demand is unsatisfied (a crashed
# elected node must be re-elected on a timer, like autotune)
COMPILE_CACHE_REPLAN_SECONDS = 30.0

# ---------------------------------------------------------------------------
# Elastic fault-tolerant training jobs (api/tpujob.py ->
# controllers/job_controller.py -> workloads/training.py). The job
# controller owns one TPUSlice per TPUJob (named <job> + JOB_SLICE_SUFFIX)
# and drives shrink/grow by patching its placement shape; the data plane
# (the gang's trainer) and the control plane meet at the job progress
# ConfigMap (<job> + JOB_PROGRESS_SUFFIX): the trainer publishes step /
# checkpoint watermarks, the controller reads them into status.job and
# writes the one key it owns (the pre-grow checkpoint barrier request).
# ---------------------------------------------------------------------------
JOB_SLICE_SUFFIX = "-slice"
JOB_PROGRESS_SUFFIX = "-progress"
# trainer-owned progress keys
JOB_PROGRESS_STEP = "step"                      # last completed train step
JOB_PROGRESS_EPOCH = "checkpointEpoch"          # newest checkpoint epoch
JOB_PROGRESS_CHECKPOINT_STEP = "checkpointStep"  # step that epoch covers
JOB_PROGRESS_WORLD = "world"                    # hosts the trainer is sized for
JOB_PROGRESS_STATUS = "status"                  # running | complete | error
JOB_PROGRESS_ERROR = "error"                    # last trainer error text
JOB_PROGRESS_CHECKPOINT_ACK = "checkpointAck"   # echoes the barrier token
JOB_PROGRESS_RUNNING = "running"
JOB_PROGRESS_COMPLETE = "complete"
JOB_PROGRESS_FAILED = "error"
# controller-owned progress key: the pre-grow checkpoint barrier (the
# trainer checkpoints and echoes the token into checkpointAck; only then
# does the controller patch the slice shape up, so a planned grow loses
# zero steps)
JOB_CHECKPOINT_REQUEST = "checkpointRequest"
# controller-owned restart handshake: on a trainer error the controller
# burns a restart unit and bumps this token; the gang resumes from the
# newest good checkpoint and echoes it (the in-cluster analog of fresh
# worker pods replacing crashed ones)
JOB_RESTART_REQUEST = "restartRequest"
JOB_PROGRESS_RESTART_ACK = "restartAck"
# restart-attempt counter persisted on the TPUJob (kube/backoff.py
# annotation-counter shape, same idea as REPAIR_RETRIES_ANNOTATION):
# consecutive failed attempts; reset when the job reaches Running
JOB_RESTARTS_ANNOTATION = "tpu.google.com/job-restarts"
# re-check cadence while a job is non-terminal: grow opportunities and
# trainer progress don't always map to a watch event the predicate keeps
JOB_RESYNC_SECONDS = 5.0
# status.job history bounds (shrink/grow history, last restart causes)
JOB_HISTORY_LIMIT = 10
JOB_CAUSES_LIMIT = 5

# ---------------------------------------------------------------------------
# Pod data plane (tpu_operator/dataplane/). The job and serving
# controllers render one worker Pod per gang member / per replica
# through the same manifest-render + hash-converge machinery the slice
# manager agent uses for its gang pods. A worker pod's main is selected
# by POD_MAIN_LABEL; the sim kubelet (kube/sim.py PodKubelet) resolves
# the label value against the dataplane worker registry and runs the
# main in a thread, so the whole data plane proves out on the CPU sim.
# Workers rendezvous through the job progress ConfigMap: each member
# publishes rendezvous.<index> = its gang hash, and the chief gates
# training until every expected index has checked in with the same
# hash (a stale hash is a worker from a previous generation).
# ---------------------------------------------------------------------------
POD_MAIN_LABEL = "tpu.google.com/pod-main"
POD_MAIN_JOB_WORKER = "tpu-job-worker"
POD_MAIN_SERVING_WORKER = "tpu-serving-worker"
# spec-hash annotation on rendered worker pods (same delete+recreate
# convergence as GANG_HASH_ANNOTATION on the slice manager's gang pods)
WORKER_HASH_ANNOTATION = "tpu.google.com/worker-hash"
# router-weight annotation the serving controller patches onto decode
# worker pods so the data-plane router can read its weights from the
# pods themselves (the load-CM routing key stays authoritative)
WORKER_ROUTE_WEIGHT_ANNOTATION = "tpu.google.com/route-weight"
# worker env contract (rendered into the pod spec, read by pod mains)
WORKER_ENV_JOB_NAME = "TPU_JOB_NAME"
WORKER_ENV_WORKER_INDEX = "TPU_WORKER_INDEX"
WORKER_ENV_WORKER_COUNT = "TPU_WORKER_COUNT"
WORKER_ENV_GANG_HASH = "TPU_GANG_HASH"
WORKER_ENV_CHECKPOINT_DIR = "TPU_CHECKPOINT_DIR"
WORKER_ENV_SERVING_NAME = "TPU_SERVING_NAME"
WORKER_ENV_REPLICA_NAME = "TPU_REPLICA_NAME"
WORKER_ENV_POOL = "TPU_POOL"
WORKER_ENV_NAMESPACE = "TPU_NAMESPACE"
WORKER_ENV_STEPS_PER_SYNC = "TPU_STEPS_PER_SYNC"
# compile-cache addressing for serving workers: the replica's chip
# generation and topology (shape string), so the worker's warmup step
# can resolve — and on a miss, publish — its compile-cache record
WORKER_ENV_GENERATION = "TPU_GENERATION"
WORKER_ENV_TOPOLOGY = "TPU_TOPOLOGY"
# worker pod name shapes: <job> + JOB_WORKER_INFIX + <member index>,
# <serving> + SERVING_PREFILL_INFIX/SERVING_DECODE_INFIX + <index>
JOB_WORKER_INFIX = "-worker-"
# worker-owned progress-CM key prefix (disjoint from the trainer's and
# the controllers' keys): rendezvous.<index> = gang hash
JOB_RENDEZVOUS_PREFIX = "rendezvous."

# ---------------------------------------------------------------------------
# Traffic-driven elastic serving (api/tpuserving.py ->
# controllers/serving_controller.py -> workloads/serving.py). The
# serving controller owns one TPUSlice per replica (named <serving> +
# SERVING_REPLICA_INFIX + index) and scales the replica set through the
# placement engine from observed demand. Demand and the controller's
# routing decision meet at the load ConfigMap (<serving> +
# SERVING_LOAD_SUFFIX): the traffic side (router/sim) publishes arrival
# rate, queue depth and measured TTFT; the controller reads them into
# status.serving and writes the one key it owns (the routing-weight
# map, which the router consumes on its next tick).
# ---------------------------------------------------------------------------
SERVING_REPLICA_INFIX = "-replica-"
SERVING_LOAD_SUFFIX = "-load"
# traffic-side load keys
SERVING_LOAD_ARRIVAL_RATE = "arrivalRate"     # requests/s (EWMA over ticks)
SERVING_LOAD_QUEUE_DEPTH = "queueDepth"       # requests waiting for a slot
SERVING_LOAD_TTFT_P50 = "ttftP50"             # measured, seconds
SERVING_LOAD_TTFT_P99 = "ttftP99"             # measured, seconds
SERVING_LOAD_TOKENS_PER_S = "tokensPerS"      # aggregate decode throughput
# controller-owned load key: JSON {replica slice name: weight}; the
# router routes only to weight > 0 (degraded-fabric and unplaced
# replicas are excluded here, not by every router re-deriving blame)
SERVING_ROUTING_KEY = "routing"
# autoscaler cadence while a serving is non-terminal (demand moves
# without any watch event the predicate maps)
SERVING_RESYNC_SECONDS = 5.0
# hysteresis: scale-ups are immediate (a burst is exactly when capacity
# is needed); scale-downs wait until demand has sat below the shrunk
# capacity for a full cooldown — a diurnal lull shrinks the fleet, a
# burst's trailing edge doesn't flap it
SERVING_SCALE_DOWN_COOLDOWN_SECONDS = 30.0
# scale down only when demand fits the shrunk replica set at this
# utilization (head-room so the next tick's noise doesn't re-breach)
SERVING_SCALE_DOWN_HEADROOM = 0.8
# status.serving scale-decision history bound (last N with reasons)
SERVING_DECISIONS_LIMIT = 5

# ---------------------------------------------------------------------------
# Disaggregated prefill/decode pools (spec.disaggregation on TPUServing).
# Prefill replicas (compute-rich shapes) chunk-prefill prompts and hand
# the paged KV to a decode replica; each pool autoscales on its own
# signal — prefill on TTFT p99 against the SLO, decode on tokens/s
# demand — published under its own load-CM keys so neither pool's
# controller re-derives the other's blame.
# ---------------------------------------------------------------------------
SERVING_PREFILL_INFIX = "-prefill-"
SERVING_DECODE_INFIX = "-decode-"
SERVING_POOL_PREFILL = "prefill"
SERVING_POOL_DECODE = "decode"
# traffic-side per-pool load keys (alongside the aggregate keys above)
SERVING_LOAD_PREFILL_TTFT_P99 = "prefillTtftP99"   # seconds, prefill pool only
SERVING_LOAD_DECODE_TOKENS_PER_S = "decodeTokensPerS"  # decode pool throughput
SERVING_LOAD_KV_HIT_RATIO = "kvHitRatio"           # router KV reuse [0,1]
SERVING_LOAD_HANDOFF_BYTES = "handoffBytes"        # cumulative prefill->decode KV bytes
# controller-owned load key: JSON {pool name: replica count} so the
# router and must-gather see the pool split without listing slices
SERVING_POOLS_KEY = "pools"

# ---------------------------------------------------------------------------
# Capacity planning & scheduled defragmentation (tpu_operator/planning/
# + controllers/defrag_controller.py). The defrag controller proposes at
# most one migration per pass, only inside an idle window (no placement
# in flight, fleet demand below the headroom fraction), and executes it
# through the owning workload's own safe path: a TPUJob gang migrates
# behind the PR 13 checkpoint barrier (the defrag-owned progress-CM
# request key below), a TPUServing replica through the drain-then-
# re-place path (its router weight drops to zero the moment the gang is
# torn down, and the engine re-seats it). Gangs owned by neither are
# never touched. Budget + cooldown below are what make thrash
# structurally impossible: a migration costs a checkpoint/drain, so the
# controller must never spend more than the budget per window no matter
# how the fragmentation series wiggles.
# ---------------------------------------------------------------------------
DEFRAG_STATE_CONFIGMAP = "tpu-defrag-state"   # decision history + budget ledger
DEFRAG_STATE_KEY = "state.json"
DEFRAG_REPLAN_SECONDS = 30.0                  # pass cadence while idle
DEFRAG_COOLDOWN_SECONDS = 300.0               # min gap between migrations
DEFRAG_MIGRATION_BUDGET = 2                   # max migrations per window
DEFRAG_BUDGET_WINDOW_SECONDS = 1800.0
DEFRAG_UTILIZATION_HEADROOM = 0.9             # no defrag above this utilization
DEFRAG_MIN_FRAG_GAIN = 0.02                   # deltas below this are noise
DEFRAG_DECISIONS_LIMIT = 5                    # state-CM history bound
# defrag-controller-owned progress-CM key (disjoint from the job
# controller's checkpointRequest/restartRequest and the trainer's acks):
# a new token here asks the job controller to checkpoint-barrier and
# re-place the gang at the barrier — the job controller records the
# token it honored in status.job.defragHandled so a token is never
# executed twice
JOB_DEFRAG_REQUEST = "defragRequest"

# ---------------------------------------------------------------------------
# Predictive health (PR 19): per-host risk scoring + proactive migration.
# The PR 7/8 telemetry precedes hard failures — a straggling host's
# gang-artifact ratio climbs, its ICI edges decay into the link-health
# map, the exporter's perf verdict flips — so the risk scorer folds
# those precursors (plus the repair FSM's retry history) into one
# per-host score. Over RISK_THRESHOLD the controller moves work off
# the host while it is still alive: a TPUJob gang behind the SAME
# checkpoint barrier the defrag path rides (zero lost steps), a
# TPUServing replica through drain-then-re-place (never the last
# routable sibling). Scores decay multiplicatively per pass once the
# signal clears, so a false alarm releases its budget instead of
# pinning the host risky forever. Stale artifacts (publisher no longer
# placed where the artifact says) score as NO signal — the same
# convention the fabric analyzer applies before blaming a host.
# ---------------------------------------------------------------------------
RISK_STATE_CONFIGMAP = "tpu-node-risk"        # scores + budget + migration log
RISK_STATE_KEY = "risk.json"
RISK_THRESHOLD = 0.6                          # act at/above this score
RISK_DECAY = 0.7                              # per-pass multiplicative decay
RISK_SCORE_FLOOR = 0.05                       # below this the host leaves the ledger
RISK_WEIGHT_STRAGGLER = 1.0                   # x (ratio - 1.0), capped at 1.0
RISK_WEIGHT_FABRIC_EDGE = 0.25                # per degraded ICI edge touching the host
RISK_WEIGHT_GREY = 0.5                        # exporter perf verdict (grey failure)
RISK_WEIGHT_REPAIR = 0.15                     # per recorded repair retry, capped
RISK_WEIGHT_REPAIR_CAP = 0.3
# per-host migration budget: a noisy scorer must never thrash a gang
# with repeated planned migrations — each request charges the host's
# RetryBudget and persists nextAttemptAt in the state CM (K005), and a
# host whose risk subsides without dying settles realized=false and
# releases the budget
RISK_MIGRATION_RETRY_LIMIT = 3
RISK_MIGRATION_BASE_SECONDS = 60.0
RISK_MIGRATION_MAX_SECONDS = 900.0
RISK_MIGRATIONS_LIMIT = 5                     # state-CM migration-log bound
# predicted-vs-realized settlement: a prediction may settle FALSE only
# once the score has subsided AND the grace window passed (the kill the
# precursor announced needs time to land — settling false the pass
# after the gang walks away would mislabel every correct prediction,
# because migrating away is exactly what makes the signal go stale);
# an unsettled prediction expires false at the timeout either way
RISK_SETTLE_GRACE_SECONDS = 120.0
RISK_SETTLE_TIMEOUT_SECONDS = 1800.0          # unsettled predictions expire false
# risk-controller-owned progress-CM key (disjoint from defragRequest and
# the job controller's own keys): a new token asks the job controller to
# checkpoint-barrier and re-place the gang — honored tokens land in
# status.job.riskHandled so redelivery never migrates twice
JOB_RISK_MIGRATE_REQUEST = "riskMigrateRequest"

# ---------------------------------------------------------------------------
# Multi-tenant fairness (PR 20): TPUQuota + DRF fair-share + the
# preemption economy. Tenancy is resolved from TENANT_LABEL on
# TPUSlice/TPUJob/TPUServing (dotted hierarchy, e.g. "acme.search" —
# "/" is illegal in a label value); TPUQuota objects declare per-level
# guaranteed chips × generation and a fair-share weight. With zero
# TPUQuota objects the placement engine's admission stays byte-identical
# to stock priority-then-FIFO (the node_risk empty-map convention).
# Preemption decisions and per-tenant time-to-place samples are booked
# into the controller-owned ledger CM; an unreadable ledger fails the
# pass CLOSED (K003) — a quota-blind write could mask a cross-tenant
# eviction from the audit trail.
# ---------------------------------------------------------------------------
TENANT_LABEL = "tpu.google.com/tenant"        # dotted tenant path (org.team.class)
TENANT_DEFAULT = "default"                    # untenanted workloads account here
TENANCY_LEDGER_CONFIGMAP = "tpu-tenancy-ledger"
TENANCY_DECISIONS_KEY = "decisions.json"      # bounded preemption-decision log
TENANCY_PLACEMENTS_KEY = "placements.json"    # per-tenant time-to-place samples
TENANCY_DECISIONS_LIMIT = 50                  # ledger decision-log bound
TENANCY_PLACEMENT_SAMPLES_LIMIT = 64          # per-tenant sample-ring bound
TENANCY_RESYNC_SECONDS = 30.0                 # tenancy controller resync cadence

# Repair FSM state (cordon → evict → reinstall → revalidate → uncordon,
# terminal: quarantined), persisted on the node like the upgrade FSM's.
REPAIR_STATE_LABEL = "tpu.google.com/tpu.repair-state"
REPAIR_STATE_SINCE_ANNOTATION = "tpu.google.com/tpu.repair-state-since"
REPAIR_RETRIES_ANNOTATION = "tpu.google.com/tpu.repair-retries"
# earliest unix time the next repair attempt may charge the retry
# budget: persisted alongside the counter so a watch-event storm (or an
# operator crash-loop) cannot burn the budget faster than the backoff
# schedule — the same nextAttemptAt gate the TPUJob FSM rides
REPAIR_NEXT_ATTEMPT_ANNOTATION = "tpu.google.com/tpu.repair-next-attempt-at"
# what put the node into repair: "health" (the agent's probe verdict) or
# "perf" (the exporter's sustained floor breach) — revalidation reads it
# to know which signal must clear before the node may uncordon
REPAIR_REASON_ANNOTATION = "tpu.google.com/tpu.repair-reason"
REPAIR_REASON_HEALTH = "health"
REPAIR_REASON_PERF = "perf"

# Host path shared between the health agent (writer) and the device plugin
# (reader): per-chip verdict file consumed by ListAndWatch.
HEALTH_DIR = "/run/tpu/health"
HEALTH_VERDICTS_FILE = "verdicts.json"

# ---------------------------------------------------------------------------
# Annotations.
# ---------------------------------------------------------------------------
LAST_APPLIED_HASH_ANNOTATION = "tpu.google.com/last-applied-hash"
# Apply-set ownership record (the server-side-apply analog,
# kube/objects.py apply_set_merge): one annotation per field manager,
# ``<prefix><manager>`` -> JSON of the label/annotation key→value maps
# that manager last applied. Lets a label-sweep writer declare its
# desired owned set in ONE write — removals derive from the record, not
# from a read-modify-write loop, and survive operator restarts.
APPLY_SET_ANNOTATION_PREFIX = "tpu.google.com/apply-set."
# the node labeller's field-manager identity (clusterpolicy controller)
APPLY_SET_MANAGER_LABELLER = "tpu-operator-labeller"
# the slice manager's worker-identity field manager
APPLY_SET_MANAGER_SLICE = "tpu-slice-manager"
DRIVER_AUTO_UPGRADE_ANNOTATION = "tpu.google.com/libtpu-auto-upgrade-enabled"
STATE_LABEL = "tpu.google.com/operator.state"  # ownership label for cleanup

# ---------------------------------------------------------------------------
# The extended resource advertised by the device plugin.
# ---------------------------------------------------------------------------
TPU_RESOURCE_NAME = "google.com/tpu"

# ---------------------------------------------------------------------------
# Validation status files (reference: /run/nvidia/validations,
# validator/main.go:131-166). These are the cross-DaemonSet barrier: every
# operand's init container polls for the file of the component it needs.
# ---------------------------------------------------------------------------
VALIDATION_DIR = "/run/tpu/validations"
LIBTPU_READY_FILE = "libtpu-ready"
PLUGIN_READY_FILE = "plugin-ready"
WORKLOAD_READY_FILE = "workload-ready"
METRICS_READY_FILE = "metrics-ready"
ALL_READY_FILE = "all-ready"

# Host paths.
LIBTPU_INSTALL_DIR = "/home/kubernetes/bin/tpu"  # where libtpu.so lands
LIBTPU_CTR_READY_FILE = ".libtpu-ctr-ready"

# ---------------------------------------------------------------------------
# Operator runtime.
# ---------------------------------------------------------------------------
OPERATOR_NAMESPACE_ENV = "OPERATOR_NAMESPACE"
DEFAULT_OPERATOR_NAMESPACE = "tpu-operator"
CLUSTER_POLICY_NAME_LABEL = "app.kubernetes.io/managed-by"
OPERATOR_NAME = "tpu-operator"

# Requeue / poll intervals (reference: clusterpolicy_controller.go:165,199).
REQUEUE_NOT_READY_SECONDS = 5.0
REQUEUE_NO_TPU_NODES_SECONDS = 45.0
UPGRADE_REPLAN_SECONDS = 120.0
HEALTH_REPLAN_SECONDS = 30.0
# Node-event burst coalescing: watch events landing within this window
# collapse into one reconcile (a label sweep fans out one event per node)
NODE_EVENT_COALESCE_SECONDS = 0.05

# ---------------------------------------------------------------------------
# Apiserver-client resilience (kube/retry.py + http_client.py): retry
# budget and full-jitter backoff for idempotent verbs on 5xx/transport
# errors, a per-request wall-clock deadline, and the circuit breaker
# that fail-fasts while the apiserver is unreachable so controllers park
# work via add_rate_limited instead of hot-looping on long timeouts.
# ---------------------------------------------------------------------------
API_RETRY_BUDGET = 4  # max re-sends of one logical request
API_RETRY_BASE_DELAY_SECONDS = 0.1  # full-jitter backoff: uniform(0, base*2^n)
API_RETRY_MAX_DELAY_SECONDS = 2.0
API_REQUEST_DEADLINE_SECONDS = 20.0  # retries never push one request past this
API_BREAKER_FAILURE_THRESHOLD = 5  # consecutive transport failures -> open
API_BREAKER_RESET_SECONDS = 5.0  # open -> half-open probe interval
# "apiserver degraded" window for the status condition: degraded while
# the breaker is not closed, or this many request failures landed within
# the window (retried-and-recovered attempts count — flakiness IS the
# signal)
API_DEGRADED_FAILURE_THRESHOLD = 3
API_DEGRADED_WINDOW_SECONDS = 10.0
REQUEUE_DEGRADED_SECONDS = 5.0  # re-check cadence while Degraded is set
# slow heartbeat at the Ready terminal (controller-runtime SyncPeriod
# analog): a quiet Ready cluster generates no events, so without it a
# degradation that BEGINS while quiet (watch reconnects failing feed the
# resilience state but enqueue nothing) would never surface as the
# Degraded condition until some unrelated event landed. Costs one
# cached-read reconcile (zero writes when nothing changed) per interval.
READY_RESYNC_SECONDS = 60.0
# watch-stream stall detection: no bytes (events, bookmarks, heartbeats)
# for this long -> abandon the stream and re-list. Real apiservers
# bookmark periodically; the in-repo fake heartbeats every ~5 s idle.
WATCH_STALL_SECONDS = 300.0

# ---------------------------------------------------------------------------
# Flight recorder (kube/trace.py): every reconcile produces a trace
# (queue wait + body + every apiserver call inside it); completed traces
# land in a process-wide ring buffer bounded by these knobs — always-on
# observability whose memory ceiling is fixed by construction, not by
# workload behavior. Dumped by `tpuop-cfg must-gather` (traces.txt /
# slow-reconciles.txt) and aggregated by bench.py's attribution block.
# ---------------------------------------------------------------------------
FLIGHT_RECORDER_CAPACITY = 256  # completed traces held (oldest evicted)
FLIGHT_RECORDER_MAX_SPANS_PER_TRACE = 512  # per-trace span cap (excess counted)

# Container runtimes (reference: getRuntime state_manager.go:714-751).
RUNTIME_CONTAINERD = "containerd"
RUNTIME_CRIO = "crio"
RUNTIME_DOCKER = "docker"
