"""Default operand images + env-var fallbacks.

The reference resolves operand images from the CR with an env-var fallback
for OLM digest pinning (internal/image/image.go:45-49, env names like
``VALIDATOR_IMAGE``). Same contract here: CR fields win, then the env var,
then the built-in default.
"""

from __future__ import annotations

import os

# operand key -> (env var, default image)
DEFAULTS = {
    "libtpu": ("LIBTPU_INSTALLER_IMAGE", "gcr.io/tpu-operator/libtpu-installer:1.0.0"),
    "device_plugin": ("TPU_DEVICE_PLUGIN_IMAGE", "gcr.io/tpu-operator/tpu-device-plugin:1.0.0"),
    "tfd": ("TPU_FEATURE_DISCOVERY_IMAGE", "gcr.io/tpu-operator/tpu-feature-discovery:1.0.0"),
    # the discovery bootstrap ships in the validator/agents image (same
    # codebase as the other agents; shim: tpu-node-discovery)
    "node_discovery": ("VALIDATOR_IMAGE", "gcr.io/tpu-operator/tpu-operator-validator:1.0.0"),
    "slice_manager": ("TPU_SLICE_MANAGER_IMAGE", "gcr.io/tpu-operator/tpu-slice-manager:1.0.0"),
    "metrics_exporter": ("TPU_METRICS_EXPORTER_IMAGE", "gcr.io/tpu-operator/tpu-metrics-exporter:1.0.0"),
    "node_status_exporter": ("VALIDATOR_IMAGE", "gcr.io/tpu-operator/tpu-operator-validator:1.0.0"),
    "validator": ("VALIDATOR_IMAGE", "gcr.io/tpu-operator/tpu-operator-validator:1.0.0"),
    # the health agent ships in the validator/agents image (shim:
    # tpu-health-monitor), like the discovery bootstrap
    "health_monitor": ("VALIDATOR_IMAGE", "gcr.io/tpu-operator/tpu-operator-validator:1.0.0"),
    # the autotune sweep agent also ships in the validator/agents image
    # (shim: tpu-autotuner) — its payloads ARE the validator's kernels
    "autotuner": ("VALIDATOR_IMAGE", "gcr.io/tpu-operator/tpu-operator-validator:1.0.0"),
    # the compile prewarm agent ships in the validator/agents image too
    # (shim: tpu-compile-cache) — it compiles the serving payloads
    "compile_cache": ("VALIDATOR_IMAGE", "gcr.io/tpu-operator/tpu-operator-validator:1.0.0"),
}


def resolve(component: str, spec) -> str:
    """CR image fields -> env fallback -> built-in default."""
    env_var, default = DEFAULTS[component]
    path = spec.image_path(env_var)
    return path or os.environ.get(env_var, "") or default
