"""Validating admission webhook.

Reference: the manager's webhook server on :9443
(cmd/gpu-operator/main.go). Serves AdmissionReview v1 at:

    /validate-clusterpolicy   lint (tpuop-cfg rules) + singleton guard
    /validate-tpuslice        lint + node-selector disjointness

Rejecting bad CRs at admission gives users immediate feedback instead of
an Error condition minutes later.
"""

from __future__ import annotations

import json
import logging
import os
import ssl
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional, Tuple

from tpu_operator.api.clusterpolicy import CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND
from tpu_operator.api.tpuslice import TPUSlice
from tpu_operator.controllers.tpuslice_validator import ValidationError, validate_node_selectors
from tpu_operator.kube.client import Client

log = logging.getLogger(__name__)


def review_clusterpolicy(client: Optional[Client], obj: dict, operation: str) -> List[str]:
    from tpu_operator.cmd.tpuop_cfg import validate_clusterpolicy

    problems = validate_clusterpolicy(obj)
    if client is not None and operation == "CREATE":
        existing = client.list(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND)
        others = [o for o in existing if o["metadata"]["name"] != obj.get("metadata", {}).get("name")]
        if others:
            problems.append(
                "a ClusterPolicy already exists "
                f"({others[0]['metadata']['name']}); the CRD is a cluster singleton"
            )
    return problems


def review_tpuslice(client: Optional[Client], obj: dict, operation: str) -> List[str]:
    from tpu_operator.cmd.tpuop_cfg import validate_tpuslice

    problems = validate_tpuslice(obj)
    if client is not None and not problems:
        try:
            validate_node_selectors(client, TPUSlice.from_unstructured(obj))
        except ValidationError as e:
            problems.append(str(e))
    return problems


def handle_review(client: Optional[Client], path: str, review: dict) -> dict:
    """AdmissionReview in -> AdmissionReview out."""
    request = review.get("request", {}) or {}
    obj = request.get("object", {}) or {}
    operation = request.get("operation", "CREATE")
    if path.endswith("clusterpolicy"):
        problems = review_clusterpolicy(client, obj, operation)
    elif path.endswith("tpuslice"):
        problems = review_tpuslice(client, obj, operation)
    else:
        problems = [f"unknown webhook path {path}"]
    response = {"uid": request.get("uid", ""), "allowed": not problems}
    if problems:
        response["status"] = {"code": 422, "message": "; ".join(problems)}
    return {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview", "response": response}


class WebhookServer:
    """The apiserver only calls webhooks over HTTPS: pass cert/key paths
    (mounted from the webhook Secret) to serve TLS like the reference's
    :9443 server; plain HTTP is for tests only."""

    def __init__(
        self,
        client: Optional[Client],
        addr: Tuple[str, int] = ("0.0.0.0", 9443),
        cert_file: Optional[str] = None,
        key_file: Optional[str] = None,
    ):
        self.client = client
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802
                length = int(self.headers.get("Content-Length", 0))
                try:
                    review = json.loads(self.rfile.read(length))
                    result = handle_review(outer.client, self.path, review)
                    code = 200
                except Exception as e:  # noqa: BLE001 — malformed review
                    result = {"error": str(e)}
                    code = 400
                body = json.dumps(result).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        self.server = ThreadingHTTPServer(addr, Handler)
        self._cert_file, self._key_file = cert_file, key_file
        self._ssl_ctx: Optional[ssl.SSLContext] = None
        if cert_file and key_file:
            self._ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            self._ssl_ctx.load_cert_chain(cert_file, key_file)
            self.server.socket = self._ssl_ctx.wrap_socket(self.server.socket, server_side=True)

    def reload_certs(self) -> None:
        """Re-read the serving chain from disk into the live SSL context:
        new handshakes pick up a rotated cert with zero downtime (existing
        connections finish on the old one)."""
        if self._ssl_ctx is not None and self._cert_file and self._key_file:
            self._ssl_ctx.load_cert_chain(self._cert_file, self._key_file)

    @property
    def address(self) -> Tuple[str, int]:
        return self.server.server_address

    def start(self) -> "WebhookServer":
        threading.Thread(target=self.server.serve_forever, daemon=True).start()
        return self

    def stop(self) -> None:
        self.server.shutdown()


def generate_self_signed_cert(directory: str, hostname: str = "tpu-operator-webhook") -> Tuple[str, str, str]:
    """Dev/bootstrap helper: CA-signed serving cert pair on disk. Returns
    (cert_path, key_path, ca_bundle_b64) — the bundle goes into the
    ValidatingWebhookConfiguration's clientConfig.caBundle. Thin wrapper
    over the certs module (WebhookCertManager owns the production
    rotation loop)."""
    import base64

    from cryptography.hazmat.primitives import serialization

    from tpu_operator import certs

    ca_cert, ca_key = certs.make_ca(f"{hostname}-ca", 365 * certs.DAY)
    cert_pem, key_pem = certs.issue_serving_cert(
        ca_cert,
        ca_key,
        hostname,
        [hostname, f"{hostname}.tpu-operator.svc"],
        365 * certs.DAY,
    )
    os.makedirs(directory, exist_ok=True)
    cert_path = os.path.join(directory, "tls.crt")
    key_path = os.path.join(directory, "tls.key")
    with open(cert_path, "wb") as f:
        f.write(cert_pem)
    with open(key_path, "wb") as f:
        f.write(key_pem)
    ca_b64 = base64.b64encode(ca_cert.public_bytes(serialization.Encoding.PEM)).decode()
    return cert_path, key_path, ca_b64
