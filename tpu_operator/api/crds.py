"""CustomResourceDefinition objects for the operator's CRDs.

The reference ships generated CRD YAML under
deployments/gpu-operator/crds/; here the CRDs are generated from the typed
specs (kubebuilder-style, but at runtime) so `tpuop-cfg crds` and the fake
apiserver always agree with the dataclasses.
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, Dict, List, Optional, get_args, get_origin

from tpu_operator.api import clusterpolicy, tpujob, tpuquota, tpuserving, tpuslice
from tpu_operator.api.common import SpecBase

CRD_API_VERSION = "apiextensions.k8s.io/v1"
GROUP = "tpu.google.com"


def _schema_for_type(tp: Any) -> dict:
    origin = get_origin(tp)
    if origin is typing.Union:  # Optional[X]
        args = [a for a in get_args(tp) if a is not type(None)]
        return _schema_for_type(args[0]) if args else {"x-kubernetes-preserve-unknown-fields": True}
    if origin in (list, List):
        args = get_args(tp)
        item = _schema_for_type(args[0]) if args else {"x-kubernetes-preserve-unknown-fields": True}
        return {"type": "array", "items": item}
    if origin in (dict, Dict):
        args = get_args(tp)
        if args and args[1] is str:
            return {"type": "object", "additionalProperties": {"type": "string"}}
        return {"type": "object", "x-kubernetes-preserve-unknown-fields": True}
    if isinstance(tp, type) and issubclass(tp, SpecBase):
        return _schema_for_spec(tp)
    if tp is str:
        return {"type": "string"}
    if tp is bool:
        return {"type": "boolean"}
    if tp is int:
        return {"type": "integer"}
    if tp is float:
        return {"type": "number"}
    if tp is dict:
        return {"type": "object", "x-kubernetes-preserve-unknown-fields": True}
    return {"x-kubernetes-preserve-unknown-fields": True}


def _schema_for_spec(cls: type) -> dict:
    hints = typing.get_type_hints(cls)
    props = {}
    for f in dataclasses.fields(cls):
        if not f.init:
            continue
        key = f.metadata.get("json", f.name)
        schema = _schema_for_type(hints.get(f.name, dict))
        if "enum" in f.metadata:
            schema = dict(schema, enum=f.metadata["enum"])
        props[key] = schema
    return {"type": "object", "properties": props}


def _crd(
    kind: str,
    plural: str,
    singular: str,
    version: str,
    spec_cls: type,
    status_cls: type,
    scope: str = "Cluster",
    short_names: Optional[List[str]] = None,
) -> dict:
    return {
        "apiVersion": CRD_API_VERSION,
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{plural}.{GROUP}"},
        "spec": {
            "group": GROUP,
            "names": {
                "kind": kind,
                "listKind": f"{kind}List",
                "plural": plural,
                "singular": singular,
                **({"shortNames": short_names} if short_names else {}),
            },
            "scope": scope,
            "versions": [
                {
                    "name": version,
                    "served": True,
                    "storage": True,
                    "subresources": {"status": {}},
                    "additionalPrinterColumns": [
                        {"name": "Status", "type": "string", "jsonPath": ".status.state"},
                        {"name": "Age", "type": "date", "jsonPath": ".metadata.creationTimestamp"},
                    ],
                    "schema": {
                        "openAPIV3Schema": {
                            "type": "object",
                            "properties": {
                                "spec": _schema_for_spec(spec_cls),
                                "status": _schema_for_spec(status_cls),
                            },
                        }
                    },
                }
            ],
        },
    }


def cluster_policy_crd() -> dict:
    return _crd(
        kind=clusterpolicy.CLUSTER_POLICY_KIND,
        plural="clusterpolicies",
        singular="clusterpolicy",
        version="v1",
        spec_cls=clusterpolicy.ClusterPolicySpec,
        status_cls=clusterpolicy.ClusterPolicyStatus,
    )


def tpu_slice_crd() -> dict:
    return _crd(
        kind=tpuslice.TPU_SLICE_KIND,
        plural="tpuslices",
        singular="tpuslice",
        version="v1alpha1",
        spec_cls=tpuslice.TPUSliceSpec,
        status_cls=tpuslice.TPUSliceStatus,
        short_names=["ts"],
    )


def tpu_job_crd() -> dict:
    return _crd(
        kind=tpujob.TPU_JOB_KIND,
        plural="tpujobs",
        singular="tpujob",
        version="v1alpha1",
        spec_cls=tpujob.TPUJobSpec,
        status_cls=tpujob.TPUJobStatus,
        short_names=["tj"],
    )


def tpu_serving_crd() -> dict:
    return _crd(
        kind=tpuserving.TPU_SERVING_KIND,
        plural="tpuservings",
        singular="tpuserving",
        version="v1alpha1",
        spec_cls=tpuserving.TPUServingSpec,
        status_cls=tpuserving.TPUServingStatus,
        short_names=["tsv"],
    )


def tpu_quota_crd() -> dict:
    return _crd(
        kind=tpuquota.TPU_QUOTA_KIND,
        plural="tpuquotas",
        singular="tpuquota",
        version="v1alpha1",
        spec_cls=tpuquota.TPUQuotaSpec,
        status_cls=tpuquota.TPUQuotaStatus,
        short_names=["tq"],
    )


def all_crds() -> List[dict]:
    return [
        cluster_policy_crd(),
        tpu_slice_crd(),
        tpu_job_crd(),
        tpu_serving_crd(),
        tpu_quota_crd(),
    ]
