"""ClusterPolicy CRD (tpu.google.com/v1).

TPU-native redesign of the reference's ClusterPolicy
(api/nvidia/v1/clusterpolicy_types.go:38-91): one cluster-scoped singleton
whose sub-specs configure each operand the operator deploys. The NVIDIA
stack maps onto the TPU stack as:

    driver (CUDA kernel modules)        -> libtpu (libtpu installer)
    toolkit (container runtime hook)    -> (not needed: device plugin mounts
                                            /dev/accel* + libtpu directly)
    devicePlugin (k8s-device-plugin)    -> devicePlugin (Cloud TPU plugin)
    gfd (gpu-feature-discovery)         -> tpuFeatureDiscovery
    mig/migManager (sub-GPU partition)  -> sliceManager (multi-host slice
                                            topology + gang placement)
    dcgm + dcgmExporter                 -> metricsExporter (libtpu metrics)
    nodeStatusExporter                  -> nodeStatusExporter
    validator (CUDA vectorAdd)          -> validator (JAX psum over ICI)
    sandbox/vgpu/vfio/kata/cc           -> out of scope: no TPU analog

Status semantics (State enum, conditions) mirror
clusterpolicy_types.go:1638-1661 exactly.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from tpu_operator import consts
from tpu_operator.api.common import (
    ComponentCommon,
    ImageSpec,
    SpecBase,
    field,
    sub,
    sub_optional,
)

CLUSTER_POLICY_API_VERSION = "tpu.google.com/v1"
CLUSTER_POLICY_KIND = "ClusterPolicy"


class State:
    """reference: gpuv1.State clusterpolicy_types.go:1638-1645."""

    IGNORED = "ignored"
    READY = "ready"
    NOT_READY = "notReady"
    DISABLED = "disabled"


# ---------------------------------------------------------------------------
# Sub-specs.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class OperatorSpec(SpecBase):
    """reference: OperatorSpec clusterpolicy_types.go:122-145."""

    default_runtime: str = field(json="defaultRuntime", default=consts.RUNTIME_CONTAINERD)
    init_container: ImageSpec = sub(ImageSpec, json="initContainer")
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)


@dataclasses.dataclass
class RollingUpdateSpec(SpecBase):
    max_unavailable: str = field(json="maxUnavailable", default="1")


@dataclasses.dataclass
class DaemonsetsSpec(SpecBase):
    """Common config stamped onto every operand DaemonSet
    (reference: DaemonsetsSpec clusterpolicy_types.go:195-228)."""

    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    tolerations: List[dict] = field(default_factory=list)
    priority_class_name: str = field(json="priorityClassName", default="system-node-critical")
    update_strategy: str = field(json="updateStrategy", default="RollingUpdate")
    rolling_update: Optional[RollingUpdateSpec] = sub_optional(RollingUpdateSpec, json="rollingUpdate")


@dataclasses.dataclass
class DrainSpec(SpecBase):
    """reference: vendored k8s-operator-libs upgrade DrainSpec."""

    enable: bool = field(default=True)
    force: bool = field(default=False)
    pod_selector: str = field(json="podSelector", default="")
    timeout_seconds: int = field(json="timeoutSeconds", default=300)
    delete_empty_dir: bool = field(json="deleteEmptyDir", default=False)


@dataclasses.dataclass
class PodDeletionSpec(SpecBase):
    force: bool = field(default=False)
    timeout_seconds: int = field(json="timeoutSeconds", default=300)
    delete_empty_dir: bool = field(json="deleteEmptyDir", default=False)


@dataclasses.dataclass
class WaitForCompletionSpec(SpecBase):
    pod_selector: str = field(json="podSelector", default="")
    timeout_seconds: int = field(json="timeoutSeconds", default=0)


@dataclasses.dataclass
class UpgradePolicySpec(SpecBase):
    """Rolling-upgrade policy for libtpu version bumps (reference:
    DriverUpgradePolicySpec in the vendored upgrade lib)."""

    auto_upgrade: bool = field(json="autoUpgrade", default=False)
    max_parallel_upgrades: int = field(json="maxParallelUpgrades", default=1)
    max_unavailable: str = field(json="maxUnavailable", default="25%")
    wait_for_completion: WaitForCompletionSpec = sub(WaitForCompletionSpec, json="waitForCompletion")
    pod_deletion: PodDeletionSpec = sub(PodDeletionSpec, json="podDeletion")
    drain: DrainSpec = sub(DrainSpec)


@dataclasses.dataclass
class LibtpuSpec(ComponentCommon):
    """The driver-state analog: installs a pinned libtpu.so onto each TPU
    node (reference: DriverSpec clusterpolicy_types.go:452-570). There are
    no kernel modules to build — libtpu is a userspace library — so the
    precompiled/DriverToolkit machinery collapses into a versioned copy.
    """

    install_dir: str = field(json="installDir", default=consts.LIBTPU_INSTALL_DIR)
    use_tpu_slice_crd: Optional[bool] = field(json="useTPUSliceCRD", default=None)
    upgrade_policy: UpgradePolicySpec = sub(UpgradePolicySpec, json="upgradePolicy")
    startup_probe: Optional[dict] = field(json="startupProbe", default=None)
    liveness_probe: Optional[dict] = field(json="livenessProbe", default=None)

    def use_slice_crd(self) -> bool:
        return bool(self.use_tpu_slice_crd)


@dataclasses.dataclass
class DevicePluginConfigSpec(SpecBase):
    """ConfigMap-based plugin config selection (reference:
    DevicePluginConfig clusterpolicy_types.go:745-760): ``name`` is a
    ConfigMap of named configs, ``default`` the fallback config key; nodes
    opt into a specific config via the plugin-config node label."""

    name: str = field(default="")
    default: str = field(default="")


@dataclasses.dataclass
class DevicePluginSpec(ComponentCommon):
    config: DevicePluginConfigSpec = sub(DevicePluginConfigSpec)


@dataclasses.dataclass
class TPUFeatureDiscoverySpec(ComponentCommon):
    """GFD analog: emits tpu.google.com/{accelerator-type,topology,
    chips-per-node,slice-hosts,generation} node labels."""


@dataclasses.dataclass
class NodeDiscoverySpec(ComponentCommon):
    """NFD-analog bootstrap: a gate-free DaemonSet on every Linux node
    that probes /dev/accel* (native tpuinfo) and publishes the
    tpu.google.com accelerator labels, so self-managed (non-GKE) TPU-VM
    clusters are recognized without anyone stamping the
    cloud.google.com/gke-tpu-* labels (reference: the NFD worker the
    gpu-operator chart deploys, feeding state_manager.go:113-117)."""


@dataclasses.dataclass
class SliceManagerConfigSpec(SpecBase):
    name: str = field(default="")
    default: str = field(default="")


@dataclasses.dataclass
class SliceManagerSpec(ComponentCommon):
    """MIG-manager analog. TPUs have no sub-chip partitioning; the unit of
    partitioning is the multi-host slice. The slice manager renders the
    per-slice gang plumbing (headless Service + worker identity env) and
    reconciles the per-node ``tpu.google.com/slice.config`` label the way
    mig-manager reconciles ``nvidia.com/mig.config``."""

    config: SliceManagerConfigSpec = sub(SliceManagerConfigSpec)


@dataclasses.dataclass
class ServiceMonitorSpec(SpecBase):
    enabled: Optional[bool] = field(default=None)
    interval: str = field(default="15s")
    honor_labels: bool = field(json="honorLabels", default=False)
    additional_labels: Dict[str, str] = field(json="additionalLabels", default_factory=dict)

    def is_enabled(self) -> bool:
        return bool(self.enabled)


@dataclasses.dataclass
class MetricsExporterSpec(ComponentCommon):
    """dcgm + dcgm-exporter analog: one operand scraping libtpu runtime
    metrics (TensorCore utilization, HBM usage, ICI link bandwidth) into
    Prometheus exposition format."""

    port: int = field(default=8431)
    service_monitor: ServiceMonitorSpec = sub(ServiceMonitorSpec, json="serviceMonitor")


@dataclasses.dataclass
class NodeStatusExporterSpec(ComponentCommon):
    """reference: NodeStatusExporterSpec — per-node validation status
    metrics served by the validator image."""


@dataclasses.dataclass
class ComponentValidatorSpec(SpecBase):
    """Per-component validator tuning (reference: PluginValidatorSpec et al.
    clusterpolicy_types.go:323-383)."""

    env: List[dict] = field(default_factory=list)


@dataclasses.dataclass
class ValidatorSpec(ComponentCommon):
    """reference: ValidatorSpec clusterpolicy_types.go:255-320. Components:
    ``libtpu`` (driver analog), ``plugin``, ``workload`` (CUDA analog — JAX
    device-count smoke), ``slice`` (multi-host psum over ICI)."""

    libtpu: ComponentValidatorSpec = sub(ComponentValidatorSpec)
    plugin: ComponentValidatorSpec = sub(ComponentValidatorSpec)
    workload: ComponentValidatorSpec = sub(ComponentValidatorSpec)
    slice: ComponentValidatorSpec = sub(ComponentValidatorSpec)
    # Optional performance floors (no reference analog — their validator
    # gates only on resource presence, main.go:1096-1174, so a degraded
    # node sails to Ready). When set, the workload component fails below
    # minTflops (bf16 matmul on this node's chips) and the slice component
    # fails below minPsumGbpsPerChip (allreduce bus bandwidth over ICI) —
    # NotReady, status file withheld, operands stay gated.
    min_tflops: Optional[float] = field(json="minTflops", default=None)
    min_psum_gbps_per_chip: Optional[float] = field(
        json="minPsumGbpsPerChip", default=None
    )


@dataclasses.dataclass
class RemediationSpec(SpecBase):
    """Auto-remediation knobs for degraded TPU nodes. No reference analog
    (the gpu-operator stops at DCGM health metrics); the model is GKE
    node auto-repair, bounded by a retry budget so a persistently sick
    node lands in the ``quarantined`` terminal label instead of cycling
    forever."""

    enable: bool = field(default=True)
    retry_limit: int = field(json="retryLimit", default=3)
    # force falls back to plain DELETE for PDB-blocked evictions
    # (kubectl drain --disable-eviction semantics)
    force: bool = field(default=False)
    # per-repair-state budget; an eviction blocked past it quarantines the
    # node, a revalidation stuck past it burns one retry and restarts
    timeout_seconds: int = field(json="timeoutSeconds", default=300)
    # degradation must persist this long before repair starts: a freshly
    # joined node legitimately looks degraded while libtpu installs and
    # the plugin comes up — cordoning it mid-provision would kill the
    # install and burn retry budget on every node join
    grace_period_seconds: int = field(json="gracePeriodSeconds", default=300)


@dataclasses.dataclass
class HealthMonitorSpec(ComponentCommon):
    """The closed-loop health subsystem: a per-node agent (DaemonSet)
    probing /dev/accel* presence, the libtpu install marker, the device
    plugin socket, and an optional matmul sanity check; plus the operator
    remediation controller consuming its verdicts (DCGM health check →
    node auto-repair analog)."""

    interval: int = field(default=30)  # seconds between agent probe ticks
    # matmul sanity probe gating, same contract as the metrics exporter's
    # active probes: auto skips quietly when a tenant owns the chip
    active_probes: str = field(json="activeProbes", default="auto")
    remediation: RemediationSpec = sub(RemediationSpec)


@dataclasses.dataclass
class AutotunerSpec(ComponentCommon):
    """Per-generation kernel autotuning (ROADMAP item 5): a sweep
    operand scheduled onto one ELECTED node per un-swept TPU generation
    (the autotune controller manages the election label), measuring
    flash-attention block shapes, matmul chain tilings, and the int8
    path; winners are cached per (generation, kernel, shape class,
    libtpu version) and folded into the perf-floors pipeline. No
    reference analog — NVIDIA tunes kernels inside CUDA libraries; on
    TPU the block-shape choice lives in the operator's own pallas
    payloads, so the operator owns the loop."""

    # seconds between agent reconcile passes on an elected node
    interval: int = field(default=60)
    # chips the sweep pod claims via the google.com/tpu resource —
    # exclusive chip ownership for the sweep window (no co-tenant skews
    # the measurement); match the generation's chips-per-host
    chips: int = field(default=4)


@dataclasses.dataclass
class CompileCacheSpec(ComponentCommon):
    """Persistent XLA compile cache + AOT prewarm (ROADMAP item 4): a
    prewarm operand scheduled onto one ELECTED node per generation with
    unsatisfied prewarm demand (the compile-cache controller manages the
    election label). Compiled-executable records are content-addressed
    by (generation, topology, model hash, libtpu version); entries
    invalidate on libtpu image-tag change exactly like the autotune
    results, so a rolling upgrade re-compiles each generation once. No
    reference analog — CUDA kernels ship precompiled; XLA recompiles per
    (program, topology), so warm scale-ups need the operator to own the
    cache."""

    # seconds between agent reconcile passes on an elected node
    interval: int = field(default=60)
    # chips the prewarm pod claims via the google.com/tpu resource — the
    # compile must lower against the real device topology
    chips: int = field(default=4)
    # node-local persistent compilation cache directory (hostPath): the
    # serialized executables survive the prewarm pod
    cache_dir: str = field(json="cacheDir", default="/var/cache/tpu-compile")


@dataclasses.dataclass
class MultiSliceSpec(SpecBase):
    """Multi-slice (DCN-connected slices) support: the validator and the
    slice manager wire JAX distributed-coordinator addresses across slices
    (BASELINE config 5). No reference analog — NVIDIA's cross-node story
    (NCCL) lives in workload images."""

    enabled: Optional[bool] = field(default=None)
    coordinator_port: int = field(json="coordinatorPort", default=8476)

    def is_enabled(self) -> bool:
        return bool(self.enabled)


@dataclasses.dataclass
class PSASpec(SpecBase):
    """Pod Security Admission labelling of the operand namespace
    (reference: PSASpec clusterpolicy_types.go:189-192)."""

    enabled: Optional[bool] = field(default=None)

    def is_enabled(self) -> bool:
        return bool(self.enabled)


# ---------------------------------------------------------------------------
# The spec + object.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ClusterPolicySpec(SpecBase):
    operator: OperatorSpec = sub(OperatorSpec)
    daemonsets: DaemonsetsSpec = sub(DaemonsetsSpec)
    libtpu: LibtpuSpec = sub(LibtpuSpec)
    device_plugin: DevicePluginSpec = sub(DevicePluginSpec, json="devicePlugin")
    tpu_feature_discovery: TPUFeatureDiscoverySpec = sub(TPUFeatureDiscoverySpec, json="tfd")
    node_discovery: NodeDiscoverySpec = sub(NodeDiscoverySpec, json="nodeDiscovery")
    slice_manager: SliceManagerSpec = sub(SliceManagerSpec, json="sliceManager")
    metrics_exporter: MetricsExporterSpec = sub(MetricsExporterSpec, json="metricsExporter")
    node_status_exporter: NodeStatusExporterSpec = sub(NodeStatusExporterSpec, json="nodeStatusExporter")
    validator: ValidatorSpec = sub(ValidatorSpec)
    health_monitor: HealthMonitorSpec = sub(HealthMonitorSpec, json="healthMonitor")
    autotuner: AutotunerSpec = sub(AutotunerSpec)
    compile_cache: CompileCacheSpec = sub(CompileCacheSpec, json="compileCache")
    multi_slice: MultiSliceSpec = sub(MultiSliceSpec, json="multiSlice")
    psa: PSASpec = sub(PSASpec)


@dataclasses.dataclass
class ClusterPolicyStatus(SpecBase):
    """reference: ClusterPolicyStatus clusterpolicy_types.go:1648-1661."""

    state: str = field(default="")
    namespace: str = field(default="")
    conditions: List[dict] = field(default_factory=list)
    # rolling-upgrade progress published by the upgrade reconciler
    # (inProgress/done/failed/pending counts + per-node FSM state); must
    # be declared or a real apiserver's structural pruning drops it
    upgrade: dict = field(default_factory=dict)
    # node-health / remediation progress published by the health
    # reconciler (degraded/remediating/quarantined counts + per-node
    # repair state); declared for the same structural-pruning reason
    health: dict = field(default_factory=dict)


@dataclasses.dataclass
class ClusterPolicy:
    metadata: dict
    spec: ClusterPolicySpec
    status: ClusterPolicyStatus

    @property
    def name(self) -> str:
        return self.metadata.get("name", "")

    @classmethod
    def from_unstructured(cls, obj: dict) -> "ClusterPolicy":
        return cls(
            metadata=obj.get("metadata", {}),
            spec=ClusterPolicySpec.from_dict(obj.get("spec")),
            status=ClusterPolicyStatus.from_dict(obj.get("status")),
        )

    def to_unstructured(self) -> dict:
        return {
            "apiVersion": CLUSTER_POLICY_API_VERSION,
            "kind": CLUSTER_POLICY_KIND,
            "metadata": self.metadata,
            "spec": self.spec.to_dict(),
            "status": self.status.to_dict(),
        }


def new_cluster_policy(name: str = "cluster-policy", spec: Optional[dict] = None) -> dict:
    return {
        "apiVersion": CLUSTER_POLICY_API_VERSION,
        "kind": CLUSTER_POLICY_KIND,
        "metadata": {"name": name},
        "spec": spec or {},
    }
