"""TPUQuota CRD (tpu.google.com/v1alpha1): hierarchical multi-tenant quotas.

A TPUQuota declares one level of the tenant hierarchy — ``spec.tenant``
is a dotted path ("acme", "acme.search", "acme.search.training": org →
team → workload class; "/" is illegal in a k8s label value, so the
hierarchy separator is "."). Each level carries a fair-share ``weight``
and a ``guaranteed`` map of chips per TPU generation (v4/v5e/v5p/v6e —
the ``nodeinfo`` generation key). Workloads resolve to a tenant via the
``tpu.google.com/tenant`` label on TPUSlice/TPUJob/TPUServing (job and
serving controllers propagate the label onto the slices they own).

Semantics (``tenancy/fairshare.py``):

- Guarantees roll up the hierarchy: "acme.search" usage counts against
  both its own guarantee and "acme"'s.
- Borrowing idle capacity beyond the guarantee is allowed, but borrowed
  chips are reclaimable — a borrower outside every guarantee is a legal
  cross-tenant preemption victim; a gang inside its owner's guaranteed
  quota never is (while the preemptor's tenant is over its own).
- With zero TPUQuota objects in the cluster, placement admission is
  byte-identical to stock priority-then-FIFO.

The tenancy controller (``controllers/tenancy_controller.py``) publishes
per-tenant usage/share/borrow accounting into ``status.tenancy`` and the
``tpu_operator_tenant_*`` gauges.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from tpu_operator.api.common import SpecBase, field

TPU_QUOTA_API_VERSION = "tpu.google.com/v1alpha1"
TPU_QUOTA_KIND = "TPUQuota"


@dataclasses.dataclass
class TPUQuotaSpec(SpecBase):
    """One hierarchy level. ``tenant`` is the dotted path this quota
    binds to; ``weight`` scales the tenant's dominant share in the
    fair-share ordering (2.0 = entitled to twice the share of a
    weight-1.0 tenant before sorting behind it); ``guaranteed`` maps TPU
    generation → chips the tenant may hold un-preemptably."""

    tenant: str = field(default="")
    weight: float = field(default=1.0)
    guaranteed: Dict[str, int] = field(default_factory=dict)


@dataclasses.dataclass
class TPUQuotaStatus(SpecBase):
    """``state`` is Active or Invalid (malformed spec — the quota grants
    nothing, fail closed); ``tenancy`` is the controller's accounting
    block: used/guaranteed/borrowed chips per generation, weighted
    dominant share, and fair-share attainment."""

    state: str = field(default="")
    conditions: List[dict] = field(default_factory=list)
    tenancy: dict = field(default_factory=dict)


@dataclasses.dataclass
class TPUQuota:
    metadata: dict
    spec: TPUQuotaSpec
    status: TPUQuotaStatus

    @property
    def name(self) -> str:
        return self.metadata.get("name", "")

    @classmethod
    def from_unstructured(cls, obj: dict) -> "TPUQuota":
        return cls(
            metadata=obj.get("metadata", {}),
            spec=TPUQuotaSpec.from_dict(obj.get("spec")),
            status=TPUQuotaStatus.from_dict(obj.get("status")),
        )

    def to_unstructured(self) -> dict:
        return {
            "apiVersion": TPU_QUOTA_API_VERSION,
            "kind": TPU_QUOTA_KIND,
            "metadata": self.metadata,
            "spec": self.spec.to_dict(),
            "status": self.status.to_dict(),
        }


def new_tpu_quota(name: str, spec: Optional[dict] = None) -> dict:
    return {
        "apiVersion": TPU_QUOTA_API_VERSION,
        "kind": TPU_QUOTA_KIND,
        "metadata": {"name": name},
        "spec": spec or {},
    }
