"""Shared spec building blocks.

Mirrors the reference's common spec types (EnvVar, ResourceRequirements,
image fields + ImagePath resolution — api/nvidia/v1/clusterpolicy_types.go:148-170,
internal/image/image.go:25-53) in idiomatic Python: every sub-spec is a
dataclass that tolerantly loads from its unstructured dict form and dumps
back without empty fields.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List, Optional, Type, TypeVar

T = TypeVar("T", bound="SpecBase")


def _is_empty(value: Any) -> bool:
    """Go omitempty parity: zero-value strings/dicts/lists are omitted on
    dump (False and 0 are kept — they are meaningful spec values)."""
    return value is None or value == {} or value == [] or (isinstance(value, str) and value == "")


@dataclasses.dataclass
class SpecBase:
    """Base for all spec dataclasses: dict round-tripping with unknown-field
    tolerance (matching Kubernetes' pruning-off behavior for CRDs)."""

    @classmethod
    def from_dict(cls: Type[T], data: Optional[dict]) -> T:
        data = data or {}
        kwargs = {}
        for field in dataclasses.fields(cls):
            if not field.init:
                continue
            key = field.metadata.get("json", field.name)
            if key not in data:
                continue
            value = data[key]
            loader = field.metadata.get("loader")
            if loader is not None and value is not None:
                value = loader(value)
            kwargs[field.name] = value
        return cls(**kwargs)

    def to_dict(self) -> dict:
        out: Dict[str, Any] = {}
        for field in dataclasses.fields(self):
            value = getattr(self, field.name)
            if _is_empty(value):
                continue
            key = field.metadata.get("json", field.name)
            if isinstance(value, SpecBase):
                dumped = value.to_dict()
                if dumped:
                    out[key] = dumped
            elif isinstance(value, list) and value and isinstance(value[0], SpecBase):
                out[key] = [v.to_dict() for v in value]
            else:
                out[key] = value
        return out


def field(
    json: Optional[str] = None,
    default: Any = None,
    default_factory: Any = None,
    loader: Any = None,
    enum: Any = None,
):
    """Dataclass field with a JSON key, optional nested loader, and an
    optional closed value set (rendered as an OpenAPI ``enum`` in the
    generated CRD so the apiserver rejects typos at admission)."""
    metadata: Dict[str, Any] = {}
    if json:
        metadata["json"] = json
    if enum is not None:
        metadata["enum"] = list(enum)
    if loader is not None:
        metadata["loader"] = loader
    if default_factory is not None:
        return dataclasses.field(default_factory=default_factory, metadata=metadata)
    return dataclasses.field(default=default, metadata=metadata)


def sub(cls: Type[T], json: Optional[str] = None):
    """Field holding a nested SpecBase, defaulting to its zero value."""
    return field(json=json, default_factory=cls, loader=cls.from_dict)


def sub_optional(cls: Type[T], json: Optional[str] = None):
    """Field holding an optional nested SpecBase (None when absent)."""
    return field(json=json, default=None, loader=cls.from_dict)


# ---------------------------------------------------------------------------
# Env vars. Kept in k8s wire form ({name, value}) since they flow straight
# into container specs (reference: EnvVar clusterpolicy_types.go:148-154).
# ---------------------------------------------------------------------------


def env_list_to_map(env: Optional[List[dict]]) -> Dict[str, str]:
    return {e["name"]: e.get("value", "") for e in (env or [])}


def merge_env(base: Optional[List[dict]], override: Optional[List[dict]]) -> List[dict]:
    """Merge env lists; entries in ``override`` win by name."""
    merged = {e["name"]: dict(e) for e in (base or [])}
    for e in override or []:
        merged[e["name"]] = dict(e)
    return list(merged.values())


# ---------------------------------------------------------------------------
# Image path resolution (reference: internal/image/image.go:25-53 and the
# CRD-side variant clusterpolicy_types.go:1699+): repository/image/version
# compose into "repo/image:version", a sha256 "version" becomes a digest
# reference, and when the CR carries no image fields an env var (OLM-style
# digest pinning) is consulted.
# ---------------------------------------------------------------------------


class ImageSpecMixin:
    repository: str
    image: str
    version: str

    def image_path(self, env_var: Optional[str] = None) -> str:
        if self.image:
            image = f"{self.repository}/{self.image}" if self.repository else self.image
            if self.version:
                sep = "@" if self.version.startswith("sha256:") else ":"
                return f"{image}{sep}{self.version}"
            return image
        if env_var:
            return os.environ.get(env_var, "")
        return ""


@dataclasses.dataclass
class ImageSpec(SpecBase, ImageSpecMixin):
    """repository + image + version (+ pull policy/secrets) for one operand."""

    repository: str = field(default="")
    image: str = field(default="")
    version: str = field(default="")
    image_pull_policy: str = field(json="imagePullPolicy", default="IfNotPresent")
    image_pull_secrets: List[str] = field(json="imagePullSecrets", default_factory=list)


@dataclasses.dataclass
class ComponentCommon(ImageSpec):
    """Fields shared by every operand sub-spec: enablement, image, scheduling
    and container knobs (reference pattern repeated across all *Spec types,
    e.g. DevicePluginSpec clusterpolicy_types.go)."""

    enabled: Optional[bool] = field(default=None)
    env: List[dict] = field(default_factory=list)
    args: List[str] = field(default_factory=list)
    resources: Optional[dict] = field(default=None)

    def is_enabled(self, default: bool = True) -> bool:
        return default if self.enabled is None else bool(self.enabled)
