"""TPUSlice CRD (tpu.google.com/v1alpha1).

Analog of the reference's NVIDIADriver CRD
(api/nvidia/v1alpha1/nvidiadriver_types.go:40-185): where NVIDIADriver lets
a cluster run different driver builds on different node pools, TPUSlice
lets a cluster pin different libtpu versions / slice configurations per
node pool, each TPUSlice CR selecting a disjoint set of TPU nodes and
owning the libtpu-installer DaemonSets rendered for them.

Like the reference, a node may be selected by at most one CR
(internal/validator/validator.go:31-90), and each CR fans out one
DaemonSet per node pool (internal/state/nodepool.go:55-132) — for TPUs a
"pool" is the set of nodes sharing accelerator type + topology (one
multi-host slice family), since libtpu versions must match across a slice.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from tpu_operator import consts
from tpu_operator.api.common import ComponentCommon, SpecBase, field, sub

TPU_SLICE_API_VERSION = "tpu.google.com/v1alpha1"
TPU_SLICE_KIND = "TPUSlice"


class SliceType:
    """reference: DriverType nvidiadriver_types.go:429-441 (gpu / vgpu /
    vgpu-host-manager). TPUs have no virtualized mode; the distinction that
    matters is single-host vs multi-host slices."""

    SINGLE_HOST = "single-host"
    MULTI_HOST = "multi-host"


@dataclasses.dataclass
class PlacementSpec(SpecBase):
    """Topology-aware placement request (no reference analog — NVIDIA
    has no ICI torus to pack). ``shape`` is the contiguous axis-aligned
    HOST block requested on the pool's torus ("4x4x4", or "4x2" for 2-D
    pools); empty shape = placement not requested (legacy implicit
    per-pool gang pickup). The placement controller admits requests in
    priority-then-FIFO order, writes per-node assignment labels the
    slice manager consumes, and — under ``preemptionPolicy:
    PreemptLower`` — tears down the minimal set of strictly-lower-
    priority gangs when no free block exists."""

    shape: str = field(default="")
    priority: int = field(default=0)
    preemption_policy: str = field(
        json="preemptionPolicy", default="Never", enum=["Never", "PreemptLower"]
    )
    # optional node-pool pin (nodepool.NodePool.name); empty = any pool
    pool: str = field(default="")

    def requested(self) -> bool:
        return bool(self.shape)


@dataclasses.dataclass
class TPUSliceSpec(ComponentCommon):
    """Per-instance libtpu deployment spec (reference:
    NVIDIADriverSpec nvidiadriver_types.go:40-185)."""

    slice_type: str = field(json="sliceType", default=SliceType.MULTI_HOST)
    node_selector: Dict[str, str] = field(json="nodeSelector", default_factory=dict)
    install_dir: str = field(json="installDir", default=consts.LIBTPU_INSTALL_DIR)
    priority_class_name: str = field(json="priorityClassName", default="system-node-critical")
    tolerations: List[dict] = field(default_factory=list)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    node_affinity: Optional[dict] = field(json="nodeAffinity", default=None)
    placement: PlacementSpec = sub(PlacementSpec)

    def get_node_selector(self) -> Dict[str, str]:
        """Default to all TPU nodes when unset (reference:
        GetNodeSelector nvidiadriver_types.go:504-516)."""
        if self.node_selector:
            return dict(self.node_selector)
        return {consts.TPU_PRESENT_LABEL: "true"}


@dataclasses.dataclass
class TPUSliceStatus(SpecBase):
    """reference: NVIDIADriverStatus nvidiadriver_types.go:444-460."""

    state: str = field(default="")
    conditions: List[dict] = field(default_factory=list)
    # placement queue progress published by the placement controller
    # (phase Queued|Scheduled|Unschedulable, pool, assigned nodes,
    # block origin, message); declared or a real apiserver's structural
    # pruning drops it
    placement: dict = field(default_factory=dict)


@dataclasses.dataclass
class TPUSlice:
    metadata: dict
    spec: TPUSliceSpec
    status: TPUSliceStatus

    @property
    def name(self) -> str:
        return self.metadata.get("name", "")

    @classmethod
    def from_unstructured(cls, obj: dict) -> "TPUSlice":
        return cls(
            metadata=obj.get("metadata", {}),
            spec=TPUSliceSpec.from_dict(obj.get("spec")),
            status=TPUSliceStatus.from_dict(obj.get("status")),
        )

    def to_unstructured(self) -> dict:
        return {
            "apiVersion": TPU_SLICE_API_VERSION,
            "kind": TPU_SLICE_KIND,
            "metadata": self.metadata,
            "spec": self.spec.to_dict(),
            "status": self.status.to_dict(),
        }


def new_tpu_slice(name: str, spec: Optional[dict] = None) -> dict:
    return {
        "apiVersion": TPU_SLICE_API_VERSION,
        "kind": TPU_SLICE_KIND,
        "metadata": {"name": name},
        "spec": spec or {},
    }
