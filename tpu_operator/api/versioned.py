"""Typed clientset for the operator's CRDs.

Reference: the generated clientset under ``api/versioned``
(clientset.go:133 + per-type typed clients + fakes) consumed by external
automation and tests. Here: thin typed wrappers over any ``Client``
(HTTP or fake), so consumers read/write ClusterPolicy/TPUSlice as typed
objects instead of raw dicts.
"""

from __future__ import annotations

from typing import List, Optional

from tpu_operator.api.clusterpolicy import (
    CLUSTER_POLICY_API_VERSION,
    CLUSTER_POLICY_KIND,
    ClusterPolicy,
)
from tpu_operator.api.tpuslice import (
    TPU_SLICE_API_VERSION,
    TPU_SLICE_KIND,
    TPUSlice,
)
from tpu_operator.kube.client import Client


class _TypedClient:
    api_version: str
    kind: str
    typed_cls: type

    def __init__(self, client: Client):
        self.client = client

    def get(self, name: str):
        return self.typed_cls.from_unstructured(self.client.get(self.api_version, self.kind, name))

    def get_or_none(self, name: str):
        obj = self.client.get_or_none(self.api_version, self.kind, name)
        return self.typed_cls.from_unstructured(obj) if obj is not None else None

    def list(self, label_selector=None) -> List:
        return [
            self.typed_cls.from_unstructured(obj)
            for obj in self.client.list(self.api_version, self.kind, label_selector=label_selector)
        ]

    def create(self, typed):
        return self.typed_cls.from_unstructured(self.client.create(typed.to_unstructured()))

    def update(self, typed):
        return self.typed_cls.from_unstructured(self.client.update(typed.to_unstructured()))

    def update_status(self, typed):
        return self.typed_cls.from_unstructured(self.client.update_status(typed.to_unstructured()))

    def delete(self, name: str) -> None:
        self.client.delete(self.api_version, self.kind, name)


class ClusterPolicies(_TypedClient):
    api_version = CLUSTER_POLICY_API_VERSION
    kind = CLUSTER_POLICY_KIND
    typed_cls = ClusterPolicy


class TPUSlices(_TypedClient):
    api_version = TPU_SLICE_API_VERSION
    kind = TPU_SLICE_KIND
    typed_cls = TPUSlice


class Clientset:
    """reference: versioned.Clientset — one handle, per-type accessors."""

    def __init__(self, client: Client):
        self._client = client
        self.cluster_policies = ClusterPolicies(client)
        self.tpu_slices = TPUSlices(client)

    @classmethod
    def in_cluster(cls) -> "Clientset":
        from tpu_operator.kube.http_client import HttpClient

        return cls(HttpClient.in_cluster())

    @classmethod
    def fake(cls, seed: Optional[List[dict]] = None) -> "Clientset":
        """reference: api/versioned/fake — a clientset over the in-memory
        apiserver, optionally pre-seeded."""
        from tpu_operator.kube.fake import FakeClient

        client = FakeClient()
        for obj in seed or []:
            client.create(obj)
        return cls(client)

    @property
    def raw(self) -> Client:
        return self._client
