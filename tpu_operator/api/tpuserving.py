"""TPUServing CRD (tpu.google.com/v1alpha1): traffic-driven elastic serving.

A TPUServing declares a *model footprint* (the gang shape one inference
replica needs, plus an optional generation/pool pin), a replica window
(min/max), and the SLO the autoscaler defends (p99 time-to-first-token
and decode step time). The serving controller
(``controllers/serving_controller.py``) owns one TPUSlice per replica
and drives the replica count from observed demand: arrival rate and
queue depth from the load ConfigMap the traffic side publishes, step
time from the PR 7 gang telemetry artifacts. Scale-ups are admitted
priority-then-FIFO through the placement engine; scale-downs pick the
victim whose removal most *reduces* torus fragmentation (the allocator's
own scoring, replayed minus each candidate); routing weights exclude
replicas whose fabric artifact shows degraded ICI edges.

The inference payload itself is ``workloads/serving.py``: a
continuous-batching decode engine over the int8 matmul +
flash-attention kernels, running the per-generation autotune winners.

No NVIDIA-reference analog: the gpu-operator stops at provisioning;
the serving layer is where demand drives the placement stack
(PAPERS.md: "Fine-Tuning and Serving Gemma 4 31B on Google Cloud TPU").
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from tpu_operator.api.common import SpecBase, field, sub

TPU_SERVING_API_VERSION = "tpu.google.com/v1alpha1"
TPU_SERVING_KIND = "TPUServing"


class ServingPhase:
    """The serving FSM. ``Failed`` is terminal (retry budget exhausted on
    placement); everything else recomputes from cluster state every pass."""

    PENDING = "Pending"
    SCALING = "Scaling"   # desired != ready: replicas placing or draining
    SERVING = "Serving"   # every desired replica placed and routable
    DEGRADED = "Degraded"  # serving, but some replica excluded/unplaced
    FAILED = "Failed"


SERVING_TERMINAL_PHASES = (ServingPhase.FAILED,)


@dataclasses.dataclass
class ServingModelSpec(SpecBase):
    """What one replica runs: the host-block ``shape`` a replica's gang
    needs on the pool's torus (TPUSlice placement grammar), an optional
    accelerator-generation hint (documentation + the autotune winners
    the decode engine resolves), and an optional node-pool pin forwarded
    to every replica slice."""

    shape: str = field(default="")
    generation: str = field(default="")
    pool: str = field(default="")
    priority: int = field(default=0)


@dataclasses.dataclass
class ServingReplicasSpec(SpecBase):
    """The replica window the autoscaler moves inside. ``targetRps`` is
    one replica's sustainable request rate — the capacity denominator
    demand is divided by; keep it at or below the measured decode-bench
    throughput so the SLO check has headroom."""

    min: int = field(default=1)
    max: int = field(default=1)
    target_rps: float = field(json="targetRps", default=10.0)
    # scale-down hysteresis: demand must fit the shrunk set for this
    # long (and this long since the last scale action) before a replica
    # is retired — bursts scale up instantly, lulls shrink slowly
    cooldown_seconds: float = field(json="cooldownSeconds", default=30.0)


@dataclasses.dataclass
class ServingSLOSpec(SpecBase):
    """The targets the autoscaler defends: measured p99 TTFT above
    ``ttftP99Seconds`` or a gang-median decode step above
    ``stepSeconds`` reads as an overloaded fleet and scales up even when
    the rate math alone still fits."""

    ttft_p99_seconds: float = field(json="ttftP99Seconds", default=2.0)
    step_seconds: float = field(json="stepSeconds", default=0.0)


@dataclasses.dataclass
class ServingBackoffSpec(SpecBase):
    """Placement-retry budget: consecutive autoscaler passes in which a
    wanted replica stays unplaceable burn the budget (full-jitter
    delays, ``kube/backoff.py``); exhaustion quarantines the serving in
    ``Failed`` with an Event instead of hammering the placement queue."""

    base_seconds: float = field(json="baseSeconds", default=1.0)
    max_seconds: float = field(json="maxSeconds", default=60.0)
    retry_limit: int = field(json="retryLimit", default=5)


@dataclasses.dataclass
class ServingDisaggregationSpec(SpecBase):
    """Disaggregated prefill/decode pools. When ``enabled``, the replica
    window (``spec.replicas``) governs the *decode* pool and a separate
    prefill pool of ``prefillMin``..``prefillMax`` replicas chunk-prefills
    prompts and hands the paged KV to decode replicas. Each pool scales
    on its own signal: prefill on measured prefill TTFT p99 against the
    SLO, decode on the rate math plus ``decodeTokensPerSFloor`` (scale up
    when aggregate decode throughput sags below the floor under load).
    ``prefillShape``/``prefillPool`` override the model shape/pool pin
    for prefill replicas (compute-rich blocks on a different pool)."""

    enabled: bool = field(default=False)
    prefill_min: int = field(json="prefillMin", default=1)
    prefill_max: int = field(json="prefillMax", default=1)
    prefill_shape: str = field(json="prefillShape", default="")
    prefill_pool: str = field(json="prefillPool", default="")
    decode_tokens_per_s_floor: float = field(
        json="decodeTokensPerSFloor", default=0.0)


@dataclasses.dataclass
class TPUServingSpec(SpecBase):
    model: ServingModelSpec = sub(ServingModelSpec)
    replicas: ServingReplicasSpec = sub(ServingReplicasSpec)
    slo: ServingSLOSpec = sub(ServingSLOSpec)
    backoff: ServingBackoffSpec = sub(ServingBackoffSpec)
    disaggregation: ServingDisaggregationSpec = sub(ServingDisaggregationSpec)


@dataclasses.dataclass
class TPUServingStatus(SpecBase):
    """``state`` mirrors the FSM phase for printer columns; ``serving``
    is the bookkeeping block (phase, desired/ready replicas, per-replica
    lifecycle, routing weights, last scale decisions with reasons, SLO
    attainment) the controller publishes as a key-scoped status patch."""

    state: str = field(default="")
    conditions: List[dict] = field(default_factory=list)
    serving: dict = field(default_factory=dict)


@dataclasses.dataclass
class TPUServing:
    metadata: dict
    spec: TPUServingSpec
    status: TPUServingStatus

    @property
    def name(self) -> str:
        return self.metadata.get("name", "")

    @classmethod
    def from_unstructured(cls, obj: dict) -> "TPUServing":
        return cls(
            metadata=obj.get("metadata", {}),
            spec=TPUServingSpec.from_dict(obj.get("spec")),
            status=TPUServingStatus.from_dict(obj.get("status")),
        )

    def to_unstructured(self) -> dict:
        return {
            "apiVersion": TPU_SERVING_API_VERSION,
            "kind": TPU_SERVING_KIND,
            "metadata": self.metadata,
            "spec": self.spec.to_dict(),
            "status": self.status.to_dict(),
        }


def new_tpu_serving(name: str, spec: Optional[dict] = None) -> dict:
    return {
        "apiVersion": TPU_SERVING_API_VERSION,
        "kind": TPU_SERVING_KIND,
        "metadata": {"name": name},
        "spec": spec or {},
    }
