"""TPUJob CRD (tpu.google.com/v1alpha1): elastic fault-tolerant training.

A TPUJob declares a long-running training workload plus its *elasticity
contract*: the desired gang shape, the smallest shape the workload is
still viable on, the checkpoint cadence the resume guarantee is bounded
by, and the restart backoff budget that separates a chaos-buffeted job
(shrinks, resumes, finishes) from a poisoned one (quarantines in
``Failed`` instead of crash-looping through the placement queue).

The job controller (``controllers/job_controller.py``) owns the full
lifecycle as a bounded FSM — Pending → Placing → Running →
Checkpointing → Shrinking/Growing → Resuming → Succeeded/Failed — by
driving ONE owned TPUSlice through the placement engine: shrink patches
the slice's ``spec.placement.shape`` down to the largest sub-block the
torus allocator ranks placeable, grow patches it back up when capacity
heals. Checkpoint-epoch bookkeeping lives in ``status.job`` so a
restarted operator re-derives the same world.

No NVIDIA-reference analog: the gpu-operator stops at provisioning; the
job layer is where "Exploration of TPUs for AI Applications"-style
fleet resilience (checkpoint, shrink to what still places, grow back on
heal) becomes an operator concern.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from tpu_operator.api.common import SpecBase, field, sub

TPU_JOB_API_VERSION = "tpu.google.com/v1alpha1"
TPU_JOB_KIND = "TPUJob"


class JobPhase:
    """The bounded job FSM. ``Succeeded``/``Failed`` are terminal;
    everything else recomputes from cluster state every pass."""

    PENDING = "Pending"
    PLACING = "Placing"
    RUNNING = "Running"
    CHECKPOINTING = "Checkpointing"
    SHRINKING = "Shrinking"
    GROWING = "Growing"
    RESUMING = "Resuming"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


TERMINAL_PHASES = (JobPhase.SUCCEEDED, JobPhase.FAILED)


@dataclasses.dataclass
class JobWorkloadSpec(SpecBase):
    """What trains: total step count plus model knobs forwarded to the
    trainer (``workloads/training.py``; keys follow BurninConfig field
    names, e.g. ``d_model``, ``seq_len``, ``batch``)."""

    steps: int = field(default=100)
    config: dict = field(default_factory=dict)


@dataclasses.dataclass
class JobGangSpec(SpecBase):
    """Desired vs minimum viable gang geometry. ``shape`` is the host
    block requested on the pool's torus (TPUSlice placement grammar);
    ``minShape`` bounds how far the job may shrink — a sub-block below
    its volume is not worth resuming on (model doesn't fit, step time
    unacceptable) and reads as unplaceable instead."""

    shape: str = field(default="")
    min_shape: str = field(json="minShape", default="")
    priority: int = field(default=0)
    preemption_policy: str = field(
        json="preemptionPolicy", default="Never", enum=["Never", "PreemptLower"]
    )
    # optional node-pool pin, forwarded to the owned TPUSlice
    pool: str = field(default="")


@dataclasses.dataclass
class JobCheckpointSpec(SpecBase):
    """Checkpoint cadence: the resume guarantee is "no step lost beyond
    the last checkpoint", so ``everySteps`` IS the blast radius of an
    unplanned fault. ``dir`` names the store location the gang workers
    mount (in-sim: a local directory the harness owns)."""

    every_steps: int = field(json="everySteps", default=10)
    dir: str = field(default="")


@dataclasses.dataclass
class JobBackoffSpec(SpecBase):
    """Restart backoff knobs: consecutive failed attempts (nothing
    placeable, trainer error on resume) back off with full jitter and
    burn the retry budget; a successful return to Running resets the
    streak. Exhaustion quarantines the job in ``Failed``."""

    base_seconds: float = field(json="baseSeconds", default=1.0)
    max_seconds: float = field(json="maxSeconds", default=60.0)
    retry_limit: int = field(json="retryLimit", default=5)


@dataclasses.dataclass
class TPUJobSpec(SpecBase):
    workload: JobWorkloadSpec = sub(JobWorkloadSpec)
    gang: JobGangSpec = sub(JobGangSpec)
    checkpoint: JobCheckpointSpec = sub(JobCheckpointSpec)
    backoff: JobBackoffSpec = sub(JobBackoffSpec)


@dataclasses.dataclass
class TPUJobStatus(SpecBase):
    """``state`` mirrors the FSM phase for printer columns; ``job`` is
    the bookkeeping block (phase, step/epoch watermarks, current vs
    desired shape, shrink history, last restart causes) the controller
    publishes as a key-scoped status patch."""

    state: str = field(default="")
    conditions: List[dict] = field(default_factory=list)
    job: dict = field(default_factory=dict)


@dataclasses.dataclass
class TPUJob:
    metadata: dict
    spec: TPUJobSpec
    status: TPUJobStatus

    @property
    def name(self) -> str:
        return self.metadata.get("name", "")

    @classmethod
    def from_unstructured(cls, obj: dict) -> "TPUJob":
        return cls(
            metadata=obj.get("metadata", {}),
            spec=TPUJobSpec.from_dict(obj.get("spec")),
            status=TPUJobStatus.from_dict(obj.get("status")),
        )

    def to_unstructured(self) -> dict:
        return {
            "apiVersion": TPU_JOB_API_VERSION,
            "kind": TPU_JOB_KIND,
            "metadata": self.metadata,
            "spec": self.spec.to_dict(),
            "status": self.status.to_dict(),
        }


def new_tpu_job(name: str, spec: Optional[dict] = None) -> dict:
    return {
        "apiVersion": TPU_JOB_API_VERSION,
        "kind": TPU_JOB_KIND,
        "metadata": {"name": name},
        "spec": spec or {},
    }
