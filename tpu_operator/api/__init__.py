"""Typed API layer for the operator's CRDs.

TPU-native analog of the reference's ``api/nvidia`` package
(api/nvidia/v1/clusterpolicy_types.go, api/nvidia/v1alpha1/nvidiadriver_types.go).
Objects round-trip to/from their unstructured (dict) wire form at the client
boundary, the way the reference's typed structs round-trip through
apimachinery.
"""

from tpu_operator.api.clusterpolicy import (  # noqa: F401
    CLUSTER_POLICY_API_VERSION,
    CLUSTER_POLICY_KIND,
    ClusterPolicy,
    ClusterPolicySpec,
    State,
)
from tpu_operator.api.tpuslice import (  # noqa: F401
    TPU_SLICE_API_VERSION,
    TPU_SLICE_KIND,
    TPUSlice,
    TPUSliceSpec,
)
