from tpu_operator.states.clusterpolicy_states import (  # noqa: F401
    STATE_ORDER,
    build_render_data,
    new_cluster_policy_states,
)
