"""The ordered operand states driven by ClusterPolicy.

Reference: the 19-entry state registration in
controllers/state_manager.go:791-810. The TPU mapping (SURVEY.md §2.5):

    pre-requisites              -> pre-requisites (operand PriorityClass;
                                   no RuntimeClasses — TPUs need no
                                   container-runtime hook)
    (NFD worker, chart subchart) -> state-node-discovery (the bootstrap
                                   that recognizes TPU hardware on
                                   non-GKE clusters; deploys with no
                                   TPU gate, like NFD runs everywhere)
    state-operator-metrics      -> state-operator-metrics
    state-driver                -> state-libtpu
    state-container-toolkit     -> (none: device plugin mounts /dev/accel*
                                   and libtpu directly)
    state-operator-validation   -> state-operator-validation
    state-device-plugin         -> state-device-plugin
    state-mps-control-daemon    -> (none: no CUDA MPS analog)
    state-dcgm(-exporter)       -> state-metrics-exporter
    gpu-feature-discovery       -> state-tpu-feature-discovery
    state-mig-manager           -> state-slice-manager
    state-node-status-exporter  -> state-node-status-exporter
    sandbox/vgpu/vfio/kata/cc   -> (none: no TPU virtualization analog)

Execution order == list order, enablement gates mirror
``isStateEnabled`` (state_manager.go:990-1034), and operand states are
skipped while the cluster has no TPU nodes (``hasGPUNodes`` skip,
object_controls.go:4089-4096).
"""

from __future__ import annotations

import os
from typing import List

from tpu_operator import consts, images
from tpu_operator.catalog import InfoCatalog
from tpu_operator.state.skel import StateSkel, SyncResult, SyncStates

MANIFEST_ROOT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "manifests")

STATE_ORDER = [
    "pre-requisites",
    "state-node-discovery",
    "state-operator-metrics",
    "state-libtpu",
    "state-device-plugin",
    "state-operator-validation",
    "state-tpu-feature-discovery",
    "state-slice-manager",
    "state-metrics-exporter",
    "state-node-status-exporter",
    "state-health-monitor",
    "state-autotuner",
    "state-compile-cache",
]


def _image_tag(image: str) -> str:
    return image.rsplit(":", 1)[1] if ":" in image else image


def _component_data(spec, key: str, **extra) -> dict:
    data = {
        "image": images.resolve(key, spec),
        "image_pull_policy": spec.image_pull_policy,
        "env": spec.env,
        "args": spec.args,
        "resources": spec.resources,
    }
    data.update(extra)
    return data


def build_render_data(catalog: InfoCatalog) -> dict:
    """One templating-data dict shared by every state's manifests (the
    reference's TemplatingData / per-operand Transform funcs collapsed into
    declarative templates)."""
    spec = catalog.cluster_policy.spec
    ds = spec.daemonsets
    sm_enabled = spec.metrics_exporter.service_monitor.is_enabled()
    from tpu_operator.perf import default_floors, floors_json

    return {
        "namespace": catalog.namespace,
        "runtime": catalog.runtime,
        "tpu_resource": consts.TPU_RESOURCE_NAME,
        "validation_dir": consts.VALIDATION_DIR,
        # per-generation perf floors (pre-requisites renders the
        # ConfigMap; exporter + validator DaemonSets reference it)
        "perf_floors_configmap": consts.PERF_FLOORS_CONFIGMAP,
        "perf_floors": default_floors(),
        "perf_floors_json": floors_json(),
        # published autotune winners (configMapKeyRef, optional: the key
        # appears once the first generation sweep lands)
        "autotune_results_configmap": consts.AUTOTUNE_RESULTS_CONFIGMAP,
        "autotune_winners_key": consts.AUTOTUNE_WINNERS_KEY,
        "libtpu_ready_file": consts.LIBTPU_READY_FILE,
        "plugin_ready_file": consts.PLUGIN_READY_FILE,
        "workload_ready_file": consts.WORKLOAD_READY_FILE,
        "all_ready_file": consts.ALL_READY_FILE,
        "libtpu_ctr_ready_file": consts.LIBTPU_CTR_READY_FILE,
        "service_monitors_enabled": sm_enabled,
        "operator_metrics": {"port": 8080},
        "daemonsets": {
            "labels": ds.labels,
            "annotations": ds.annotations,
            "tolerations": ds.tolerations,
            "priority_class_name": ds.priority_class_name,
            "update_strategy": ds.update_strategy,
            "rolling_update_max_unavailable": (
                ds.rolling_update.max_unavailable if ds.rolling_update else "1"
            ),
        },
        "libtpu": _component_data(spec.libtpu, "libtpu", install_dir=spec.libtpu.install_dir),
        "device_plugin": _component_data(
            spec.device_plugin,
            "device_plugin",
            config_name=spec.device_plugin.config.name,
            config_default=spec.device_plugin.config.default,
            # staleness horizon for the health agent's verdicts file,
            # derived from the agent's own probe cadence: a long interval
            # must not make fresh verdicts look stale mid-tick
            health_verdicts_ttl=max(600, 4 * int(spec.health_monitor.interval or 30)),
        ),
        "tfd": _component_data(spec.tpu_feature_discovery, "tfd"),
        "node_discovery": _component_data(spec.node_discovery, "node_discovery"),
        "slice_manager": _component_data(
            spec.slice_manager,
            "slice_manager",
            config_name=spec.slice_manager.config.name,
            config_default=spec.slice_manager.config.default,
        ),
        "metrics_exporter": _component_data(
            spec.metrics_exporter,
            "metrics_exporter",
            port=spec.metrics_exporter.port,
            service_monitor={
                "enabled": sm_enabled,
                "interval": spec.metrics_exporter.service_monitor.interval,
                "honor_labels": spec.metrics_exporter.service_monitor.honor_labels,
                "additional_labels": spec.metrics_exporter.service_monitor.additional_labels,
            },
        ),
        "node_status_exporter": _component_data(spec.node_status_exporter, "node_status_exporter", port=8000),
        "health_monitor": _component_data(
            spec.health_monitor,
            "health_monitor",
            interval=spec.health_monitor.interval or 30,
            active_probes=spec.health_monitor.active_probes or "auto",
        ),
        "autotuner": _component_data(
            spec.autotuner,
            "autotuner",
            interval=spec.autotuner.interval or 60,
            chips=spec.autotuner.chips or 4,
            # the sweep-cache invalidation key: the libtpu image tag, the
            # same value the autotune controller derives — a rolling
            # libtpu upgrade changes it and re-sweeps every generation
            libtpu_version=_image_tag(images.resolve("libtpu", spec.libtpu)),
            results_configmap=consts.AUTOTUNE_RESULTS_CONFIGMAP,
            elected_label=consts.AUTOTUNE_ELECTED_LABEL,
            elected_value=consts.AUTOTUNE_ELECTED,
        ),
        "compile_cache": _component_data(
            spec.compile_cache,
            "compile_cache",
            interval=spec.compile_cache.interval or 60,
            chips=spec.compile_cache.chips or 4,
            # the record-invalidation key: the libtpu image tag, the
            # same value the compile-cache controller derives — a
            # rolling libtpu upgrade changes it and re-compiles each
            # generation once
            libtpu_version=_image_tag(images.resolve("libtpu", spec.libtpu)),
            cache_configmap=consts.COMPILE_CACHE_CONFIGMAP,
            cache_dir=spec.compile_cache.cache_dir or consts.COMPILE_CACHE_DIR_DEFAULT,
            cache_dir_env=consts.COMPILE_CACHE_DIR_ENV,
            elected_label=consts.COMPILE_CACHE_ELECTED_LABEL,
            elected_value=consts.COMPILE_CACHE_ELECTED,
        ),
        "health_dir": consts.HEALTH_DIR,
        "validator": _component_data(
            spec.validator,
            "validator",
            libtpu_env=spec.validator.libtpu.env,
            plugin_env=spec.validator.plugin.env,
            workload_env=spec.validator.workload.env,
            slice_env=spec.validator.slice.env,
            min_tflops=spec.validator.min_tflops,
            min_psum_gbps_per_chip=spec.validator.min_psum_gbps_per_chip,
        ),
        "multi_slice": {
            "enabled": spec.multi_slice.is_enabled(),
            "coordinator_port": spec.multi_slice.coordinator_port,
        },
    }


class ClusterPolicyState(StateSkel):
    """One operand state of the ClusterPolicy state machine."""

    # operand states deploy per-node DaemonSets and are skipped while the
    # cluster has no TPU nodes (reference: object_controls.go:4089-4096)
    requires_tpu_nodes = True

    def __init__(self, name: str):
        super().__init__(name, [os.path.join(MANIFEST_ROOT, name)])

    def get_render_data(self, catalog: InfoCatalog) -> dict:
        return build_render_data(catalog)

    def sync(self, client, catalog: InfoCatalog, owner=None) -> SyncResult:
        if self.requires_tpu_nodes and not catalog.has_tpu_nodes:
            return SyncResult(state=SyncStates.IGNORE)
        return super().sync(client, catalog, owner)


class PreRequisitesState(ClusterPolicyState):
    requires_tpu_nodes = False

    def __init__(self):
        super().__init__("pre-requisites")


class NodeDiscoveryState(ClusterPolicyState):
    """NFD-analog bootstrap (see manifests/state-node-discovery). MUST
    deploy while the cluster has no recognized TPU nodes — finding them
    is its purpose — so the has-TPU-nodes skip does not apply."""

    requires_tpu_nodes = False

    def __init__(self):
        super().__init__("state-node-discovery")

    def is_enabled(self, catalog: InfoCatalog) -> bool:
        return catalog.cluster_policy.spec.node_discovery.is_enabled()


class OperatorMetricsState(ClusterPolicyState):
    requires_tpu_nodes = False

    def __init__(self):
        super().__init__("state-operator-metrics")


class LibtpuState(ClusterPolicyState):
    def __init__(self):
        super().__init__("state-libtpu")

    def is_enabled(self, catalog: InfoCatalog) -> bool:
        spec = catalog.cluster_policy.spec.libtpu
        # when TPUSlice CRs own libtpu deployment the ClusterPolicy state
        # steps aside (reference: UseNvidiaDriverCRD gate)
        return spec.is_enabled() and not spec.use_slice_crd()


class DevicePluginState(ClusterPolicyState):
    def __init__(self):
        super().__init__("state-device-plugin")

    def is_enabled(self, catalog: InfoCatalog) -> bool:
        return catalog.cluster_policy.spec.device_plugin.is_enabled()


class OperatorValidationState(ClusterPolicyState):
    def __init__(self):
        super().__init__("state-operator-validation")

    def is_enabled(self, catalog: InfoCatalog) -> bool:
        return catalog.cluster_policy.spec.validator.is_enabled()


class TFDState(ClusterPolicyState):
    def __init__(self):
        super().__init__("state-tpu-feature-discovery")

    def is_enabled(self, catalog: InfoCatalog) -> bool:
        return catalog.cluster_policy.spec.tpu_feature_discovery.is_enabled()


class SliceManagerState(ClusterPolicyState):
    def __init__(self):
        super().__init__("state-slice-manager")

    def is_enabled(self, catalog: InfoCatalog) -> bool:
        return catalog.cluster_policy.spec.slice_manager.is_enabled()


class MetricsExporterState(ClusterPolicyState):
    def __init__(self):
        super().__init__("state-metrics-exporter")

    def is_enabled(self, catalog: InfoCatalog) -> bool:
        return catalog.cluster_policy.spec.metrics_exporter.is_enabled()


class NodeStatusExporterState(ClusterPolicyState):
    def __init__(self):
        super().__init__("state-node-status-exporter")

    def is_enabled(self, catalog: InfoCatalog) -> bool:
        return catalog.cluster_policy.spec.node_status_exporter.is_enabled()


class HealthMonitorState(ClusterPolicyState):
    """The node health agent (DCGM-health → node-auto-repair analog):
    probes chips/libtpu/plugin-socket per node and publishes verdicts the
    device plugin and the remediation controller consume."""

    def __init__(self):
        super().__init__("state-health-monitor")

    def is_enabled(self, catalog: InfoCatalog) -> bool:
        return catalog.cluster_policy.spec.health_monitor.is_enabled()


class AutotunerState(ClusterPolicyState):
    """Per-generation kernel autotuning: a DaemonSet whose nodeSelector
    includes the controller-managed election label, so its pod — and
    the chips it claims via the google.com/tpu resource — exists only
    on the one elected node per un-swept generation, for exactly the
    sweep window."""

    def __init__(self):
        super().__init__("state-autotuner")

    def is_enabled(self, catalog: InfoCatalog) -> bool:
        return catalog.cluster_policy.spec.autotuner.is_enabled()


class CompileCacheState(ClusterPolicyState):
    """Persistent compile cache prewarm: a DaemonSet whose nodeSelector
    includes the controller-managed election label, so its pod — and
    the chips it claims via the google.com/tpu resource — exists only
    on the one elected node per generation with unsatisfied prewarm
    demand, for exactly the compile window. The node-local cache
    directory (hostPath) keeps the serialized executables after the
    pod is descheduled."""

    def __init__(self):
        super().__init__("state-compile-cache")

    def is_enabled(self, catalog: InfoCatalog) -> bool:
        return catalog.cluster_policy.spec.compile_cache.is_enabled()


def new_cluster_policy_states() -> List[StateSkel]:
    """reference: addState x19, state_manager.go:791-810."""
    states = [
        PreRequisitesState(),
        NodeDiscoveryState(),
        OperatorMetricsState(),
        LibtpuState(),
        DevicePluginState(),
        OperatorValidationState(),
        TFDState(),
        SliceManagerState(),
        MetricsExporterState(),
        NodeStatusExporterState(),
        HealthMonitorState(),
        AutotunerState(),
        CompileCacheState(),
    ]
    assert [s.name for s in states] == STATE_ORDER
    return states
