"""TPUSlice libtpu state: per-node-pool DaemonSet fan-out.

Reference: ``internal/state/driver.go`` — ``stateDriver`` renders the
driver DaemonSet once per node pool (driver.go:222-278) with unique names
(getDriverName driver.go:406-460), cleans stale DaemonSets for vanished
pools (:173-201), and is owned by one NVIDIADriver CR. Here: one libtpu
DaemonSet per (accelerator type, topology, GKE pool), owned by one
TPUSlice CR, with OnDelete update strategy so version bumps are rolled by
the upgrade controller, not the DS controller.
"""

from __future__ import annotations

import os
import re
from typing import List

from tpu_operator.utils import object_hash

from tpu_operator import consts, images
from tpu_operator.api.tpuslice import TPUSlice
from tpu_operator.catalog import InfoCatalog
from tpu_operator.kube.objects import ObjectDict
from tpu_operator.nodepool import NodePool
from tpu_operator.render import Renderer
from tpu_operator.state.skel import StateSkel
from tpu_operator.states.clusterpolicy_states import MANIFEST_ROOT


def _dns_safe(name: str) -> str:
    """DNS-1123 truncation with a content-hash suffix: long slice+pool
    combinations must never collide to one DaemonSet name (the reference
    hashes into getDriverName for the same reason)."""
    clean = re.sub(r"[^a-z0-9-]", "-", name.lower()).strip("-")
    if len(clean) <= 63:
        return clean
    return f"{clean[:54].rstrip('-')}-{object_hash(name)[:8]}"


def ds_name_for(slice_name: str, pool: NodePool) -> str:
    """reference: getDriverName/getDriverAppName driver.go:406-460."""
    return _dns_safe(f"libtpu-{slice_name}-{pool.name}")


class TPUSliceLibtpuState(StateSkel):
    """State label value is per-CR so two TPUSlice CRs never collect each
    other's objects during stale cleanup."""

    def __init__(self, tpu_slice: TPUSlice):
        super().__init__(
            f"tpuslice-{tpu_slice.name}",
            [os.path.join(MANIFEST_ROOT, "tpuslice-libtpu-common")],
        )
        self.tpu_slice = tpu_slice
        self.pool_renderer = Renderer([os.path.join(MANIFEST_ROOT, "tpuslice-libtpu-pool")])

    def _common_data(self, catalog: InfoCatalog) -> dict:
        spec = self.tpu_slice.spec
        return {
            "namespace": catalog.namespace,
            "slice_name": self.tpu_slice.name,
            "slice_labels": spec.labels,
            "slice_annotations": spec.annotations,
            "tpu_resource": consts.TPU_RESOURCE_NAME,
            "validation_dir": consts.VALIDATION_DIR,
            "install_dir": spec.install_dir,
            "image": images.resolve("libtpu", spec),
            "image_pull_policy": spec.image_pull_policy,
            "env": spec.env,
            "args": spec.args,
            "resources": spec.resources,
            "priority_class_name": spec.priority_class_name,
            "tolerations": spec.tolerations,
            "node_affinity": spec.node_affinity,
        }

    def render_all(self, catalog: InfoCatalog) -> List[ObjectDict]:
        data = self._common_data(catalog)
        objects = self.renderer.render_objects(data)
        for pool in catalog.node_pools or []:
            pool_selector = dict(pool.selector)
            pool_selector.update(self.tpu_slice.spec.node_selector)
            pool_data = dict(
                data,
                pool=pool,
                ds_name=ds_name_for(self.tpu_slice.name, pool),
                pool_selector=pool_selector,
            )
            objects.extend(self.pool_renderer.render_objects(pool_data))
        return objects
