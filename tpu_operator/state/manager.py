"""State manager: ordered list of states + aggregate sync.

Reference: ``internal/state/manager.go:31-128`` — ``SyncState`` iterates the
states and aggregates per-state results into one overall status.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import List, Optional

from tpu_operator.kube.client import Client
from tpu_operator.kube.objects import ObjectDict
from tpu_operator.state.skel import StateSkel, SyncResult, SyncStates

log = logging.getLogger(__name__)


@dataclasses.dataclass
class Results:
    status: str
    states: dict  # state name -> SyncResult

    @property
    def ready(self) -> bool:
        return self.status == SyncStates.READY


class StateManager:
    def __init__(self, states: List[StateSkel]):
        self.states = list(states)

    def state_names(self) -> List[str]:
        return [s.name for s in self.states]

    def sync_state(self, client: Client, catalog, owner: Optional[ObjectDict] = None) -> Results:
        """reference: Manager.SyncState manager.go:75-109."""
        per_state = {}
        overall = SyncStates.READY
        for state in self.states:
            result = state.sync(client, catalog, owner)
            per_state[state.name] = result
            if result.state == SyncStates.ERROR:
                overall = SyncStates.ERROR
            elif result.state == SyncStates.NOT_READY and overall != SyncStates.ERROR:
                overall = SyncStates.NOT_READY
            log.debug("state %s -> %s", state.name, result.state)
        return Results(status=overall, states=per_state)
