"""State skeleton: shared create-or-update / readiness machinery.

Reference: ``stateSkel`` internal/state/state_skel.go:43-50 — render
manifests, stamp owner references + state labels, apply with the
last-applied-hash annotation so unchanged objects are never rewritten
(update-loop / spec-thrash protection, SURVEY.md §7 "hard part (b)"), then
report readiness per kind.
"""

from __future__ import annotations

import copy
import dataclasses
import logging
from typing import Callable, Dict, List, Optional

from tpu_operator import consts, utils
from tpu_operator.kube import errors
from tpu_operator.kube.client import Client
from tpu_operator.kube.objects import (
    ObjectDict,
    get_annotation,
    object_key,
    set_annotation,
    set_label,
    set_owner_reference,
)
from tpu_operator.render import Renderer

log = logging.getLogger(__name__)


class SyncStates:
    """reference: SyncStateReady/NotReady/Ignore/Error (internal/state/types)."""

    READY = "ready"
    NOT_READY = "notReady"
    IGNORE = "ignore"
    ERROR = "error"


@dataclasses.dataclass
class SyncResult:
    state: str
    objects: List[ObjectDict] = dataclasses.field(default_factory=list)
    error: Optional[str] = None

    @property
    def ready(self) -> bool:
        return self.state in (SyncStates.READY, SyncStates.IGNORE)


# readiness checker signature: (client, desired_obj) -> bool
ReadinessCheck = Callable[[Client, ObjectDict], bool]


def daemonset_ready(client: Client, obj: ObjectDict) -> bool:
    """reference: isDaemonSetReady object_controls.go:3439-3515 /
    state_skel.go:383-444 — a DaemonSet is ready when every scheduled pod is
    available AND up to date; zero desired pods (no matching nodes) counts
    as ready so operands no-op on clusters without their nodes."""
    md = obj["metadata"]
    try:
        live = client.get(obj["apiVersion"], obj["kind"], md["name"], md.get("namespace"))  # tpuop-lint: kinds=apps/v1/DaemonSet
    except errors.NotFound:
        return False
    status = live.get("status", {})
    desired = status.get("desiredNumberScheduled", 0)
    if desired == 0:
        return True
    return (
        status.get("numberAvailable", 0) == desired
        and status.get("updatedNumberScheduled", 0) == desired
    )


def deployment_ready(client: Client, obj: ObjectDict) -> bool:
    md = obj["metadata"]
    try:
        # no shipped state renders a Deployment today; the check exists for
        # render completeness only, so it contributes no RBAC requirement
        live = client.get(obj["apiVersion"], obj["kind"], md["name"], md.get("namespace"))  # tpuop-lint: ignore
    except errors.NotFound:
        return False
    want = live.get("spec", {}).get("replicas", 1)
    return live.get("status", {}).get("availableReplicas", 0) >= want


def pod_succeeded_or_running(client: Client, obj: ObjectDict) -> bool:
    md = obj["metadata"]
    try:
        live = client.get(obj["apiVersion"], obj["kind"], md["name"], md.get("namespace"))  # tpuop-lint: kinds=v1/Pod
    except errors.NotFound:
        return False
    return live.get("status", {}).get("phase") in ("Running", "Succeeded")


READINESS_CHECKS: Dict[str, ReadinessCheck] = {
    "DaemonSet": daemonset_ready,
    "Deployment": deployment_ready,
    "Pod": pod_succeeded_or_running,
    # everything else (SA/Role/RB/CM/Service/ServiceMonitor/...) is ready on
    # creation, like the reference's supported-GVK handling
}


def _strip_volatile(obj: ObjectDict) -> ObjectDict:
    """Content relevant for change detection: everything except server-set
    metadata and status."""
    md = obj.get("metadata", {})
    kept_md = {
        k: v
        for k, v in md.items()
        if k in ("name", "namespace", "labels", "annotations", "ownerReferences")
    }
    annotations = dict(kept_md.get("annotations") or {})
    annotations.pop(consts.LAST_APPLIED_HASH_ANNOTATION, None)
    if annotations:
        kept_md["annotations"] = annotations
    else:
        kept_md.pop("annotations", None)
    out = {k: v for k, v in obj.items() if k not in ("metadata", "status")}
    out["metadata"] = kept_md
    return out


def desired_hash(obj: ObjectDict) -> str:
    return utils.object_hash(_strip_volatile(obj))


class StateSkel:
    """Base class for all operand states."""

    name: str = ""
    description: str = ""

    def __init__(self, name: str, manifest_dirs: List[str]):
        self.name = name
        self.renderer = Renderer(manifest_dirs)

    # -- hooks ---------------------------------------------------------------

    def get_render_data(self, catalog) -> dict:
        """Build the templating-data dict from the info catalog (cluster
        policy spec, cluster facts...). Subclasses override."""
        return {}

    def is_enabled(self, catalog) -> bool:
        """Enablement gate (reference: isStateEnabled state_manager.go:990)."""
        return True

    # -- sync ----------------------------------------------------------------

    def render_all(self, catalog) -> List[ObjectDict]:
        """All desired objects for this state. Default: one render pass over
        the manifest dir; fan-out states (per-node-pool DaemonSets, the
        reference's stateDriver pattern driver.go:222-278) override this to
        render once per pool. Renders are memoized on the render-data hash:
        a steady-state reconcile (same spec, same cluster facts) costs one
        dict hash instead of a full jinja pass over every manifest."""
        data = self.get_render_data(catalog)
        data_hash = utils.object_hash(data)
        cached = getattr(self, "_render_cache", None)
        if cached is not None and cached[0] == data_hash:
            return copy.deepcopy(cached[1])
        objects = self.renderer.render_objects(data)
        self._render_cache = (data_hash, copy.deepcopy(objects))
        return objects

    def sync(self, client: Client, catalog, owner: Optional[ObjectDict] = None) -> SyncResult:
        if not self.is_enabled(catalog):
            self.delete_owned(client, catalog)
            return SyncResult(state=SyncStates.IGNORE)
        try:
            objects = self.render_all(catalog)
        except Exception as e:  # noqa: BLE001 — render failure is a state error
            log.exception("state %s: render failed", self.name)
            return SyncResult(state=SyncStates.ERROR, error=str(e))
        desired_keys = set()
        for obj in objects:
            self._decorate(obj, owner)
            desired_keys.add(object_key(obj))
            try:
                self.apply_object(client, obj)
            except errors.ApiError as e:
                log.warning("state %s: apply %s failed: %s", self.name, obj["metadata"].get("name"), e)
                return SyncResult(state=SyncStates.ERROR, objects=objects, error=str(e))
        self.delete_owned(client, catalog, keep=desired_keys)
        ready = all(self.check_ready(client, obj) for obj in objects)
        return SyncResult(state=SyncStates.READY if ready else SyncStates.NOT_READY, objects=objects)

    def _decorate(self, obj: ObjectDict, owner: Optional[ObjectDict]) -> None:
        set_label(obj, consts.STATE_LABEL, self.name)
        if owner is not None:
            set_owner_reference(obj, owner)
        set_annotation(obj, consts.LAST_APPLIED_HASH_ANNOTATION, desired_hash(obj))

    def apply_object(self, client: Client, obj: ObjectDict) -> None:
        """Create-or-update gated on the hash annotation
        (reference: state_skel.go:223-285 + DaemonSet hash discipline
        object_controls.go:4177-4212).

        Reads may be served from an informer cache (CachedReadClient), so
        a just-created object can look absent for one watch delivery; the
        AlreadyExists fallback re-reads LIVE and updates, instead of
        failing the whole state sync until the cache catches up."""
        md = obj["metadata"]
        try:
            existing = client.get(obj["apiVersion"], obj["kind"], md["name"], md.get("namespace"))  # tpuop-lint: kinds=state-owned
        except errors.NotFound:
            try:
                client.create(obj)  # tpuop-lint: kinds=state-owned
                return
            except errors.AlreadyExists:
                live = getattr(client, "live", client)
                existing = live.get(obj["apiVersion"], obj["kind"], md["name"], md.get("namespace"))  # tpuop-lint: kinds=state-owned
        if get_annotation(existing, consts.LAST_APPLIED_HASH_ANNOTATION) == get_annotation(
            obj, consts.LAST_APPLIED_HASH_ANNOTATION
        ):
            return  # unchanged — never rewrite (no thrash)
        merged = dict(obj)
        merged_md = dict(md)
        merged_md["resourceVersion"] = existing["metadata"].get("resourceVersion")
        merged.pop("status", None)
        merged["metadata"] = merged_md
        client.update(merged)  # tpuop-lint: kinds=state-owned

    def delete_owned(self, client: Client, catalog, keep: Optional[set] = None) -> None:
        """Delete every object carrying this state's ownership label that is
        no longer desired (reference: stale cleanup via state label,
        state_skel.go:62-165 supported-GVK delete list)."""
        keep = keep or set()
        selector = {consts.STATE_LABEL: self.name}
        for api_version, kind in self.owned_kinds():
            try:
                for obj in client.list(api_version, kind, label_selector=selector):  # tpuop-lint: kinds=state-owned
                    if object_key(obj) in keep:
                        continue
                    md = obj["metadata"]
                    try:
                        client.delete(api_version, kind, md["name"], md.get("namespace"))  # tpuop-lint: kinds=state-owned
                        log.info("state %s: deleted stale %s %s", self.name, kind, md["name"])
                    except errors.NotFound:
                        pass
            except errors.ApiError:
                continue

    def owned_kinds(self) -> List[tuple]:
        """(apiVersion, kind) pairs this state may have created — the delete
        list scanned for stale objects."""
        return [
            ("apps/v1", "DaemonSet"),
            ("v1", "ServiceAccount"),
            ("v1", "ConfigMap"),
            ("v1", "Service"),
            ("rbac.authorization.k8s.io/v1", "Role"),
            ("rbac.authorization.k8s.io/v1", "RoleBinding"),
            ("rbac.authorization.k8s.io/v1", "ClusterRole"),
            ("rbac.authorization.k8s.io/v1", "ClusterRoleBinding"),
            ("monitoring.coreos.com/v1", "ServiceMonitor"),
            ("monitoring.coreos.com/v1", "PrometheusRule"),
            ("scheduling.k8s.io/v1", "PriorityClass"),
        ]

    def check_ready(self, client: Client, obj: ObjectDict) -> bool:
        check = READINESS_CHECKS.get(obj["kind"])
        if check is None:
            return True
        return check(client, obj)
