"""State engine (reference: internal/state — the v2 engine).

Per SURVEY.md §7.2 the rebuild adopts the reference's v2 design everywhere:
every operand is a ``State`` that renders templated manifests into objects
and create-or-updates them with hash-annotation discipline, rather than the
v1 typed-``Resources``/``controlFunc`` duplication of
controllers/object_controls.go.
"""

from tpu_operator.state.skel import StateSkel, SyncResult, SyncStates  # noqa: F401
from tpu_operator.state.manager import StateManager  # noqa: F401
