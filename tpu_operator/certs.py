"""Webhook serving-certificate management with rotation.

Reference: the GPU operator ships webhook certs via helm/OLM conventions
and leaves renewal to cert-manager. This operator owns the loop itself
(cert-manager is not a given on GKE): a CA + serving cert pair is
generated on first start, republished to the TLS Secret the Deployment
mounts, and the ValidatingWebhookConfiguration's per-webhook caBundle is
patched so the apiserver trusts the new chain. A background loop
re-checks expiry and rotates before the not-after date; the serving
socket reloads the chain in place so admissions keep flowing through a
rotation (WebhookServer.reload_certs).
"""

from __future__ import annotations

import base64
import datetime
import logging
import os
import threading
from typing import Optional, Tuple

from tpu_operator.kube import errors
from tpu_operator.kube.client import Client
from tpu_operator.kube.objects import new_object

log = logging.getLogger(__name__)

DAY = 24 * 3600

_PEM_CERT_END = b"-----END CERTIFICATE-----"


def _split_pem_certs(bundle: bytes):
    """Split a PEM bundle into individual certificate blocks."""
    certs = []
    rest = bundle
    while _PEM_CERT_END in rest:
        head, _, rest = rest.partition(_PEM_CERT_END)
        certs.append(head.lstrip() + _PEM_CERT_END + b"\n")
    return certs


def _new_key():
    from cryptography.hazmat.primitives.asymmetric import rsa

    return rsa.generate_private_key(public_exponent=65537, key_size=2048)


def _key_pem(key) -> bytes:
    from cryptography.hazmat.primitives import serialization

    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption(),
    )


def make_ca(common_name: str, validity_seconds: int):
    """Self-signed CA. Returns (ca_cert, ca_key)."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes
    from cryptography.x509.oid import NameOID

    key = _new_key()
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(seconds=60))
        .not_valid_after(now + datetime.timedelta(seconds=validity_seconds))
        .add_extension(x509.BasicConstraints(ca=True, path_length=None), critical=True)
        .sign(key, hashes.SHA256())
    )
    return cert, key


def issue_serving_cert(ca_cert, ca_key, hostname: str, sans, validity_seconds: int):
    """CA-signed serving cert for the webhook Service DNS names.
    Returns (cert_pem, key_pem) with the CA appended to the chain."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.x509.oid import NameOID

    key = _new_key()
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, hostname)]))
        .issuer_name(ca_cert.subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(seconds=60))
        .not_valid_after(now + datetime.timedelta(seconds=validity_seconds))
        .add_extension(
            x509.SubjectAlternativeName([x509.DNSName(n) for n in sans]), critical=False
        )
        .sign(ca_key, hashes.SHA256())
    )
    chain = cert.public_bytes(serialization.Encoding.PEM) + ca_cert.public_bytes(
        serialization.Encoding.PEM
    )
    return chain, _key_pem(key)


class WebhookCertManager:
    """Generate, publish, and rotate the webhook's serving certificate.

    All state lives on disk (cert_dir) and in the cluster (Secret +
    VWC caBundle), so restarts resume cleanly — the same statelessness
    contract the rest of the operator follows.
    """

    def __init__(
        self,
        client: Optional[Client],
        namespace: str,
        cert_dir: str,
        service: str = "tpu-operator-webhook",
        secret_name: str = "tpu-operator-webhook-tls",
        vwc_name: str = "tpu-operator",
        validity_seconds: int = 365 * DAY,
        rotate_before_seconds: int = 30 * DAY,
    ):
        self.client = client
        self.namespace = namespace
        self.cert_dir = cert_dir
        self.service = service
        self.secret_name = secret_name
        self.vwc_name = vwc_name
        self.validity_seconds = validity_seconds
        self.rotate_before_seconds = rotate_before_seconds
        self.cert_path = os.path.join(cert_dir, "tls.crt")
        self.key_path = os.path.join(cert_dir, "tls.key")
        self._server = None  # attached WebhookServer, reloaded on rotation
        self._stop = threading.Event()

    # -- inspection ----------------------------------------------------------

    def expires_at(self) -> Optional[datetime.datetime]:
        from cryptography import x509

        try:
            with open(self.cert_path, "rb") as f:
                cert = x509.load_pem_x509_certificate(f.read())
        except (OSError, ValueError):
            return None
        return cert.not_valid_after_utc

    def needs_rotation(self) -> bool:
        expires = self.expires_at()
        if expires is None:
            return True
        remaining = (expires - datetime.datetime.now(datetime.timezone.utc)).total_seconds()
        return remaining <= self.rotate_before_seconds

    # -- rotation ------------------------------------------------------------

    def ensure(self) -> bool:
        """Converge the serving cert; returns True when it changed.

        Order is trust-first so admissions never break mid-sequence:
        (1) adopt a still-fresh cert from the published Secret (restart /
        second replica: converge on the shared cert instead of minting a
        competing CA); else (2) append the new CA to every VWC caBundle
        (old CAs kept, so apiservers with a cached bundle still verify),
        (3) publish the Secret, (4) write disk, (5) hot-reload the server.
        Any publish failure aborts before the serving cert changes and
        retries on the next loop pass."""
        if not self.needs_rotation():
            # disk cert is fine, but the published Secret/caBundle may have
            # drifted (helm upgrade reapplying an empty caBundle, deleted
            # Secret) — reconcile them from disk every pass
            self._sync_published()
            return False
        if self._adopt_from_secret():
            if self._server is not None:
                self._server.reload_certs()
            # the VWC caBundle may not carry the adopted chain's CA (e.g. a
            # helm upgrade reapplied an empty bundle while we were down);
            # with failurePolicy=Fail that blocks every CR write until the
            # next pass, so re-assert trust before declaring success
            self._sync_published()
            log.info("webhook cert adopted from Secret %s", self.secret_name)
            return True
        sans = [
            self.service,
            f"{self.service}.{self.namespace}",
            f"{self.service}.{self.namespace}.svc",
        ]
        ca_cert, ca_key = make_ca(f"{self.service}-ca", self.validity_seconds)
        cert_pem, key_pem = issue_serving_cert(
            ca_cert, ca_key, sans[-1], sans, self.validity_seconds
        )
        from cryptography.hazmat.primitives import serialization

        ca_pem = ca_cert.public_bytes(serialization.Encoding.PEM)
        if not self._patch_vwc_bundle(ca_pem):
            return False
        if not self._publish_secret(cert_pem, key_pem):
            return False
        self._write_atomic(self.cert_path, cert_pem)
        self._write_atomic(self.key_path, key_pem, mode=0o600)
        if self._server is not None:
            self._server.reload_certs()
        log.info(
            "webhook cert rotated (expires %s)", self.expires_at().isoformat(timespec="seconds")
        )
        return True

    def _read_disk_chain(self) -> Optional[Tuple[bytes, bytes, bytes]]:
        """(cert_pem, key_pem, ca_pem) from disk, or None when absent.
        The CA is the chain's last cert; a single-cert file is its own CA
        (self-signed bootstrap)."""
        try:
            with open(self.cert_path, "rb") as f:
                cert_pem = f.read()
            with open(self.key_path, "rb") as f:
                key_pem = f.read()
        except OSError:
            return None
        chain = _split_pem_certs(cert_pem)
        ca_pem = chain[-1] if len(chain) > 1 else chain[0] if chain else b""
        if not ca_pem:
            return None
        return cert_pem, key_pem, ca_pem

    def _sync_published(self) -> None:
        """Re-assert the cluster-published state from the disk cert: the
        Secret must carry the same chain and every VWC bundle must contain
        our CA (drift here breaks admissions long before expiry)."""
        if self.client is None:
            return
        disk = self._read_disk_chain()
        if disk is None:
            return
        cert_pem, key_pem, ca_pem = disk
        try:
            secret = self.client.get_or_none("v1", "Secret", self.secret_name, self.namespace)
        except errors.ApiError:
            return
        data = (secret or {}).get("data") or {}
        if base64.b64decode(data.get("tls.crt", "") or "") != cert_pem:
            # the cert manager runs on every replica, not just the leader:
            # when the Secret differs, prefer adopting it (it is the shared
            # source of truth) — republishing unconditionally would have two
            # replicas that minted independently rewrite the Secret back and
            # forth every pass. Republish only when the Secret's cert is
            # stale or malformed.
            if self._adopt_from_secret():
                if self._server is not None:
                    self._server.reload_certs()
                disk = self._read_disk_chain()
                if disk is None:
                    return
                cert_pem, key_pem, ca_pem = disk
            else:
                self._publish_secret(cert_pem, key_pem)
        try:
            vwc = self.client.get_or_none(
                "admissionregistration.k8s.io/v1",
                "ValidatingWebhookConfiguration",
                self.vwc_name,
            )
        except errors.ApiError:
            return
        if vwc is None:
            return
        missing = any(
            ca_pem.strip()
            not in base64.b64decode(h.get("clientConfig", {}).get("caBundle", "") or "")
            for h in vwc.get("webhooks", [])
        )
        if missing:
            self._patch_vwc_bundle(ca_pem)

    def _adopt_from_secret(self) -> bool:
        """Use the cluster Secret's cert when it is fresher than ours —
        the shared source of truth across restarts and replicas."""
        if self.client is None:
            return False
        from cryptography import x509
        from cryptography.hazmat.primitives import serialization

        try:
            secret = self.client.get_or_none("v1", "Secret", self.secret_name, self.namespace)
        except errors.ApiError:
            return False
        data = (secret or {}).get("data") or {}
        if "tls.crt" not in data or "tls.key" not in data:
            return False
        try:
            cert_pem = base64.b64decode(data["tls.crt"])
            key_pem = base64.b64decode(data["tls.key"])
            cert = x509.load_pem_x509_certificate(cert_pem)
            key = serialization.load_pem_private_key(key_pem, password=None)
            # a mismatched pair must never land on disk: load_cert_chain
            # would fail and needs_rotation() would still report fresh
            if key.public_key().public_numbers() != cert.public_key().public_numbers():
                return False
        except Exception:  # noqa: BLE001 — malformed secret: mint fresh
            return False
        remaining = (
            cert.not_valid_after_utc - datetime.datetime.now(datetime.timezone.utc)
        ).total_seconds()
        if remaining <= self.rotate_before_seconds:
            return False
        self._write_atomic(self.cert_path, cert_pem)
        self._write_atomic(self.key_path, key_pem, mode=0o600)
        return True

    @staticmethod
    def _write_atomic(path: str, data: bytes, mode: int = 0o644) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, mode)
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    # how many predecessor CAs stay in the caBundle through rotations
    _KEEP_OLD_CAS = 2

    def _patch_vwc_bundle(self, new_ca_pem: bytes) -> bool:
        """Prepend the new CA to every webhook's caBundle, keeping recent
        predecessors so apiservers holding a cached bundle (or pods still
        serving the previous cert) stay verifiable through the rollover."""
        if self.client is None:
            return True
        try:
            vwc = self.client.get_or_none(
                "admissionregistration.k8s.io/v1",
                "ValidatingWebhookConfiguration",
                self.vwc_name,
            )
        except errors.ApiError as e:
            log.warning("could not read VWC %s: %s", self.vwc_name, e)
            return False
        if vwc is None:
            return True  # no VWC installed (e.g. chart webhook disabled): nothing to trust-sync
        for hook in vwc.get("webhooks", []):
            cfg = hook.setdefault("clientConfig", {})
            old = base64.b64decode(cfg.get("caBundle", "") or "")
            keep = _split_pem_certs(old)[: self._KEEP_OLD_CAS]
            cfg["caBundle"] = base64.b64encode(new_ca_pem + b"".join(keep)).decode()
        try:
            self.client.update(vwc)
            return True
        except errors.ApiError as e:
            log.warning("could not patch VWC caBundle: %s", e)
            return False

    def _publish_secret(self, cert_pem: bytes, key_pem: bytes) -> bool:
        if self.client is None:
            return True
        secret = new_object(
            "v1",
            "Secret",
            self.secret_name,
            self.namespace,
            type="kubernetes.io/tls",
            data={
                "tls.crt": base64.b64encode(cert_pem).decode(),
                "tls.key": base64.b64encode(key_pem).decode(),
            },
        )
        try:
            self.client.apply(secret)
            return True
        except errors.ApiError as e:
            log.warning("could not publish webhook Secret: %s", e)
            return False

    # -- serving integration -------------------------------------------------

    def attach(self, server) -> None:
        self._server = server

    def run_forever(self, interval: float = 3600.0) -> None:
        while not self._stop.is_set():
            try:
                self.ensure()
            except Exception as e:  # noqa: BLE001 — rotation must retry, never die
                log.warning("cert rotation check failed: %s", e)
            # while the cert is missing/expiring (e.g. bootstrap against an
            # unreachable apiserver), retry fast instead of hourly
            try:
                wait = interval if not self.needs_rotation() else min(interval, 15.0)
            except Exception:  # noqa: BLE001
                wait = interval
            self._stop.wait(wait)

    def start(self, interval: float = 3600.0) -> "WebhookCertManager":
        threading.Thread(target=self.run_forever, args=(interval,), daemon=True).start()
        return self

    def stop(self) -> None:
        self._stop.set()
