"""3-D torus model of one node pool's hosts + the block allocator.

Coordinates come from the ``tpu.google.com/torus-coords`` node label
("x-y-z", published by node discovery from the TPU VM runtime contract,
or stamped by the platform). Pools whose nodes carry no coordinates
degrade to a deterministic row-major layout over the sorted node names —
placement still works, it just can't see the real wiring.

Search is wraparound-aware where the hardware is: the ICI links wrap on
every axis of a pod-scale 3-D torus (v4/v5p), so a block crossing the
"edge" is exactly as contiguous as one in the middle — but v5e/v6e are
2-D meshes with no wrap links, so ``wrap=False`` pools only place blocks
that fit without folding (a wrapped block there would advertise an ICI
hop that doesn't exist and silently degrade the gang onto DCN). The
allocator prefers snug placements (least free surface exposed) so large
blocks keep finding room — the best-fit fragmentation score the
placement engine ranks candidates by.
"""

from __future__ import annotations

import dataclasses
import itertools
from collections import Counter
from typing import Callable, Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from tpu_operator import consts
from tpu_operator.kube.objects import ObjectDict
from tpu_operator.nodeinfo import parse_topology

Coord = Tuple[int, int, int]

# Per-host chip geometry by local chip count: how a host's chips sit in
# the chip-level torus (v4/v5p attach 4 chips as a 2x2x1 block; 8-chip
# v5e hosts span 2x4 of the 2-D mesh). Used both to derive the host grid
# from a chip topology and to express a placed host block back in chips.
_HOST_CHIP_BLOCKS: Dict[int, Tuple[int, int, int]] = {
    1: (1, 1, 1),
    4: (2, 2, 1),
    8: (2, 4, 1),
}

_NEIGHBOR_STEPS: Tuple[Coord, ...] = (
    (1, 0, 0), (-1, 0, 0), (0, 1, 0), (0, -1, 0), (0, 0, 1), (0, 0, -1),
)


def parse_shape(shape: str) -> Optional[Coord]:
    """'4x4x4' -> (4, 4, 4); '2x4' -> (2, 4, 1); invalid/empty -> None."""
    dims = parse_topology(shape)
    if not dims or len(dims) > 3:
        return None
    while len(dims) < 3:
        dims.append(1)
    return (dims[0], dims[1], dims[2])


def host_grid_dims(topology: str, chips_per_host: int) -> Optional[Coord]:
    """The host-level grid implied by a chip topology: each axis divides
    by the per-host chip block where it can ('16x16x8' @ 4 chips/host ->
    (8, 8, 8) hosts). None when the topology doesn't parse or a block
    axis doesn't divide its topology axis (unknown wiring — callers fall
    back to a 1-D chain, which the allocator still handles)."""
    dims = parse_shape(topology)
    if dims is None:
        return None
    block = _HOST_CHIP_BLOCKS.get(max(1, chips_per_host))
    if block is None:
        return None
    grid = []
    for axis, per_host in zip(dims, block):
        if axis % per_host:
            return None
        grid.append(axis // per_host)
    return (grid[0], grid[1], grid[2])


def chip_topology_for(shape: Coord, chips_per_host: int, topology_dims: int = 3) -> str:
    """A placed host block expressed in chips — what gang workers expect
    in TPU_TOPOLOGY ('2x2x2' hosts @ 4 chips/host -> '4x4x2'). The
    string follows the generation's convention: 3-D torus generations
    (v4/v5p) always write three axes ('4x4x1'), 2-D mesh generations
    (v5e/v6e) drop the trailing unit axis ('4x4')."""
    block = _HOST_CHIP_BLOCKS.get(max(1, chips_per_host), (1, 1, 1))
    dims = [s * b for s, b in zip(shape, block)]
    while len(dims) > max(2, topology_dims) and dims[-1] == 1:
        dims.pop()
    return "x".join(str(d) for d in dims)


def worker_coords(worker_id: int, dims: Coord) -> Coord:
    """Row-major (x fastest) coordinate of one worker in a host grid —
    the Cloud TPU VM worker-id enumeration order."""
    x_dim, y_dim, _ = dims
    return (worker_id % x_dim, (worker_id // x_dim) % y_dim, worker_id // (x_dim * y_dim))


def _near_cubic_dims(n: int) -> Coord:
    """The most-cubic (a>=b>=c) factorization of n — the fallback grid
    when nodes carry no coordinates. Deterministic in n alone."""
    best = (n, 1, 1)
    for c in range(1, int(round(n ** (1 / 3))) + 2):
        if n % c:
            continue
        m = n // c
        for b in range(c, int(m ** 0.5) + 1):
            if m % b:
                continue
            cand = (m // b, b, c)
            if cand[0] >= cand[1] >= cand[2] and max(cand) < max(best):
                best = cand
    return best


@dataclasses.dataclass(frozen=True)
class Block:
    """One concrete candidate placement: an origin + oriented shape and
    the wrapped cell set it covers, cells in row-major block order (so
    worker ids follow the ICI wiring)."""

    origin: Coord
    shape: Coord  # the oriented (possibly rotated) shape actually placed
    cells: Tuple[Coord, ...]
    exposure: int = 0  # free-surface score at find time (lower = snugger)

    @property
    def origin_str(self) -> str:
        return "-".join(str(c) for c in self.origin)


class Torus:
    """Occupancy model of one pool's host torus. Cells are host
    coordinates; each holds at most one owner (a TPUSlice placement).
    Unavailable cells (quarantined / in-repair / missing hosts) are
    never free and never count as preemptable."""

    def __init__(self, dims: Coord, node_at: Dict[Coord, str], wrap: bool = True):
        self.dims = dims
        self.wrap = wrap  # False on mesh generations: no edge links
        self.node_at = dict(node_at)  # coord -> node name
        self.coords_of = {n: c for c, n in self.node_at.items()}
        self._owner: Dict[Coord, str] = {}
        self._unavailable: Set[Coord] = set()
        # severed ICI links (fabric-telemetry link blame): a block may
        # not contain BOTH endpoints of a cut edge — its collectives
        # would route the degraded cable — but each endpoint host alone
        # stays fully placeable
        self._cut_edges: Set[FrozenSet[Coord]] = set()

    # -- construction --------------------------------------------------------

    @classmethod
    def from_nodes(
        cls,
        nodes: Sequence[ObjectDict],
        wrap: bool = True,
        grid: Optional[Coord] = None,
    ) -> "Torus":
        """Build from one pool's nodes. Every node must carry a distinct
        torus-coords label for the labelled layout to be trusted; any
        gap or duplicate drops the whole pool to the deterministic
        row-major fallback (a half-labelled pool must not mix layouts).
        ``wrap=False`` for mesh generations without edge ICI links.
        ``grid`` is the pool's true host-grid size (from its topology
        label): without it the dims are inferred as max(coord)+1, which
        on a partially-registered pool understates the torus and invents
        wrap adjacency between hosts that are really several hops apart
        — unregistered positions become holes instead."""
        named = sorted(nodes, key=lambda n: n["metadata"]["name"])
        coords: Dict[Coord, str] = {}
        ok = bool(named)
        for node in named:
            raw = (node["metadata"].get("labels") or {}).get(consts.TORUS_COORDS_LABEL, "")
            parts = raw.split("-")
            try:
                at = tuple(int(p) for p in parts)
            except ValueError:
                ok = False
                break
            if len(at) != 3 or min(at) < 0 or at in coords:
                ok = False
                break
            if grid is not None and any(c >= d for c, d in zip(at, grid)):
                ok = False  # a coord outside the declared grid: distrust all
                break
            coords[at] = node["metadata"]["name"]
        if ok and coords:
            dims = grid or tuple(max(c[i] for c in coords) + 1 for i in range(3))
            return cls((dims[0], dims[1], dims[2]), coords, wrap=wrap)
        # fallback layout: anchored to the DECLARED grid whenever the
        # members fit it, so the dims never depend on the current member
        # count — _near_cubic_dims(n) would re-dimension the whole torus
        # on any membership change (8 hosts (2,2,2) -> 9 hosts (3,3,1)),
        # shifting every synthetic coordinate and tearing down every
        # scheduled gang in the pool. Missing members are tail holes.
        # (Name-rank assignment still shifts coords after a mid-rank
        # member removal — unavoidable without real coordinates.)
        if grid is not None and len(named) <= grid[0] * grid[1] * grid[2]:
            dims = grid
        else:
            dims = _near_cubic_dims(max(1, len(named)))
        return cls(
            dims,
            {worker_coords(i, dims): n["metadata"]["name"] for i, n in enumerate(named)},
            wrap=wrap,
        )

    # -- occupancy -----------------------------------------------------------

    def set_unavailable(self, node_names: Sequence[str]) -> None:
        for name in node_names:
            at = self.coords_of.get(name)
            if at is not None:
                self._unavailable.add(at)

    def set_degraded_edges(self, edges: Sequence[Tuple[str, str]]) -> None:
        """Mark ICI links as severed, by endpoint NODE NAMES (the
        link-health map's vocabulary). Unknown endpoints are ignored —
        a record can outlive a host. Unlike ``set_unavailable`` this
        removes no capacity: only block shapes that would straddle the
        edge become infeasible."""
        for a, b in edges:
            at_a, at_b = self.coords_of.get(a), self.coords_of.get(b)
            if at_a is not None and at_b is not None and at_a != at_b:
                self._cut_edges.add(frozenset((at_a, at_b)))

    def _edge_cut(self, cells: Sequence[Coord]) -> bool:
        """Whether a block covering ``cells`` straddles a severed edge:
        both endpoints inside one block means the block's sub-torus —
        and the ICI ring order worker ids follow — routes through the
        degraded link."""
        if not self._cut_edges:
            return False
        block = set(cells)
        return any(edge <= block for edge in self._cut_edges)

    def occupy(self, owner: str, cells: Sequence[Coord]) -> None:
        for cell in cells:
            self._owner[cell] = owner

    def release(self, owner: str) -> List[Coord]:
        freed = [c for c, o in self._owner.items() if o == owner]
        for cell in freed:
            del self._owner[cell]
        return freed

    def owner_cells(self, owner: str) -> List[Coord]:
        return sorted(c for c, o in self._owner.items() if o == owner)

    def owners(self) -> Set[str]:
        return set(self._owner.values())

    def _free(self, cell: Coord) -> bool:
        return cell in self.node_at and cell not in self._unavailable and cell not in self._owner

    def free_count(self) -> int:
        return sum(1 for cell in self.node_at if self._free(cell))

    def in_service_count(self) -> int:
        """Hosts the allocator could ever place on: registered cells the
        health subsystem has not taken out of service."""
        return sum(1 for cell in self.node_at if cell not in self._unavailable)

    def utilization(self) -> float:
        """Occupied fraction of the pool's in-service capacity — the
        ``tpu_operator_fleet_utilization{pool}`` series. Out-of-service
        hosts are subtracted from the denominator (capacity the fleet
        cannot deliver is not capacity going idle); an empty or fully
        quarantined pool reads 0.0."""
        in_service = self.in_service_count()
        if in_service == 0:
            return 0.0
        occupied = sum(1 for cell in self._owner if cell not in self._unavailable)
        return round(occupied / in_service, 4)

    # -- allocation ----------------------------------------------------------

    def _wrap(self, cell: Coord) -> Coord:
        if not self.wrap:
            # mesh: no edge links — out-of-grid coords stay out-of-grid,
            # so they're never free, never owned, never a neighbor
            return cell
        return (cell[0] % self.dims[0], cell[1] % self.dims[1], cell[2] % self.dims[2])

    def _block_cells(self, origin: Coord, shape: Coord) -> Tuple[Coord, ...]:
        # row-major over the block (x fastest): worker i's torus neighbor
        # is worker i+1 along the fastest axis
        return tuple(
            self._wrap((origin[0] + i, origin[1] + j, origin[2] + k))
            for k in range(shape[2])
            for j in range(shape[1])
            for i in range(shape[0])
        )

    def orientations(self, shape: Coord) -> List[Coord]:
        """Distinct axis-aligned rotations of the shape that fit the
        torus dims (a block axis longer than its torus axis would wrap
        onto itself — never placeable)."""
        seen = []
        for perm in sorted(set(itertools.permutations(shape))):
            if all(p <= d for p, d in zip(perm, self.dims)):
                seen.append(perm)
        return seen

    def is_contiguous_block(self, cells: Sequence[Coord], shape: Coord) -> bool:
        """Whether ``cells`` (in worker order) are exactly one oriented
        row-major block of ``shape`` anchored at ``cells[0]`` — the
        invariant a placed gang's coordinates must satisfy for its
        worker ids to follow the ICI wiring."""
        if not cells:
            return False
        if self._edge_cut(cells):
            # a severed link inside the block cuts its contiguity: the
            # cells may be geometrically adjacent, but the gang's
            # collectives would route the degraded cable — re-place
            return False
        return any(
            tuple(cells) == self._block_cells(cells[0], oriented)
            for oriented in self.orientations(shape)
        )

    def exposure(self, cells: Sequence[Coord], cap: Optional[int] = None) -> int:
        """Free cells adjacent (6-neighbor, wraparound) to the block but
        outside it — the new free surface this placement would create.
        Lower is snugger: flush against occupied/unavailable cells or
        closing a pocket, which is what keeps big contiguous runs alive.
        ``cap`` is the allocator's pruning hook: once the count exceeds
        it the candidate has already lost, so the walk stops and any
        value > cap is returned (exactness only matters below the cap)."""
        block = set(cells)
        touched: Set[Coord] = set()
        for cell in block:
            for step in _NEIGHBOR_STEPS:
                at = self._wrap((cell[0] + step[0], cell[1] + step[1], cell[2] + step[2]))
                if at not in block and self._free(at):
                    touched.add(at)
            if cap is not None and len(touched) > cap:
                return len(touched)
        return len(touched)

    def find_block(
        self,
        shape: Coord,
        victim_ok: Optional[Callable[[str], bool]] = None,
        scorer: Optional[Callable[[Coord, Coord, Tuple[Coord, ...]], float]] = None,
    ) -> Optional[Tuple[Block, FrozenSet[str]]]:
        """Best placement for ``shape``: tries every orientation at every
        origin, requiring each covered cell to be free — or, when
        ``victim_ok`` is given, occupied by an owner it accepts (the
        preemption path). Ranking: fewest victims, then fewest victim
        cells (evicting a 2x2x2 beats evicting a 4x4x4), then least free
        exposure, then (origin, orientation) for determinism. ``scorer``
        (the policy hook the capacity planner's defrag-aware scoring
        rides) ranks between victim cells and exposure — a candidate a
        scorer prefers wins even at worse exposure, but never at the
        cost of extra preemption. Returns ``(block, victims)`` or None;
        ``victims`` is empty on a clean fit."""
        best = None
        best_key = None
        origins = sorted(self.node_at)
        cells_of = Counter(self._owner.values())  # owner -> occupied cells
        for shape_idx, oriented in enumerate(self.orientations(shape)):
            for origin in origins:
                if victim_ok is None and not self._free(origin):
                    # clean-fit fast path: the origin is always a member
                    # cell, so an occupied origin kills the candidate
                    # before the full cell walk (what keeps the 4096-host
                    # fleet sim's per-placement cost bounded)
                    continue
                if not self.wrap and any(
                    origin[i] + oriented[i] > self.dims[i] for i in range(3)
                ):
                    continue  # block would hang past a mesh edge
                cells = self._block_cells(origin, oriented)
                if self._edge_cut(cells):
                    continue  # the block would straddle a severed link
                victims: Set[str] = set()
                feasible = True
                for cell in cells:
                    if self._free(cell):
                        continue
                    owner = self._owner.get(cell)
                    if owner is not None and victim_ok is not None and victim_ok(owner):
                        victims.add(owner)
                        continue
                    feasible = False
                    break
                if not feasible:
                    continue
                victim_cells = sum(cells_of[v] for v in victims)
                # exposure() is the expensive part of the key (a 6-neighbor
                # walk over every cell): skip it when the cheap prefix
                # already loses against the current best
                if best_key is not None and (len(victims), victim_cells) > best_key[:2]:
                    continue
                policy = scorer(origin, oriented, cells) if scorer is not None else 0.0
                if best_key is not None and (len(victims), victim_cells, policy) > best_key[:3]:
                    continue  # lost before the expensive exposure walk
                # when the cheap prefix TIES the best, exposure decides —
                # and only values at or below the best's can win, so the
                # walk may stop early past that cap
                cap = (
                    best_key[3]
                    if best_key is not None
                    and (len(victims), victim_cells, policy) == best_key[:3]
                    else None
                )
                exposure = self.exposure(cells, cap=cap)
                key = (len(victims), victim_cells, policy, exposure, origin, shape_idx)
                if best_key is None or key < best_key:
                    best_key = key
                    best = (Block(origin, oriented, cells, exposure), frozenset(victims))
                    if scorer is None and key[:4] == (0, 0, 0.0, 0):
                        return best  # a perfectly snug clean fit can't be beaten
        return best

    def pack_scorer(self) -> Callable[[Coord, Coord, Tuple[Coord, ...]], float]:
        """The defrag-aware policy scorer: prefer placements packed
        toward the origin corner (Chebyshev distance of the block's
        farthest unwrapped extent). Best-fit's exposure ranking keeps
        blocks snug against *each other*; corner packing additionally
        keeps the free space consolidated at one end of the torus, which
        is what holds a large contiguous run open for the next big gang.
        Returned as a closure so callers can hand it straight to
        ``find_block(scorer=...)``."""

        def score(origin: Coord, oriented: Coord, _cells) -> float:
            return float(max(origin[i] + oriented[i] for i in range(3)))

        return score

    # -- scoring -------------------------------------------------------------

    def fragmentation(self) -> float:
        """External fragmentation of the free space: 1 - (largest free
        block volume / free hosts), probing cubes clamped to the torus
        dims (a 2-D pool's probe is a square with unit z — otherwise an
        empty flat torus would read as fragmented). Severed edges count:
        a probe block straddling a degraded link is not placeable, so a
        cut through otherwise-free space reads as fragmentation — which
        it is. 0.0 = all free capacity reachable as one block (or
        nothing free at all); toward 1.0 = plenty of free hosts but no
        contiguous block to place on."""
        free = self.free_count()
        if free == 0:
            return 0.0
        for side in range(max(self.dims), 0, -1):
            shape = tuple(min(side, d) for d in self.dims)
            volume = shape[0] * shape[1] * shape[2]
            if volume > free:
                continue
            for origin in sorted(self.node_at):
                cells = self._block_cells(origin, shape)
                if all(self._free(c) for c in cells) and not self._edge_cut(cells):
                    return round(1.0 - volume / free, 4)
        # unreachable: the side=1 probe is a single cell, and free > 0
        # guarantees at least one free cell that is its own 1x1x1 block
        return 0.0
