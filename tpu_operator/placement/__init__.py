"""Topology-aware slice placement.

Models each TPU node pool as a 3-D torus of hosts (the ICI wiring: v4/
v5p pods are 3-D tori of chips, v5e/v6e 2-D meshes — a 2-D shape is a
torus with a unit z axis) and allocates contiguous axis-aligned host
blocks for TPUSlice ``spec.placement`` requests. Contiguity on the ICI
is what keeps gang collectives at wire speed: a fragmented gang routes
``psum`` over DCN hops and the whole slice degrades (PAPERS.md,
"Exploration of TPUs for AI Applications" on torus topology).

- ``torus.py`` — the torus model + block allocator + fragmentation
  scoring (pure geometry, no apiserver).
- ``engine.py`` — the planning core: admission in priority-then-FIFO
  order, gang-integrity validation, minimal-victim preemption. Pure
  (cluster state in, decisions out) so drills and chaos riders can
  replay it deterministically.
- ``controllers/placement_controller.py`` — the reconciler applying an
  engine plan to the cluster (assignment labels, status.placement,
  events, metrics).
"""

from tpu_operator.placement.engine import (  # noqa: F401
    PlacementEngine,
    PlacementPhase,
    PreemptionPolicy,
)
from tpu_operator.placement.torus import Block, Torus, parse_shape  # noqa: F401
