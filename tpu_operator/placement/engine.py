"""Placement planning core: admission queue + gang integrity + preemption.

Pure function of cluster state: feed it the current TPUSlices and Nodes,
get back a :class:`Plan` — per-slice placement status, per-node label
deltas, events to record, and per-pool fragmentation. The controller
applies the plan; drills and chaos riders replay the engine directly.

The assignment labels on nodes (``tpu.google.com/placement`` +
``placement-index``) are the source of truth for what is currently
placed — not ``status.placement`` — so a restarted operator (or one that
crashed between the label writes and the status write) re-derives the
same world and converges instead of double-booking.

Queue semantics (``status.placement.phase``):

- ``Queued``     — admitted, waiting for its first attempt this pass
  (fresh request, re-placement after a lost gang member, or preempted).
- ``Scheduled``  — a contiguous block is assigned; labels written.
- ``Unschedulable`` — attempted and failed: no block free, and
  preemption (if allowed) found no victim set.

Admission is priority-then-FIFO. A higher-priority ``Unschedulable``
slice with ``preemptionPolicy: PreemptLower`` preempts the MINIMAL
victim set: the allocator ranks candidate blocks by (victim count,
victim cells, free-surface exposure), so a block displacing one small
low-priority gang always beats one displacing two. Victims are torn
down (labels cleared, phase back to ``Queued``) and requeue behind the
preemptor — cordon-free gang teardown, never node eviction.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

from tpu_operator import consts
from tpu_operator.kube.objects import ObjectDict
from tpu_operator.nodeinfo import ACCELERATORS
from tpu_operator.nodepool import get_node_pools
from tpu_operator.placement.torus import (
    Torus,
    chip_topology_for,
    host_grid_dims,
    parse_shape,
)
from tpu_operator.tenancy.fairshare import resolve_tenant

PLACEMENT_MANAGER = "tpu-placement"

# the label triple that IS a gang assignment (the engine's source of
# truth). Every teardown path — the engine's own clears, the job
# controller's checkpoint-barrier teardown, the defrag controller's
# drain-then-re-place, the replay helper's virtual strip — derives from
# this one tuple, so adding an assignment label can never leave one
# path half-stripping gangs.
ASSIGNMENT_LABELS: Tuple[str, ...] = (
    consts.PLACEMENT_LABEL,
    consts.PLACEMENT_INDEX_LABEL,
    consts.PLACEMENT_TOPOLOGY_LABEL,
)


def assignment_clear_delta() -> Dict[str, Optional[str]]:
    """The labels-only merge-patch delta that tears one node out of its
    gang (None values clear)."""
    return {label: None for label in ASSIGNMENT_LABELS}


class PlacementPhase:
    QUEUED = "Queued"
    SCHEDULED = "Scheduled"
    UNSCHEDULABLE = "Unschedulable"


class PreemptionPolicy:
    NEVER = "Never"
    PREEMPT_LOWER = "PreemptLower"


def _labels(node: ObjectDict) -> dict:
    return node["metadata"].get("labels") or {}


def labels_unavailable(labels: dict) -> bool:
    """The health-subsystem exclusion predicate, shared with the slice
    manager so the two can never disagree about who is in a gang: a node
    mid-repair (any repair FSM state, incl. terminal quarantine),
    flagged degraded, or carrying the exporter's sustained perf-floor
    breach is out of service. The perf clause is the grey-failure path:
    a slow-but-alive chip gates every peer's collectives, so it leaves
    the gang (and is never a placement candidate) the same way a dead
    one does."""
    return (
        bool(labels.get(consts.REPAIR_STATE_LABEL))
        or labels.get(consts.TPU_HEALTH_LABEL) == consts.HEALTH_DEGRADED
        or labels.get(consts.TPU_PERF_LABEL) == consts.PERF_DEGRADED
    )


def node_unavailable(node: ObjectDict) -> bool:
    """A host the health subsystem has taken out of service: never a
    placement candidate, and a gang holding it has lost a member."""
    return labels_unavailable(_labels(node))


def _pool_wraps(accelerator_type: str) -> bool:
    """Whether a pool's ICI links wrap at the edges: 3-D torus
    generations (v4/v5p) wrap, 2-D mesh generations (v5e/v6e) don't.
    Unknown accelerators default to no wrap — a non-wrapping block is
    contiguous on either family, the wrapped one only on a torus."""
    info = ACCELERATORS.get(accelerator_type)
    return info is not None and info.topology_dims >= 3


def _topology_dims(accelerator_type: str) -> int:
    """How many axes the generation's topology strings carry (v4/v5p
    write '4x4x1', v5e/v6e write '4x4'); unknown families keep 3 — an
    explicit unit axis is never wrong, a silently dropped one can be."""
    info = ACCELERATORS.get(accelerator_type)
    return info.topology_dims if info is not None else 3


@dataclasses.dataclass
class PlacementRequest:
    """One TPUSlice's parsed spec.placement."""

    name: str
    shape: str
    priority: int
    policy: str
    pool: str  # optional pool pin
    created: str  # creationTimestamp for FIFO within a priority band
    # dotted tenant path from the tpu.google.com/tenant label (or
    # spec.placement.tenant); "" = untenanted — accounts under the
    # default tenant when a fair-share policy is active, ignored
    # entirely when none is
    tenant: str = ""

    @classmethod
    def from_slice(cls, obj: ObjectDict) -> Optional["PlacementRequest"]:
        placement = (obj.get("spec") or {}).get("placement") or {}
        shape = str(placement.get("shape") or "")
        if not shape:
            return None
        try:
            priority = int(placement.get("priority") or 0)
        except (TypeError, ValueError):
            priority = 0
        return cls(
            name=obj["metadata"]["name"],
            shape=shape,
            priority=priority,
            policy=str(placement.get("preemptionPolicy") or PreemptionPolicy.NEVER),
            pool=str(placement.get("pool") or ""),
            created=obj["metadata"].get("creationTimestamp", ""),
            tenant=resolve_tenant(obj),
        )


@dataclasses.dataclass
class Plan:
    # slice name -> the status.placement block to publish
    statuses: Dict[str, dict] = dataclasses.field(default_factory=dict)
    # node name -> label delta (None values clear)
    label_deltas: Dict[str, Dict[str, Optional[str]]] = dataclasses.field(default_factory=dict)
    # (slice name, event type, reason, message)
    events: List[Tuple[str, str, str, str]] = dataclasses.field(default_factory=list)
    fragmentation: Dict[str, float] = dataclasses.field(default_factory=dict)
    queue_depth: int = 0
    # slices whose gang was torn down this pass (preempted or lost a
    # member): the controller requeues promptly so they re-place
    teardowns: List[str] = dataclasses.field(default_factory=list)
    # preemption-economy audit records (victim, victimTenant, preemptor,
    # preemptorTenant, fragDelta, borrowed, pool) the controller books
    # into the tpu-tenancy-ledger CM; populated only when a fair-share
    # policy is active — the stock path never writes here
    preemption_decisions: List[dict] = dataclasses.field(default_factory=list)

    def _delta(self, node: str) -> Dict[str, Optional[str]]:
        return self.label_deltas.setdefault(node, {})

    def assign(self, slice_name: str, ordered_nodes: Sequence[str], chip_topology: str) -> None:
        for index, node in enumerate(ordered_nodes):
            delta = self._delta(node)
            delta[consts.PLACEMENT_LABEL] = slice_name
            delta[consts.PLACEMENT_INDEX_LABEL] = str(index)
            delta[consts.PLACEMENT_TOPOLOGY_LABEL] = chip_topology

    def clear(self, nodes: Sequence[str]) -> None:
        for node in nodes:
            delta = self._delta(node)
            # an assignment written later in the same pass wins over the
            # teardown of the node's previous owner
            if consts.PLACEMENT_LABEL not in delta or delta[consts.PLACEMENT_LABEL] is None:
                delta[consts.PLACEMENT_LABEL] = None
                delta[consts.PLACEMENT_INDEX_LABEL] = None
                delta[consts.PLACEMENT_TOPOLOGY_LABEL] = None


def shrink_candidates(desired: Tuple[int, int, int], min_volume: int) -> List[Tuple[int, int, int]]:
    """Every sub-shape of ``desired`` worth shrinking to, largest first:
    shapes that fit inside the desired block (component-wise after
    sorting — the allocator tries orientations anyway) with volume in
    [min_volume, desired volume], deduped up to rotation. Largest-first
    is the elasticity contract: a job shrinks no further than capacity
    forces it to."""
    a, b, c = sorted(desired, reverse=True)
    seen: Dict[Tuple[int, int, int], Tuple[int, int, int]] = {}
    for x in range(1, a + 1):
        for y in range(1, b + 1):
            for z in range(1, c + 1):
                canon = tuple(sorted((x, y, z), reverse=True))
                if canon[1] > b or canon[2] > c:
                    continue  # doesn't fit inside the desired block
                if not max(1, min_volume) <= x * y * z <= a * b * c:
                    continue
                seen.setdefault(canon, canon)
    # largest volume first; most-cubic (then lexicographic) tiebreak so
    # every controller replica ranks identically
    return sorted(
        seen.values(),
        key=lambda s: (-(s[0] * s[1] * s[2]), s[0] - s[2], s),
    )


def largest_placeable_shape(
    slices: Sequence[ObjectDict],
    nodes: Sequence[ObjectDict],
    desired: Tuple[int, int, int],
    min_volume: int,
    degraded_links: Optional[Sequence[Tuple[str, str]]] = None,
    pool: str = "",
    exclude: Sequence[str] = (),
) -> Optional[Tuple[int, int, int]]:
    """The largest sub-block of ``desired`` the allocator ranks placeable
    RIGHT NOW — the TPUJob shrink/grow oracle. Clean fits only (an
    elastic resize never preempts); ``exclude`` names slices whose
    current assignments count as free (the job's own gang, which moves).
    The engine's plan() is replayed first so intact foreign gangs occupy
    their cells, broken/orphaned ones free theirs, and pending requests
    take the blocks admission would give them — the candidate ranking
    then sees the same world the next placement pass will."""
    kept = [s for s in slices if s["metadata"]["name"] not in set(exclude)]
    engine = PlacementEngine(kept, nodes, degraded_links=degraded_links)
    engine.plan()
    pool_names = [pool] if pool else sorted(engine.pools)
    for shape in shrink_candidates(desired, min_volume):
        for pool_name in pool_names:
            entry = engine.pools.get(pool_name)
            if entry is None:
                continue
            if entry[1].find_block(shape) is not None:
                return shape
    return None


def strip_assignments(
    nodes: Sequence[ObjectDict], owners: Sequence[str]
) -> List[ObjectDict]:
    """Copies of ``nodes`` with the assignment labels of ``owners``
    cleared — the world after those gangs are torn down but before the
    engine re-places anything. Only metadata.labels is copied; the rest
    of each node object is shared (the engine reads, never writes)."""
    drop = set(owners)
    out: List[ObjectDict] = []
    for node in nodes:
        labels = node["metadata"].get("labels") or {}
        if labels.get(consts.PLACEMENT_LABEL) not in drop:
            out.append(node)
            continue
        stripped = {k: v for k, v in labels.items() if k not in ASSIGNMENT_LABELS}
        copy = dict(node)
        copy["metadata"] = dict(node["metadata"])
        copy["metadata"]["labels"] = stripped
        out.append(copy)
    return out


def replay_minus_candidate(
    slices: Sequence[ObjectDict],
    nodes: Sequence[ObjectDict],
    candidate: str,
    migrate: bool = False,
    degraded_links: Optional[Sequence[Tuple[str, str]]] = None,
) -> Plan:
    """THE replay-minus-candidate primitive every victim/migration score
    derives from, factored once so the serving controller's scale-down
    math and the defrag proposer can never diverge. Replays the engine
    over a world without the candidate's current assignment:

    - ``migrate=False`` (scale-down semantics): the candidate slice is
      gone entirely — its cells free up and nothing re-places it.
    - ``migrate=True`` (defrag semantics): the candidate keeps its
      placement request but loses its current assignment labels, so the
      replay re-admits it and the plan shows where the NEXT placement
      pass would seat it — the post-migration world.

    Either way pending requests re-admit into the freed space (the same
    see-the-next-pass convention as :func:`largest_placeable_shape`)."""
    if migrate:
        kept = list(slices)
        world = strip_assignments(nodes, [candidate])
    else:
        kept = [s for s in slices if s["metadata"]["name"] != candidate]
        world = list(nodes)
    return PlacementEngine(kept, world, degraded_links=degraded_links).plan()


def scale_down_scores(
    slices: Sequence[ObjectDict],
    nodes: Sequence[ObjectDict],
    candidates: Sequence[str],
    degraded_links: Optional[Sequence[Tuple[str, str]]] = None,
) -> Dict[str, Tuple[float, float]]:
    """Fragmentation impact of removing each candidate slice: candidate
    name -> (frag_after, frag_delta) for the pool the candidate's gang
    occupies, with the engine replayed minus that candidate
    (:func:`replay_minus_candidate`, ``migrate=False``). Candidates
    not currently placed score (-1.0, -1.0): deleting an unplaced
    replica frees a queue slot and cannot fragment anything, so it is
    always the cheapest victim."""
    base_engine = PlacementEngine(slices, nodes, degraded_links=degraded_links)
    base_plan = base_engine.plan()
    pool_of = _scheduled_pools(base_engine, base_plan, candidates)
    scores: Dict[str, Tuple[float, float]] = {}
    for name in candidates:
        pool = pool_of.get(name)
        if pool is None:
            scores[name] = (-1.0, -1.0)
            continue
        plan = replay_minus_candidate(
            slices, nodes, name, migrate=False, degraded_links=degraded_links
        )
        after = plan.fragmentation.get(pool, 0.0)
        scores[name] = (after, round(after - base_plan.fragmentation.get(pool, 0.0), 4))
    return scores


def _scheduled_pools(
    base_engine: "PlacementEngine", base_plan: Plan, candidates: Sequence[str]
) -> Dict[str, str]:
    """candidate -> pool for the candidates the base replay ranks
    currently Scheduled (falling back to the object's own status block
    for intact gangs the replay didn't re-derive)."""
    pool_of: Dict[str, str] = {}
    for name in candidates:
        status = base_plan.statuses.get(name)
        if status is None:
            obj = base_engine.slices.get(name) or {}
            status = (obj.get("status") or {}).get("placement") or {}
        if status.get("phase") == PlacementPhase.SCHEDULED and status.get("pool"):
            pool_of[name] = str(status["pool"])
    return pool_of


def migration_scores(
    slices: Sequence[ObjectDict],
    nodes: Sequence[ObjectDict],
    candidates: Sequence[str],
    degraded_links: Optional[Sequence[Tuple[str, str]]] = None,
) -> Dict[str, dict]:
    """Defrag proposer scoring: for each currently-placed candidate,
    what the world looks like after migrating it — its assignment
    stripped and the engine replayed (:func:`replay_minus_candidate`,
    ``migrate=True``), so the candidate re-places by the allocator's own
    ranking and every pending request re-admits into the freed space.
    candidate name -> {pool (the SOURCE pool — frag_before/after/delta
    are all scored there, since the freed space that consolidates is
    the source's; a cross-pool re-seat must not difference two pools'
    unrelated numbers), dest_pool, frag_before, frag_after, frag_delta,
    lands_pending (names of previously-unplaced requests the replay now
    seats), nodes (the re-placed gang's member list), origin}.
    Candidates the base replay does not rank Scheduled, or whose replay
    fails to re-seat them, are omitted — a migration that loses the gang
    is not a proposal."""
    base_engine = PlacementEngine(slices, nodes, degraded_links=degraded_links)
    base_plan = base_engine.plan()
    pool_of = _scheduled_pools(base_engine, base_plan, candidates)
    unplaced_before = {
        name for name, status in base_plan.statuses.items()
        if status.get("phase") in (PlacementPhase.QUEUED, PlacementPhase.UNSCHEDULABLE)
    }
    scores: Dict[str, dict] = {}
    for name in candidates:
        pool = pool_of.get(name)
        if pool is None:
            continue
        plan = replay_minus_candidate(
            slices, nodes, name, migrate=True, degraded_links=degraded_links
        )
        status = plan.statuses.get(name) or {}
        if status.get("phase") != PlacementPhase.SCHEDULED:
            continue  # the replay could not re-seat the gang: never propose
        after = plan.fragmentation.get(pool, 0.0)
        before = base_plan.fragmentation.get(pool, 0.0)
        scores[name] = {
            "pool": pool,
            "dest_pool": str(status.get("pool") or pool),
            "frag_before": before,
            "frag_after": after,
            "frag_delta": round(after - before, 4),
            "lands_pending": sorted(
                n for n in unplaced_before
                if (plan.statuses.get(n) or {}).get("phase") == PlacementPhase.SCHEDULED
            ),
            "nodes": list(status.get("nodes") or []),
            "origin": str(status.get("origin") or ""),
        }
    return scores


def pick_migration(scores: Dict[str, dict]) -> Optional[str]:
    """The defrag selection rule over :func:`migration_scores` output,
    factored out beside :func:`pick_scale_down_victim` for the same
    reason — one place, no divergence: a migration that seats a
    previously-unplaceable request wins outright (most pending landings
    first), then the largest fragmentation reduction, then name for
    determinism. Returns None when nothing improves."""
    improving = {
        name: entry for name, entry in scores.items()
        if entry["lands_pending"] or entry["frag_delta"] < 0.0
    }
    if not improving:
        return None
    return min(
        improving,
        key=lambda n: (
            -len(improving[n]["lands_pending"]),
            improving[n]["frag_delta"],
            improving[n]["frag_after"],
            n,
        ),
    )


def pick_scale_down_victim(scores: Dict[str, Tuple[float, float]]) -> Optional[str]:
    """The selection rule over :func:`scale_down_scores` output, factored
    out so the serving controller and the oracle tests can never diverge
    on it: smallest fragmentation delta first (unplaced candidates'
    -1.0 wins outright), then smallest resulting fragmentation, then
    name — deterministic, so every controller replica picks the same
    victim."""
    if not scores:
        return None
    return min(scores, key=lambda n: (scores[n][1], scores[n][0], n))


def scale_down_victim(
    slices: Sequence[ObjectDict],
    nodes: Sequence[ObjectDict],
    candidates: Sequence[str],
    degraded_links: Optional[Sequence[Tuple[str, str]]] = None,
) -> Optional[str]:
    """The candidate whose removal most *reduces* its pool's torus
    fragmentation (the fleet-level perf optimization: a lull should give
    back the block that reopens the biggest contiguous run, not whatever
    replica happens to be newest)."""
    return pick_scale_down_victim(
        scale_down_scores(slices, nodes, candidates, degraded_links=degraded_links)
    )


class PlacementEngine:
    def __init__(
        self,
        slices: Sequence[ObjectDict],
        nodes: Sequence[ObjectDict],
        degraded_links: Optional[Sequence[Tuple[str, str]]] = None,
        scorer=None,
        node_risk: Optional[Dict[str, float]] = None,
        tenancy=None,
    ):
        # multi-tenant fair-share policy (tenancy.fairshare.FairSharePolicy,
        # built from the cluster's TPUQuota objects). None — the cluster
        # has no quotas — keeps every admission/preemption code path
        # byte-identical to stock priority-then-FIFO (the node_risk
        # empty-map convention); set, it swaps the pending sort for the
        # DRF weighted fair-share order and gates preemption through the
        # economy's legality + cheapest-victim-first rules.
        self.tenancy = tenancy
        # optional placement-policy hook threaded into every clean-fit
        # find_block call (torus.find_block's scorer slot) — the fleet
        # simulator's defrag-aware policy rides it; None keeps the
        # allocator's stock best-fit ranking
        self.scorer = scorer
        # risk-aware scoring (the predictive-health hook): per-host
        # scores from the risk scorer's state CM. A candidate block's
        # summed member risk ranks AHEAD of the policy/exposure key both
        # within and across pools, so a new gang avoids high-risk hosts
        # whenever a clean alternative exists — but risk never makes a
        # placeable shape unplaceable (a risky block still beats no
        # block). Empty/None reproduces the stock ranking exactly.
        self.node_risk = dict(node_risk or {})
        self.slices = {s["metadata"]["name"]: s for s in slices}
        self.nodes = {n["metadata"]["name"]: n for n in nodes}
        self.requests: Dict[str, PlacementRequest] = {}
        for obj in slices:
            req = PlacementRequest.from_slice(obj)
            if req is not None:
                self.requests[req.name] = req
        # pool name -> (NodePool, Torus); unavailable hosts are cells the
        # allocator can neither place on nor count as preemptable, and
        # degraded links (the fabric analyzer's link-health map, node
        # name pairs) are edges no block may straddle — a cut through
        # the torus that removes zero hosts
        self.pools: Dict[str, tuple] = {}
        self.node_pool: Dict[str, str] = {}
        links = [tuple(edge) for edge in (degraded_links or [])]
        # kept for the preemption economy's replay-minus-candidate
        # victim scoring (the replays must see the same cut fabric)
        self._degraded_links = links
        for pool in get_node_pools(list(self.nodes.values())):
            members = [self.nodes[n] for n in pool.node_names]
            torus = Torus.from_nodes(
                members,
                wrap=_pool_wraps(pool.info.accelerator_type),
                # the declared slice topology sizes the grid, so a
                # partially-registered pool reads as a torus with holes
                # rather than a smaller torus with fictional wrap links
                grid=host_grid_dims(pool.info.topology, pool.info.chips_per_node),
            )
            torus.set_unavailable(
                [n["metadata"]["name"] for n in members if node_unavailable(n)]
            )
            torus.set_degraded_edges(links)  # foreign endpoints ignored
            self.pools[pool.name] = (pool, torus)
            for name in pool.node_names:
                self.node_pool[name] = pool.name

    # -- current assignments -------------------------------------------------

    def _assigned_nodes(self) -> Dict[str, List[Tuple[int, str]]]:
        """slice name -> [(index, node name)] read back from node labels."""
        assigned: Dict[str, List[Tuple[int, str]]] = {}
        for name, node in self.nodes.items():
            labels = _labels(node)
            owner = labels.get(consts.PLACEMENT_LABEL)
            if not owner:
                continue
            try:
                index = int(labels.get(consts.PLACEMENT_INDEX_LABEL, "0"))
            except ValueError:
                index = 0
            assigned.setdefault(owner, []).append((index, name))
        return assigned

    def _gang_intact(self, req: PlacementRequest, members: List[Tuple[int, str]]) -> bool:
        shape = parse_shape(req.shape)
        if shape is None or len(members) != math.prod(shape):
            return False
        names = [n for _, n in members]
        indexes = sorted(i for i, _ in members)
        if indexes != list(range(len(members))):
            return False  # duplicated/skipped worker ids: re-place
        pool_names = {self.node_pool.get(n) for n in names}
        if len(pool_names) != 1 or None in pool_names:
            return False
        if req.pool and next(iter(pool_names)) != req.pool:
            return False  # spec re-pinned the slice to a different pool
        # count/index/pool checks can all pass on a SPLIT gang (a crash
        # between the label writes of a same-pass teardown + re-place
        # leaves old and new members sharing the owner label with unique
        # indexes) and on an equal-volume shape edit (4x2x1 -> 2x2x2):
        # the members' coordinates must actually form one oriented
        # contiguous block OF THE SPEC SHAPE, in worker order. Judged
        # from labels alone — the status block may be stale (a failed
        # status write after a successful re-place must not tear the
        # healthy new block down again on every pass until it lands)
        _, torus = self.pools[next(iter(pool_names))]
        ordered = [torus.coords_of.get(n) for _, n in sorted(members)]
        if None in ordered or not torus.is_contiguous_block(ordered, shape):
            return False
        return not any(node_unavailable(self.nodes[n]) for n in names)

    # -- the pass ------------------------------------------------------------

    def plan(self) -> Plan:
        plan = Plan()
        assigned = self._assigned_nodes()

        # 1. orphaned assignment labels: owner gone, or no longer requests
        #    placement — clear so hosts return to the free pool; a CR that
        #    dropped its request also loses its stale status block ({} is
        #    the clear-sentinel the controller patches as null)
        for owner, members in sorted(assigned.items()):
            if owner not in self.requests:
                plan.clear([n for _, n in members])
        for name, obj in self.slices.items():
            if name not in self.requests and (obj.get("status") or {}).get("placement"):
                plan.statuses[name] = {}

        # 2. validate every currently-assigned gang; intact ones occupy
        #    their torus cells, broken ones tear down and requeue
        scheduled: Dict[str, str] = {}  # slice -> pool
        pending: List[PlacementRequest] = []
        for req in self.requests.values():
            members = sorted(assigned.get(req.name, []))
            if not members:
                pending.append(req)
                continue
            if self._gang_intact(req, members):
                pool_name = self.node_pool[members[0][1]]
                _, torus = self.pools[pool_name]
                torus.occupy(req.name, [torus.coords_of[n] for _, n in members])
                scheduled[req.name] = pool_name
                prior = (self.slices[req.name].get("status") or {}).get("placement") or {}
                plan.statuses[req.name] = self._status(
                    PlacementPhase.SCHEDULED, req, pool=pool_name,
                    nodes=[n for _, n in members],
                    # the original block origin isn't derivable from the
                    # wrapped cell set; carry it through from the status
                    # the original placement wrote
                    origin=str(prior.get("origin") or ""),
                )
            else:
                plan.clear([n for _, n in members])
                plan.teardowns.append(req.name)
                plan.events.append((
                    req.name, "Warning", "PlacementDegraded",
                    f"gang for {req.name} lost a member, its shape changed, "
                    "or a fabric link inside its block degraded; re-placing",
                ))
                pending.append(req)

        # 3. admit pending: priority-then-FIFO, or — when TPUQuota
        #    objects exist — the DRF weighted fair-share order
        if self.tenancy is None:
            pending.sort(key=lambda r: (-r.priority, r.created, r.name))
            for req in pending:
                self._try_place(req, plan, scheduled)
        else:
            self._admit_fair(pending, plan, scheduled)

        plan.queue_depth = sum(
            1 for name in self.requests if name not in scheduled
        )
        for pool_name, (_, torus) in sorted(self.pools.items()):
            plan.fragmentation[pool_name] = torus.fragmentation()
        return plan

    def _candidate_pools(self, req: PlacementRequest) -> List[str]:
        if req.pool:
            return [req.pool] if req.pool in self.pools else []
        return sorted(self.pools)

    # -- multi-tenant fair share ---------------------------------------------

    def _req_tenant(self, req: PlacementRequest) -> str:
        return req.tenant or consts.TENANT_DEFAULT

    def _tenant_usage(self, scheduled: Dict[str, str]) -> Dict[str, Dict[str, int]]:
        """{tenant: {generation: chips}} accounted from the engine's own
        placed-plan so far this pass — intact gangs plus everything
        admission has seated, valued at the occupying cells (a shrunk
        gang charges what it actually holds)."""
        used: Dict[str, Dict[str, int]] = {}
        for name, pool_name in scheduled.items():
            req = self.requests.get(name)
            if req is None:
                continue
            pool, torus = self.pools[pool_name]
            chips = len(torus.owner_cells(name)) * pool.info.chips_per_node
            if chips <= 0:
                continue
            gens = used.setdefault(self._req_tenant(req), {})
            gen = pool.info.generation
            gens[gen] = gens.get(gen, 0) + chips
        return used

    def _demand_options(self, req: PlacementRequest, shape) -> List[Tuple[str, int]]:
        """The candidate footprints one request could land as — (TPU
        generation, chips) per candidate pool, deduped — what the quota
        headroom / legality checks measure against."""
        volume = math.prod(shape)
        options: List[Tuple[str, int]] = []
        seen = set()
        for pool_name in self._candidate_pools(req):
            pool, _ = self.pools[pool_name]
            item = (pool.info.generation, volume * pool.info.chips_per_node)
            if item not in seen:
                seen.add(item)
                options.append(item)
        return options

    def _admit_fair(
        self, pending: List[PlacementRequest], plan: Plan, scheduled: Dict[str, str]
    ) -> None:
        """DRF weighted fair-share admission: re-rank the whole queue
        after every seating (each placement moves its tenant's dominant
        share, which can demote that tenant's next request behind
        another tenant's) by (fits-inside-guaranteed-headroom, weighted
        dominant share, priority, FIFO) — so no tenant starves and
        borrowing only happens once guaranteed demand is seated."""
        queue = list(pending)
        while queue:
            used = self._tenant_usage(scheduled)

            def key(r: PlacementRequest) -> tuple:
                shape = parse_shape(r.shape)
                demands = self._demand_options(r, shape) if shape else []
                return self.tenancy.order_key(
                    self._req_tenant(r), used, demands, r.priority, r.created, r.name
                )

            queue.sort(key=key)
            self._try_place(queue.pop(0), plan, scheduled)

    def _block_risk(self, torus, cells) -> float:
        return round(
            sum(self.node_risk.get(torus.node_at[c], 0.0) for c in cells), 6
        )

    def _pool_scorer(self, torus):
        """The per-pool find_block scorer with the risk bias folded in:
        candidates rank by summed member risk FIRST, then whatever the
        policy hook says (tuple-valued scores are legal — find_block
        only ever compares scores from the same call). With no risk
        scores the stock hook passes through untouched, preserving the
        allocator's snug-clean-fit early exit."""
        if not self.node_risk:
            return self.scorer
        base = self.scorer

        def score(origin, oriented, cells):
            hazard = self._block_risk(torus, cells)
            return (hazard, base(origin, oriented, cells) if base else 0.0)

        return score

    def _try_place(self, req: PlacementRequest, plan: Plan, scheduled: Dict[str, str]) -> None:
        shape = parse_shape(req.shape)
        if shape is None:
            plan.statuses[req.name] = self._status(
                PlacementPhase.UNSCHEDULABLE, req,
                message=f"invalid placement shape {req.shape!r}",
            )
            return
        pools = self._candidate_pools(req)
        # clean fit first: ranked across pools by summed member risk
        # (the predictive-health bias — 0.0 everywhere when no scores
        # are loaded), then the allocator's own key
        best = None
        for pool_name in pools:
            _, torus = self.pools[pool_name]
            found = torus.find_block(shape, scorer=self._pool_scorer(torus))
            if found is None:
                continue
            block, _ = found
            key = (self._block_risk(torus, block.cells), block.exposure, pool_name)
            if best is None or key < best[0]:
                best = (key, pool_name, block)
        victims: frozenset = frozenset()
        decisions: List[dict] = []
        if best is None and req.policy == PreemptionPolicy.PREEMPT_LOWER:
            if self.tenancy is None:
                best, victims = self._find_with_preemption(req, shape, pools)
            else:
                best, victims, decisions = self._find_with_preemption_fair(
                    req, shape, pools, scheduled
                )
        if best is None:
            plan.statuses[req.name] = self._status(
                PlacementPhase.UNSCHEDULABLE, req,
                message=(
                    f"no free {req.shape} block"
                    + (" and no preemptable lower-priority gang"
                       if req.policy == PreemptionPolicy.PREEMPT_LOWER else "")
                    + f" in pool(s) {', '.join(pools) or '(none)'}"
                ),
            )
            return
        _, pool_name, block = best
        _, torus = self.pools[pool_name]
        for victim in sorted(victims):
            freed = torus.release(victim)
            plan.clear([torus.node_at[c] for c in freed])
            plan.teardowns.append(victim)
            scheduled.pop(victim, None)
            plan.statuses[victim] = self._status(
                PlacementPhase.QUEUED, self.requests[victim],
                message=f"preempted by higher-priority {req.name}; requeued",
            )
            plan.events.append((
                victim, "Warning", "PlacementPreempted",
                f"gang torn down: preempted by {req.name} "
                f"(priority {req.priority} > {self.requests[victim].priority})",
            ))
        torus.occupy(req.name, block.cells)
        ordered = [torus.node_at[c] for c in block.cells]
        pool, _ = self.pools[pool_name]
        plan.assign(
            req.name, ordered,
            chip_topology_for(
                block.shape, pool.info.chips_per_node,
                _topology_dims(pool.info.accelerator_type),
            ),
        )
        scheduled[req.name] = pool_name
        plan.statuses[req.name] = self._status(
            PlacementPhase.SCHEDULED, req, pool=pool_name, nodes=ordered,
            origin=block.origin_str,
        )
        plan.events.append((
            req.name, "Normal", "PlacementScheduled",
            f"placed {req.shape} block at {block.origin_str} in pool {pool_name}"
            + (f" preempting {len(victims)} gang(s)" if victims else ""),
        ))
        if decisions:
            plan.preemption_decisions.extend(decisions)

    def _find_with_preemption(self, req: PlacementRequest, shape, pools: List[str]):
        """Minimal-victim search across pools: only strictly-lower-priority
        scheduled placements are eligible victims."""

        def victim_ok(owner: str) -> bool:
            other = self.requests.get(owner)
            return other is not None and other.priority < req.priority

        best = None
        best_victims: frozenset = frozenset()
        for pool_name in pools:
            _, torus = self.pools[pool_name]
            found = torus.find_block(shape, victim_ok=victim_ok)
            if found is None:
                continue
            block, victims = found
            victim_cells = sum(len(torus.owner_cells(v)) for v in victims)
            key = (len(victims), victim_cells, block.exposure, pool_name)
            if best is None or key < best[0]:
                best = (key, pool_name, block)
                best_victims = victims
        return best, best_victims

    def _find_with_preemption_fair(
        self,
        req: PlacementRequest,
        shape,
        pools: List[str],
        scheduled: Dict[str, str],
    ):
        """The preemption economy (fair-share policy active). Differs
        from the stock minimal-victim search in two rule changes:

        - **Legality**: still strictly-lower-priority only, but a victim
          whose owner tenant is wholly inside its guaranteed quota may
          never be evicted while the preemptor's tenant is (or would go)
          over its own — protected capacity never feeds a borrower.
        - **Cheapest-victim-first**: legal victims rank by replay-minus-
          candidate fragmentation cost (scale_down_scores' frag_delta,
          then frag_after, then name — pick_scale_down_victim's order)
          and are released in that order until a clean block opens, so
          the economy pays the smallest fragmentation price, not the
          smallest victim count. Victims placed earlier this same pass
          carry no assignment labels yet, score (-1.0, -1.0), and are
          therefore the cheapest of all — evicting a seat the pass
          itself just granted undoes nothing already published.

        The torus is left exactly as found: chosen victims are released
        (and their statuses/teardowns booked) by the caller's stock
        path, so the two economies can never diverge on teardown
        bookkeeping. Returns (best, victims, decision records)."""
        used = self._tenant_usage(scheduled)
        demands = self._demand_options(req, shape)
        preemptor = self._req_tenant(req)
        legal: List[str] = []
        for victim in sorted(scheduled):
            if victim == req.name or scheduled[victim] not in pools:
                continue
            other = self.requests.get(victim)
            if other is None or other.priority >= req.priority:
                continue
            if not self.tenancy.preemption_legal(
                preemptor, self._req_tenant(other), used, demands
            ):
                continue
            legal.append(victim)
        if not legal:
            return None, frozenset(), []
        costs = scale_down_scores(
            list(self.slices.values()),
            list(self.nodes.values()),
            legal,
            degraded_links=self._degraded_links,
        )
        order = sorted(legal, key=lambda v: (costs[v][1], costs[v][0], v))
        best = None
        best_victims: frozenset = frozenset()
        best_decisions: List[dict] = []
        for pool_name in pools:
            _, torus = self.pools[pool_name]
            released: List[str] = []
            saved: Dict[str, list] = {}
            found = None
            for victim in (v for v in order if scheduled[v] == pool_name):
                saved[victim] = list(torus.owner_cells(victim))
                torus.release(victim)
                released.append(victim)
                found = torus.find_block(shape, scorer=self._pool_scorer(torus))
                if found is not None:
                    break
            # restore the torus either way; only overlap with the found
            # block makes a released victim actually needed
            needed: List[str] = []
            if found is not None:
                cells = set(found[0].cells)
                needed = [v for v in released if cells & set(saved[v])]
            for victim in reversed(released):
                torus.occupy(victim, saved[victim])
            if found is None:
                continue
            block, _ = found
            cost = round(sum(costs[v][1] for v in needed), 4)
            key = (cost, len(needed), block.exposure, pool_name)
            if best is None or key < best[0]:
                best = (key, pool_name, block)
                best_victims = frozenset(needed)
                best_decisions = [
                    {
                        "victim": v,
                        "victimTenant": self._req_tenant(self.requests[v]),
                        "preemptor": req.name,
                        "preemptorTenant": preemptor,
                        "fragDelta": costs[v][1],
                        "fragAfter": costs[v][0],
                        "borrowed": not self.tenancy.within_guarantee(
                            self._req_tenant(self.requests[v]), used
                        ),
                        "pool": pool_name,
                    }
                    for v in sorted(needed)
                ]
        return best, best_victims, best_decisions

    def _status(
        self,
        phase: str,
        req: PlacementRequest,
        pool: str = "",
        nodes: Optional[List[str]] = None,
        origin: str = "",
        message: str = "",
    ) -> dict:
        block = {
            "phase": phase,
            "shape": req.shape,
            "priority": req.priority,
        }
        if pool:
            block["pool"] = pool
        if nodes:
            block["nodes"] = list(nodes)
        if origin:
            block["origin"] = origin
        if message:
            block["message"] = message
        return block
