"""kubectl-free support-bundle collector.

Reference: ``hack/must-gather.sh`` shells out to kubectl for every
artifact, which ties support bundles to a workstation with kubectl
configured. This collector rides the in-repo ``HttpClient`` instead
(kubeconfig or in-cluster), so `tpuop-cfg must-gather` works anywhere
the operator itself can run — and, unlike a bash script, it is testable
end to end against the served fake apiserver.

Artifact layout mirrors the script's: nodes.yaml, node-labels.txt,
node-health.txt (health/repair labels + TPUHealthy conditions),
clusterpolicies.yaml, tpuslices.yaml, daemonsets.yaml, pods.yaml,
services.yaml, configmaps.yaml, events.txt, sharding.txt (shard→pool
assignment, per-shard queue depths, the slowest shard's recent
traces), pod-logs/<pod>.log.
"""

from __future__ import annotations

import logging
import os
from typing import List, Tuple

import yaml

from tpu_operator.api.clusterpolicy import CLUSTER_POLICY_API_VERSION
from tpu_operator.api.tpujob import TPU_JOB_API_VERSION
from tpu_operator.api.tpuquota import TPU_QUOTA_API_VERSION
from tpu_operator.api.tpuserving import TPU_SERVING_API_VERSION
from tpu_operator.api.tpuslice import TPU_SLICE_API_VERSION
from tpu_operator.kube import errors
from tpu_operator.kube.client import Client

log = logging.getLogger(__name__)

# (file stem, api_version, kind, namespaced)
_COLLECTIONS: List[Tuple[str, str, str, bool]] = [
    ("nodes", "v1", "Node", False),
    ("clusterpolicies", CLUSTER_POLICY_API_VERSION, "ClusterPolicy", False),
    ("tpuslices", TPU_SLICE_API_VERSION, "TPUSlice", False),
    ("tpujobs", TPU_JOB_API_VERSION, "TPUJob", False),
    ("tpuservings", TPU_SERVING_API_VERSION, "TPUServing", False),
    ("tpuquotas", TPU_QUOTA_API_VERSION, "TPUQuota", False),
    ("daemonsets", "apps/v1", "DaemonSet", True),
    ("pods", "v1", "Pod", True),
    ("services", "v1", "Service", True),
    ("configmaps", "v1", "ConfigMap", True),
]


def _write(path: str, text: str) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(text)


def collect(client: Client, namespace: str, outdir: str, log_tail: int = 2000) -> List[str]:
    """Collect the support bundle into ``outdir``; returns the relative
    paths written. Every artifact is best-effort — a failing collection
    records the error in the file instead of aborting the bundle (a
    half-broken cluster is exactly when bundles matter)."""
    written: List[str] = []

    def emit(rel: str, text: str) -> None:
        _write(os.path.join(outdir, rel), text)
        written.append(rel)

    version_fn = getattr(client, "server_version", None)
    if version_fn is not None:
        try:
            emit("version.txt", yaml.safe_dump(version_fn(), sort_keys=False))
        except errors.ApiError as e:
            emit("version.txt", f"# collection failed: {e}\n")

    all_lines: List[str] = []
    for stem, api_version, kind, namespaced in _COLLECTIONS:
        try:
            items = client.list(api_version, kind, namespace if namespaced else None)
            emit(
                f"{stem}.yaml",
                yaml.safe_dump_all(items, sort_keys=False) if items else "# none\n",
            )
            if namespaced:
                for o in items:  # the `get all -o wide` analog
                    status = o.get("status") or {}
                    brief = status.get("phase") or (
                        f"{status.get('numberAvailable', '?')}/"
                        f"{status.get('desiredNumberScheduled', '?')}"
                        if kind == "DaemonSet"
                        else ""
                    )
                    all_lines.append(f"{kind}  {o['metadata']['name']}  {brief}".rstrip())
        except errors.ApiError as e:
            emit(f"{stem}.yaml", f"# collection failed: {e}\n")
            all_lines.append(f"{kind}  # collection failed: {e}")
    emit("all.txt", "\n".join(all_lines) + "\n" if all_lines else "# none\n")

    try:
        lines = []
        for node in client.list("v1", "Node"):
            labels = node["metadata"].get("labels") or {}
            rendered = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
            lines.append(f"{node['metadata']['name']}  {rendered}")
        emit("node-labels.txt", "\n".join(lines) + "\n" if lines else "# none\n")
    except errors.ApiError as e:
        emit("node-labels.txt", f"# collection failed: {e}\n")

    try:
        # the health subsystem's per-node view: verdict label, per-chip
        # annotation, repair FSM state/retries, and the TPUHealthy
        # condition — the first things support asks for on a sick slice
        from tpu_operator import consts as _consts

        lines = []
        for node in client.list("v1", "Node"):
            md = node["metadata"]
            labels = md.get("labels") or {}
            annotations = md.get("annotations") or {}
            cond = next(
                (
                    c
                    for c in (node.get("status", {}).get("conditions") or [])
                    if c.get("type") == _consts.TPU_HEALTH_CONDITION
                ),
                None,
            )
            lines.append(
                f"{md['name']}  "
                f"health={labels.get(_consts.TPU_HEALTH_LABEL, '-')}  "
                f"repair={labels.get(_consts.REPAIR_STATE_LABEL, '-')}  "
                f"retries={annotations.get(_consts.REPAIR_RETRIES_ANNOTATION, '0')}  "
                f"slice={labels.get(_consts.TPU_SLICE_HEALTH_LABEL, '-')}  "
                f"condition={(cond or {}).get('status', '-')}"
                + (f" ({cond['message']})" if cond and cond.get("message") else "")
                + f"  chips={annotations.get(_consts.TPU_HEALTH_CHIPS_ANNOTATION, '-')}"
            )
        emit("node-health.txt", "\n".join(lines) + "\n" if lines else "# none\n")
    except errors.ApiError as e:
        emit("node-health.txt", f"# collection failed: {e}\n")

    try:
        # the placement subsystem's view: the queue (every TPUSlice with
        # a placement request + its phase) and the per-host assignment
        # dump — what "why isn't my slice scheduled" starts from
        from tpu_operator import consts as _consts

        lines = ["# placement queue"]
        queue = []
        for ts in client.list(TPU_SLICE_API_VERSION, "TPUSlice"):
            placement = (ts.get("spec") or {}).get("placement") or {}
            if not placement.get("shape"):
                continue
            st = (ts.get("status") or {}).get("placement") or {}
            queue.append(
                f"{ts['metadata']['name']}  shape={placement.get('shape')}  "
                f"priority={placement.get('priority', 0)}  "
                f"policy={placement.get('preemptionPolicy', 'Never')}  "
                f"phase={st.get('phase', '-')}  pool={st.get('pool', '-')}  "
                f"origin={st.get('origin', '-')}  "
                f"nodes={','.join(st.get('nodes') or []) or '-'}"
                + (f"  message={st.get('message')}" if st.get("message") else "")
            )
        lines.extend(queue or ["# none"])
        lines.append("")
        lines.append("# host assignments")
        assignments = []
        for node in client.list("v1", "Node"):
            labels = node["metadata"].get("labels") or {}
            if _consts.PLACEMENT_LABEL not in labels and _consts.TORUS_COORDS_LABEL not in labels:
                continue
            assignments.append(
                f"{node['metadata']['name']}  "
                f"coords={labels.get(_consts.TORUS_COORDS_LABEL, '-')}  "
                f"placement={labels.get(_consts.PLACEMENT_LABEL, '-')}  "
                f"index={labels.get(_consts.PLACEMENT_INDEX_LABEL, '-')}"
            )
        lines.extend(assignments or ["# none"])
        emit("placement.txt", "\n".join(lines) + "\n")
    except errors.ApiError as e:
        emit("placement.txt", f"# collection failed: {e}\n")

    try:
        # the elastic-training view: per-job FSM state, checkpoint
        # watermarks, shrink/grow history and the last restart causes —
        # where "why did my job shrink / why is it Failed" starts
        from tpu_operator import consts as _consts

        lines = ["# jobs"]
        rows = []
        for tj in client.list(TPU_JOB_API_VERSION, "TPUJob"):
            spec = tj.get("spec") or {}
            gang = spec.get("gang") or {}
            job = (tj.get("status") or {}).get("job") or {}
            rows.append(
                f"{tj['metadata']['name']}  phase={job.get('phase', '-')}  "
                f"step={job.get('step', 0)}  "
                f"checkpointEpoch={job.get('epoch', 0)}  "
                f"checkpointStep={job.get('checkpointStep', 0)}  "
                f"shape={job.get('shape', '-')}/{gang.get('shape', '-')}"
                f"(min={gang.get('minShape', '-')})  "
                f"hosts={job.get('hosts', 0)}  "
                f"restarts={job.get('restarts', 0)}/{job.get('totalRestarts', 0)}"
                + (f"  message={job.get('message')}" if job.get("message") else "")
            )
            for entry in job.get("shrinks") or []:
                rows.append(
                    f"  resize step={entry.get('step')}  {entry.get('kind')}  "
                    f"{entry.get('from')} -> {entry.get('to')}  "
                    f"cause={entry.get('cause')}"
                )
            for cause in (job.get("causes") or [])[-_consts.JOB_CAUSES_LIMIT:]:
                rows.append(f"  cause {cause}")
        lines.extend(rows or ["# none"])
        emit("jobs.txt", "\n".join(lines) + "\n")
    except errors.ApiError as e:
        emit("jobs.txt", f"# collection failed: {e}\n")

    try:
        # the serving view: per-serving replica map (which replica is
        # routable and why not), SLO attainment, and the last scale
        # decisions with their reasons — where "why did my serving
        # shrink / why is this replica getting no traffic" starts
        lines = ["# servings"]
        rows = []
        for sv in client.list(TPU_SERVING_API_VERSION, "TPUServing"):
            spec = sv.get("spec") or {}
            replicas_spec = spec.get("replicas") or {}
            block = (sv.get("status") or {}).get("serving") or {}
            slo = block.get("slo") or {}
            rows.append(
                f"{sv['metadata']['name']}  phase={block.get('phase', '-')}  "
                f"replicas={block.get('ready', 0)}/{block.get('desired', 0)}"
                f"(window {replicas_spec.get('min', '-')}-"
                f"{replicas_spec.get('max', '-')})  "
                f"routable={block.get('routable', 0)}  "
                f"ttftP99={slo.get('ttftP99', '-')}s"
                f"/{slo.get('ttftTarget', '-')}s  "
                f"sloAttained={slo.get('attained', '-')}"
                + (f"  message={block.get('message')}" if block.get("message") else "")
            )
            for name, state in sorted((block.get("replicas") or {}).items()):
                rows.append(f"  replica {name}  {state}")
            for decision in block.get("decisions") or []:
                rows.append(
                    f"  decision pass={decision.get('step')}  "
                    f"{decision.get('action')}  {decision.get('reason')}"
                )
        lines.extend(rows or ["# none"])
        emit("serving.txt", "\n".join(lines) + "\n")
    except errors.ApiError as e:
        emit("serving.txt", f"# collection failed: {e}\n")

    try:
        # the data-plane view: every worker pod the controllers rendered
        # (phase + generation hash + route weight), each job's rendezvous
        # handshake keys, and each serving's published router weights —
        # where "why is worker 3 stuck / why does this replica get no
        # traffic even though it's ready" starts
        import json as _json

        from tpu_operator import consts as _consts

        lines = ["# worker pods"]
        rows = []
        for pod in client.list("v1", "Pod", namespace):
            meta = pod.get("metadata") or {}
            labels = meta.get("labels") or {}
            main = labels.get(_consts.POD_MAIN_LABEL)
            if not main:
                continue
            ann = meta.get("annotations") or {}
            weight = ann.get(_consts.WORKER_ROUTE_WEIGHT_ANNOTATION)
            rows.append(
                f"{meta.get('name')}  main={main}  "
                f"phase={(pod.get('status') or {}).get('phase', '-')}  "
                f"hash={ann.get(_consts.WORKER_HASH_ANNOTATION, '-')}"
                + (f"  routeWeight={weight}" if weight is not None else "")
            )
        lines.extend(sorted(rows) or ["# none"])

        lines.append("")
        lines.append("# job rendezvous (progress ConfigMap handshake)")
        rows = []
        for tj in client.list(TPU_JOB_API_VERSION, "TPUJob"):
            name = tj["metadata"]["name"]
            cm = client.get_or_none(
                "v1", "ConfigMap", name + _consts.JOB_PROGRESS_SUFFIX, namespace
            )
            data = (cm or {}).get("data") or {}
            rdv = {
                k[len(_consts.JOB_RENDEZVOUS_PREFIX):]: v
                for k, v in sorted(data.items())
                if k.startswith(_consts.JOB_RENDEZVOUS_PREFIX)
            }
            rows.append(
                f"{name}  status={data.get(_consts.JOB_PROGRESS_STATUS, '-')}  "
                f"step={data.get(_consts.JOB_PROGRESS_STEP, '-')}  "
                f"rendezvous={rdv if rdv else '-'}"
            )
        lines.extend(rows or ["# none"])

        lines.append("")
        lines.append("# serving router weights (load ConfigMap)")
        rows = []
        for sv in client.list(TPU_SERVING_API_VERSION, "TPUServing"):
            name = sv["metadata"]["name"]
            cm = client.get_or_none(
                "v1", "ConfigMap", name + _consts.SERVING_LOAD_SUFFIX, namespace
            )
            data = (cm or {}).get("data") or {}
            routing = data.get(_consts.SERVING_ROUTING_KEY)
            pools = data.get(_consts.SERVING_POOLS_KEY)
            try:
                routing = _json.loads(routing) if routing else {}
            except ValueError:
                routing = "<malformed>"
            rows.append(f"{name}  routing={routing if routing else '-'}")
            if pools:
                rows.append(f"  pools={pools}")
        lines.extend(rows or ["# none"])
        emit("pods.txt", "\n".join(lines) + "\n")
    except errors.ApiError as e:
        emit("pods.txt", f"# collection failed: {e}\n")

    try:
        # the capacity-planning view: per-pool fragmentation/utilization
        # (the defrag controller's own replay), the last defrag
        # decisions with predicted-vs-realized deltas, and the what-if
        # engine's admission answer for every queued shape — where "when
        # will my gang land / what did defrag actually buy us" starts
        import json as _json

        from tpu_operator import consts as _consts
        from tpu_operator.controllers.fabric_telemetry import degraded_link_pairs
        from tpu_operator.placement.engine import PlacementEngine
        from tpu_operator.planning.whatif import admission_answer, queued_shapes

        slices = client.list(TPU_SLICE_API_VERSION, "TPUSlice")
        nodes = client.list("v1", "Node")
        try:
            # recorded link cuts are a placement input: answering "now"
            # for a block straddling one would contradict the CLI and
            # the engine itself
            links = degraded_link_pairs(client, namespace)
        except errors.ApiError:
            links = []
        engine = PlacementEngine(slices, nodes, degraded_links=links)
        plan = engine.plan()
        lines = ["# pools"]
        for pool_name in sorted(engine.pools):
            _, torus = engine.pools[pool_name]
            lines.append(
                f"{pool_name}  fragmentation={plan.fragmentation.get(pool_name, 0.0)}  "
                f"utilization={torus.utilization()}  "
                f"free={torus.free_count()}/{torus.in_service_count()}"
            )
        lines.append("")
        lines.append("# defrag decisions (newest last; predicted vs realized)")
        state_cm = client.get_or_none(
            "v1", "ConfigMap", _consts.DEFRAG_STATE_CONFIGMAP, namespace
        )
        raw = ((state_cm or {}).get("data") or {}).get(_consts.DEFRAG_STATE_KEY)
        decisions = []
        if raw:
            try:
                decisions = (_json.loads(raw) or {}).get("decisions") or []
            except ValueError:
                lines.append("# state.json malformed")
        for d in decisions[-_consts.DEFRAG_DECISIONS_LIMIT:]:
            realized = d.get("realized_frag")
            lines.append(
                f"{d.get('slice', '?')}  owner={d.get('owner_kind', '?')}/"
                f"{d.get('owner_name', '?')}  pool={d.get('pool', '?')}  "
                f"block {d.get('source_origin') or '?'} -> "
                f"{d.get('dest_origin') or d.get('predicted_dest_origin') or '?'}  "
                f"frag {d.get('frag_before')} -> predicted "
                f"{d.get('predicted_frag')} / realized "
                f"{'(abandoned)' if d.get('abandoned') else realized if realized is not None else '(in flight)'}"
                + (f"  seats={','.join(d.get('lands_pending') or [])}"
                   if d.get("lands_pending") else "")
            )
        if not decisions:
            lines.append("# none")
        lines.append("")
        lines.append("# admission what-ifs for queued shapes")
        queued = queued_shapes(slices)
        for name, shape in sorted(queued.items()):
            answer = admission_answer(
                slices, nodes, shape, degraded_links=links, for_slice=name
            )
            lines.append(
                f"{name}  shape={shape}  answer={answer['answer']}  "
                f"migrations={answer['migrations']}  "
                f"eta={answer['eta_seconds']}  {answer['detail']}"
            )
        if not queued:
            lines.append("# none queued")
        emit("plan.txt", "\n".join(lines) + "\n")
    except errors.ApiError as e:
        emit("plan.txt", f"# collection failed: {e}\n")

    try:
        # the multi-tenant fairness view: every tenant's usage vs its
        # declared quota, fair-share attainment (weighted dominant
        # share + measured p99 time-to-place), and the last preemption
        # decisions the economy booked — where "why did team X's gang
        # wait / who evicted whom and was it borrowing" starts
        from tpu_operator.tenancy import fairshare
        from tpu_operator.tenancy import ledger as tenancy_ledger

        quotas = client.list(TPU_QUOTA_API_VERSION, "TPUQuota")
        slices = client.list(TPU_SLICE_API_VERSION, "TPUSlice")
        nodes = client.list("v1", "Node")
        policy = fairshare.policy_from_objects(
            quotas, fairshare.capacity_by_generation(nodes)
        )
        used = fairshare.usage_from_slices(slices, nodes)
        ledger = tenancy_ledger.read_ledger(client, namespace)
        lines = ["# per-tenant usage vs quota (fair-share attainment)"]
        if policy is None:
            lines.append(
                "# no well-formed TPUQuota — stock (single-tenant) admission"
            )
            for tenant in sorted(used):
                held = fairshare.FairSharePolicy.level_usage(used, tenant)
                rendered = " ".join(f"{g}={c}" for g, c in sorted(held.items()))
                lines.append(f"{tenant}  used: {rendered}")
        else:
            for tenant in sorted(set(policy.quotas) | set(used)):
                held = policy.level_usage(used, tenant)
                quota = policy.quotas.get(tenant)
                rendered = " ".join(
                    f"{g}={c}" for g, c in sorted(held.items())
                ) or "(idle)"
                guaranteed = " ".join(
                    f"{g}={c}" for g, c in sorted(quota.guaranteed_map.items())
                ) if quota is not None else "(undeclared)"
                p99 = tenancy_ledger.place_p99(ledger, tenant) if ledger else None
                lines.append(
                    f"{tenant}  used: {rendered}  guaranteed: {guaranteed}  "
                    f"weight={policy.weight(tenant)}  "
                    f"weighted_share={round(policy.weighted_share(tenant, used), 6)}  "
                    f"borrowed={policy.borrowed_chips(tenant, used)}  "
                    f"within_guarantee={policy.within_guarantee(tenant, used)}"
                    + (f"  p99_place_s={round(p99, 3)}" if p99 is not None else "")
                )
        lines.append("")
        lines.append("# last 5 preemption decisions (newest first)")
        decisions = tenancy_ledger.last_decisions(ledger) if ledger else []
        for d in decisions:
            lines.append(
                f"{d.get('preemptor', '?')} (tenant {d.get('preemptorTenant', '?')}) "
                f"evicted {d.get('victim', '?')} "
                f"(tenant {d.get('victimTenant', '?')}, "
                f"{'borrowed' if d.get('borrowed') else 'owned'})  "
                f"pool={d.get('pool', '?')}  fragDelta={d.get('fragDelta')}  "
                f"at={d.get('at')}"
            )
        if not decisions:
            lines.append("# none booked")
        emit("tenants.txt", "\n".join(lines) + "\n")
    except errors.ApiError as e:
        emit("tenants.txt", f"# collection failed: {e}\n")

    try:
        # the predictive-health view: every host the risk scorer is
        # currently tracking (score + which signals put it there + the
        # state of its migration budget) and the last planned
        # migrations with their predicted-vs-realized verdicts — where
        # "why did my job just move / should I trust the scorer" starts
        import json as _json

        from tpu_operator import consts as _consts

        lines = ["# per-host risk (score over threshold => proactive migration)"]
        lines.append(f"# threshold={_consts.RISK_THRESHOLD}  decay={_consts.RISK_DECAY}")
        state_cm = client.get_or_none(
            "v1", "ConfigMap", _consts.RISK_STATE_CONFIGMAP, namespace
        )
        raw = ((state_cm or {}).get("data") or {}).get(_consts.RISK_STATE_KEY)
        state = {}
        if raw:
            try:
                state = _json.loads(raw) or {}
            except ValueError:
                lines.append("# risk.json malformed")
        hosts = state.get("hosts") or {}
        for host in sorted(hosts):
            entry = hosts[host] or {}
            parts = entry.get("signals") or {}
            signal_txt = " ".join(
                f"{k}={parts[k]}" for k in sorted(parts)
            ) or "(decaying; no fresh signal)"
            budget = ""
            if entry.get("attempts"):
                budget = (
                    f"  budget: attempts={entry.get('attempts')}"
                    f" nextAttemptAt={entry.get('nextAttemptAt')}"
                )
            lines.append(f"{host}  score={entry.get('score')}  {signal_txt}{budget}")
        if not hosts:
            lines.append("# none at risk")
        lines.append("")
        lines.append(
            f"# last {_consts.RISK_MIGRATIONS_LIMIT} planned migrations "
            "(newest last; predicted vs realized)"
        )
        migrations = state.get("migrations") or []
        for m in migrations[-_consts.RISK_MIGRATIONS_LIMIT:]:
            if m.get("settled"):
                verdict = "realized" if m.get("realized") else "false-alarm"
            else:
                verdict = "(in flight)"
            lines.append(
                f"{m.get('host', '?')}  owner={m.get('owner_kind', '?')}/"
                f"{m.get('owner_name', '?')}  slice={m.get('slice', '?')}  "
                f"score={m.get('score')}  token={m.get('token') or '(drain)'}  "
                f"requested_at={m.get('requested_at')}  {verdict}"
            )
        if not migrations:
            lines.append("# none")
        emit("risk.txt", "\n".join(lines) + "\n")
    except errors.ApiError as e:
        emit("risk.txt", f"# collection failed: {e}\n")

    try:
        # the data-plane telemetry view: fleet rollup (per-node perf
        # labels + generation/chips), the operator-published floor
        # table, and every gang's step-time artifact — where "why is
        # this gang slow" starts (README: Diagnosing a slow gang)
        from tpu_operator import consts as _consts

        lines = ["# fleet perf"]
        fleet = []
        for node in client.list("v1", "Node"):
            labels = node["metadata"].get("labels") or {}
            if _consts.TPU_PRESENT_LABEL not in labels and _consts.TPU_PERF_LABEL not in labels:
                continue
            fleet.append(
                f"{node['metadata']['name']}  "
                f"perf={labels.get(_consts.TPU_PERF_LABEL, '-')}  "
                f"health={labels.get(_consts.TPU_HEALTH_LABEL, '-')}  "
                f"repair={labels.get(_consts.REPAIR_STATE_LABEL, '-')}  "
                f"generation={labels.get(_consts.TFD_TPU_GENERATION_LABEL, '-')}  "
                f"chips={labels.get(_consts.TFD_CHIPS_PER_NODE_LABEL, '-')}"
            )
        lines.extend(fleet or ["# none"])
        lines.append("")
        lines.append("# perf floors (operator-published)")
        floors_cm = client.get_or_none(
            "v1", "ConfigMap", _consts.PERF_FLOORS_CONFIGMAP, namespace
        )
        if floors_cm is not None:
            lines.append((floors_cm.get("data") or {}).get(_consts.PERF_FLOORS_KEY, "# empty"))
        else:
            lines.append("# not published")
        lines.append("")
        lines.append("# gang step-time artifacts")
        gangs = []
        for cm in client.list("v1", "ConfigMap", namespace):
            raw = (cm["metadata"].get("annotations") or {}).get(
                _consts.GANG_TELEMETRY_ANNOTATION
            )
            if raw:
                gangs.append(f"{cm['metadata']['name']}  {raw}")
        lines.extend(gangs or ["# none"])
        emit("telemetry.txt", "\n".join(lines) + "\n")
    except errors.ApiError as e:
        emit("telemetry.txt", f"# collection failed: {e}\n")

    try:
        # the fleet compile cache: per-generation compiled-executable
        # records, the prewarm handshake in flight, and this process's
        # hit/miss counters — where "why was that scale-up cold" starts
        from tpu_operator import consts as _consts
        from tpu_operator.workloads import compilecache

        lines = ["# compile cache (per-generation records)"]
        cache_cm = client.get_or_none(
            "v1", "ConfigMap", _consts.COMPILE_CACHE_CONFIGMAP, namespace
        )
        data = (cache_cm or {}).get("data") or {}
        entries = compilecache.cached_entries(data)
        for gen in sorted(entries):
            entry = entries[gen]
            records = entry.get("records") or {}
            lines.append(
                f"{gen}  libtpu={entry.get('libtpu_version', '?')}  "
                f"records={len(records)}"
            )
            for key in sorted(records):
                rec = records[key] if isinstance(records[key], dict) else {}
                lines.append(
                    f"  {key}  seconds={rec.get('seconds', '?')}  "
                    f"source={rec.get('source', '?')}"
                    + (f"  serving={rec['serving']}" if rec.get("serving") else "")
                    + (f"  node={rec['node']}" if rec.get("node") else "")
                )
        if not entries:
            lines.append("# none")
        lines.append("")
        lines.append("# prewarm requests in flight")
        requests = compilecache.parse_requests(
            data.get(_consts.COMPILE_PREWARM_REQUEST_KEY)
        )
        for rid in sorted(requests):
            req = requests[rid]
            lines.append(f"{rid}  serving={req.get('serving', '?')}")
        if not requests:
            lines.append("# none")
        lines.append("")
        lines.append("# prewarm acks")
        acks = (compilecache.parse_entry(
            data.get(_consts.COMPILE_PREWARM_ACK_KEY)
        ) or {}).get("acks")
        acks = acks if isinstance(acks, dict) else {}
        for rid in sorted(acks):
            ack = acks[rid] if isinstance(acks[rid], dict) else {}
            lines.append(
                f"{rid}  node={ack.get('node', '?')}  "
                f"seconds={ack.get('seconds', '?')}  "
                f"outcome={ack.get('outcome', '?')}"
            )
        if not acks:
            lines.append("# none")
        lines.append("")
        lines.append("# this process's warm-start traffic")
        cstats = compilecache.stats()
        for gen in sorted(set(cstats["hits"]) | set(cstats["misses"])):
            lines.append(
                f"{gen}  hits={cstats['hits'].get(gen, 0)}  "
                f"misses={cstats['misses'].get(gen, 0)}"
            )
        if not (cstats["hits"] or cstats["misses"]):
            lines.append("# none")
        lines.append("")
        lines.append("# last warm-start/prewarm decisions")
        for d in cstats["decisions"]:
            lines.append(
                f"{d.get('outcome', '?')}  generation={d.get('generation', '?')}  "
                f"{d.get('detail', '')}"
            )
        if not cstats["decisions"]:
            lines.append("# none")
        emit("compile-cache.txt", "\n".join(lines) + "\n")
    except errors.ApiError as e:
        emit("compile-cache.txt", f"# collection failed: {e}\n")

    try:
        # the fabric view: the per-pool link-health map (the analyzer's
        # standing blame records), every gang's published fabric matrix,
        # the worst-10 measured edges fleet-wide, and the blame split —
        # where "slow gang: chip or link?" gets answered (README)
        import json as _json

        from tpu_operator import consts as _consts

        lines = ["# link health (operator-recorded link blame)"]
        link_cm = client.get_or_none(
            "v1", "ConfigMap", _consts.LINK_HEALTH_CONFIGMAP, namespace
        )
        recorded_edges = []
        if link_cm is not None and (link_cm.get("data") or {}):
            for pool, raw in sorted((link_cm.get("data") or {}).items()):
                lines.append(f"{pool}  {raw}")
                try:
                    for edge, rec in (_json.loads(raw).get("edges") or {}).items():
                        recorded_edges.append((pool, edge, rec))
                except ValueError:
                    pass
        else:
            lines.append("# none recorded")
        lines.append("")
        lines.append("# gang fabric artifacts")
        gangs = []
        measured = []
        for cm in client.list("v1", "ConfigMap", namespace):
            raw = (cm["metadata"].get("annotations") or {}).get(
                _consts.GANG_FABRIC_ANNOTATION
            )
            if not raw:
                continue
            gangs.append(f"{cm['metadata']['name']}  {raw}")
            try:
                artifact = _json.loads(raw)
                for edge, meta in (artifact.get("edges") or {}).items():
                    measured.append(
                        (float(meta.get("bw_gbps") or 0.0), edge,
                         cm["metadata"]["name"], str(meta.get("axis") or "-"))
                    )
            except ValueError:
                pass
        lines.extend(gangs or ["# none"])
        lines.append("")
        lines.append("# worst 10 measured edges (GB/s ascending)")
        worst = sorted(measured)[:10]
        if worst:
            lines.extend(
                f"{bw:.3f}  {edge}  axis={axis}  gang={gang}"
                for bw, edge, gang, axis in worst
            )
        else:
            lines.append("# none measured")
        lines.append("")
        lines.append("# blame decisions")
        blames = [
            f"link  {edge}  pool={pool}  "
            f"bw={rec.get('bw_gbps', '?')} median={rec.get('median_gbps', '?')}  "
            f"gang={rec.get('gang', '-')}"
            for pool, edge, rec in recorded_edges
        ]
        for node in client.list("v1", "Node"):
            labels = node["metadata"].get("labels") or {}
            if labels.get(_consts.TPU_PERF_LABEL) == _consts.PERF_DEGRADED:
                blames.append(
                    f"host  {node['metadata']['name']}  perf=degraded  "
                    f"repair={labels.get(_consts.REPAIR_STATE_LABEL, '-')}"
                )
        lines.extend(blames or ["# none"])
        emit("fabric.txt", "\n".join(lines) + "\n")
    except errors.ApiError as e:
        emit("fabric.txt", f"# collection failed: {e}\n")

    try:
        # the sharded control plane's view: shard→pool assignment (the
        # pool-shard keying over live nodes), per-shard queue depths of
        # THIS process's controllers (same in-process caveat as
        # traces.txt), and the slowest shard's recent reconcile traces —
        # where "which pool is wedging the control plane" starts
        from tpu_operator.kube.controller import live_controllers
        from tpu_operator.kube.sharding import shard_key
        from tpu_operator.kube.trace import recorder as _recorder

        lines = ["# shard -> pool assignment (nodes per shard)"]
        by_shard: dict = {}
        for node in client.list("v1", "Node"):
            by_shard.setdefault(shard_key(node), []).append(node["metadata"]["name"])
        for shard in sorted(by_shard):
            members = sorted(by_shard[shard])
            preview = ",".join(members[:5]) + ("…" if len(members) > 5 else "")
            lines.append(f"{shard}  nodes={len(members)}  [{preview}]")
        if not by_shard:
            lines.append("# none")
        lines.append("")
        lines.append("# per-shard queue depths (this process's controllers)")
        depth_lines = []
        for ctl in live_controllers():
            for shard, depth in ctl.shard_depths().items():
                depth_lines.append(f"{ctl.name}  shard={shard or '-'}  depth={depth}")
        lines.extend(depth_lines or ["# no live controllers in this process"])
        lines.append("")
        lines.append("# slowest shard's last 5 reconcile traces")
        rec = _recorder()
        shard_wall: dict = {}
        for t in rec.traces():
            key = (t.root.attrs.get("controller", "?"), str(t.root.attrs.get("shard") or ""))
            shard_wall[key] = shard_wall.get(key, 0.0) + t.root.duration
        if shard_wall:
            slow_ctl, slow_shard = max(shard_wall, key=shard_wall.get)
            lines.append(
                f"# controller={slow_ctl} shard={slow_shard or '-'} "
                f"total_wall={shard_wall[(slow_ctl, slow_shard)] * 1000:.2f}ms"
            )
            slow_traces = [
                t for t in rec.traces()
                if t.root.attrs.get("controller") == slow_ctl
                and str(t.root.attrs.get("shard") or "") == slow_shard
            ][-5:]
            for t in slow_traces:
                lines.extend(rec.render_trace(t))
        else:
            lines.append("# no traces recorded in this process")
        emit("sharding.txt", "\n".join(lines) + "\n")
    except Exception as e:  # noqa: BLE001 — never fail the bundle
        emit("sharding.txt", f"# collection failed: {e}\n")

    try:
        # cluster-wide: events for cluster-scoped objects (the CRs) land
        # in "default" per apiserver rules, not the operator namespace
        events = client.list("v1", "Event")
        events.sort(key=lambda e: e.get("lastTimestamp") or "")
        lines = [
            f"{e.get('lastTimestamp', '?')}  {e.get('type', '?')}  "
            f"{e.get('reason', '?')}  "
            f"{(e.get('involvedObject') or {}).get('kind', '?')}/"
            f"{(e.get('involvedObject') or {}).get('name', '?')}  "
            f"{e.get('message', '')}"
            for e in events
        ]
        emit("events.txt", "\n".join(lines) + "\n" if lines else "# none\n")
    except errors.ApiError as e:
        emit("events.txt", f"# collection failed: {e}\n")

    try:
        # static-analysis snapshot of the *running build*: support reads
        # it to rule out config drift before chasing cluster state. Every
        # source the repo checkout would add (goldens, kustomize) is
        # simply absent in-image, so the in-image report covers the
        # rendered states + chart the operator actually serves.
        from tpu_operator.lint.findings import render_json
        from tpu_operator.lint.runner import run_lint

        timings: dict = {}
        emit("lint-report.json", render_json(run_lint(timings=timings), timings=timings))
    except Exception as e:  # noqa: BLE001 — the bundle must never fail on lint
        emit("lint-report.json", f"# collection failed: {e}\n")

    # breaker/retry state of the collecting client itself: after a
    # degraded-cluster collection this records what the transport rode
    # out (retries by verb, breaker opens, failure classes) — the first
    # artifact support reads when "the bundle took forever" IS the bug
    from tpu_operator.kube.retry import resilience_of

    res = resilience_of(client)
    if res is not None:
        try:
            emit("api-resilience.txt", res.report())
        except Exception as e:  # noqa: BLE001 — never fail the bundle
            emit("api-resilience.txt", f"# collection failed: {e}\n")

    # the flight recorder of THIS process (kube/trace.py): every recent
    # reconcile's full span tree — queue wait, body phases, each apiserver
    # call with retry attempts — plus the slowest-N cut. In-process
    # embedders (tests, `--fake-cluster`, operators collecting their own
    # bundle) get their live reconcile history; a workstation collection
    # records its own (mostly empty) recorder, same as api-resilience.txt
    # records the collecting client.
    try:
        from tpu_operator.kube.trace import recorder

        emit("traces.txt", recorder().dump())
        emit("slow-reconciles.txt", recorder().dump_slowest(10))
    except Exception as e:  # noqa: BLE001 — never fail the bundle
        emit("traces.txt", f"# collection failed: {e}\n")

    pod_logs = getattr(client, "pod_logs", None)
    if pod_logs is not None:
        try:
            pods = client.list("v1", "Pod", namespace)
        except errors.ApiError:
            pods = []
        for pod in pods:
            name = pod["metadata"]["name"]
            spec = pod.get("spec") or {}
            containers = [
                c.get("name", "")
                for c in (spec.get("initContainers") or []) + (spec.get("containers") or [])
            ]

            def fetch(container=None) -> str:
                try:
                    return pod_logs(
                        name, namespace, container=container, tail_lines=log_tail
                    )
                except errors.ApiError as e:
                    return f"# logs unavailable: {e}\n"

            if len(containers) > 1:
                # a real apiserver 400s a log request on a multi-container
                # pod without ?container= — gather each (kubectl's
                # --all-containers) into one artifact
                text = "\n".join(
                    f"==== container {c} ====\n{fetch(c)}" for c in containers
                )
            else:
                text = fetch()
            emit(os.path.join("pod-logs", f"{name}.log"), text)
    return written
