"""tpu-validator: component dispatch + retry loop.

Reference: ``validator/main.go`` — ``Component`` interface (:51-57),
component dispatch on the COMPONENT env (:450-565), 5s retry-forever loop
(:133-134), status files as the cross-operand barrier. Components:

    libtpu    driver-validation analog (:617-635): libtpu.so installed on
              the host path + installer container ready marker
    plugin    plugin-validation analog (:813, :1096-1174): google.com/tpu
              allocatable on this node
    workload  cuda-validation analog (:1189-1308): schedule a JAX smoke
              pod, wait for Succeeded
    slice     multi-host check (BASELINE config 4): jax.distributed
              bring-up + psum allreduce over ICI, records GB/s/chip
    metrics   node-status-exporter payload (validator/metrics.go)
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
from typing import Callable, Dict, Optional

from tpu_operator import consts
from tpu_operator.kube import errors
from tpu_operator.kube.client import Client
from tpu_operator.kube.objects import new_object
from tpu_operator.validator import status as status_files

log = logging.getLogger(__name__)


@dataclasses.dataclass
class Context:
    client: Optional[Client] = None
    node_name: str = ""
    namespace: str = consts.DEFAULT_OPERATOR_NAMESPACE
    validation_dir: str = consts.VALIDATION_DIR
    install_dir: str = consts.LIBTPU_INSTALL_DIR
    validator_image: str = ""
    retry_interval: float = 5.0  # reference: sleepIntervalSeconds main.go:133
    resource_poll_retries: int = 30  # reference: gpuResourceDiscoveryWaitRetries
    pod_wait_retries: int = 60  # reference: podCreationWaitRetries
    expected_chips: Optional[int] = None
    # performance floors (spec.validator.minTflops / minPsumGbpsPerChip).
    # The reference's validator gates only on resource presence
    # (main.go:1096-1174); a floor makes a thermally-throttled chip or a
    # degraded ICI link fail validation (NotReady, status file withheld)
    # instead of sailing to Ready.
    min_tflops: Optional[float] = None
    min_psum_gbps_per_chip: Optional[float] = None

    @classmethod
    def from_env(cls, client: Optional[Client] = None) -> "Context":
        return cls(
            client=client,
            node_name=os.environ.get("NODE_NAME", ""),
            namespace=os.environ.get(consts.OPERATOR_NAMESPACE_ENV, consts.DEFAULT_OPERATOR_NAMESPACE),
            validation_dir=os.environ.get("VALIDATION_DIR", consts.VALIDATION_DIR),
            install_dir=os.environ.get("LIBTPU_INSTALL_DIR", consts.LIBTPU_INSTALL_DIR),
            validator_image=os.environ.get("VALIDATOR_IMAGE", ""),
            expected_chips=int(os.environ["EXPECTED_CHIPS"]) if os.environ.get("EXPECTED_CHIPS") else None,
            # `is None`, not `or`: an explicit MIN_TFLOPS=0 means "floor
            # disabled" and must not fall through to the published table
            min_tflops=(
                _float_env("MIN_TFLOPS")
                if os.environ.get("MIN_TFLOPS", "").strip()
                else _floor_tflops_from_env()
            ),
            min_psum_gbps_per_chip=_float_env("MIN_PSUM_GBPS_PER_CHIP"),
        )


def _float_env(name: str) -> Optional[float]:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        log.warning("invalid %s %r; floor disabled", name, raw)
        return None


def _floor_tflops_from_env() -> Optional[float]:
    """minTflops fallback: the operator-published per-generation floor
    table (PERF_FLOORS_JSON via the perf-floors ConfigMap — the same
    floors the exporter's grey-failure detection holds probes to), keyed
    by this node's runtime generation. None off-TPU, when unset, or on
    an unrecognized generation: the floor never guesses."""
    blob = os.environ.get("PERF_FLOORS_JSON", "").strip()
    if not blob:
        return None
    try:
        from tpu_operator.perf import floors_for
        from tpu_operator.workloads.matmul_bench import chip_generation

        gen = chip_generation()
        if not gen:
            return None
        return floors_for(gen, blob).get("matmul_tflops")
    except Exception as e:  # noqa: BLE001 — a bad table disables, never fails
        log.warning("perf-floor fallback unavailable: %s", e)
        return None


def enforce_floor(what: str, measured: float, floor: Optional[float]) -> None:
    """Raise (→ retry loop → NotReady) when a measured rate is below its
    configured floor; no-op when no floor is set."""
    if floor is not None and measured < floor:
        raise RuntimeError(
            f"{what} {measured:.2f} below configured floor {floor:.2f}"
        )


# ---------------------------------------------------------------------------
# Components. Each returns a payload dict on success, raises on failure.
# ---------------------------------------------------------------------------


def validate_libtpu(ctx: Context) -> dict:
    """reference: Driver.runValidation main.go:617-635 — the driver is
    ready when the host install dir carries libtpu.so and the installer
    container's ready marker."""
    lib = os.path.join(ctx.install_dir, "libtpu.so")
    marker = os.path.join(ctx.install_dir, consts.LIBTPU_CTR_READY_FILE)
    if not os.path.exists(lib):
        raise RuntimeError(f"libtpu.so not found at {lib}")
    if not os.path.exists(marker):
        raise RuntimeError(f"installer ready marker missing: {marker}")
    return {"libtpu": lib, "size": os.path.getsize(lib)}


def validate_plugin(ctx: Context) -> dict:
    """reference: Plugin.validateGPUResource main.go:1115-1174 — poll this
    node's allocatable for the extended resource the device plugin
    advertises."""
    if ctx.client is None or not ctx.node_name:
        raise RuntimeError("plugin validation requires a kube client and NODE_NAME")
    for _ in range(ctx.resource_poll_retries):
        node = ctx.client.get_or_none("v1", "Node", ctx.node_name)
        if node is not None:
            allocatable = node.get("status", {}).get("allocatable", {}) or {}
            chips = int(allocatable.get(consts.TPU_RESOURCE_NAME, "0") or "0")
            if chips > 0:
                return {"resource": consts.TPU_RESOURCE_NAME, "chips": chips}
        time.sleep(ctx.retry_interval)
    raise RuntimeError(
        f"{consts.TPU_RESOURCE_NAME} never became allocatable on {ctx.node_name}"
    )


def workload_pod(ctx: Context) -> dict:
    """The JAX smoke pod spec (reference: cuda-workload-validation.yaml —
    the vectorAdd pod, GPU limit, restartPolicy OnFailure)."""
    return new_object(
        "v1",
        "Pod",
        f"tpu-workload-validation-{ctx.node_name or 'node'}",
        ctx.namespace,
        labels={"app": "tpu-workload-validation"},
        spec={
            "restartPolicy": "Never",
            # schedule through the scheduler (hostname selector + the TPU
            # limit below) so the pod exercises the same google.com/tpu
            # accounting plugin validation just proved — nodeName pinning
            # would bypass both (reference: plugin-workload-validation.yaml
            # schedules with a GPU limit)
            "nodeSelector": (
                {"kubernetes.io/hostname": ctx.node_name} if ctx.node_name else None
            ),
            "tolerations": [
                {"key": consts.TPU_RESOURCE_NAME, "operator": "Exists", "effect": "NoSchedule"},
                # validation runs while the upgrade FSM still has the node
                # cordoned (VALIDATION before UNCORDON), so the pod must
                # tolerate the cordon taint to schedule at all
                {
                    "key": "node.kubernetes.io/unschedulable",
                    "operator": "Exists",
                    "effect": "NoSchedule",
                },
            ],
            "containers": [
                {
                    "name": "tpu-smoke",
                    "image": ctx.validator_image or "tpu-operator-validator",
                    "command": ["python", "-m", "tpu_operator.validator.workload_entry"],
                    "env": [
                        {"name": "COMPONENT", "value": "smoke"},
                        *(
                            [{"name": "EXPECTED_CHIPS", "value": str(ctx.expected_chips)}]
                            if ctx.expected_chips
                            else []
                        ),
                        *(
                            [{"name": "MIN_TFLOPS", "value": str(ctx.min_tflops)}]
                            if ctx.min_tflops is not None
                            else []
                        ),
                    ],
                    "resources": {
                        "limits": {consts.TPU_RESOURCE_NAME: str(ctx.expected_chips or 1)}
                    },
                }
            ],
        },
    )


def validate_workload(ctx: Context) -> dict:
    """reference: CUDA.runWorkload main.go:1232-1308 + waitForPod
    :1055-1072 — schedule the smoke pod, wait Succeeded, clean up."""
    if ctx.client is None:
        raise RuntimeError("workload validation requires a kube client")
    pod = workload_pod(ctx)
    name, ns = pod["metadata"]["name"], ctx.namespace
    existing = ctx.client.get_or_none("v1", "Pod", name, ns)
    if existing is not None:  # stale from a previous attempt
        ctx.client.delete("v1", "Pod", name, ns)
    ctx.client.create(pod)  # tpuop-lint: kinds=v1/Pod
    try:
        for _ in range(ctx.pod_wait_retries):
            live = ctx.client.get_or_none("v1", "Pod", name, ns)
            phase = (live or {}).get("status", {}).get("phase")
            if phase == "Succeeded":
                return {"pod": name, "phase": phase}
            if phase == "Failed":
                raise RuntimeError(f"workload pod {name} failed")
            time.sleep(ctx.retry_interval)
        raise RuntimeError(f"workload pod {name} did not succeed in time")
    finally:
        try:
            ctx.client.delete("v1", "Pod", name, ns)
        except errors.ApiError:
            pass


def validate_slice(ctx: Context) -> dict:
    """Multi-host ICI check (BASELINE config 4): bring up jax.distributed
    from the gang env, run the psum allreduce (GB/s/chip), the
    long-context ring-attention exactness check over the same ring, and
    the pipeline-parallel schedule over the device chain."""
    from tpu_operator.workloads import allreduce, distributed, pipeline, ringattention

    dist = distributed.initialize()
    report = allreduce.run_allreduce()
    report["hosts"] = dist.num_processes
    report["process_id"] = dist.process_id
    if report.get("devices", 0) > 1:
        # the ICI bandwidth floor only means something on a real
        # multi-chip ring; a single chip measures dispatch, not fabric
        enforce_floor(
            "psum bus GB/s/chip",
            report.get("peak_busbw_gbps_per_chip", 0.0),
            ctx.min_psum_gbps_per_chip,
        )
    import jax

    n = len(jax.devices())
    report["ring_attention"] = ringattention.run_ring_attention_check(
        seq_len=max(128, 32 * n)
    )
    report["pipeline"] = pipeline.run_pipeline_check()
    # the within-chip half of the long-context story: the pallas flash
    # kernel must agree with dense attention on this node's accelerator
    from tpu_operator.workloads import flashattention

    report["flash_attention"] = flashattention.run_flash_attention_check(
        seq_len=256, block_q=128, block_k=128
    )
    # and the two levels composed: flash as the ring's local attention
    report["ring_flash_attention"] = ringattention.run_ring_attention_check(
        seq_len=max(128, 32 * n), local_impl="flash"
    )
    # the full collective-primitive set (all-gather / reduce-scatter /
    # all-to-all / ppermute beside the headline psum)
    from tpu_operator.workloads import collectives

    # max(n, ...) keeps the payload nonzero on slices wider than 2048
    report["collectives"] = collectives.run_collectives_check(
        per_device=max(n, (2048 // n) * n)
    )
    return report


def validate_smoke(ctx: Context) -> dict:
    """In-pod payload of the workload pod (the vectorAdd itself). With a
    minTflops floor configured, also measures the bf16 matmul rate on
    this node's chips and fails below the floor — catching a throttled or
    degraded chip the correctness check would pass."""
    from tpu_operator.workloads import smoke

    report = smoke.run_smoke(expected_devices=ctx.expected_chips)
    if ctx.min_tflops is not None:
        import jax

        from tpu_operator.workloads.matmul_bench import matmul_tflops

        # measure EVERY local chip and gate on the slowest: one throttled
        # chip must not hide behind a healthy default device
        rates = {}
        for dev in jax.local_devices():
            mm = matmul_tflops(size=4096, iters=8, device=dev)
            rates[str(dev)] = round(mm["tflops"], 2)
        report["matmul_bf16_tflops_per_chip"] = rates
        slowest = min(rates, key=rates.get)
        report["matmul_bf16_tflops"] = rates[slowest]
        enforce_floor(
            f"bf16 matmul TFLOP/s ({slowest})", rates[slowest], ctx.min_tflops
        )
    return report


ComponentFn = Callable[[Context], dict]

COMPONENTS: Dict[str, tuple] = {
    # name -> (fn, status file)
    "libtpu": (validate_libtpu, consts.LIBTPU_READY_FILE),
    "plugin": (validate_plugin, consts.PLUGIN_READY_FILE),
    "workload": (validate_workload, consts.WORKLOAD_READY_FILE),
    "slice": (validate_slice, "slice-ready"),
    "smoke": (validate_smoke, None),
}


def run_component(
    name: str,
    ctx: Context,
    max_attempts: Optional[int] = None,
) -> dict:
    """Retry-forever loop (reference: main.go:133-139): clear the stale
    status file, run the check every retry_interval until it passes, then
    write the status file other operands are blocked on."""
    fn, ready_file = COMPONENTS[name]
    if ready_file:
        status_files.clear_status(ready_file, ctx.validation_dir)
    attempt = 0
    while True:
        attempt += 1
        try:
            payload = fn(ctx)
            break
        except Exception as e:  # noqa: BLE001 — every failure retries, like the reference
            log.warning("validation %s attempt %d failed: %s", name, attempt, e)
            if max_attempts is not None and attempt >= max_attempts:
                raise
            time.sleep(ctx.retry_interval)
    if ready_file:
        status_files.write_status(ready_file, ctx.validation_dir, payload)
    return payload


def _in_cluster_client() -> Optional[Client]:
    """The plugin/workload/metrics components talk to the apiserver; inside
    a pod the in-cluster config is always present."""
    if not os.environ.get("KUBERNETES_SERVICE_HOST"):
        return None
    from tpu_operator.kube.http_client import HttpClient

    return HttpClient.in_cluster()


def main() -> int:
    logging.basicConfig(level=logging.INFO)
    component = os.environ.get("COMPONENT", "")
    if component == "metrics":
        from tpu_operator.validator.metrics import NodeMetrics

        metrics = NodeMetrics(Context.from_env(client=_in_cluster_client()),
                              port=int(os.environ.get("METRICS_PORT", "8000")))
        metrics.run_forever()
        return 0
    if component not in COMPONENTS:
        log.error("unknown COMPONENT %r (valid: %s)", component, ", ".join(COMPONENTS))
        return 1
    ctx = Context.from_env(client=_in_cluster_client())
    run_component(component, ctx)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
