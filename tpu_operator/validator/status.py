"""Validation status files (reference: validator/main.go:131-166)."""

from __future__ import annotations

import json
import os
import tempfile
from typing import Optional

from tpu_operator import consts


def status_path(name: str, validation_dir: Optional[str] = None) -> str:
    return os.path.join(validation_dir or consts.VALIDATION_DIR, name)


def write_status(name: str, validation_dir: Optional[str] = None, payload: Optional[dict] = None) -> str:
    """Create/refresh a status file; payload (if any) is stored as JSON so
    downstream consumers (node metrics exporter) can read results."""
    path = status_path(name, validation_dir)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    # atomic: the files are barrier flags on a hostPath shared across
    # containers — a torn read must be impossible
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path), prefix=f".{name}.")
    try:
        with os.fdopen(fd, "w") as f:
            if payload is not None:
                json.dump(payload, f)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except FileNotFoundError:
            pass
        raise
    return path


def clear_status(name: str, validation_dir: Optional[str] = None) -> None:
    """reference: deleteStatusFile — stale results must be removed before a
    re-check so consumers never trust an outdated barrier."""
    try:
        os.remove(status_path(name, validation_dir))
    except FileNotFoundError:
        pass


def read_status(name: str, validation_dir: Optional[str] = None) -> Optional[dict]:
    """None when the file is absent; {} when present but empty."""
    try:
        with open(status_path(name, validation_dir)) as f:
            content = f.read().strip()
            return json.loads(content) if content else {}
    except FileNotFoundError:
        return None
    except json.JSONDecodeError:
        return {}
