"""Validator operand (reference: validator/ — the nvidia-validator image).

One binary, component selected by ``COMPONENT`` env; each component checks
its piece of the TPU stack and writes a status file under
``/run/tpu/validations``. The status files are the cross-DaemonSet
synchronization barrier: other operands' init containers poll for them
(reference: validator/main.go:131-166, the ``*-ready`` files under
/run/nvidia/validations).
"""

from tpu_operator.validator.main import COMPONENTS, Context, run_component  # noqa: F401
