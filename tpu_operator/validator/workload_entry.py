"""In-pod entry for the workload-validation pod: the vectorAdd analog.

The workload pod (validator/main.py:workload_pod) runs this module with a
google.com/tpu limit; success (exit 0) marks the node's TPU stack usable
end to end (reference: the vectorAdd container in
cuda-workload-validation.yaml).
"""

import json
import os

from tpu_operator.workloads.smoke import run_smoke


def main() -> int:
    expected = os.environ.get("EXPECTED_CHIPS")
    report = run_smoke(expected_devices=int(expected) if expected else None)
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
