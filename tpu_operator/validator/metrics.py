"""Node metrics exporter (the node-status-exporter payload).

Reference: ``validator/metrics.go`` — a per-node Prometheus server that
(1) watches the validation status files (:157-188, 30s cadence),
(2) re-runs the libtpu validation every 60s (:235-248), and
(3) counts this node's TPU devices (:190-299). Metric names mirror
``gpu_operator_node_*`` with the tpu swap.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Optional

import prometheus_client

from tpu_operator import consts
from tpu_operator.validator import status as status_files
from tpu_operator.validator.main import Context, validate_libtpu

log = logging.getLogger(__name__)

WATCHED_COMPONENTS = (
    consts.LIBTPU_READY_FILE,
    consts.PLUGIN_READY_FILE,
    consts.WORKLOAD_READY_FILE,
    "slice-ready",
)


class NodeMetrics:
    def __init__(
        self,
        ctx: Context,
        port: int = 8000,
        status_interval: float = 30.0,  # reference: metrics.go:39-46
        revalidate_interval: float = 60.0,
        registry: Optional[prometheus_client.CollectorRegistry] = None,
    ):
        self.ctx = ctx
        self.port = port
        self.status_interval = status_interval
        self.revalidate_interval = revalidate_interval
        self.registry = registry or prometheus_client.CollectorRegistry()
        node = ctx.node_name or "unknown"
        self.component_ready = prometheus_client.Gauge(
            "tpu_operator_node_component_ready",
            "1 when the component's validation status file is present",
            ["node", "component"],
            registry=self.registry,
        )
        self.tpu_chips = prometheus_client.Gauge(
            "tpu_operator_node_tpu_chips",
            "TPU chips advertised by the device plugin on this node",
            ["node"],
            registry=self.registry,
        )
        self.libtpu_validations = prometheus_client.Counter(
            "tpu_operator_node_libtpu_revalidations_total",
            "Periodic libtpu re-validation attempts",
            ["node", "result"],
            registry=self.registry,
        )
        self.slice_busbw = prometheus_client.Gauge(
            "tpu_operator_node_slice_allreduce_busbw_gbps",
            "Last slice-validation allreduce bus bandwidth (GB/s/chip)",
            ["node"],
            registry=self.registry,
        )
        self.slice_ring_attention_err = prometheus_client.Gauge(
            "tpu_operator_node_slice_ring_attention_max_abs_err",
            "Ring-vs-dense attention exactness from the last slice validation",
            ["node"],
            registry=self.registry,
        )
        self.slice_flash_attention_err = prometheus_client.Gauge(
            "tpu_operator_node_slice_flash_attention_max_abs_err",
            "Pallas-flash-vs-dense attention exactness from the last slice validation",
            ["node"],
            registry=self.registry,
        )
        self.slice_ring_flash_err = prometheus_client.Gauge(
            "tpu_operator_node_slice_ring_flash_attention_max_abs_err",
            "Composed flash-in-ring attention exactness from the last slice validation",
            ["node"],
            registry=self.registry,
        )
        self.slice_pipeline_err = prometheus_client.Gauge(
            "tpu_operator_node_slice_pipeline_max_abs_err",
            "Pipelined-vs-sequential exactness from the last slice validation "
            "(failed checks never write the file — alert on component_ready)",
            ["node"],
            registry=self.registry,
        )
        self._node = node
        self._stop = threading.Event()

    @classmethod
    def from_env(cls) -> "NodeMetrics":
        return cls(Context.from_env(), port=int(os.environ.get("METRICS_PORT", "8000")))

    # -- collection passes ---------------------------------------------------

    def collect_status_files(self) -> None:
        for component in WATCHED_COMPONENTS:
            payload = status_files.read_status(component, self.ctx.validation_dir)
            self.component_ready.labels(self._node, component).set(0 if payload is None else 1)
            if component == "slice-ready" and payload:
                busbw = payload.get("peak_busbw_gbps_per_chip")
                if busbw is not None:
                    self.slice_busbw.labels(self._node).set(busbw)
                ring = payload.get("ring_attention") or {}
                if ring.get("max_abs_err") is not None:
                    self.slice_ring_attention_err.labels(self._node).set(ring["max_abs_err"])
                flash = payload.get("flash_attention") or {}
                if flash.get("max_abs_err") is not None:
                    self.slice_flash_attention_err.labels(self._node).set(
                        flash["max_abs_err"]
                    )
                ring_flash = payload.get("ring_flash_attention") or {}
                if ring_flash.get("max_abs_err") is not None:
                    self.slice_ring_flash_err.labels(self._node).set(
                        ring_flash["max_abs_err"]
                    )
                pipeline = payload.get("pipeline") or {}
                if pipeline.get("max_abs_err_vs_sequential") is not None:
                    self.slice_pipeline_err.labels(self._node).set(
                        pipeline["max_abs_err_vs_sequential"]
                    )

    def collect_device_count(self) -> None:
        if self.ctx.client is None or not self.ctx.node_name:
            return
        node = self.ctx.client.get_or_none("v1", "Node", self.ctx.node_name)
        if node is None:
            return
        allocatable = node.get("status", {}).get("allocatable", {}) or {}
        self.tpu_chips.labels(self._node).set(int(allocatable.get(consts.TPU_RESOURCE_NAME, "0") or "0"))

    def revalidate_libtpu(self) -> None:
        """reference: metrics.go:235-248 — keep the driver check honest
        after node reboots / driver swaps."""
        try:
            payload = validate_libtpu(self.ctx)
            status_files.write_status(consts.LIBTPU_READY_FILE, self.ctx.validation_dir, payload)
            self.libtpu_validations.labels(self._node, "success").inc()
        except Exception as e:  # noqa: BLE001
            log.warning("libtpu revalidation failed: %s", e)
            status_files.clear_status(consts.LIBTPU_READY_FILE, self.ctx.validation_dir)
            self.libtpu_validations.labels(self._node, "failure").inc()

    # -- server --------------------------------------------------------------

    def run_forever(self) -> None:
        prometheus_client.start_http_server(self.port, registry=self.registry)
        last_revalidate = 0.0
        while not self._stop.is_set():
            self.collect_status_files()
            self.collect_device_count()
            now = time.monotonic()
            if now - last_revalidate >= self.revalidate_interval:
                self.revalidate_libtpu()
                last_revalidate = now
            self._stop.wait(self.status_interval)

    def stop(self) -> None:
        self._stop.set()
