"""Small shared utilities (reference: internal/utils/utils.go)."""

from __future__ import annotations

import json
from typing import Any

FNV64_OFFSET = 0xCBF29CE484222325
FNV64_PRIME = 0x100000001B3


def fnv64a(data: bytes) -> int:
    h = FNV64_OFFSET
    for b in data:
        h ^= b
        h = (h * FNV64_PRIME) & 0xFFFFFFFFFFFFFFFF
    return h


def deep_merge(base: dict, override: dict) -> dict:
    """Helm-style values merge: nested dicts merge key-wise, everything
    else (lists included) is replaced by the override."""
    merged = dict(base)
    for k, v in (override or {}).items():
        if isinstance(v, dict) and isinstance(merged.get(k), dict):
            merged[k] = deep_merge(merged[k], v)
        else:
            merged[k] = v
    return merged


def object_hash(obj: Any) -> str:
    """Deterministic content hash of an object (reference: GetObjectHash
    internal/utils/utils.go:66-77, FNV over the marshalled object). Used
    for the last-applied-hash annotation that gates spec updates."""
    payload = json.dumps(obj, sort_keys=True, separators=(",", ":"), default=str)
    return format(fnv64a(payload.encode()), "x")
