"""Small shared utilities (reference: internal/utils/utils.go)."""

from __future__ import annotations

import json
from typing import Any

FNV64_OFFSET = 0xCBF29CE484222325
FNV64_PRIME = 0x100000001B3


def fnv64a(data: bytes) -> int:
    h = FNV64_OFFSET
    for b in data:
        h ^= b
        h = (h * FNV64_PRIME) & 0xFFFFFFFFFFFFFFFF
    return h


def object_hash(obj: Any) -> str:
    """Deterministic content hash of an object (reference: GetObjectHash
    internal/utils/utils.go:66-77, FNV over the marshalled object). Used
    for the last-applied-hash annotation that gates spec updates."""
    payload = json.dumps(obj, sort_keys=True, separators=(",", ":"), default=str)
    return format(fnv64a(payload.encode()), "x")
