"""tpuop-lint: commit-time static analysis over everything the operator
ships.

Six analyzer families (see COMPONENTS.md §6 for the rule catalog):

    manifest     every rendered operand state, the goldens, the chart
                 output, and the kustomize bases — security posture,
                 image pinning, label/reference integrity, scheduling
                 hygiene (lint/manifest_rules.py)
    rbac         AST-extracted apiserver call sites per agent/controller
                 diffed against the shipped Roles/ClusterRoles — missing
                 grants fail at runtime as 403s, excess grants are
                 over-privilege (lint/rbac_static.py)
    drift        shipped CRD YAML vs the dataclass-derived schemas, helm
                 crds/ vs kustomize crd/, goldens vs regeneration
                 (lint/drift.py)
    metrics      registered Prometheus series vs the COMPONENTS.md
                 catalog both directions, PrometheusRule expr/hygiene
                 checks, and gauge retirement for dynamic label
                 dimensions (lint/metrics_catalog.py)
    concurrency  lock discipline over the threaded control plane:
                 guarded-by inference, lock-order cycle detection,
                 blocking-under-lock, thread-spawn hygiene
                 (lint/concurrency.py; runtime counterpart
                 kube/racecheck.py)
    reconcile    reconcile-loop contracts over controllers/, dataplane/,
                 workloads/: ownership-checked pattern deletes, the
                 shared-ConfigMap key ownership map, fail-closed reads
                 gating destructive actions, publish-once status, and
                 persisted-gate retry charges
                 (lint/reconcile_contracts.py)

The motivating incident: a missing ``events`` grant that only surfaced
at runtime via the RBAC-enforcing fake apiserver (TODO.md round 5) — a
class of bug this suite catches at commit time instead.
"""

from tpu_operator.lint.findings import (  # noqa: F401
    ERROR,
    INFO,
    WARNING,
    Baseline,
    Finding,
)
from tpu_operator.lint.runner import run_lint  # noqa: F401
