"""Lint orchestration: gather object groups, run analyzers, apply the
baseline.

Manifest groups mirror how objects reach a cluster:

    state:<name>    each ClusterPolicy operand state, freshly rendered
                    (serviceMonitor enabled, the goldens' spec, so the
                    monitoring objects are linted too)
    golden:<name>   the committed golden snapshots (identical findings
                    deduplicate against the fresh render; a *divergent*
                    golden yields both its own findings and a D003)
    chart           the full chart render from deploy/values.yaml
    kustomize       the generated kustomize bases, as one group (the
                    default overlay applies them together)

Every group collector is best-effort on layout: inside the shipped
image only the package manifests exist, so goldens/kustomize simply
contribute nothing there (must-gather runs the same code path).
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import yaml

from tpu_operator.lint import (
    concurrency,
    drift,
    manifest_rules,
    metrics_catalog,
    rbac_static,
    reconcile_contracts,
)
from tpu_operator.lint.baseline import unused_entry_findings
from tpu_operator.lint.findings import (
    Baseline,
    Finding,
    dedupe,
    sort_findings,
)

PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPO_ROOT = os.path.dirname(PKG_ROOT)
DEFAULT_BASELINE = os.path.join(REPO_ROOT, ".tpuop-lint-baseline")

ANALYZERS = ("manifest", "rbac", "drift", "metrics", "concurrency", "reconcile")

# which analyzer family owns each rule-id prefix — what lets --only/
# --skip accept rule ids and still run only the analyzers involved
RULE_PREFIX_FAMILIES = {
    "TPUOP-M": "manifest",
    "TPUOP-R": "rbac",
    "TPUOP-D": "drift",
    "TPUOP-O": "metrics",
    "TPUOP-C": "concurrency",
    "TPUOP-K": "reconcile",
}


def family_of_rule(rule: str) -> Optional[str]:
    for prefix, family in RULE_PREFIX_FAMILIES.items():
        if rule.startswith(prefix):
            return family
    return None


def manifest_groups() -> List[Tuple[str, List[dict]]]:
    from tpu_operator.chart import render_chart
    from tpu_operator.states import new_cluster_policy_states

    groups: List[Tuple[str, List[dict]]] = []
    catalog = drift.golden_spec_catalog()
    for state in new_cluster_policy_states():
        groups.append(
            (f"state:{state.name}",
             state.renderer.render_objects(state.get_render_data(catalog)))
        )

    golden_dir = os.path.join(REPO_ROOT, "tests", "golden")
    if os.path.isdir(golden_dir):
        for name in sorted(os.listdir(golden_dir)):
            if not name.endswith(".yaml") or name == "helm-template.yaml":
                continue
            with open(os.path.join(golden_dir, name)) as f:
                objs = [d for d in yaml.safe_load_all(f) if d]
            groups.append((f"golden:{name[:-len('.yaml')]}", objs))

    values_path = os.path.join(REPO_ROOT, "deploy", "values.yaml")
    if os.path.exists(values_path):
        with open(values_path) as f:
            groups.append(("chart", render_chart(yaml.safe_load(f))))

    kustomize_dir = os.path.join(REPO_ROOT, "deploy", "kustomize")
    if os.path.isdir(kustomize_dir):
        objs = []
        for base in ("crd", "rbac", "manager", "samples"):
            base_dir = os.path.join(kustomize_dir, base)
            if not os.path.isdir(base_dir):
                continue
            for name in sorted(os.listdir(base_dir)):
                if name == "kustomization.yaml" or not name.endswith((".yaml", ".yml")):
                    continue
                with open(os.path.join(base_dir, name)) as f:
                    objs.extend(d for d in yaml.safe_load_all(f) if d)
        groups.append(("kustomize", objs))
    return groups


def run_lint(
    baseline_path: Optional[str] = None,
    only: Optional[Sequence[str]] = None,
    timings: Optional[Dict[str, float]] = None,
) -> List[Finding]:
    """Run the selected analyzers, dedupe, and apply the baseline.
    Returns every finding (suppressed ones marked, not dropped). Pass a
    dict as ``timings`` to receive per-analyzer wall seconds (the JSON
    report surfaces them — a slow analyzer is a CI tax everyone pays)."""
    selected = set(only or ANALYZERS)
    unknown = selected - set(ANALYZERS)
    if unknown:
        raise ValueError(
            f"unknown analyzer name(s): {', '.join(sorted(unknown))} "
            f"(valid: {', '.join(ANALYZERS)})"
        )
    findings: List[Finding] = []

    def timed(name: str, fn) -> None:
        t0 = time.monotonic()
        findings.extend(fn())
        if timings is not None:
            timings[name] = timings.get(name, 0.0) + (time.monotonic() - t0)

    groups = manifest_groups() if selected & {"manifest", "metrics"} else []
    if "manifest" in selected:
        timed("manifest", lambda: [
            f for group, objects in groups for f in manifest_rules.lint_group(group, objects)
        ])
    if "rbac" in selected:
        timed("rbac", rbac_static.analyze)
    if "drift" in selected:
        timed("drift", drift.analyze)
    if "metrics" in selected:
        timed("metrics", metrics_catalog.analyze)
        # O003/O004 ride the same rendered groups the manifest rules
        # lint: every series a shipped PrometheusRule references must
        # exist, and every alert must page with meaning (summary/
        # description) over a sustained condition (non-zero for:);
        # O005 proves every dynamically-labelled gauge can retire.
        timed("metrics", lambda: metrics_catalog.analyze_rules(groups))
        timed("metrics", lambda: metrics_catalog.analyze_rule_hygiene(groups))
        timed("metrics", metrics_catalog.analyze_gauge_retirement)
    if "concurrency" in selected:
        timed("concurrency", concurrency.analyze)
    if "reconcile" in selected:
        timed("reconcile", reconcile_contracts.analyze)
    findings = dedupe(findings)

    baseline = Baseline.load(
        DEFAULT_BASELINE if baseline_path is None else baseline_path
    )
    findings = baseline.apply(findings)
    # dead-entry warnings are judged per family, so even a partial
    # --only run condemns the unmatched entries of the families it ran
    findings.extend(unused_entry_findings(
        baseline, selected, family_of_rule,
        full_run=selected == set(ANALYZERS),
    ))
    return sort_findings(findings)
