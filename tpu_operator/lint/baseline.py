"""Baseline (suppression) file handling, shared by every analyzer family.

One module owns the load/match/unused-entry logic so each family gets
identical semantics: prefix matching stops at path boundaries, every
suppression is recorded (not dropped), and an entry that matched
nothing is itself a finding — per family, so even a partial
``--only`` run reports the dead entries of the families it ran.

Baseline format (``.tpuop-lint-baseline`` at the repo root), one entry
per line:

    RULE-ID  location-prefix  # one-line justification

An entry suppresses every finding whose rule matches exactly and whose
location starts with the given prefix.
"""

from __future__ import annotations

import dataclasses
import os
from typing import TYPE_CHECKING, Callable, Dict, List, Optional, Set

if TYPE_CHECKING:  # pragma: no cover - import cycle guard (findings re-exports us)
    from tpu_operator.lint.findings import Finding


@dataclasses.dataclass(frozen=True)
class BaselineEntry:
    rule: str
    location_prefix: str
    justification: str
    lineno: int

    def matches(self, finding: "Finding") -> bool:
        """Prefix match on a path boundary: 'vol:dev' must not swallow
        'vol:device-plugins'."""
        if finding.rule != self.rule:
            return False
        loc, prefix = finding.location, self.location_prefix
        if loc == prefix:
            return True
        if not loc.startswith(prefix):
            return False
        return prefix.endswith(("/", ":")) or loc[len(prefix)] in "/:["


class Baseline:
    """Parsed suppression file."""

    def __init__(self, entries: List[BaselineEntry], path: str = ""):
        self.entries = entries
        self.path = path
        self._hits: Dict[BaselineEntry, int] = {e: 0 for e in entries}

    @classmethod
    def from_text(cls, text: str, path: str = "") -> "Baseline":
        entries: List[BaselineEntry] = []
        for lineno, raw in enumerate(text.splitlines(), start=1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            body, _, justification = line.partition("#")
            parts = body.split()
            if len(parts) != 2:
                raise ValueError(
                    f"{path or 'baseline'}:{lineno}: expected "
                    f"'RULE location-prefix  # justification', got {raw!r}"
                )
            entries.append(
                BaselineEntry(
                    rule=parts[0],
                    location_prefix=parts[1],
                    justification=justification.strip(),
                    lineno=lineno,
                )
            )
        return cls(entries, path)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        try:
            with open(path) as f:
                return cls.from_text(f.read(), path)
        except FileNotFoundError:
            return cls([], path)

    def apply(self, findings: List["Finding"]) -> List["Finding"]:
        """Mark suppressed findings; suppression is recorded (not
        dropped) so reports can show what the baseline is absorbing."""
        out: List["Finding"] = []
        for f in findings:
            entry = next((e for e in self.entries if e.matches(f)), None)
            if entry is not None:
                self._hits[entry] += 1
                f = dataclasses.replace(f, suppressed=True)
            out.append(f)
        return out

    def unused_entries(self) -> List[BaselineEntry]:
        return [e for e, hits in self._hits.items() if hits == 0]


def unused_entry_findings(
    baseline: Baseline,
    selected_families: Set[str],
    family_of_rule: Callable[[str], Optional[str]],
    full_run: bool = True,
) -> List["Finding"]:
    """TPUOP-B001 findings for entries that matched nothing, judged per
    family: an entry is dead only if the analyzer family owning its
    rule actually ran this invocation (a ``--only manifest`` run must
    not condemn the concurrency entries it never gave a chance to
    match). Entries whose rule no family claims are judged only on a
    full run."""
    from tpu_operator.lint.findings import WARNING, make

    out: List["Finding"] = []
    for entry in baseline.unused_entries():
        family = family_of_rule(entry.rule)
        if family is None:
            if not full_run:
                continue
        elif family not in selected_families:
            continue
        out.append(make(
            "TPUOP-B001", WARNING,
            f"baseline:{os.path.basename(baseline.path)}:{entry.lineno}",
            f"baseline entry '{entry.rule} {entry.location_prefix}' matched "
            "nothing — delete it (dead exceptions hide real regressions)",
        ))
    return out
